"""Best-split search over histograms.

Reference: src/treelearner/feature_histogram.hpp:166 (FindBestThreshold — forward/backward
threshold scans with L1/L2 regularisation, missing-value default direction, min_data /
min_sum_hessian guards) and :232 (categorical one-hot + sorted-subset "optimal split").

TPU design: instead of per-feature scalar scans, all (slot, feature, threshold) candidates
are evaluated as one dense masked tensor op — cumulative sums along the bin axis, a gain
tensor of shape (S, F, B, 2 directions), then argmax reductions. Categorical features get a
parallel sorted-prefix scan. Cost is O(S * F * B), negligible next to histogram build.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30
EPS_HESS = 1e-15

# best_dir bit flags
DIR_DEFAULT_LEFT = 1   # missing values go left
DIR_CATEGORICAL = 2    # categorical split (threshold = sorted-prefix length k)
DIR_CAT_ONEHOT = 4     # categorical one-hot split (threshold = single bin)
DIR_CAT_REVERSED = 8   # sorted-subset taken from the high end of the sort order


class FeatureLayout(NamedTuple):
    """Static per-feature gather layout into the (G, Bmax) padded histogram."""
    gather_idx: jax.Array      # (F, Bmax) int32 into flattened (G*Bmax)
    valid_mask: jax.Array      # (F, Bmax) bool — bin b exists for feature f
    residual_pos: jax.Array    # (F,) int32 — bin position needing residual fill, -1 if none
    nan_bin: jax.Array         # (F,) int32 — NaN bin position, -1 if feature has none
    is_cat: jax.Array          # (F,) bool
    num_bins: jax.Array        # (F,) int32
    mzero_bin: jax.Array = None  # (F,) int32 — zero-as-missing bin, -1 if none


class SplitResult(NamedTuple):
    gain: jax.Array            # (S,) f32 — best split gain (already minus parent term)
    feature: jax.Array         # (S,) i32
    threshold: jax.Array       # (S,) i32 — numerical: bin t (left = bin <= t);
                               #            categorical: prefix length k or one-hot bin
    dir_flags: jax.Array       # (S,) i32 — DIR_* bits
    left_sum_g: jax.Array      # (S,) f32
    left_sum_h: jax.Array
    left_count: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array
    # (S, F) bool — per-feature "a candidate passed the gain gate" mask
    # (FeatureHistogram::is_splittable_, set by the scans and consumed by
    # the advanced-monotone rescan cache). None unless adv_bounds was given.
    feat_ok: Optional[jax.Array] = None


def _threshold_l1(s, l1):
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_term(sum_g, sum_h, l1, l2):
    """GetLeafGain (reference: feature_histogram.hpp CalculateSplittedLeafOutput family)."""
    t = _threshold_l1(sum_g, l1)
    return t * t / (sum_h + l2 + EPS_HESS)


def leaf_output(sum_g, sum_h, l1, l2, max_delta_step=0.0):
    out = -_threshold_l1(sum_g, l1) / (sum_h + l2 + EPS_HESS)
    return jnp.where(max_delta_step > 0.0,
                     jnp.clip(out, -max_delta_step, max_delta_step), out)


def leaf_gain_given_output(sum_g, sum_h, l1, l2, output):
    """GetLeafGainGivenOutput (reference: feature_histogram.hpp) — the gain of a leaf
    forced to a (constrained/smoothed) output instead of its optimum."""
    t = _threshold_l1(sum_g, l1)
    return -(2.0 * t * output + (sum_h + l2) * output * output)


def smooth_output(raw, count, parent_output, path_smooth):
    """Path smoothing (reference: feature_histogram.hpp path_smooth template arg):
    smoothed = raw * n/(n+a) + parent * a/(n+a)."""
    return (raw * count / (count + path_smooth)
            + parent_output * path_smooth / (count + path_smooth))


def monotone_penalty_factor(depth, penalty):
    """ComputeMonotoneSplitGainPenalty (reference: monotone_constraints.hpp)."""
    eps = 1e-10
    d = depth.astype(jnp.float32)
    f_small = 1.0 - penalty / jnp.exp2(d) + eps
    f_big = 1.0 - jnp.exp2(penalty - 1.0 - d) + eps
    out = jnp.where(penalty <= 1.0, f_small, f_big)
    return jnp.where(penalty >= d + 1.0, eps, out)


def constrained_child_outputs(lg, lh, lc, rg, rh, rc, l1, l2, lo, hi,
                              path_smooth=0.0, parent_out=None,
                              max_delta_step=0.0):
    """Child outputs under monotone bounds [lo, hi] and optional path smoothing —
    used both inside the split scan and to propagate bounds after a split.
    Clamp order matches CalculateSplittedLeafOutput (feature_histogram.hpp):
    ridge output -> max_delta_step clamp -> smoothing -> monotone clip."""
    ol = -_threshold_l1(lg, l1) / (lh + l2 + EPS_HESS)
    orr = -_threshold_l1(rg, l1) / (rh + l2 + EPS_HESS)
    if max_delta_step > 0.0:
        ol = jnp.clip(ol, -max_delta_step, max_delta_step)
        orr = jnp.clip(orr, -max_delta_step, max_delta_step)
    if path_smooth > 0.0 and parent_out is not None:
        ol = smooth_output(ol, lc, parent_out, path_smooth)
        orr = smooth_output(orr, rc, parent_out, path_smooth)
    ol = jnp.clip(ol, lo, hi)
    orr = jnp.clip(orr, lo, hi)
    return ol, orr


def adv_child_bounds(v_min, v_max, big):
    """Per-threshold child output bounds from constraint slabs: the LEFT
    child at threshold t spans bins [lo, t] so its bound is the running
    extremum up to t; the RIGHT child spans (t, hi) so its bound is the
    suffix extremum from t+1 (reference: the cumulative constraint the
    scan applies per threshold, InitCumulativeConstraints + Update)."""
    ax = v_min.ndim - 1
    lo_l = jax.lax.cummax(v_min, axis=ax)
    hi_l = jax.lax.cummin(v_max, axis=ax)
    sfx_max = jnp.flip(jax.lax.cummax(jnp.flip(v_min, -1), axis=ax), -1)
    sfx_min = jnp.flip(jax.lax.cummin(jnp.flip(v_max, -1), axis=ax), -1)
    pad = [(0, 0)] * (v_min.ndim - 1) + [(0, 1)]
    lo_r = jnp.pad(sfx_max, pad, constant_values=-big)[..., 1:]
    hi_r = jnp.pad(sfx_min, pad, constant_values=big)[..., 1:]
    return lo_l, hi_l, lo_r, hi_r


def _layout_is_identity(layout: FeatureLayout, num_groups: int,
                        bmax: int) -> bool:
    """True when features map 1:1 onto groups with no EFB bundling, so the
    per-feature gather is the identity (trace-time check on the concrete
    layout constants; False if the layout is traced)."""
    try:
        idx = np.asarray(layout.gather_idx)
    except Exception:
        return False
    F = idx.shape[0]
    if F != num_groups or idx.shape[1] != bmax:
        return False
    expect = np.arange(F)[:, None] * bmax + np.arange(bmax)[None, :]
    return bool(np.array_equal(idx, expect))


def _layout_group_perm(layout: FeatureLayout, num_groups: int,
                       bmax: int):
    """(F,) group index per feature when every feature owns a whole group
    (single-feature groups in ANY order — the bucket-sorted device layout),
    else None.  The per-feature "gather" is then a cheap whole-slice take
    along the group axis instead of the latency-bound (S*F*Bmax)-row
    generic gather."""
    try:
        idx = np.asarray(layout.gather_idx)
        valid = np.asarray(layout.valid_mask)
    except Exception:
        return None
    F = idx.shape[0]
    if F != num_groups or idx.shape[1] != bmax:
        return None
    if not valid[:, 0].all():
        return None
    base = idx[:, 0]
    if (base % bmax).any():
        return None
    perm = base // bmax
    expect = base[:, None] + np.arange(bmax)[None, :]
    # only VALID positions must line up (features with fewer bins than bmax
    # leave zeros in the gather table; the take path masks them anyway)
    if not np.array_equal(np.where(valid, idx, expect), expect):
        return None
    if not np.array_equal(np.sort(perm), np.arange(F)):
        return None
    return perm.astype(np.int32)


def round_int(x):
    """Common::RoundInt (common.h:911) — the reference derives per-bin data
    counts from hessian sums as RoundInt(hess * cnt_factor) rather than
    storing a count channel (feature_histogram.hpp:529,544)."""
    return jnp.floor(x + 0.5)


def gather_feature_histograms(hist: jax.Array, layout: FeatureLayout,
                              *parents: jax.Array) -> jax.Array:
    """(S, G, Bmax, C) group-padded hist -> (S, F, Bmax, C) per-feature hist
    (C = 2 grad/hess channels; parents = the matching per-slot totals).

    Fills EFB-bundle shared-default bins by residual: default = parent_total -
    others.  When the layout is the identity (no bundling — the common dense
    case) the latency-bound (S*F*Bmax)-row gather is skipped entirely: on TPU
    that gather costs ~10 ms per round and would dominate split finding."""
    s_dim, num_groups, bmax, num_ch = hist.shape
    assert len(parents) == num_ch
    if _layout_is_identity(layout, num_groups, bmax):
        hf = hist * layout.valid_mask[None, :, :, None]
    else:
        perm = _layout_group_perm(layout, num_groups, bmax)
        if perm is not None:
            # bucket-sorted single-feature groups: whole-slice take on the
            # group axis instead of the (S*F*Bmax)-row generic gather
            hf = hist[:, jnp.asarray(perm)] \
                * layout.valid_mask[None, :, :, None]
        else:
            flat = hist.reshape(s_dim, -1, num_ch)        # (S, G*Bmax, C)
            hf = flat[:, layout.gather_idx, :]            # (S, F, Bmax, C)
            hf = hf * layout.valid_mask[None, :, :, None]
    try:
        any_resid = bool((np.asarray(layout.residual_pos) >= 0).any())
    except Exception:
        any_resid = True
    if not any_resid:
        return hf
    has_resid = layout.residual_pos >= 0                  # (F,)
    resid_oh = jax.nn.one_hot(jnp.maximum(layout.residual_pos, 0),
                              hf.shape[2], dtype=hf.dtype)          # (F, Bmax)
    parent = jnp.stack(parents, -1)                                 # (S, C)
    resid = parent[:, None, :] - hf.sum(axis=2)                     # (S, F, C)
    hf = hf + (resid_oh * has_resid[:, None])[None, :, :, None] * resid[:, :, None, :]
    return hf


def find_best_splits(
    hist: jax.Array,               # (S, G, Bmax, 3)
    parent_g: jax.Array,           # (S,)
    parent_h: jax.Array,
    parent_c: jax.Array,
    layout: FeatureLayout,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: int,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
    col_mask: Optional[jax.Array] = None,    # (S, F) or (F,) float/bool feature sampling
    cat_l2: float = 10.0,
    cat_smooth: float = 10.0,
    max_cat_threshold: int = 32,
    max_cat_to_onehot: int = 4,
    min_data_per_group: int = 100,
    enable_categorical: bool = True,
    monotone: Optional[jax.Array] = None,   # (F,) i32 in {-1,0,1}
    out_lo: Optional[jax.Array] = None,     # (S,) leaf output lower bounds
    out_hi: Optional[jax.Array] = None,     # (S,) leaf output upper bounds
    slot_depth: Optional[jax.Array] = None,  # (S,) i32 — for monotone penalty
    monotone_penalty: float = 0.0,
    path_smooth: float = 0.0,
    parent_out: Optional[jax.Array] = None,  # (S,) parent (smoothed) outputs
    extra_key: Optional[jax.Array] = None,   # PRNG key — extra_trees random thresholds
    cegb_penalty: Optional[jax.Array] = None,  # (S, F) gain penalty (CEGB)
    adv_bounds=None,   # (v_min, v_max) (S, F, Bmax) — advanced monotone slabs
    splittable=None,   # (S, F) bool — sticky is_splittable mask (advanced only)
    max_delta_step: float = 0.0,
) -> SplitResult:
    """Monotone constraints use the reference's "basic" method
    (monotone_constraints.hpp BasicLeafConstraints): candidate outputs are clipped
    to the leaf's inherited [out_lo, out_hi] bounds, order-violating splits are
    rejected, and gains are evaluated at the constrained outputs. Path smoothing
    and monotonicity apply to numerical features only (matching the reference's
    restriction of monotone constraints to numerical features)."""
    S, G, Bmax, _ = hist.shape
    F = layout.gather_idx.shape[0]
    hf = gather_feature_histograms(hist, layout, parent_g, parent_h)
    hg, hh = hf[..., 0], hf[..., 1]                       # (S, F, Bmax)
    # per-bin data counts are ESTIMATED from hessians exactly like the
    # reference (feature_histogram.hpp:529,544: cnt_factor = num_data /
    # sum_hessian; cnt = RoundInt(hess * cnt_factor)) — histograms carry
    # only grad/hess channels
    cnt_factor = parent_c / jnp.maximum(parent_h, EPS_HESS)
    hc = round_int(hh * cnt_factor[:, None, None])        # (S, F, Bmax)

    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]
    use_output_gain = (monotone is not None) or (path_smooth > 0.0) \
        or (adv_bounds is not None) or (max_delta_step > 0.0)
    if adv_bounds is not None:
        # ADVANCED monotone method: per-threshold child bounds from the
        # constraint slabs (monotone_constraints.hpp:859). Only the REVERSE
        # scan walks the piecewise segments: CumulativeFeatureConstraint's
        # Update() only ever DECREMENTS its indices, so the forward scan's
        # indices stay frozen at their init position — its left child reads
        # the first segment's values and its right child the whole-array
        # extrema, constant across thresholds (monotone_constraints.hpp:147
        # Update + InitCumulativeConstraints(REVERSE=false); verified
        # empirically against an instrumented stock CLI).
        a_lo_l, a_hi_l, a_lo_r, a_hi_r = adv_child_bounds(
            adv_bounds[0], adv_bounds[1], -NEG_INF)
        adv_rev = (a_lo_l, a_hi_l, a_lo_r, a_hi_r)
        adv_fwd = (adv_bounds[0][..., 0:1], adv_bounds[1][..., 0:1],
                   jnp.max(adv_bounds[0], -1, keepdims=True),
                   jnp.min(adv_bounds[1], -1, keepdims=True))
    mono_b = monotone[None, :, None] if monotone is not None else None
    lo_b = out_lo[:, None, None] if out_lo is not None else -jnp.inf
    hi_b = out_hi[:, None, None] if out_hi is not None else jnp.inf
    po_b = parent_out[:, None, None] if parent_out is not None else None

    # ---------------- numerical scan ----------------
    cg = jnp.cumsum(hg, axis=-1)
    ch = jnp.cumsum(hh, axis=-1)
    cc = jnp.cumsum(hc, axis=-1)

    nbins = layout.num_bins                                # (F,)
    bin_iota = jnp.arange(Bmax)[None, None, :]             # broadcast (1,1,Bmax)
    has_nan = (layout.nan_bin >= 0)[None, :, None]
    nan_idx = jnp.maximum(layout.nan_bin, 0)
    # zeroed for no-NaN features: their single (reverse) scan must not pick up
    # bin 0 via the clamped gather below
    nan_g = jnp.take_along_axis(hg, nan_idx[None, :, None].repeat(S, 0), axis=-1)
    nan_h = jnp.take_along_axis(hh, nan_idx[None, :, None].repeat(S, 0), axis=-1)
    nan_c = jnp.take_along_axis(hc, nan_idx[None, :, None].repeat(S, 0), axis=-1)
    nan_g = jnp.where(has_nan, nan_g, 0.0)
    nan_h = jnp.where(has_nan, nan_h, 0.0)
    nan_c = jnp.where(has_nan, nan_c, 0.0)
    # zero-as-missing (MissingType::Zero): the default bin's content leaves
    # BOTH accumulating sides and follows the scan direction, and the scans
    # SKIP_DEFAULT_BIN (reference: FindBestThresholdSequentially's
    # skip_default_bin — the reverse scan never evaluates threshold
    # default_bin-1, the forward scan never evaluates threshold default_bin)
    mzb = (layout.mzero_bin if layout.mzero_bin is not None
           else jnp.full(F, -1, jnp.int32))
    has_mz = (mzb >= 0)[None, :, None]
    mz_idx = jnp.maximum(mzb, 0)
    z_g = jnp.where(has_mz, jnp.take_along_axis(
        hg, mz_idx[None, :, None].repeat(S, 0), axis=-1), 0.0)
    z_h = jnp.where(has_mz, jnp.take_along_axis(
        hh, mz_idx[None, :, None].repeat(S, 0), axis=-1), 0.0)
    z_c = jnp.where(has_mz, jnp.take_along_axis(
        hc, mz_idx[None, :, None].repeat(S, 0), axis=-1), 0.0)
    miss_g = nan_g + z_g                   # a feature has at most one kind
    miss_h = nan_h + z_h
    miss_c = nan_c + z_c
    has_miss = has_nan | has_mz

    def split_gain(lg, lh, lc, rc, adv=None):
        rg, rh = pg - lg, ph - lh
        if use_output_gain:
            if adv_bounds is not None:
                b_lo_l, b_hi_l, b_lo_r, b_hi_r = adv
                ol, _ = constrained_child_outputs(
                    lg, lh, lc, rg, rh, rc, lambda_l1, lambda_l2,
                    b_lo_l, b_hi_l, path_smooth, po_b, max_delta_step)
                _, orr = constrained_child_outputs(
                    lg, lh, lc, rg, rh, rc, lambda_l1, lambda_l2,
                    b_lo_r, b_hi_r, path_smooth, po_b, max_delta_step)
            else:
                ol, orr = constrained_child_outputs(
                    lg, lh, lc, rg, rh, rc, lambda_l1, lambda_l2, lo_b, hi_b,
                    path_smooth, po_b, max_delta_step)
            gain = leaf_gain_given_output(lg, lh, lambda_l1, lambda_l2, ol) + \
                   leaf_gain_given_output(rg, rh, lambda_l1, lambda_l2, orr)
            if mono_b is not None:
                viol = ((mono_b > 0) & (ol > orr)) | ((mono_b < 0) & (ol < orr))
                gain = jnp.where((mono_b != 0) & viol, NEG_INF, gain)
        else:
            gain = leaf_term(lg, lh, lambda_l1, lambda_l2) + \
                   leaf_term(rg, rh, lambda_l1, lambda_l2)
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
              (lh >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, NEG_INF)

    # The reference evaluates numerical thresholds with one or two sequential
    # scans (feature_histogram.hpp:833 FindBestThresholdSequentially):
    #   * REVERSE (right-to-left): the ONLY scan for features without missing
    #     values, and the missing-LEFT scan for NaN features. Its strict
    #     `current_gain > best_gain` update means the HIGHEST of gain-tied
    #     thresholds wins (ties happen whenever a bin is empty in a leaf).
    #   * forward (left-to-right): the missing-RIGHT scan for NaN features;
    #     the LOWEST tied threshold wins. It also covers threshold nb-2
    #     ("all data bins left, NaN right"), which REVERSE does not.
    #   * On a gain tie between scans, REVERSE wins (the forward scan must
    #     strictly beat it: `best_gain > output->gain + min_gain_shift`), and
    #     `output->default_left = REVERSE`, so no-missing features always
    #     record default_left=true, matching stock model bytes.
    data_bins = jnp.where(layout.nan_bin[None, :, None] >= 0,
                          nbins[None, :, None] - 1, nbins[None, :, None])
    # Data-count estimates follow each scan's ACCUMULATION direction: the
    # reverse scan sums RoundInt'd per-bin counts over the RIGHT data bins
    # and derives left = num_data - right (feature_histogram.hpp:857-884);
    # forward accumulates the left side. The two differ after rounding —
    # e.g. an inflated left-cumsum can report right = 3 when the right bins
    # round to 5 — and stock's min_data_in_leaf gate uses the scan's own
    # estimate, so the gate must too.
    # effective cumsums EXCLUDE the zero-as-missing bin once passed
    past_z = has_mz & (bin_iota >= mzb[None, :, None])
    cg_eff = cg - jnp.where(past_z, z_g, 0.0)
    ch_eff = ch - jnp.where(past_z, z_h, 0.0)
    cc_eff = cc - jnp.where(past_z, z_c, 0.0)
    ccDB = jnp.take_along_axis(
        cc_eff,
        jnp.maximum(jnp.broadcast_to(data_bins - 1, cc.shape[:2] + (1,)),
                    0), axis=-1)                           # (S, F, 1)
    rc_rev = ccDB - cc_eff                                 # right rounded counts
    lc_rev = pc - rc_rev
    lc_fwd = cc_eff
    rc_fwd = pc - cc_eff
    # rev: missing left — left side = cumsum at t + missing-bin contents
    adv_r = adv_rev if adv_bounds is not None else None
    adv_f = adv_fwd if adv_bounds is not None else None
    gain_rev = split_gain(cg_eff + miss_g, ch_eff + miss_h, lc_rev, rc_rev,
                          adv=adv_r)
    # fwd: missing right — left side = plain cumsum at t (missing-typed
    # features only)
    gain_fwd = jnp.where(has_miss,
                         split_gain(cg_eff, ch_eff, lc_fwd, rc_fwd, adv=adv_f),
                         NEG_INF)
    # rev thresholds: t in [0, data_bins-2] minus the skipped default-bin
    # position for zero-as-missing; fwd adds t = data_bins-1 ("NaN vs the
    # rest") for NaN features but stays within [0, data_bins-2] minus the
    # default bin for zero-as-missing
    rev_skip = has_mz & (bin_iota == mzb[None, :, None] - 1)
    fwd_skip = has_mz & (bin_iota == mzb[None, :, None])
    fwd_hi = jnp.where(has_mz, data_bins - 1, data_bins)
    gain_rev = jnp.where((bin_iota < (data_bins - 1)) & ~rev_skip,
                         gain_rev, NEG_INF)
    gain_fwd = jnp.where((bin_iota < fwd_hi) & ~fwd_skip, gain_fwd, NEG_INF)

    # relative (vs parent) gain so per-feature penalties compose before the
    # argmax. Under max_delta_step the parent's gain shift is evaluated at
    # its CLAMPED output (BeforeNumerical -> GetLeafGain<USE_MAX_OUTPUT>),
    # so candidate gates see the same shift stock's scan does.
    if max_delta_step > 0.0:
        p_out_c = leaf_output(parent_g, parent_h, lambda_l1, lambda_l2,
                              max_delta_step)
        parent_term_num = leaf_gain_given_output(
            parent_g, parent_h, lambda_l1, lambda_l2, p_out_c)
    else:
        parent_term_num = leaf_term(parent_g, parent_h, lambda_l1, lambda_l2)

    def _rel(num_gain):
        num_rel = num_gain - parent_term_num[:, None, None]
        num_rel = jnp.where(num_gain <= NEG_INF / 2, NEG_INF, num_rel)
        if monotone is not None and monotone_penalty > 0.0 and slot_depth is not None:
            pen = monotone_penalty_factor(slot_depth, monotone_penalty)[:, None, None]
            num_rel = jnp.where((mono_b != 0) & (num_rel > 0), num_rel * pen, num_rel)
        if extra_key is not None:
            # extra_trees: evaluate ONE random threshold per (slot, feature)
            # (reference: feature_histogram.hpp rand_threshold under extra_trees)
            rand_t = jax.random.randint(
                extra_key, (S, F), 0, 1 << 30) % jnp.maximum(nbins[None, :] - 1, 1)
            num_rel = jnp.where(bin_iota == rand_t[..., None], num_rel, NEG_INF)
        return num_rel

    rel_rev, rel_fwd = _rel(gain_rev), _rel(gain_fwd)
    if splittable is not None:
        # advanced-monotone rescans skip features whose LAST scan of this
        # leaf found no candidate above the gain gate (the sticky
        # FeatureHistogram::is_splittable_ — RecomputeBestSplitForLeaf
        # `continue`s them, serial_tree_learner.cpp:1083, and FindBestSplits
        # propagates parent-false to fresh children, :399)
        sp_b = splittable[:, :, None]
        rel_rev = jnp.where(sp_b, rel_rev, NEG_INF)
        rel_fwd = jnp.where(sp_b, rel_fwd, NEG_INF)
    if adv_bounds is not None:
        # is_splittable_ update: any threshold whose gain beats
        # min_gain_shift (feature_histogram.hpp:919 — set before the cegb
        # adjustment; the reference also flags before the monotone penalty,
        # which coincides with this for the default penalty=0);
        # categorical features are left unfiltered
        feat_ok = (jnp.any(rel_rev > min_gain_to_split, axis=-1)
                   | jnp.any(rel_fwd > min_gain_to_split, axis=-1)
                   | layout.is_cat[None, :])
    else:
        feat_ok = None

    def _pick_num_best(rel_rev, rel_fwd):
        """Per-(slot, feature) winner with the reference's scan-order
        tie-breaks: reverse prefers the highest tied threshold, forward the
        lowest, and reverse beats forward on equal gain."""
        t_rev = (rel_rev.shape[-1] - 1) - jnp.argmax(rel_rev[..., ::-1], axis=-1)
        g_rev = jnp.take_along_axis(rel_rev, t_rev[..., None], -1)[..., 0]
        t_fwd = jnp.argmax(rel_fwd, axis=-1)
        g_fwd = jnp.take_along_axis(rel_fwd, t_fwd[..., None], -1)[..., 0]
        use_rev = g_rev >= g_fwd
        return (jnp.where(use_rev, t_rev, t_fwd),
                jnp.where(use_rev, g_rev, g_fwd), use_rev)

    if not enable_categorical:
        # numeric-only fast path: much smaller compiled program (no per-bin argsort)
        best_t, best_gain_f, use_rev_f = _pick_num_best(rel_rev, rel_fwd)
        if cegb_penalty is not None:
            # cost-effective gradient boosting: subtract the split cost from
            # every candidate's gain (cost_effective_gradient_boosting.hpp:80)
            best_gain_f = jnp.where(best_gain_f > NEG_INF / 2,
                                    best_gain_f - cegb_penalty, NEG_INF)
        if col_mask is not None:
            cm = jnp.broadcast_to(jnp.asarray(col_mask, bool), best_gain_f.shape)
            best_gain_f = jnp.where(cm, best_gain_f, NEG_INF)
        best_f = jnp.argmax(best_gain_f, axis=-1)
        ar = jnp.arange(S)
        rel_gain = best_gain_f[ar, best_f]
        t = best_t[ar, best_f]
        dflt_l = use_rev_f[ar, best_f]

        def pick(a3):
            return a3[ar, best_f, t]

        lg = pick(cg_eff) + jnp.where(
            dflt_l, pick(jnp.broadcast_to(miss_g, cg.shape)), 0.0)
        lh = pick(ch_eff) + jnp.where(
            dflt_l, pick(jnp.broadcast_to(miss_h, ch.shape)), 0.0)
        lc = jnp.where(dflt_l, pick(jnp.broadcast_to(lc_rev, cg.shape)),
                       pick(jnp.broadcast_to(lc_fwd, cg.shape)))
        rel_gain = jnp.where(rel_gain > min_gain_to_split, rel_gain, NEG_INF)
        dir_flags = jnp.where(dflt_l, DIR_DEFAULT_LEFT, 0)
        return SplitResult(
            gain=rel_gain, feature=best_f.astype(jnp.int32),
            threshold=t.astype(jnp.int32), dir_flags=dir_flags.astype(jnp.int32),
            left_sum_g=lg, left_sum_h=lh, left_count=lc,
            right_sum_g=parent_g - lg, right_sum_h=parent_h - lh,
            right_count=parent_c - lc, feat_ok=feat_ok)

    # ---------------- categorical ----------------
    is_cat = layout.is_cat[None, :, None]
    cat_l2_total = lambda_l2 + cat_l2

    def split_gain_cat(lg, lh, lc):
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        if max_delta_step > 0.0:
            ol = leaf_output(lg, lh, lambda_l1, cat_l2_total, max_delta_step)
            orr = leaf_output(rg, rh, lambda_l1, cat_l2_total, max_delta_step)
            gain = leaf_gain_given_output(lg, lh, lambda_l1, cat_l2_total, ol) \
                + leaf_gain_given_output(rg, rh, lambda_l1, cat_l2_total, orr)
        else:
            gain = leaf_term(lg, lh, lambda_l1, cat_l2_total) + \
                   leaf_term(rg, rh, lambda_l1, cat_l2_total)
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
              (lh >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
        return jnp.where(ok, gain, NEG_INF)

    oh_gain = split_gain_cat(hg, hh, hc)
    oh_valid = layout.valid_mask[None] & (hc >= min_data_per_group) & is_cat
    oh_gain = jnp.where(oh_valid, oh_gain, NEG_INF)

    # sorted subset: order bins by g/(h + cat_smooth), prefix scans both directions
    ratio = hg / (hh + cat_smooth)
    big = 1e10
    eligible = layout.valid_mask[None] & (hc >= min_data_per_group)
    ratio = jnp.where(eligible, ratio, big)                # ineligible sort to the end
    order = jnp.argsort(ratio, axis=-1)                    # (S, F, Bmax) ascending
    sg = jnp.take_along_axis(hg, order, -1)
    sh = jnp.take_along_axis(hh, order, -1)
    sc = jnp.take_along_axis(hc, order, -1)
    n_elig = eligible.sum(axis=-1)                         # (S, F)
    csg, csh, csc = jnp.cumsum(sg, -1), jnp.cumsum(sh, -1), jnp.cumsum(sc, -1)
    k_iota = 1 + jnp.arange(Bmax)[None, None, :]           # prefix length k = t+1
    k_ok = (k_iota <= jnp.minimum(max_cat_threshold, n_elig[..., None] - 1))
    fwd_gain = jnp.where(k_ok, split_gain_cat(csg, csh, csc), NEG_INF)
    # reversed direction: prefix of the descending order = suffix of ascending ELIGIBLE
    # bins; compute via totals of eligible set
    eg = jnp.sum(hg * eligible, -1, keepdims=True)
    eh = jnp.sum(hh * eligible, -1, keepdims=True)
    ec = jnp.sum(hc * eligible, -1, keepdims=True)
    rev_lg, rev_lh, rev_lc = eg - csg, eh - csh, ec - csc  # suffix after position t
    rev_k = n_elig[..., None] - k_iota                     # suffix length
    rev_ok = (rev_k >= 1) & (rev_k <= max_cat_threshold)
    rev_gain = jnp.where(rev_ok, split_gain_cat(rev_lg, rev_lh, rev_lc), NEG_INF)

    use_onehot = (nbins[None, :, None] <= max_cat_to_onehot)
    sorted_gain = jnp.maximum(fwd_gain, rev_gain)
    sorted_rev = rev_gain > fwd_gain
    cat_gain = jnp.where(use_onehot, oh_gain, jnp.maximum(oh_gain, sorted_gain))
    cat_use_oh = use_onehot | (oh_gain >= sorted_gain)
    cat_gain = jnp.where(is_cat, cat_gain, NEG_INF)

    # categorical rel gain uses the cat-regularised parent term (reference:
    # feature_histogram.hpp computes the gain shift with l2 + cat_l2)
    if max_delta_step > 0.0:
        p_out_cc = leaf_output(parent_g, parent_h, lambda_l1, cat_l2_total,
                               max_delta_step)
        parent_term_cat = leaf_gain_given_output(
            parent_g, parent_h, lambda_l1, cat_l2_total, p_out_cc)
    else:
        parent_term_cat = leaf_term(parent_g, parent_h, lambda_l1,
                                    cat_l2_total)
    cat_rel = cat_gain - parent_term_cat[:, None, None]
    cat_rel = jnp.where(cat_gain <= NEG_INF / 2, NEG_INF, cat_rel)

    # ---------------- combine ----------------
    t_num, g_num, use_rev_f = _pick_num_best(rel_rev, rel_fwd)
    t_cat = jnp.argmax(cat_rel, axis=-1)
    g_cat = jnp.take_along_axis(cat_rel, t_cat[..., None], -1)[..., 0]
    is_cat_f = layout.is_cat[None, :]                      # (1, F)
    best_t = jnp.where(is_cat_f, t_cat, t_num)             # (S, F)
    best_gain_f = jnp.where(is_cat_f, g_cat, g_num)
    if cegb_penalty is not None:
        best_gain_f = jnp.where(best_gain_f > NEG_INF / 2,
                                best_gain_f - cegb_penalty, NEG_INF)

    if col_mask is not None:
        cm = jnp.broadcast_to(jnp.asarray(col_mask, bool), best_gain_f.shape)
        best_gain_f = jnp.where(cm, best_gain_f, NEG_INF)

    best_f = jnp.argmax(best_gain_f, axis=-1)              # (S,)
    ar = jnp.arange(S)
    best_gain = best_gain_f[ar, best_f]
    t = best_t[ar, best_f]                                 # (S,)

    # gather split sums / flags at the winner
    f_is_cat = layout.is_cat[best_f]
    f_use_oh = cat_use_oh[ar, best_f, t]
    f_rev = sorted_rev[ar, best_f, t]
    dflt_l = use_rev_f[ar, best_f] & ~f_is_cat

    def pick(a3):
        return a3[ar, best_f, t]

    lg_num = pick(cg_eff) + jnp.where(
        dflt_l, pick(jnp.broadcast_to(miss_g, cg.shape)), 0.0)
    lh_num = pick(ch_eff) + jnp.where(
        dflt_l, pick(jnp.broadcast_to(miss_h, ch.shape)), 0.0)
    lc_num = jnp.where(dflt_l, pick(jnp.broadcast_to(lc_rev, cg.shape)),
                       pick(jnp.broadcast_to(lc_fwd, cg.shape)))
    lg_oh, lh_oh, lc_oh = pick(hg), pick(hh), pick(hc)
    lg_fs, lh_fs, lc_fs = pick(csg), pick(csh), pick(csc)
    lg_rs = eg[ar, best_f, 0] - lg_fs
    lh_rs = eh[ar, best_f, 0] - lh_fs
    lc_rs = ec[ar, best_f, 0] - lc_fs

    lg = jnp.where(f_is_cat,
                   jnp.where(f_use_oh, lg_oh, jnp.where(f_rev, lg_rs, lg_fs)), lg_num)
    lh = jnp.where(f_is_cat,
                   jnp.where(f_use_oh, lh_oh, jnp.where(f_rev, lh_rs, lh_fs)), lh_num)
    lc = jnp.where(f_is_cat,
                   jnp.where(f_use_oh, lc_oh, jnp.where(f_rev, lc_rs, lc_fs)), lc_num)

    rel_gain = jnp.where(best_gain > min_gain_to_split, best_gain, NEG_INF)

    dir_flags = (jnp.where(dflt_l & ~f_is_cat, DIR_DEFAULT_LEFT, 0)
                 | jnp.where(f_is_cat, DIR_CATEGORICAL, 0)
                 | jnp.where(f_is_cat & f_use_oh, DIR_CAT_ONEHOT, 0)
                 | jnp.where(f_is_cat & ~f_use_oh & f_rev, DIR_CAT_REVERSED, 0))
    # categorical sorted threshold is the prefix LENGTH k = t+1; one-hot keeps bin t
    thr = jnp.where(f_is_cat & ~f_use_oh, t + 1, t).astype(jnp.int32)

    return SplitResult(
        gain=rel_gain,
        feature=best_f.astype(jnp.int32),
        threshold=thr,
        dir_flags=dir_flags.astype(jnp.int32),
        left_sum_g=lg, left_sum_h=lh, left_count=lc,
        right_sum_g=parent_g - lg, right_sum_h=parent_h - lh,
        right_count=parent_c - lc, feat_ok=feat_ok,
    )


def categorical_left_bitset(hist_f: jax.Array, threshold: jax.Array,
                            dir_flags: jax.Array, valid_mask: jax.Array,
                            cat_smooth: float, min_data_per_group: int,
                            cnt_factor: jax.Array) -> jax.Array:
    """Recompute the left-side bin membership mask (Bmax,) for a chosen categorical split.

    For one-hot splits the mask is a single bin; for sorted-subset splits it is the
    first/last k bins of the g/(h+cat_smooth) ordering (reference: feature_histogram.hpp
    categorical best-subset selection). cnt_factor (per slot) estimates bin counts
    from hessians, as the reference does."""
    hg, hh = hist_f[..., 0], hist_f[..., 1]
    hc = round_int(hh * cnt_factor[..., None])
    Bmax = hg.shape[-1]
    eligible = valid_mask & (hc >= min_data_per_group)
    ratio = jnp.where(eligible, hg / (hh + cat_smooth), 1e10)
    order = jnp.argsort(ratio, axis=-1)
    rank = jnp.argsort(order, axis=-1)                     # rank of each bin in the sort
    n_elig = eligible.sum(-1, keepdims=True)
    onehot = (dir_flags & DIR_CAT_ONEHOT) != 0
    rev = (dir_flags & DIR_CAT_REVERSED) != 0
    k = threshold
    in_prefix = rank < k[..., None]
    in_suffix = (rank >= k[..., None]) & (rank < n_elig)
    mask_sorted = jnp.where(rev[..., None], in_suffix, in_prefix) & eligible
    mask_oh = jax.nn.one_hot(k, Bmax, dtype=bool)
    return jnp.where(onehot[..., None], mask_oh, mask_sorted)
