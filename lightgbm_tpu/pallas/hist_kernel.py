"""Fused Pallas TPU histogram kernel — the framework's hot op.

Reference analog: src/io/dense_bin.hpp:99-170 (ConstructHistogramInner — per-row
scatter-add into an L1-resident histogram) and src/treelearner/cuda/
cuda_histogram_constructor.cu (shared-memory atomic adds). TPUs have neither fast
scatter nor atomics; the dense alternative (one-hot matmul in XLA) materialises an
(N, Bmax)-shaped one-hot per feature group, whose HBM traffic dominates.

This kernel removes that traffic with a nibble decomposition: bin = 16*hi + lo, so

    hist[s, g, 16h+l, c] = sum_t  w[c, t] * 1[hi_g[t] == h] * 1[lo_g[t] == l]
                         = (A_g B_g^T)[c*HI+h, l]

with A_g[c*HI+h, t] = w[c, t]*onehot(hi)[h, t]  (VPU build, (3*HI, T))
and  B_g[l, t]      = onehot(lo)[l, t]          (VPU build, (LO, T)).

Per row-block only 3*HI + LO ≈ 64 one-hot sublanes are generated (vs Bmax = 256),
everything stays in VMEM, and the contraction runs on the MXU. Rows are pre-sorted
by slot (ops/compact.py) so each block accumulates into exactly one histogram slot;
the block -> slot mapping and the block's row window arrive via scalar prefetch, and
per-block DMAs slice the sorted arrays directly from HBM at 128-aligned row offsets
(no padded copy).

Output layout (S, 3*HI, G*LO): keeps the minor dimension wide (G*LO = 448 lanes for
28 groups) so VMEM<->HBM writebacks of a slot's accumulator stay dense.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LO = 16  # low-nibble width; HI = ceil(Bmax / LO)


def _hist_kernel(scalar_ref, bins_hbm, w_hbm, out_ref, bins_vmem, w_vmem,
                 acc_ref, sem_b, sem_w, *, T: int, G: int, HI: int):
    # bins_hbm is (G_pad, Nc) and w_hbm (8, Nc): leading dims padded to the sublane
    # tile so the per-block DMA slices are aligned; only rows < G / < 3 are used.
    b = pl.program_id(0)
    slot = scalar_ref[b, 0]
    start = pl.multiple_of(scalar_ref[b, 1], 128)
    row_lo = scalar_ref[b, 2]
    row_hi = scalar_ref[b, 3]
    first = scalar_ref[b, 4]

    cp_b = pltpu.make_async_copy(bins_hbm.at[:, pl.ds(start, T)], bins_vmem, sem_b)
    cp_w = pltpu.make_async_copy(w_hbm.at[:, pl.ds(start, T)], w_vmem, sem_w)

    @pl.when(slot >= 0)
    def _():
        cp_b.start()
        cp_w.start()

    @pl.when(first == 1)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    @pl.when(slot >= 0)
    def _():
        cp_b.wait()
        cp_w.wait()
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
        row_ok = ((lane >= row_lo) & (lane < row_hi)).astype(jnp.float32)  # (1, T)
        w = w_vmem[0:3, :] * row_ok                               # (3, T)
        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (HI, T), 0)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (LO, T), 0)

        for g in range(G):                                        # static unroll
            bg = bins_vmem[g:g + 1, :].astype(jnp.int32)          # (1, T)
            hi = bg // LO
            lo = bg - hi * LO
            oh_hi = (hi_iota == hi).astype(jnp.float32)           # (HI, T)
            oh_lo = (lo_iota == lo).astype(jnp.float32)           # (LO, T)
            # A[c*HI+h, t] = w[c, t] * oh_hi[h, t] (sublane-merging reshape)
            A = (w[:, None, :] * oh_hi[None, :, :]).reshape(3 * HI, T)
            bh = jax.lax.dot_general(A, oh_lo, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.HIGHEST)  # (3HI, LO)
            acc_ref[:, g * LO:(g + 1) * LO] = bh

        out_ref[0] += acc_ref[...]


@functools.partial(jax.jit, static_argnames=("num_slots", "max_group_bins",
                                             "num_groups", "block_rows"))
def hist_sorted_pallas(bins_sorted_T: jax.Array, w_sorted: jax.Array,
                       block_scalars: jax.Array, counts: jax.Array,
                       num_slots: int, max_group_bins: int, num_groups: int,
                       block_rows: int = 4096) -> jax.Array:
    """Histograms from slot-sorted rows.

    bins_sorted_T: (G_pad, Nc) uint8 — sorted bin matrix, transposed, leading dim
      padded to the sublane tile; padded by at least one block beyond the last real
      row (blocks may over-read).
    w_sorted: (8, Nc) float32 — sorted (grad, hess, cnt, 0...); zeros on invalid rows.
    block_scalars: (NB, 5) int32 from ops.compact.plan_compaction.
    counts: (S,) int32 rows per slot (empty slots produce zero histograms).

    Returns (S, G, Bmax, 3) float32.
    """
    G_pad, Nc = bins_sorted_T.shape
    assert G_pad % 8 == 0 and w_sorted.shape[0] == 8, \
        "pad leading dims to the sublane tile before calling (see caller)"
    G = num_groups
    S = num_slots
    T = block_rows
    HI = -(-max_group_bins // LO)
    NB = block_scalars.shape[0]

    out = pl.pallas_call(
        functools.partial(_hist_kernel, T=T, G=G, HI=HI),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(
                (1, 3 * HI, G * LO),
                lambda b, sref: (jnp.maximum(sref[b, 0], 0), 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((G_pad, T), jnp.uint8),
                pltpu.VMEM((8, T), jnp.float32),
                pltpu.VMEM((3 * HI, G * LO), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, 3 * HI, G * LO), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(block_scalars, bins_sorted_T, w_sorted)

    # (S, 3, HI, G, LO) -> (S, G, HI*LO, 3), trimmed to Bmax; zero empty slots
    hist = out.reshape(S, 3, HI, G, LO).transpose(0, 3, 2, 4, 1)
    hist = hist.reshape(S, G, HI * LO, 3)[:, :, :max_group_bins, :]
    return jnp.where(counts[:, None, None, None] > 0, hist, 0.0)


def build_histograms_sorted(bins: jax.Array, slot: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array, num_slots: int,
                            max_group_bins: int, block_rows: int = 4096) -> jax.Array:
    """Drop-in replacement for ops.histogram.build_histograms using the sorted
    Pallas path: plan compaction, gather rows into sorted order (fast row-major
    gathers), and run the fused kernel."""
    from ..ops.compact import plan_compaction

    n, G = bins.shape
    g_pad = -(-G // 8) * 8
    plan = plan_compaction(slot, num_slots, block_rows)
    # sorted row payloads: row gathers along axis 0 are cheap on TPU
    bins_sorted = jnp.take(bins, plan.perm, axis=0)               # (N, G)
    w = jnp.stack([grad, hess, cnt], axis=1)                      # (N, 3)
    w_sorted = jnp.take(w, plan.perm, axis=0)
    # kernel layout: transpose, pad leading dim to the sublane tile (aligned DMA
    # slices) and the row dim by one block of over-read slack
    bins_T = jnp.pad(bins_sorted.T, ((0, g_pad - G), (0, block_rows)))
    w_T = jnp.pad(w_sorted.T.astype(jnp.float32), ((0, 8 - 3), (0, block_rows)))
    return hist_sorted_pallas(bins_T, w_T, plan.block_scalars, plan.counts,
                              num_slots, max_group_bins, G, block_rows)
