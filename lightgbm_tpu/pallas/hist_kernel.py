"""Fused Pallas TPU histogram kernels — the framework's hot op.

Reference analog: src/io/dense_bin.hpp:99-170 (ConstructHistogramInner — per-row
scatter-add into an L1-resident histogram) and src/treelearner/cuda/
cuda_histogram_constructor.cu (shared-memory atomic adds). TPUs have neither fast
scatter nor atomics, so the histogram is expressed as a one-hot contraction on the
MXU over slot-sorted row blocks (ops/compact.py): each fixed-size block of rows
belongs to exactly one histogram slot, so the kernel accumulates into a single
VMEM-resident accumulator per slot and writes it back once per slot.

XLA's row gather runs at ~1.6G elements/s on TPU, which makes materialising the
sorted (N, G) uint8 bin matrix the dominant cost. The kernels therefore take bins
PACKED 4-per-int32 (G//4 words per row — 4x fewer gathered elements) and unpack
with shifts on the VPU inside the kernel.

Two kernels, chosen by the padded per-group bin count Bmax:

  * direct (Bmax <= 128): per block ONE wide contraction
        acc[g*B+b, c] += sum_t 1[bin_g[t] == b] * w[c, t]
    i.e. (G*B, T) one-hot  @  (T, 8) weights. The one-hot lives only in VMEM; the
    MXU cost is streaming-bound (G*B*T operand values), ~3*B flops per row-group.

  * nibble (Bmax > 128): bin = 16*hi + lo, so per group
        hist[16h+l, c] = (A_g B_g^T)[c*HI+h, l]
    with A_g[c*HI+h, t] = w[c, t]*onehot(hi)[h, t] and B_g[l, t] = onehot(lo)[l, t],
    keeping one-hot build cost at G*(3*HI + LO) sublanes per block instead of G*Bmax.

The one-hot operand is exact in bfloat16; the weight operand is split into
high/low bfloat16 parts (two MXU passes) so the f32 weights accumulate without
the default bf16 rounding — cheaper than Precision.HIGHEST's 3x3 decomposition.

Both kernels use Pallas grid pipelining (BlockSpec index maps) for the block inputs
— no manual DMA — and scalar-prefetched (slot, first, last) per-block metadata.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams was renamed CompilerParams across JAX releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from ..ops.compact import num_blocks, plan_blocks, plan_single_slot
from ..telemetry.watchdog import watched_jit

LO = 16  # nibble kernel low-digit width; HI = ceil(Bmax / LO)

_INTERPRET = False  # flipped by tests to run kernels in interpret mode on CPU


def pack_bins(bins: jax.Array) -> jax.Array:
    """(N, G) uint8 -> (N, ceil(G/4)) int32, 4 bins per word (little-endian)."""
    n, g = bins.shape
    gw = -(-g // 4) * 4
    if gw != g:
        bins = jnp.pad(bins, ((0, 0), (0, gw - g)))
    w = bins.reshape(n, gw // 4, 4).astype(jnp.int32)
    return (w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24))


def _unpack_group(words, g):
    """Extract group g's bin column from packed words (GW, T) i32 -> (1, T) i32."""
    word = words[g // 4:g // 4 + 1, :]
    shift = (g % 4) * 8
    return jax.lax.shift_right_logical(word, shift) & 0xFF


def _wsplit(w):
    """Split f32 weights into (hi, lo) bf16 parts: w ~= hi + lo exactly enough."""
    hi = w.astype(jnp.bfloat16)
    lo = (w - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _direct_kernel(scalar_ref, bins_ref, w_ref, out_ref, oh_ref, acc_ref,
                   *, T: int, G: int, B: int):
    b = pl.program_id(0)
    slot = scalar_ref[b, 0]
    first = scalar_ref[b, 1]
    last = scalar_ref[b, 2]

    @pl.when(slot >= 0)
    def _():
        @pl.when(first == 1)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        biota = jax.lax.broadcasted_iota(jnp.int32, (B, T), 0)
        for g in range(G):  # static unroll
            bg = _unpack_group(bins_ref[...], g)                 # (1, T)
            oh_ref[g * B:(g + 1) * B, :] = (biota == bg).astype(jnp.bfloat16)
        # (G*B, T) @ (8, T)^T -> (G*B, 8); contraction over the lane (T) dim.
        # Two bf16 passes reconstruct f32-accurate weight sums.
        w_hi, w_lo = _wsplit(w_ref[...])
        oh = oh_ref[...]
        dot = functools.partial(jax.lax.dot_general,
                                dimension_numbers=(((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        acc_ref[...] += dot(oh, w_hi) + dot(oh, w_lo)

        @pl.when(last == 1)
        def _():
            out_ref[0] = acc_ref[...].T                          # (8, G*B)


def _nibble_kernel(scalar_ref, bins_ref, w_ref, out_ref, acc_ref,
                   *, T: int, G: int, HI: int):
    b = pl.program_id(0)
    slot = scalar_ref[b, 0]
    first = scalar_ref[b, 1]
    last = scalar_ref[b, 2]

    @pl.when(slot >= 0)
    def _():
        @pl.when(first == 1)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        w_hi, w_lo = _wsplit(w_ref[0:3, :])                      # (3, T) each
        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (HI, T), 0)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (LO, T), 0)
        dot = functools.partial(jax.lax.dot_general,
                                dimension_numbers=(((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        for g in range(G):  # static unroll
            bg = _unpack_group(bins_ref[...], g)                 # (1, T)
            hi = bg // LO
            lo = bg - hi * LO
            oh_hi = (hi_iota == hi).astype(jnp.bfloat16)         # (HI, T)
            oh_lo = (lo_iota == lo).astype(jnp.bfloat16)         # (LO, T)
            # A[c*HI+h, t] = w[c, t] * oh_hi[h, t] (sublane-merging reshape)
            A = ((w_hi[:, None, :] * oh_hi[None, :, :]).reshape(3 * HI, T),
                 (w_lo[:, None, :] * oh_hi[None, :, :]).reshape(3 * HI, T))
            bh = dot(A[0], oh_lo) + dot(A[1], oh_lo)             # (3HI, LO)
            acc_ref[:, g * LO:(g + 1) * LO] += bh

        @pl.when(last == 1)
        def _():
            out_ref[0] = acc_ref[...]


@functools.partial(watched_jit, name="pallas_hist_direct", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "block_rows"))
def _hist_direct(bins_T, w_T, scalars, counts, num_slots, bmax, num_groups,
                 block_rows):
    GW, n_tot = bins_T.shape
    S, T, G = num_slots, block_rows, num_groups
    B = -(-bmax // 8) * 8                                        # sublane-pad bins
    NB = scalars.shape[0]

    out = pl.pallas_call(
        functools.partial(_direct_kernel, T=T, G=G, B=B),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((GW, T), lambda b, sref: (0, b)),
                pl.BlockSpec((8, T), lambda b, sref: (0, b)),
            ],
            out_specs=pl.BlockSpec(
                (1, 8, G * B), lambda b, sref: (jnp.maximum(sref[b, 0], 0), 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G * B, T), jnp.bfloat16),
                pltpu.VMEM((G * B, 8), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, 8, G * B), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(scalars, bins_T, w_T)

    hist = out.reshape(S, 8, G, B)[:, :3, :, :bmax]              # (S, 3, G, Bmax)
    hist = jnp.transpose(hist, (0, 2, 3, 1))                     # (S, G, Bmax, 3)
    return jnp.where(counts[:, None, None, None] > 0, hist, 0.0)


@functools.partial(watched_jit, name="pallas_hist_nibble", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "block_rows"))
def _hist_nibble(bins_T, w_T, scalars, counts, num_slots, bmax, num_groups,
                 block_rows):
    GW, n_tot = bins_T.shape
    S, T, G = num_slots, block_rows, num_groups
    HI = -(-bmax // LO)
    NB = scalars.shape[0]

    out = pl.pallas_call(
        functools.partial(_nibble_kernel, T=T, G=G, HI=HI),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((GW, T), lambda b, sref: (0, b)),
                pl.BlockSpec((8, T), lambda b, sref: (0, b)),
            ],
            out_specs=pl.BlockSpec(
                (1, 3 * HI, G * LO),
                lambda b, sref: (jnp.maximum(sref[b, 0], 0), 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((3 * HI, G * LO), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, 3 * HI, G * LO), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(scalars, bins_T, w_T)

    # (S, 3, HI, G, LO) -> (S, G, HI*LO, 3), trimmed to Bmax; zero empty slots
    hist = out.reshape(S, 3, HI, G, LO).transpose(0, 3, 2, 4, 1)
    hist = hist.reshape(S, G, HI * LO, 3)[:, :, :bmax, :]
    return jnp.where(counts[:, None, None, None] > 0, hist, 0.0)


def _wide_kernel(bins_ref, slot_ref, w_ref, out_ref, *, T: int, G: int,
                 B: int, S: int, K: int, f32_dots: bool):
    """K-channel natural-order accumulate path (batched multiclass): rows
    stream through in natural order, the class-independent bin one-hot is
    built ONCE per block, and the contraction runs against the stacked
    (3*S*K, T) class x slot weight operand. The sorted direct/nibble
    kernels cannot serve this case — each row belongs to K DIFFERENT slots
    (one per class tree), so no single sort order exists."""
    b = pl.program_id(0)
    i32, f32 = jnp.int32, jnp.float32
    bf16 = f32 if f32_dots else jnp.bfloat16

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    # unpack the 4-per-word packed group bins -> (G, T)
    rows = []
    for g in range(G):  # static unroll
        word_g = bins_ref[g // 4:g // 4 + 1, :]
        rows.append(jax.lax.shift_right_logical(word_g, (g % 4) * 8) & 0xFF)
    bins_G = jnp.concatenate(rows, axis=0)
    # B-major one-hot rows r = b * G + g via the key/iota compare (the
    # stream kernel's measured-fastest construct)
    g_iota = jax.lax.broadcasted_iota(i32, (G, T), 0)
    key = bins_G * G + g_iota
    key_t = jnp.concatenate([key] * B, axis=0)               # (B*G, T)
    r_iota = jax.lax.broadcasted_iota(i32, (B * G, T), 0)
    oh = (key_t == r_iota).astype(bf16)

    s_iota = jax.lax.broadcasted_iota(i32, (S, T), 0)
    sohs = [(s_iota == slot_ref[k:k + 1, :]).astype(bf16)
            for k in range(K)]                               # (S, T) each
    w_hi, w_lo = _wsplit(w_ref[...])                         # (Wpad, T)

    def build_A(w):
        # class-major rows j = k*3S + c*S + s; c in (grad, hess, cnt);
        # cnt is the shared row 2K
        return jnp.concatenate(
            [w[r:r + 1, :] * sohs[k]
             for k in range(K)
             for r in (2 * k, 2 * k + 1, 2 * K)], axis=0)    # (3*S*K, T)

    dot = functools.partial(jax.lax.dot_general,
                            dimension_numbers=(((1,), (1,)), ((), ())),
                            preferred_element_type=f32)
    out_ref[...] += dot(oh, build_A(w_hi)) + dot(oh, build_A(w_lo))


def wide_block_rows(bmax: int, num_groups: int, num_class: int,
                    num_slots: int) -> int:
    """Block size for the wide K-channel kernel: the (G*B, T) bf16 one-hot
    plus the T-independent (G*B, 3*S*K) f32 VMEM-resident histogram block
    must share the ~16 MB/core budget."""
    B = -(-bmax // 8) * 8
    m_rows = num_groups * B
    budget = 12 * 2 ** 20 - m_rows * 3 * num_slots * num_class * 4
    for T in (2048, 1024, 512, 256):
        if m_rows * T * 2 <= budget:
            return T
    return 256


def wide_hist_fits(num_class: int, num_slots: int, bmax: int,
                   num_groups: int) -> bool:
    """True when the widened (G*B, 3*S*K) block leaves room for a useful
    one-hot block; otherwise callers fall back to per-class sorted
    kernels."""
    B = -(-bmax // 8) * 8
    if bmax > 128:
        return False   # the key construct is sized for the direct regime
    hist_bytes = num_groups * B * 3 * num_slots * num_class * 4
    return hist_bytes + num_groups * B * 256 * 2 <= 12 * 2 ** 20


@functools.partial(watched_jit, name="pallas_hist_wide", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "num_class", "block_rows"))
def _hist_wide(bins_T, slot, w_T, num_slots, bmax, num_groups, num_class,
               block_rows):
    GW, n_pad = bins_T.shape
    K, S, T, G = num_class, num_slots, block_rows, num_groups
    B = -(-bmax // 8) * 8
    NB = n_pad // T
    out = pl.pallas_call(
        functools.partial(_wide_kernel, T=T, G=G, B=B, S=S, K=K,
                          f32_dots=_INTERPRET
                          or jax.default_backend() not in ("tpu", "axon")),
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((GW, T), lambda b: (0, b)),
            pl.BlockSpec((K, T), lambda b: (0, b)),
            pl.BlockSpec((w_T.shape[0], T), lambda b: (0, b)),
        ],
        out_specs=pl.BlockSpec((B * G, 3 * S * K), lambda b: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * G, 3 * S * K), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET or jax.default_backend() not in ("tpu", "axon"),
    )(bins_T, slot, w_T)
    # (B*G, 3SK) b-major rows -> (K, S, G, Bmax, 3)
    hist = out.reshape(B, G, K, 3, S).transpose(2, 4, 1, 0, 3)
    return hist[:, :, :, :bmax, :]


def build_histograms_wide(bins: jax.Array, slot: jax.Array, grad: jax.Array,
                          hess: jax.Array, cnt: jax.Array, num_slots: int,
                          max_group_bins: int,
                          bins_packed: jax.Array = None) -> jax.Array:
    """K-class histograms from ONE widened kernel pass (batched multiclass).

    slot/grad/hess: (K, N) per-class; cnt: (N,) shared.
    Returns (K, S, G, Bmax, 3) float32.
    """
    K, n = slot.shape
    G = bins.shape[1]
    if bins_packed is None:
        bins_packed = pack_bins(bins)
    gw = bins_packed.shape[1]
    gw_pad = -(-gw // 8) * 8
    T = wide_block_rows(max_group_bins, G, K, num_slots)
    n_pad = -(-n // T) * T
    bins_T = jnp.pad(bins_packed.T.astype(jnp.int32),
                     ((0, gw_pad - gw), (0, n_pad - n)))
    slot_p = jnp.pad(slot.astype(jnp.int32), ((0, 0), (0, n_pad - n)),
                     constant_values=-1)
    w_rows = 2 * K + 1
    w_pad = -(-w_rows // 8) * 8
    w2 = jnp.stack([grad, hess], axis=1).reshape(2 * K, n)   # 2k/2k+1 rows
    w_T = jnp.concatenate([w2.astype(jnp.float32),
                           cnt.reshape(1, n).astype(jnp.float32),
                           jnp.zeros((w_pad - w_rows, n), jnp.float32)],
                          axis=0)
    w_T = jnp.pad(w_T, ((0, 0), (0, n_pad - n)))
    return _hist_wide(bins_T, slot_p, w_T, num_slots, max_group_bins, G, K, T)


def build_histograms_sorted(bins: jax.Array, slot: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array, num_slots: int,
                            max_group_bins: int, block_rows: int = 1024,
                            bins_packed: jax.Array = None) -> jax.Array:
    """Drop-in replacement for ops.histogram.build_histograms using the slot-sorted
    Pallas path: plan blocks, gather packed block rows (invalid positions hit a
    zero pad row), and run the fused kernel. Returns (S, G, Bmax, 3) float32.

    bins_packed: optional precomputed pack_bins(bins) (N, ceil(G/4)) i32 — pass it
    when bins are static across calls (training) to skip re-packing.
    """
    n, G = bins.shape
    if bins_packed is None:
        bins_packed = pack_bins(bins)
    gw = bins_packed.shape[1]
    gw_pad = -(-gw // 8) * 8                       # int32 sublane tile
    if num_slots == 1:
        plan = plan_single_slot(n, block_rows)
    else:
        plan = plan_blocks(slot, num_slots, block_rows)

    bp_pad = jnp.concatenate([bins_packed,
                              jnp.zeros((1, gw), jnp.int32)], axis=0)
    w = jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32),
                   cnt.astype(jnp.float32)], axis=1)             # (N, 3)
    w_pad = jnp.concatenate([w, jnp.zeros((1, 3), jnp.float32)], axis=0)

    bb = jnp.take(bp_pad, plan.gather_idx, axis=0)               # (NB*T, GW)
    wb = jnp.take(w_pad, plan.gather_idx, axis=0)                # (NB*T, 3)
    bins_T = jnp.pad(bb.T, ((0, gw_pad - gw), (0, 0)))           # (GW_pad, NB*T)
    w_T = jnp.pad(wb.T, ((0, 8 - 3), (0, 0)))                    # (8, NB*T)

    if max_group_bins <= 128:
        return _hist_direct(bins_T, w_T, plan.scalars, plan.counts,
                            num_slots, max_group_bins, G, block_rows)
    return _hist_nibble(bins_T, w_T, plan.scalars, plan.counts,
                        num_slots, max_group_bins, G, block_rows)
