"""Fused Pallas TPU histogram kernels — the framework's hot op.

Reference analog: src/io/dense_bin.hpp:99-170 (ConstructHistogramInner — per-row
scatter-add into an L1-resident histogram) and src/treelearner/cuda/
cuda_histogram_constructor.cu (shared-memory atomic adds). TPUs have neither fast
scatter nor atomics, so the histogram is expressed as a one-hot contraction on the
MXU over slot-sorted row blocks (ops/compact.py): each fixed-size block of rows
belongs to exactly one histogram slot, so the kernel accumulates into a single
VMEM-resident accumulator per slot and writes it back once per slot.

XLA's row gather runs at ~1.6G elements/s on TPU, which makes materialising the
sorted (N, G) uint8 bin matrix the dominant cost. The kernels therefore take bins
PACKED 4-per-int32 (G//4 words per row — 4x fewer gathered elements) and unpack
with shifts on the VPU inside the kernel.

Two kernels, chosen by the padded per-group bin count Bmax:

  * direct (Bmax <= 128): per block ONE wide contraction
        acc[g*B+b, c] += sum_t 1[bin_g[t] == b] * w[c, t]
    i.e. (G*B, T) one-hot  @  (T, 8) weights. The one-hot lives only in VMEM; the
    MXU cost is streaming-bound (G*B*T operand values), ~3*B flops per row-group.

  * nibble (Bmax > 128): bin = 16*hi + lo, so per group
        hist[16h+l, c] = (A_g B_g^T)[c*HI+h, l]
    with A_g[c*HI+h, t] = w[c, t]*onehot(hi)[h, t] and B_g[l, t] = onehot(lo)[l, t],
    keeping one-hot build cost at G*(3*HI + LO) sublanes per block instead of G*Bmax.

The one-hot operand is exact in bfloat16; the weight operand is split into
high/low bfloat16 parts (two MXU passes) so the f32 weights accumulate without
the default bf16 rounding — cheaper than Precision.HIGHEST's 3x3 decomposition.

Both kernels use Pallas grid pipelining (BlockSpec index maps) for the block inputs
— no manual DMA — and scalar-prefetched (slot, first, last) per-block metadata.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams was renamed CompilerParams across JAX releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from ..ops.compact import num_blocks, plan_blocks, plan_single_slot
from ..telemetry.watchdog import watched_jit

LO = 16  # nibble kernel low-digit width; HI = ceil(Bmax / LO)

_INTERPRET = False  # flipped by tests to run kernels in interpret mode on CPU


def pack_bins(bins: jax.Array) -> jax.Array:
    """(N, G) uint8 -> (N, ceil(G/4)) int32, 4 bins per word (little-endian)."""
    n, g = bins.shape
    gw = -(-g // 4) * 4
    if gw != g:
        bins = jnp.pad(bins, ((0, 0), (0, gw - g)))
    w = bins.reshape(n, gw // 4, 4).astype(jnp.int32)
    return (w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24))


def _unpack_group(words, g):
    """Extract group g's bin column from packed words (GW, T) i32 -> (1, T) i32."""
    word = words[g // 4:g // 4 + 1, :]
    shift = (g % 4) * 8
    return jax.lax.shift_right_logical(word, shift) & 0xFF


def _wsplit(w):
    """Split f32 weights into (hi, lo) bf16 parts: w ~= hi + lo exactly enough."""
    hi = w.astype(jnp.bfloat16)
    lo = (w - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _direct_kernel(scalar_ref, bins_ref, w_ref, out_ref, oh_ref, acc_ref,
                   *, T: int, G: int, B: int):
    b = pl.program_id(0)
    slot = scalar_ref[b, 0]
    first = scalar_ref[b, 1]
    last = scalar_ref[b, 2]

    @pl.when(slot >= 0)
    def _():
        @pl.when(first == 1)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        biota = jax.lax.broadcasted_iota(jnp.int32, (B, T), 0)
        for g in range(G):  # static unroll
            bg = _unpack_group(bins_ref[...], g)                 # (1, T)
            oh_ref[g * B:(g + 1) * B, :] = (biota == bg).astype(jnp.bfloat16)
        # (G*B, T) @ (8, T)^T -> (G*B, 8); contraction over the lane (T) dim.
        # Two bf16 passes reconstruct f32-accurate weight sums.
        w_hi, w_lo = _wsplit(w_ref[...])
        oh = oh_ref[...]
        dot = functools.partial(jax.lax.dot_general,
                                dimension_numbers=(((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        acc_ref[...] += dot(oh, w_hi) + dot(oh, w_lo)

        @pl.when(last == 1)
        def _():
            out_ref[0] = acc_ref[...].T                          # (8, G*B)


def _nibble_kernel(scalar_ref, bins_ref, w_ref, out_ref, acc_ref,
                   *, T: int, G: int, HI: int):
    b = pl.program_id(0)
    slot = scalar_ref[b, 0]
    first = scalar_ref[b, 1]
    last = scalar_ref[b, 2]

    @pl.when(slot >= 0)
    def _():
        @pl.when(first == 1)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        w_hi, w_lo = _wsplit(w_ref[0:3, :])                      # (3, T) each
        hi_iota = jax.lax.broadcasted_iota(jnp.int32, (HI, T), 0)
        lo_iota = jax.lax.broadcasted_iota(jnp.int32, (LO, T), 0)
        dot = functools.partial(jax.lax.dot_general,
                                dimension_numbers=(((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        for g in range(G):  # static unroll
            bg = _unpack_group(bins_ref[...], g)                 # (1, T)
            hi = bg // LO
            lo = bg - hi * LO
            oh_hi = (hi_iota == hi).astype(jnp.bfloat16)         # (HI, T)
            oh_lo = (lo_iota == lo).astype(jnp.bfloat16)         # (LO, T)
            # A[c*HI+h, t] = w[c, t] * oh_hi[h, t] (sublane-merging reshape)
            A = ((w_hi[:, None, :] * oh_hi[None, :, :]).reshape(3 * HI, T),
                 (w_lo[:, None, :] * oh_hi[None, :, :]).reshape(3 * HI, T))
            bh = dot(A[0], oh_lo) + dot(A[1], oh_lo)             # (3HI, LO)
            acc_ref[:, g * LO:(g + 1) * LO] += bh

        @pl.when(last == 1)
        def _():
            out_ref[0] = acc_ref[...]


@functools.partial(watched_jit, name="pallas_hist_direct", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "block_rows"))
def _hist_direct(bins_T, w_T, scalars, counts, num_slots, bmax, num_groups,
                 block_rows):
    GW, n_tot = bins_T.shape
    S, T, G = num_slots, block_rows, num_groups
    B = -(-bmax // 8) * 8                                        # sublane-pad bins
    NB = scalars.shape[0]

    out = pl.pallas_call(
        functools.partial(_direct_kernel, T=T, G=G, B=B),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((GW, T), lambda b, sref: (0, b)),
                pl.BlockSpec((8, T), lambda b, sref: (0, b)),
            ],
            out_specs=pl.BlockSpec(
                (1, 8, G * B), lambda b, sref: (jnp.maximum(sref[b, 0], 0), 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((G * B, T), jnp.bfloat16),
                pltpu.VMEM((G * B, 8), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, 8, G * B), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(scalars, bins_T, w_T)

    hist = out.reshape(S, 8, G, B)[:, :3, :, :bmax]              # (S, 3, G, Bmax)
    hist = jnp.transpose(hist, (0, 2, 3, 1))                     # (S, G, Bmax, 3)
    return jnp.where(counts[:, None, None, None] > 0, hist, 0.0)


@functools.partial(watched_jit, name="pallas_hist_nibble", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "block_rows"))
def _hist_nibble(bins_T, w_T, scalars, counts, num_slots, bmax, num_groups,
                 block_rows):
    GW, n_tot = bins_T.shape
    S, T, G = num_slots, block_rows, num_groups
    HI = -(-bmax // LO)
    NB = scalars.shape[0]

    out = pl.pallas_call(
        functools.partial(_nibble_kernel, T=T, G=G, HI=HI),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((GW, T), lambda b, sref: (0, b)),
                pl.BlockSpec((8, T), lambda b, sref: (0, b)),
            ],
            out_specs=pl.BlockSpec(
                (1, 3 * HI, G * LO),
                lambda b, sref: (jnp.maximum(sref[b, 0], 0), 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((3 * HI, G * LO), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((S, 3 * HI, G * LO), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(scalars, bins_T, w_T)

    # (S, 3, HI, G, LO) -> (S, G, HI*LO, 3), trimmed to Bmax; zero empty slots
    hist = out.reshape(S, 3, HI, G, LO).transpose(0, 3, 2, 4, 1)
    hist = hist.reshape(S, G, HI * LO, 3)[:, :, :bmax, :]
    return jnp.where(counts[:, None, None, None] > 0, hist, 0.0)


def build_histograms_sorted(bins: jax.Array, slot: jax.Array, grad: jax.Array,
                            hess: jax.Array, cnt: jax.Array, num_slots: int,
                            max_group_bins: int, block_rows: int = 1024,
                            bins_packed: jax.Array = None) -> jax.Array:
    """Drop-in replacement for ops.histogram.build_histograms using the slot-sorted
    Pallas path: plan blocks, gather packed block rows (invalid positions hit a
    zero pad row), and run the fused kernel. Returns (S, G, Bmax, 3) float32.

    bins_packed: optional precomputed pack_bins(bins) (N, ceil(G/4)) i32 — pass it
    when bins are static across calls (training) to skip re-packing.
    """
    n, G = bins.shape
    if bins_packed is None:
        bins_packed = pack_bins(bins)
    gw = bins_packed.shape[1]
    gw_pad = -(-gw // 8) * 8                       # int32 sublane tile
    if num_slots == 1:
        plan = plan_single_slot(n, block_rows)
    else:
        plan = plan_blocks(slot, num_slots, block_rows)

    bp_pad = jnp.concatenate([bins_packed,
                              jnp.zeros((1, gw), jnp.int32)], axis=0)
    w = jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32),
                   cnt.astype(jnp.float32)], axis=1)             # (N, 3)
    w_pad = jnp.concatenate([w, jnp.zeros((1, 3), jnp.float32)], axis=0)

    bb = jnp.take(bp_pad, plan.gather_idx, axis=0)               # (NB*T, GW)
    wb = jnp.take(w_pad, plan.gather_idx, axis=0)                # (NB*T, 3)
    bins_T = jnp.pad(bb.T, ((0, gw_pad - gw), (0, 0)))           # (GW_pad, NB*T)
    w_T = jnp.pad(wb.T, ((0, 8 - 3), (0, 0)))                    # (8, NB*T)

    if max_group_bins <= 128:
        return _hist_direct(bins_T, w_T, plan.scalars, plan.counts,
                            num_slots, max_group_bins, G, block_rows)
    return _hist_nibble(bins_T, w_T, plan.scalars, plan.counts,
                        num_slots, max_group_bins, G, block_rows)
