"""Streaming batch-prediction Pallas TPU kernel.

Reference analog: src/boosting/gbdt_prediction.cpp (PredictRaw: per-row loop
over trees, recursive node walk) and src/application/predictor.hpp:237.

TPU re-design: per-row pointer chasing is hostile to both XLA (per-step row
gathers run at ~100M rows/s) and the MXU.  This kernel streams row blocks
through VMEM once; ALL tree node tables live in VMEM simultaneously
(~24 rows x L cols x T trees x 4 B — 6 MB for 500 trees x 255 leaves), and the
walk advances every row through one tree level with a (24, L) @ (L, T)
node-one-hot matmul.  Child pointers and leaf values are 7-bit/bf16-pair
digit-encoded so the bf16 matmuls stay exact.  Trees iterate in a
`lax.fori_loop` with dynamic VMEM slices, so compile time is independent of
the model size.

Categorical splits walk on-device too: each cat node's left-set is a
bitset over the feature's BINS (the value-domain `cat_threshold` words are
re-projected through the bin mapper's category list at table-build time),
stored in a per-tree side table of 7-bit digit rows — five digit rows
reconstruct one exact 32-bit word, and the word for a row's bin is picked
with the same one-hot masked dot as every other per-node field.  NaN /
unseen / negative category values are pre-binned to a sentinel bin one
past the feature's span whose bit is always zero, reproducing the host
walk's "not in bitset -> right" routing.  Zero-as-missing default routing
(MISSING_ZERO) rides two more table rows, mirroring the training stream
kernel.  The host fallback is linear trees only.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams was renamed CompilerParams across JAX releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from ..telemetry.watchdog import watched_jit

ROWS_PER_TREE = 24
(P_WORD_LO, P_WORD_HI, P_SHIFT, P_SPAN, P_DEFBIN, P_BUNDLED, P_HASNAN,
 P_NANBIN, P_NBINS, P_THR, P_DEFLEFT, P_LEFT_LO, P_LEFT_HI, P_RIGHT_LO,
 P_RIGHT_HI, P_LEAF_HI, P_LEAF_LO, P_ISCAT, P_HASMZ, P_MZBIN, P_CATB_LO,
 P_CATB_HI) = range(22)

# digit rows per tree in the categorical side table: one 32-bit bitset
# word = five 7-bit digits (each exact in bf16, reassembled with shifts)
CAT_DIGITS = 5

_INTERPRET = False


def _predict_kernel(bins_ref, tabs_ref, cat_ref, out_ref, *, T, L, GW, CW,
                    n_trees, max_depth, has_cat: bool, es_freq: int = 0,
                    es_margin: float = 0.0):
    i32, bf16, f32 = jnp.int32, jnp.bfloat16, jnp.float32
    words = bins_ref[...]                                    # (GW, T)
    l_iota = jax.lax.broadcasted_iota(i32, (L, T), 0)
    gw_iota = jax.lax.broadcasted_iota(i32, (GW, T), 0)

    def tree_body(t, carry):
        # score-only carry when early stop is off: the active mask and its
        # per-tree select exist only under es_freq > 0
        score, active = carry if es_freq else (carry, None)
        tab = tabs_ref[pl.ds(t * ROWS_PER_TREE, ROWS_PER_TREE), :]  # (24, L)
        tab_bf = tab.astype(bf16)
        if has_cat:
            # this tree's bitset digit rows, (CAT_DIGITS, CW)
            cat_bf = cat_ref[pl.ds(t * CAT_DIGITS, CAT_DIGITS), :].astype(bf16)
        enc = jnp.zeros((1, T), i32)       # node 0; >= L means "at leaf ~"

        def step(_, enc):
            at_leaf = enc >= L
            node = jnp.where(at_leaf, 0, enc)
            node_oh = (l_iota == node).astype(bf16)          # (L, T)
            vals = jax.lax.dot_general(
                tab_bf, node_oh, (((1,), (0,)), ((), ())),
                preferred_element_type=f32)                  # (24, T)
            iv = vals.astype(i32)
            wordi = iv[P_WORD_LO:P_WORD_LO + 1] + (iv[P_WORD_HI:P_WORD_HI + 1] << 7)
            word = jnp.sum(jnp.where(gw_iota == wordi, words, 0), axis=0,
                           keepdims=True)
            gb = jax.lax.shift_right_logical(word, iv[P_SHIFT:P_SHIFT + 1]) & 0xFF
            span = iv[P_SPAN:P_SPAN + 1]
            defbin = iv[P_DEFBIN:P_DEFBIN + 1]
            nbins = iv[P_NBINS:P_NBINS + 1]
            ls = gb - span
            ge_def = jnp.where(ls >= defbin, 1, 0)
            fb_b = jnp.where((ls >= 0) & (ls < nbins - 1), ls + ge_def, defbin)
            fb = jnp.where(iv[P_BUNDLED:P_BUNDLED + 1] > 0, fb_b, gb)
            is_nan_i = (iv[P_HASNAN:P_HASNAN + 1]
                        * jnp.where(fb == iv[P_NANBIN:P_NANBIN + 1], 1, 0))
            # MISSING_ZERO default routing (training stream kernel parity:
            # stream_kernel.py T_HASMZ/T_MZBIN)
            is_mz_i = (iv[P_HASMZ:P_HASMZ + 1]
                       * jnp.where(fb == iv[P_MZBIN:P_MZBIN + 1], 1, 0))
            le_thr = jnp.where(fb <= iv[P_THR:P_THR + 1], 1, 0)
            go_left = jnp.where(is_nan_i + is_mz_i > 0,
                                iv[P_DEFLEFT:P_DEFLEFT + 1], le_thr)
            if has_cat:
                # bitset membership: word index = per-node base + fb >> 5,
                # selected with a one-hot masked dot over the digit rows
                # (exactly one 1.0 * digit product per output — exact);
                # missing flags never apply to categorical nodes (host
                # walk: miss &= ~is_cat)
                catb = (iv[P_CATB_LO:P_CATB_LO + 1]
                        + (iv[P_CATB_HI:P_CATB_HI + 1] << 7))
                wi = catb + jax.lax.shift_right_logical(fb, 5)   # (1, T)
                cw_iota = jax.lax.broadcasted_iota(i32, (CW, T), 0)
                woh = (cw_iota == wi).astype(bf16)               # (CW, T)
                digs = jax.lax.dot_general(
                    cat_bf, woh, (((1,), (0,)), ((), ())),
                    preferred_element_type=f32)                  # (5, T)
                dg = digs.astype(i32)
                cword = (dg[0:1] + (dg[1:2] << 7) + (dg[2:3] << 14)
                         + (dg[3:4] << 21) + (dg[4:5] << 28))
                cbit = jax.lax.shift_right_logical(cword, fb & 31) & 1
                go_left = jnp.where(iv[P_ISCAT:P_ISCAT + 1] > 0, cbit,
                                    go_left)
            left = iv[P_LEFT_LO:P_LEFT_LO + 1] + (iv[P_LEFT_HI:P_LEFT_HI + 1] << 7)
            right = (iv[P_RIGHT_LO:P_RIGHT_LO + 1]
                     + (iv[P_RIGHT_HI:P_RIGHT_HI + 1] << 7))
            nxt = jnp.where(go_left > 0, left, right)
            return jnp.where(at_leaf, enc, nxt)

        enc = jax.lax.fori_loop(0, max_depth, step, enc)
        leaf = jnp.maximum(enc - L, 0)
        leaf_oh = (l_iota == leaf).astype(bf16)
        lv = jax.lax.dot_general(
            tab_bf[P_LEAF_HI:P_LEAF_LO + 1], leaf_oh, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                      # (2, T)
        if not es_freq:
            return score + lv[0:1] + lv[1:2]
        # prediction early stopping (reference: prediction_early_stop.cpp
        # CreateBinary): every es_freq trees, rows whose margin 2|score|
        # clears the threshold freeze — the host loop's `active`
        # bookkeeping vectorized per block, applied to the device walk's
        # own (bf16-summed) scores, so rows landing within bf16 error of
        # the margin may freeze one checkpoint apart from the f64 host loop
        score = score + jnp.where(active > 0, lv[0:1] + lv[1:2], 0.0)
        at_check = ((t + 1) % es_freq) == 0
        stopped = (2.0 * jnp.abs(score)) > es_margin
        return score, jnp.where(at_check & stopped, 0, active)

    init = jnp.zeros((1, T), f32)
    if es_freq:
        score, _ = jax.lax.fori_loop(0, n_trees, tree_body,
                                     (init, jnp.ones((1, T), i32)))
    else:
        score = jax.lax.fori_loop(0, n_trees, tree_body, init)
    out_ref[...] = score


@functools.partial(watched_jit, name="predict_stream", warn_after=0,
                   static_argnames=("num_leaves", "n_trees", "max_depth",
                                    "block_rows", "has_cat", "es_freq",
                                    "es_margin"))
def predict_stream(bins_T: jax.Array, tabs: jax.Array, cat_tab: jax.Array,
                   num_leaves: int, n_trees: int, max_depth: int,
                   block_rows: int = 1024, has_cat: bool = False,
                   es_freq: int = 0, es_margin: float = 0.0):
    """Raw-score prediction: (GW, N_pad) packed bins + (n_trees*24, L) tables
    + (n_trees*5, CW) categorical bitset digit rows -> (N_pad,) f32 summed
    leaf values.  es_freq > 0 enables the binary prediction-early-stop
    margin check every es_freq trees."""
    GW, n_pad = bins_T.shape
    T = block_rows
    NB = n_pad // T
    L = num_leaves
    CW = cat_tab.shape[1]

    out = pl.pallas_call(
        functools.partial(_predict_kernel, T=T, L=L, GW=GW, CW=CW,
                          n_trees=n_trees, max_depth=max_depth,
                          has_cat=has_cat, es_freq=es_freq,
                          es_margin=es_margin),
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((GW, T), lambda b: (0, b)),
            pl.BlockSpec((n_trees * ROWS_PER_TREE, L), lambda b: (0, 0)),
            # sized off the actual table: numeric-only models pass a
            # minimal (CAT_DIGITS, 128) dummy the kernel never reads, so
            # no dead (n_trees*5, CW) VMEM block rides along
            pl.BlockSpec((cat_tab.shape[0], CW), lambda b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, T), lambda b: (0, b)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(bins_T, tabs, cat_tab)
    return out[0]


def build_predict_tables(trees, routing_np, num_leaves: int,
                         bin_mappers=None):
    """Host-side: (n_trees * 24, L) f32 node tables + (n_trees * 5, CW)
    f32 categorical bitset digit rows from host Tree objects.

    trees: list of tree.Tree (numeric + categorical splits; linear trees
    stay on the host).
    routing_np: dict of numpy routing arrays (feat_group, span_start,
    default_bin, bundled, nan_bin, num_bins, mzero_bin) indexed by
    ORIGINAL feature id.
    bin_mappers: training BinMappers — numeric thresholds are requantized
    from the REAL threshold (file-loaded trees carry threshold_bin=0; same
    rule as models/gbdt.py _tree_to_device), and categorical value-domain
    bitsets are re-projected onto bin indices (bit b set iff the bin's
    category ``categories[b]`` is in the node's value bitset).  Each cat
    feature's bitset spans ceil((num_bins + 1) / 32) words so the sentinel
    bin ``num_bins`` (NaN / unseen / negative values, pre-binned by the
    caller) always reads a zero bit and routes right like the host walk.
    Child encoding: internal child c >= 0 stays c; leaf child c < 0 becomes
    L + (~c).  Values that can exceed 255 are 7-bit digit-split; leaf values
    are bf16 hi/lo pairs."""
    L = num_leaves
    n_trees = len(trees)
    tabs = np.zeros((n_trees * ROWS_PER_TREE, L), np.float32)
    mzero = routing_np.get("mzero_bin")
    tree_words = []
    for ti, t in enumerate(trees):
        base = ti * ROWS_PER_TREE
        ni = max(t.num_leaves - 1, 0)
        # single-leaf trees (ni == 0) leave all child rows zero: the walk
        # stays on node 0 and the final jnp.maximum(enc - L, 0) resolves to
        # leaf 0, whose value is written below
        feats = np.asarray(t.split_feature[:ni], np.int64)
        grp = routing_np["feat_group"][feats]
        tabs[base + P_WORD_LO, :ni] = (grp >> 2) % 128
        tabs[base + P_WORD_HI, :ni] = (grp >> 2) // 128
        tabs[base + P_SHIFT, :ni] = (grp & 3) * 8
        tabs[base + P_SPAN, :ni] = routing_np["span_start"][feats]
        tabs[base + P_DEFBIN, :ni] = routing_np["default_bin"][feats]
        tabs[base + P_BUNDLED, :ni] = routing_np["bundled"][feats]
        nanb = routing_np["nan_bin"][feats]
        tabs[base + P_HASNAN, :ni] = (nanb >= 0).astype(np.float32)
        tabs[base + P_NANBIN, :ni] = np.maximum(nanb, 0)
        tabs[base + P_NBINS, :ni] = routing_np["num_bins"][feats]
        if mzero is not None and ni:
            mzb = mzero[feats]
            tabs[base + P_HASMZ, :ni] = (mzb >= 0).astype(np.float32)
            tabs[base + P_MZBIN, :ni] = np.maximum(mzb, 0)
        dt = (np.asarray(t.decision_type[:ni], np.uint8).astype(np.int32)
              if ni else np.zeros(0, np.int32))
        is_cat = (dt & 1) > 0
        tabs[base + P_ISCAT, :ni] = is_cat.astype(np.float32)
        if bin_mappers is not None:
            thr_b = np.zeros(ni, np.float32)
            for i in range(ni):
                if is_cat[i]:
                    continue   # cat nodes never compare against P_THR
                m = bin_mappers[int(feats[i])]
                thr_b[i] = np.searchsorted(m.upper_bounds,
                                           t.threshold[i], side="left")
            tabs[base + P_THR, :ni] = thr_b
        else:
            tabs[base + P_THR, :ni] = np.asarray(t.threshold_bin[:ni])
        tabs[base + P_DEFLEFT, :ni] = (np.asarray(t.decision_type[:ni]) & 2) > 0

        # categorical side table: per cat node, project the value-domain
        # bitset onto this feature's bins and record the node's word base
        words_t: list = []
        for i in np.nonzero(is_cat)[0]:
            f = int(feats[i])
            nb = int(routing_np["num_bins"][f])
            nw = (nb + 1 + 31) // 32     # +1: the sentinel bin past span
            base_w = len(words_t)
            tabs[base + P_CATB_LO, i] = base_w % 128
            tabs[base + P_CATB_HI, i] = base_w // 128
            k = int(t.threshold_bin[i])
            s, e = int(t.cat_boundaries[k]), int(t.cat_boundaries[k + 1])
            wv = np.asarray(t.cat_threshold[s:e], np.uint32)
            words = np.zeros(nw, np.uint32)
            cats = (bin_mappers[f].categories if bin_mappers is not None
                    else np.zeros(0, np.int64))
            for b in range(min(len(cats), nb)):
                c = int(cats[b])
                if c >= 0 and c // 32 < len(wv) \
                        and (int(wv[c // 32]) >> (c % 32)) & 1:
                    words[b // 32] |= np.uint32(1 << (b % 32))
            words_t.extend(int(w) for w in words)
        tree_words.append(words_t)

        def enc_child(c):
            c = np.asarray(c, np.int64)
            return np.where(c >= 0, c, L + ~c).astype(np.float64)

        lc = enc_child(t.left_child[:ni])
        rc = enc_child(t.right_child[:ni])
        tabs[base + P_LEFT_LO, :ni] = lc % 128
        tabs[base + P_LEFT_HI, :ni] = lc // 128
        tabs[base + P_RIGHT_LO, :ni] = rc % 128
        tabs[base + P_RIGHT_HI, :ni] = rc // 128

        lv = np.zeros(L, np.float32)
        lv[:t.num_leaves] = np.asarray(t.leaf_value[:t.num_leaves], np.float32)
        hi = _to_bf16_f32(lv)
        tabs[base + P_LEAF_HI, :] = hi
        tabs[base + P_LEAF_LO, :] = _to_bf16_f32(lv - hi)

    # digit-encode the per-tree word lists into the (n_trees*5, CW) table
    # (CW lanes padded to a multiple of 128 for VMEM tiling)
    cwt = max(max((len(w) for w in tree_words), default=0), 1)
    cwt = -(-cwt // 128) * 128
    cat_tab = np.zeros((max(n_trees, 1) * CAT_DIGITS, cwt), np.float32)
    for ti, words_t in enumerate(tree_words):
        for wj, w in enumerate(words_t):
            for d in range(CAT_DIGITS):
                cat_tab[ti * CAT_DIGITS + d, wj] = (w >> (7 * d)) & 127
    return tabs, cat_tab


def tree_max_depth(t) -> int:
    """Exact max depth of a host Tree via iterative traversal (leaf-wise trees
    can be up to num_leaves-1 deep)."""
    ni = max(t.num_leaves - 1, 0)
    if ni == 0:
        return 1
    depth = 1
    stack = [(0, 1)]
    lc = np.asarray(t.left_child)
    rc = np.asarray(t.right_child)
    while stack:
        node, d = stack.pop()
        depth = max(depth, d)
        for c in (int(lc[node]), int(rc[node])):
            if c >= 0:
                stack.append((c, d + 1))
    return depth


def _to_bf16_f32(x: np.ndarray) -> np.ndarray:
    """Round f32 -> bf16 (round-to-nearest-even) -> back to f32, in numpy."""
    u = np.asarray(x, np.float32).view(np.uint32)
    rounded = (u + 0x7FFF + ((u >> 16) & 1)) & 0xFFFF0000
    return rounded.view(np.float32)
