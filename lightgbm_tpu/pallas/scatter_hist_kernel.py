"""Scatter-add Pallas histogram backend (hist_backend=scatter).

Reference analog: src/treelearner/cuda/cuda_histogram_constructor.cu — the
CUDA constructor never materializes a one-hot operand; each thread block
scatter-adds its rows' (grad, hess) straight into a shared-memory histogram
tile.  This backend is the TPU-side existence proof of that formulation: it
skips the one-hot build entirely and accumulates every row block into ONE
VMEM-resident (S*G, B*Cp) histogram tile with a vectorized functional
segment-add (`acc.at[rows, lanes].add(w)`), so per-block cost is O(T*G*C)
update elements instead of the one-hot contraction's O(G*B*T) MACs — the
win grows with B and tree depth, exactly where the CUDA constructor wins.

Portability note (docs/PERF.md gives the measured verdict): Mosaic's
lowering of a functional scatter into a VMEM tile is the open risk on real
TPU cores — the MXU has no scatter datapath, which is the reason the repo's
default formulations are contractions.  The backend therefore ships gated:
`scatter_hist_fits` bounds the tile to the same ~12 MB VMEM budget as
`wide_hist_fits`, dispatch in ops/histogram.py falls back to the one-hot
path whenever the gate refuses, and off-TPU the kernel runs in interpret
mode (pure jnp scatter-add — exact, and fast enough for the A/B suite).

Layout: out[slot * G + g, bin * Cp + c] with Cp = C channels padded to a
multiple of 4; C = 3 (grad, hess, count) or 3*K for the batched-multiclass
widened variant (class-major channels c = k*3 + ch).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..telemetry.watchdog import watched_jit

# TPUCompilerParams was renamed CompilerParams across JAX releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

_INTERPRET = False  # flipped by tests to run kernels in interpret mode on CPU


def scatter_hist_fits(num_slots: int, num_groups: int, bmax: int,
                      num_class: int = 1) -> bool:
    """True when the (S*G, B*Cp) f32 scatter tile fits the ~12 MB VMEM
    budget (the `wide_hist_fits` convention) AND the static per-group
    unroll stays small enough to compile; callers fall back to the one-hot
    formulation otherwise."""
    C = 3 * num_class
    cp = -(-C // 4) * 4
    B = -(-bmax // 8) * 8
    if bmax > 128 or num_groups > 64:
        return False
    tile = num_slots * num_groups * B * cp * 4
    return tile <= 12 * 2 ** 20


def scatter_block_rows(num_groups: int, num_class: int = 1) -> int:
    """Row-block size: the block inputs are tiny ((T, G) bins + (C, T)
    weights), so the only pressure is the scatter's temporary index
    vectors — large blocks amortize grid overhead."""
    base = 8192 // max(num_class, 1)
    return max(base, 1024)


def _scatter_kernel(bins_ref, slot_ref, w_ref, out_ref, *, T: int, G: int,
                    B: int, K: int, Cp: int):
    b = pl.program_id(0)

    @pl.when(b == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    acc = out_ref[...]
    ch3 = jnp.arange(3, dtype=jnp.int32)[None, :]
    for k in range(K):                     # static unroll over classes
        slot = slot_ref[k, :].astype(jnp.int32)
        valid = slot >= 0
        s = jnp.where(valid, slot, 0)
        # (T, 3) per-class (grad, hess, cnt) updates, invalid rows zeroed
        wv = (w_ref[3 * k:3 * (k + 1), :]
              * valid[None, :].astype(jnp.float32)).T
        for g in range(G):                 # static unroll over groups
            fb = bins_ref[:, g].astype(jnp.int32)
            rows = s * G + g
            lanes = fb * Cp + 3 * k
            acc = acc.at[rows[:, None], lanes[:, None] + ch3].add(wv)
    out_ref[...] = acc


@functools.partial(watched_jit, name="pallas_hist_scatter", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "num_class", "block_rows"))
def _hist_scatter(bins_T, slot, w_T, num_slots, bmax, num_groups, num_class,
                  block_rows):
    T, G = block_rows, num_groups
    K, S = num_class, num_slots
    B = -(-bmax // 8) * 8
    cp = -(-(3 * K) // 4) * 4
    n_pad = bins_T.shape[0]
    NB = n_pad // T
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, T=T, G=G, B=B, K=K, Cp=cp),
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((T, G), lambda b: (b, 0)),
            pl.BlockSpec((K, T), lambda b: (0, b)),
            pl.BlockSpec((3 * K, T), lambda b: (0, b)),
        ],
        out_specs=pl.BlockSpec((S * G, B * cp), lambda b: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((S * G, B * cp), jnp.float32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET or jax.default_backend() not in ("tpu", "axon"),
    )(bins_T, slot, w_T)
    # (S*G, B*Cp) -> (K, S, G, Bmax, 3)
    hist = out.reshape(S, G, B, cp)[:, :, :bmax, :3 * K]
    hist = hist.reshape(S, G, bmax, K, 3)
    return jnp.transpose(hist, (3, 0, 1, 2, 4))


def build_histograms_scatter(bins: jax.Array, slot: jax.Array,
                             grad: jax.Array, hess: jax.Array,
                             cnt: jax.Array, num_slots: int,
                             max_group_bins: int) -> jax.Array:
    """Single-class scatter histograms: (S, G, Bmax, 3) float32.

    Same contract as ops.histogram.build_histograms (slot < 0 skips the
    row); rows are streamed unsorted — no block plan, no one-hot."""
    return build_histograms_scatter_k(
        bins, slot[None], grad[None], hess[None], cnt, 1, num_slots,
        max_group_bins)[0]


def build_histograms_scatter_k(bins: jax.Array, slot: jax.Array,
                               grad: jax.Array, hess: jax.Array,
                               cnt: jax.Array, num_class: int,
                               num_slots: int,
                               max_group_bins: int) -> jax.Array:
    """K-class scatter histograms (batched multiclass): (K, S, G, Bmax, 3).

    slot/grad/hess: (K, N) per-class; cnt: (N,) shared."""
    K, n = slot.shape
    G = bins.shape[1]
    T = scatter_block_rows(G, K)
    n_pad = -(-n // T) * T
    bins_p = jnp.pad(bins.astype(jnp.int32), ((0, n_pad - n), (0, 0)))
    slot_p = jnp.pad(slot.astype(jnp.int32), ((0, 0), (0, n_pad - n)),
                     constant_values=-1)
    w3 = jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32),
                    jnp.broadcast_to(cnt, grad.shape).astype(jnp.float32)],
                   axis=1).reshape(3 * K, n)        # rows k*3 + (g, h, c)
    w_T = jnp.pad(w3, ((0, 0), (0, n_pad - n)))
    return _hist_scatter(bins_p, slot_p, w_T, num_slots, max_group_bins, G,
                         K, T)
