"""Fused streaming route+histogram Pallas TPU kernel — the v2 hot path.

Reference analog: src/io/dense_bin.hpp:99-170 (ConstructHistogramInner),
src/treelearner/data_partition.hpp (leaf row partition) and
src/treelearner/cuda/cuda_data_partition.cu + cuda_histogram_constructor.cu
(the CUDA backend splits these into separate scatter/atomic kernels).

TPU re-design rationale: measured on a v5e, XLA's random row gather runs at
~100M rows/s and scatter at ~11M rows/s, while sequential streaming runs at
HBM bandwidth (hundreds of GB/s).  The round-1 design (sort rows by histogram
slot, gather them into single-slot blocks, then contract) was therefore
latency-bound: ~10 full-data sort+gather+route passes per tree.  This kernel
removes ALL data movement: rows stream through in natural order ONCE per
round, and one fused pass both
  (1) routes each row through this round's chosen splits (per-leaf split
      tables applied via a one-hot matmul on the MXU), and
  (2) accumulates histograms for the S "smaller children" of the round, with
      the histogram-slot one-hot FOLDED into the contraction weights:

        hist[(g,b), (c,s)] += sum_t 1[bin_g[t]=b] * w[c,t] * 1[slot[t]=s]

      i.e. per group one (B, T) x (T, 3S) matmul; the (3S, T) right operand
      A[(c,s),t] = w[c,t]*slot_oh[s,t] is built once per block on the VPU.

Per-leaf split tables (threshold, feature word/shift, EFB span, NaN bin,
categorical bitset, child ids, slot ids) are tiny (L rows) and live in VMEM;
per-row values are fetched with a (24, L) @ (L, T) one-hot matmul.  Table
values are 7-bit digit-encoded where they can exceed 256 so the bf16 matmul
stays exact.

The histogram output uses a constant-index BlockSpec, so it stays resident in
VMEM across the whole grid and is written back to HBM once.  f32 weights are
split into two bf16 parts (hi + lo) and contracted twice so gradient sums
accumulate with f32 accuracy.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# TPUCompilerParams was renamed CompilerParams across JAX releases
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

from .hist_kernel import _wsplit  # shared f32 -> (hi, lo) bf16 split
from ..telemetry.watchdog import watched_jit
from ..binning import bucket_group_pad, bucket_run_rows

NUM_TAB = 24          # per-leaf table rows (padded to a sublane multiple)
MAX_SLOTS = 255       # slot table rows are single bf16 digits (exact <= 256)
_INTERPRET = False    # force-interpret override (tests)


def _interp() -> bool:
    """Pallas interpret mode: forced by tests, or automatic off-TPU so the
    stream backend is runnable on CPU meshes (dryruns, distributed tests)."""
    return _INTERPRET or jax.default_backend() not in ("tpu", "axon")


import os as _os
# Perf-ablation probes (dev only): additive variants that double one kernel
# phase so its cost can be measured through the real bench. Several modes
# deliberately CORRUPT results — never set this for real training.
_ABLATE = _os.environ.get("LGBTPU_KABLATE", "")
_KNOWN_ABLATE = ("", "nohist", "constoh", "dblcon", "dblroute", "dblA",
                 "dbldot", "dbldot_i8", "noA")
if _ABLATE not in _KNOWN_ABLATE:
    raise ValueError(f"unknown LGBTPU_KABLATE={_ABLATE!r}; one of "
                     f"{_KNOWN_ABLATE[1:]}")
if _ABLATE:
    import sys as _sys
    print(f"WARNING: LGBTPU_KABLATE={_ABLATE} perf probe active — training "
          "results may be intentionally wrong", file=_sys.stderr)

# table row indices
(T_CHOSEN, T_NEWID_LO, T_NEWID_HI, T_WORD_LO, T_WORD_HI, T_SHIFT, T_SPAN,
 T_DEFBIN, T_BUNDLED, T_HASNAN, T_NANBIN, T_NBINS, T_THR, T_DEFLEFT, T_ISCAT,
 T_SLOT_L, T_SLOT_R, T_SLOT_KEEP, T_HASMZ, T_MZBIN) = range(20)


def _digits(v):
    """Split a non-negative int array into (lo7, hi) digits exact in bf16."""
    v = v.astype(jnp.int32)
    return (v & 127).astype(jnp.float32), (v >> 7).astype(jnp.float32)


def _route_step(iv, bins_ref, bins32, GW, T, u8_layout):
    """Shared single-table routing math: decode one (NUM_TAB, T) block of
    gathered table values into each row's routing decision.

    Used by BOTH the per-round fused kernel (_route_hist_kernel) and the
    fused route-replay kernel (_route_replay_kernel), so the two can never
    drift — the replay's bit-identity to the per-round route-only passes
    rests on this sharing.

    Returns (chosen_i, newid, fb, go_left_i, slot_l1, slot_r1, slot_k1)
    with go_left_i the NUMERIC decision (threshold + NaN/missing-zero
    default direction); the caller overlays the categorical bit where it
    has the bitset operand."""
    i32 = jnp.int32
    chosen_i = iv[T_CHOSEN:T_CHOSEN + 1, :]
    newid = iv[T_NEWID_LO:T_NEWID_LO + 1, :] + (iv[T_NEWID_HI:T_NEWID_HI + 1, :] << 7)
    wordi = iv[T_WORD_LO:T_WORD_LO + 1, :] + (iv[T_WORD_HI:T_WORD_HI + 1, :] << 7)
    shift = iv[T_SHIFT:T_SHIFT + 1, :]
    span = iv[T_SPAN:T_SPAN + 1, :]
    defbin = iv[T_DEFBIN:T_DEFBIN + 1, :]
    bundled_i = iv[T_BUNDLED:T_BUNDLED + 1, :]
    has_nan_i = iv[T_HASNAN:T_HASNAN + 1, :]
    nanbin = iv[T_NANBIN:T_NANBIN + 1, :]
    nbins = iv[T_NBINS:T_NBINS + 1, :]
    thr = iv[T_THR:T_THR + 1, :]
    defleft_i = iv[T_DEFLEFT:T_DEFLEFT + 1, :]

    # select the split feature's group-local bin for every row
    if u8_layout:
        # unpacked (G_pad, T) int8 storage: same HBM bytes as the packed
        # 4-per-word form (28 B/row either way at G=28) but no per-group
        # shift/mask unpack work in the kernel
        grpi = wordi * 4 + jax.lax.shift_right_logical(shift, 3)
        gp_iota = jax.lax.broadcasted_iota(i32, bins32.shape, 0)
        gb = jnp.sum(jnp.where(gp_iota == grpi, bins32, 0), axis=0,
                     keepdims=True)                      # (1, T)
    else:
        # packed: select the split feature's group word, then its byte
        words = bins_ref[...]                            # (GW, T) i32
        gw_iota = jax.lax.broadcasted_iota(i32, (GW, T), 0)
        word = jnp.sum(jnp.where(gw_iota == wordi, words, 0), axis=0,
                       keepdims=True)                    # (1, T)
        gb = jax.lax.shift_right_logical(word, shift) & 0xFF

    # feature-local bin for EFB bundles (ops/grow.py feature_local_bin)
    ls = gb - span
    ge_def = jnp.where(ls >= defbin, 1, 0)
    fb_b = jnp.where((ls >= 0) & (ls < nbins - 1), ls + ge_def, defbin)
    fb = jnp.where(bundled_i > 0, fb_b, gb)

    has_mz_i = iv[T_HASMZ:T_HASMZ + 1, :]
    mzbin = iv[T_MZBIN:T_MZBIN + 1, :]
    is_nan_i = has_nan_i * jnp.where(fb == nanbin, 1, 0)
    is_mz_i = has_mz_i * jnp.where(fb == mzbin, 1, 0)
    le_thr = jnp.where(fb <= thr, 1, 0)
    go_left_i = jnp.where(is_nan_i + is_mz_i > 0, defleft_i, le_thr)
    return (chosen_i, newid, fb, go_left_i,
            iv[T_SLOT_L:T_SLOT_L + 1, :], iv[T_SLOT_R:T_SLOT_R + 1, :],
            iv[T_SLOT_KEEP:T_SLOT_KEEP + 1, :])


def _route_hist_kernel(bins_ref, leaf_ref, w_ref, tabs_ref, bits_ref,
                       newleaf_ref, *outs, T, G, B, S, L, GW,
                       has_cat: bool, two_pass: bool = True,
                       int_weights: bool = False, f32_dots: bool = False,
                       u8_layout: bool = False, with_hist: bool = True,
                       bin_buckets=None, m_rows: int = 0, K: int = 1):
    if with_hist:
        hist_ref, cnt_ref = outs
    else:
        # route-only variant: no histogram output ref exists at all, so the
        # (G*B, 2*S*K) VMEM-resident block is never allocated
        hist_ref, (cnt_ref,) = None, outs
    b = pl.program_id(0)
    i32, f32 = jnp.int32, jnp.float32
    # interpret mode on CPU: XLA:CPU's Eigen DotThunk rejects bf16 at some
    # shapes; f32 operands carry the identical (bf16-rounded) values, so the
    # contraction results match the TPU MXU's bf16 x bf16 -> f32 exactly
    bf16 = f32 if f32_dots else jnp.bfloat16

    # ---------------- route (per class; the bin one-hot below is shared) ---
    # K > 1 is the BATCHED MULTICLASS path: K class trees grow in lockstep,
    # so the kernel routes each row through K per-class split tables and
    # accumulates one widened (m_rows, 2*S*K) histogram block — the
    # class-independent bin one-hot is built ONCE and contracted against
    # the class x slot channel axis (vs K separate kernel launches each
    # rebuilding the one-hot).
    l_iota = jax.lax.broadcasted_iota(i32, (L, T), 0)
    bins32 = bins_ref[...].astype(i32) if u8_layout else None  # (G_pad, T)
    # FOLDED multiclass route gather (docs/PERF.md lever): the K per-class
    # (NUM_TAB, L) @ (L, T) table dots merge into ONE block-diagonal
    # (K*NUM_TAB, K*L) @ (K*L, T) dot — class k's leaf one-hot occupies
    # rows [k*L, (k+1)*L) and the LHS zero-masks tabs outside its column
    # band, so every output element still sums exactly one 1.0 * value
    # product (bit-exact; zero products add exact zeros).  Gated on the
    # operands fitting VMEM; the per-class loop remains the fallback.
    fold_routes = (K > 1 and K * L * T * 2 <= 8 * 2 ** 20
                   and NUM_TAB * K * K * L * 4 <= 4 * 2 ** 20)
    if fold_routes:
        kl_iota = jax.lax.broadcasted_iota(i32, (K * L, T), 0)
        lid_all = jnp.concatenate(
            [jnp.broadcast_to(leaf_ref[k:k + 1, :] + k * L, (L, T))
             for k in range(K)], axis=0)
        oh_all = (kl_iota == lid_all).astype(bf16)           # (K*L, T)
        col_iota = jax.lax.broadcasted_iota(i32, (NUM_TAB, K * L), 1)
        bd = jnp.concatenate(
            [jnp.where((col_iota >= k * L) & (col_iota < (k + 1) * L),
                       tabs_ref[...], 0.0) for k in range(K)], axis=0)
        vals_all = jax.lax.dot_general(
            bd, oh_all, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                      # (K*NUM_TAB, T)
    slots = []                                               # per-class (1,T)
    for k in range(K):  # static unroll
        lid = leaf_ref[k:k + 1, :]                           # (1, T) i32
        if fold_routes:
            # NUM_TAB row slices stay sublane-aligned (24 = 3 x 8); the
            # categorical-bits dot below rebuilds its per-class one-hot
            # instead of slicing oh_all at the unaligned k*L offset
            leaf_oh = None
            vals = vals_all[k * NUM_TAB:(k + 1) * NUM_TAB, :]
        else:
            leaf_oh = (l_iota == lid).astype(bf16)           # (L, T)
            vals = jax.lax.dot_general(
                tabs_ref[:, k * L:(k + 1) * L], leaf_oh,
                (((1,), (0,)), ((), ())),
                preferred_element_type=f32)                  # (NUM_TAB, T)
        # flags stay i32 (0/1) throughout — Mosaic cannot handle i1 vectors
        # as select OPERANDS (i8<->i1 truncation); predicates are fresh
        # comparisons.  The per-table routing math is the shared
        # _route_step (also the replay kernel's step — never drifts).
        iv = vals.astype(i32)
        (chosen_i, newid, fb, go_left_i,
         slot_l1, slot_r1, slot_k1) = _route_step(iv, bins_ref, bins32,
                                                  GW, T, u8_layout)
        is_cat_i = iv[T_ISCAT:T_ISCAT + 1, :]
        if has_cat:
            # per-row categorical bit: (Bmax, L) @ (L, T) one-hot, pick fb
            if leaf_oh is None:
                leaf_oh = (l_iota == lid).astype(bf16)       # (L, T)
            br = jax.lax.dot_general(
                bits_ref[:, k * L:(k + 1) * L].astype(bf16), leaf_oh,
                (((1,), (0,)), ((), ())),
                preferred_element_type=f32)                  # (B, T)
            b_iota_c = jax.lax.broadcasted_iota(i32, (B, T), 0)
            cat_bit = jnp.sum(jnp.where(b_iota_c == fb, br, 0.0), axis=0,
                              keepdims=True)
            go_left_cat = jnp.where(cat_bit > 0.5, 1, 0)
            go_left_i = jnp.where(is_cat_i > 0, go_left_cat, go_left_i)

        new_lid = jnp.where(chosen_i * (1 - go_left_i) > 0, newid, lid)
        slot1 = jnp.where(chosen_i > 0,
                          jnp.where(go_left_i > 0, slot_l1, slot_r1), slot_k1)
        if _ABLATE == "dblroute":    # perf probe: one extra route gather
            leaf_oh2 = (l_iota == lid + L).astype(bf16)
            vals2 = jax.lax.dot_general(
                tabs_ref[:, k * L:(k + 1) * L], leaf_oh2,
                (((1,), (0,)), ((), ())), preferred_element_type=f32)
            new_lid = new_lid + vals2[0:1, :].astype(i32)
        newleaf_ref[k:k + 1, :] = new_lid
        slots.append(slot1 - 1)

    # ---------------- histogram ----------------
    @pl.when(b == 0)
    def _():
        if with_hist:
            hist_ref[...] = jnp.zeros_like(hist_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)

    s_iota = jax.lax.broadcasted_iota(i32, (S, T), 0)
    slot_ohs = [(s_iota == slot).astype(bf16) for slot in slots]  # (S, T) ea
    slot_oh = (jnp.concatenate(slot_ohs, axis=0) if K > 1
               else slot_ohs[0])                             # (S*K, T)
    # EXACT per-slot data counts (one tiny (1,T)x(T,S*K) dot) — needed by
    # every variant including route-only rounds: they become the model's
    # leaf_count values (DataPartition::leaf_count,
    # serial_tree_learner.cpp:798)
    cnt_row = w_ref[2 * K:2 * K + 1, :]
    cnt_ref[0:1, :] += jax.lax.dot_general(
        cnt_row.astype(bf16), slot_oh, (((1,), (1,)), ((), ())),
        preferred_element_type=f32)
    if not with_hist:
        # route-only round (a tree's LAST split round: the children's
        # histograms would never be scanned, so the dominant one-hot
        # contraction — and the whole VMEM-resident histogram block — is
        # dropped)
        return
    w2 = w_ref[0:2 * K, :]                                   # (2K, T) f32
    w_hi, w_lo = _wsplit(w2)

    # build the bin-match one-hot shared by the int and float contraction
    # paths. The one-hot is built B-MAJOR — row r = b * G + g — via
    # key = bin * G + g tiled B times against a flat 2-D iota: measured
    # ~40% of kernel time used to go into the (G, B, T) 3-D
    # broadcast-compare layout this replaces.
    if u8_layout:
        bins_G = bins32[:G, :]                               # (G, T) no unpack
    else:
        # unpack the 4-per-word packed group bins
        rows = []
        for g in range(G):  # static unroll
            word_g = bins_ref[g // 4:g // 4 + 1, :]
            rows.append(jax.lax.shift_right_logical(word_g, (g % 4) * 8) & 0xFF)
        bins_G = jnp.concatenate(rows, axis=0)               # (G, T)
    # (a per-bin compare-block construct — B int8 compares of (G, T)
    # concatenated — measured 14% SLOWER than this key form: the 64-block
    # concat relayout costs more than the (B*G, T) key/iota compare)
    if bin_buckets is None:
        g_iota = jax.lax.broadcasted_iota(i32, (G, T), 0)
        key = bins_G * G + g_iota                            # (G, T)
        key_t = jnp.concatenate([key] * B, axis=0)           # (B*G, T) tiled
        r_iota = jax.lax.broadcasted_iota(i32, (B * G, T), 0)
        oh_match = key_t == r_iota        # (B*G, T) bool, row r = b * G + g
        if _ABLATE == "dblcon":  # additive probe: one extra (never-hit) construct
            key_t2 = jnp.concatenate([key + B * G] * B, axis=0)
            oh_match = oh_match | (key_t2 == r_iota)
    else:
        # BUCKETED M-axis: groups are laid out in runs of equal bin-bucket
        # size (binning.device_group_order), and each run contributes
        # Bk * Gk8 one-hot rows — M = sum of rounded per-group bin counts
        # instead of G * Bmax, which is where low-cardinality features'
        # histogram cost actually goes (the reference's scatter never paid
        # per-bin; this is the matmul formulation's equivalent).  Row
        # r = roff_k + b * Gk8 + g_local; the key trick is per run.  Gk
        # pads to a sublane multiple (8) with never-matching keys so the
        # Bk tiled concat pieces stay aligned.
        parts = []
        goff = roff = 0
        for Bk, Gk in bin_buckets:
            Gk8 = bucket_group_pad(Gk)
            sub = bins_G[goff:goff + Gk, :]                  # (Gk, T)
            # real keys first, then pad rows pinned to -1 (below every
            # r_iota value). Padding the BIN value instead (1 << 24) only
            # worked while (1 << 24) * Gk8 stayed inside int32 — at
            # Gk8 >= 128 that product wraps and a pad row could alias a
            # real histogram row.
            gi_k = jax.lax.broadcasted_iota(i32, (Gk, T), 0)
            key_k = sub * Gk8 + gi_k + roff
            if Gk8 > Gk:
                key_k = jnp.concatenate(
                    [key_k, jnp.full((Gk8 - Gk, T), -1, i32)], axis=0)
            parts.extend([key_k] * Bk)
            goff += Gk
            roff += Bk * Gk8
        if m_rows > roff:
            parts.append(jnp.full((m_rows - roff, T), -1, i32))
        key_t = jnp.concatenate(parts, axis=0)               # (m_rows, T)
        r_iota = jax.lax.broadcasted_iota(i32, (m_rows, T), 0)
        oh_match = key_t == r_iota

    if int_weights:
        # Quantized-gradient histograms (reference: gradient_discretizer.cpp
        # + the int8/int16 ConstructHistogram variants, dense_bin.hpp): the
        # grow layer passes integer-valued grad/hess rows, the contraction
        # runs on the int8 MXU (~25% faster than bf16 at these shapes), and
        # int32 accumulation makes the histogram sums EXACT.
        # build A in i32 (Mosaic cannot legalize i8*i8 multiplies), then
        # convert the (2*S*K, T) operand to int8 once; class-major rows
        # j = k*2S + c*S + s match the caller's unflatten
        slot_ohs_i = [(s_iota == slot).astype(i32) for slot in slots]
        w_i = jnp.round(w2).astype(i32)                      # int-valued rows
        A_i = jnp.concatenate(
            [w_i[2 * k + c:2 * k + c + 1, :] * slot_ohs_i[k]
             for k in range(K) for c in range(2)], axis=0)
        if _ABLATE == "nohist":      # int-path probe: no one-hot, no dot
            hist_ref[...] += jnp.sum(A_i, axis=1)[None, :]
            return
        if f32_dots:
            # CPU interpret: f32 products of |v| <= 127 ints are exact and
            # per-block sums stay below 2^24, so rounding back is lossless
            d = jax.lax.dot_general(
                oh_match.astype(f32), A_i.astype(f32),
                (((1,), (1,)), ((), ())), preferred_element_type=f32)
            hist_ref[...] += d.astype(i32)
        else:
            if _ABLATE == "constoh":     # int-path probe: constant operand
                oh_i = jnp.full((B * G, T), 1, jnp.int8)
            else:
                oh_i = oh_match.astype(jnp.int8)
            if _ABLATE == "noA":         # int-path probe: constant A operand
                A_8 = jnp.full((2 * S, T), 1, jnp.int8)
            else:
                A_8 = A_i.astype(jnp.int8)
            hist_ref[...] += jax.lax.dot_general(
                oh_i, A_8, (((1,), (1,)), ((), ())),
                preferred_element_type=i32)
            if _ABLATE == "dbldot_i8":   # additive probe: one extra int8 dot
                d2 = jax.lax.dot_general(
                    oh_i, jnp.flip(A_8, 1), (((1,), (1,)), ((), ())),
                    preferred_element_type=i32)
                # |d2| < 2^30 so this adds exactly 0, but the compiler
                # cannot prove it — the extra dot survives DCE
                hist_ref[...] += jnp.abs(d2) // jnp.int32(2 ** 30)
        return

    # (histograms carry only grad/hess — per-bin counts are estimated from
    # hessians at split-find time like the reference; exact per-slot counts
    # came from the hoisted cnt dot above)
    def build_A(w):
        # (1, T) x (S, T) broadcast-multiplies + sublane concat; the 3-D
        # broadcast form lowers to a much slower relayout. Class-major rows
        # j = k*2S + c*S + s (matches the caller's unflatten).
        return jnp.concatenate(
            [w[2 * k + c:2 * k + c + 1, :].astype(bf16) * slot_ohs[k]
             for k in range(K) for c in range(2)],
            axis=0)                                          # (2*S*K, T)

    A_hi = build_A(w_hi)
    if _ABLATE == "dblA":        # perf probe: one extra A-operand build
        A_hi = A_hi + build_A(w_lo) * bf16(0.0)
    dot = functools.partial(jax.lax.dot_general,
                            dimension_numbers=(((1,), (1,)), ((), ())),
                            preferred_element_type=f32)
    # ONE (G*B, T) @ (T, 3S) contraction per block: per-group (B, T) dots
    # have M=B=64 — half an MXU tile — so merging groups into a single
    # one-hot doubles MXU utilisation (the dominant cost of training).
    oh = oh_match.astype(bf16)
    if _ABLATE == "nohist":      # fixed costs only (route + A + writes)
        hist_ref[...] += jnp.sum(A_hi, axis=1)[None, :]
        return
    if _ABLATE == "constoh":     # dot with a constant operand (no one-hot)
        oh = jnp.full((G * B, T), 0.5, bf16)
    if _ABLATE == "dbldot":      # perf probe: one extra bf16 dot
        hist_ref[...] += dot(oh, build_A(w_lo)) * 1e-30
    if _ABLATE == "dbldot_i8":   # perf probe: one extra int8 dot
        oh_i8 = oh_match.astype(jnp.int8)
        a_i8 = build_A(w_lo).astype(jnp.int8)
        d2 = jax.lax.dot_general(oh_i8, a_i8,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.int32)
        hist_ref[...] += d2.astype(f32) * 1e-30
    if two_pass:
        A_lo = build_A(w_lo)
        hist_ref[...] += dot(oh, A_hi) + dot(oh, A_lo)
    else:
        # single-precision weights (the reference's GPU default,
        # gpu_use_dp=false): one bf16 pass, f32 accumulation
        hist_ref[...] += dot(oh, A_hi)


def stream_block_rows(bmax: int, num_groups: int = 28,
                      int_hist: bool = False,
                      bin_buckets=None, hist_channels: int = 0) -> int:
    """Rows per kernel block, sized so the (G*B, T) one-hot operand stays
    within ~8 MB of VMEM: int8 one-hots (quantized-gradient path) take
    4096-row blocks (measured ~3% faster than 2048 end to end), bf16
    one-hots 2048 (4096 at bf16 REGRESSES 5x — VMEM pressure kills the
    pipeline). Wide layouts (many EFB groups, e.g. high-dimensional sparse
    data) step down to 512/256-row blocks.

    hist_channels: column count of the VMEM-resident histogram block
    (2*S*K on the batched multiclass path). When > 0 its T-independent
    footprint is charged against the one-hot budget, so the widened
    K-channel program steps the block size down instead of blowing VMEM."""
    import os
    env = os.environ.get("LGBTPU_BLOCK_ROWS")
    if env:
        return int(env)
    if jax.default_backend() not in ("tpu", "axon"):
        # CPU interpret mode: keep dots narrow for XLA:CPU
        return 1024
    B = -(-bmax // 8) * 8
    oh_bytes = 1 if int_hist else 2
    if bin_buckets is not None:
        m_rows = -(-sum(bucket_run_rows(bk, gk)
                        for bk, gk in bin_buckets) // 128) * 128
    else:
        m_rows = num_groups * B
    # int8 one-hots get a 9 MB budget: at MSLR shapes (G=136, B=64) that
    # admits T=1024 (8.9 MB one-hot + 4.45 MB hist block still compiles),
    # measured 3% faster end-to-end than the T=512 the 8 MB budget forces.
    # bf16 is hard-capped at 2048: T=4096 at bf16 REGRESSED 5x even when
    # the one-hot fit the budget (VMEM pressure kills the pipeline), and
    # small bucketed m_rows would otherwise re-admit it
    budget = (9 if int_hist else 8) * 2 ** 20
    if hist_channels:
        # the (m_rows, C) histogram block stays VMEM-resident across the
        # whole grid; the binary path's C=2S block was small enough to
        # ignore, the K-widened block is not
        budget -= max(0, m_rows * hist_channels * 4 - 2 * 2 ** 20)
    tiers = (4096, 2048, 1024, 512, 256) if int_hist \
        else (2048, 1024, 512, 256)
    for T in tiers:
        if m_rows * T * oh_bytes <= budget:
            return T
    return 256


class StreamLayout(NamedTuple):
    """Static transposed-packed data for the streaming kernel (built once per
    training run): bins packed 4 groups/int32, transposed to (GW, N_pad)."""
    bins_T: jax.Array        # (GW_pad, N_pad) i32
    n_pad: int
    num_groups: int


def _use_u8_layout(max_bin_value: int = 127) -> bool:
    """Unpacked (G_pad, N_pad) int8 bins: identical HBM bytes to the packed
    4-per-word form, but the kernel skips all shift/mask unpack work.
    Requires bins < 128 (int8); LGBTPU_STREAM_PACKED=1 forces the old
    packed layout."""
    return _os.environ.get("LGBTPU_STREAM_PACKED", "") != "1"


def pack_bins_T(bins: jax.Array, block_rows: int = 1024,
                max_bins: int = 256) -> StreamLayout:
    """(N, G) uint8 -> transposed (GW_pad, N_pad) i32 packed layout, or the
    (G_pad, N_pad) i8 unpacked layout when bins fit int8 (the kernel
    dispatches on the dtype)."""
    n, g = bins.shape
    n_pad = -(-n // block_rows) * block_rows
    if max_bins <= 127 and _use_u8_layout():
        g_pad = -(-g // 32) * 32           # i8 tiling: 32-sublane multiples
        w = jnp.pad(bins, ((0, n_pad - n), (0, g_pad - g))).astype(jnp.int8)
        return StreamLayout(bins_T=w.T, n_pad=n_pad, num_groups=g)
    gw = -(-g // 4)
    gw_pad = -(-gw // 8) * 8
    w = jnp.pad(bins, ((0, n_pad - n), (0, gw_pad * 4 - g))).astype(jnp.int32)
    w = w.reshape(n_pad, gw_pad, 4)
    packed = (w[..., 0] | (w[..., 1] << 8) | (w[..., 2] << 16) | (w[..., 3] << 24))
    return StreamLayout(bins_T=packed.T, n_pad=n_pad, num_groups=g)


@functools.partial(watched_jit, name="route_and_hist", warn_after=0,
                   static_argnames=("num_slots", "bmax", "num_groups",
                                    "num_leaves", "block_rows", "has_cat",
                                    "two_pass", "int_weights", "with_hist",
                                    "bin_buckets", "num_class"))
def route_and_hist(bins_T: jax.Array, leaf_id: jax.Array, w_T: jax.Array,
                   tabs: jax.Array, bits: jax.Array, num_slots: int, bmax: int,
                   num_groups: int, num_leaves: int, block_rows: int = 1024,
                   has_cat: bool = True, two_pass: bool = True,
                   int_weights: bool = False, with_hist: bool = True,
                   bin_buckets=None, num_class: int = 1):
    """One fused streaming pass: route rows through this round's splits and
    build grad/hess histograms and exact data counts of the rows' NEW slots.

    bins_T: (GW_pad, N_pad) i32 from pack_bins_T.
    leaf_id: (K, N_pad) i32 current leaf per row (per class; K = num_class).
    w_T: (Wpad, N_pad) f32, rows 2k/2k+1 = class k's grad/hess (bagging mask
    applied) and row 2K = cnt; K=1 keeps the legacy 0..2 = grad, hess, cnt.
    tabs: (NUM_TAB, K*L) f32 per-leaf split tables (see build_route_tables).
    bits: (Bpad, K*L) bf16 categorical left bitsets (dummy when !has_cat).
    Returns (new_leaf_id (K, N_pad) i32, hist (S, G, Bmax, 2) f32 grad/hess
    — (K, S, G, Bmax, 2) when num_class > 1 — and slot_cnt (S,) / (K, S)
    f32 exact per-slot data counts).

    num_class > 1 is the BATCHED MULTICLASS path: all K class trees route
    and accumulate inside ONE widened program whose bin one-hot (the
    dominant construct) is built once per block and contracted against the
    stacked class x slot channel axis.
    """
    GW, n_pad = bins_T.shape
    T = block_rows
    NB = n_pad // T
    S, G, L, K = num_slots, num_groups, num_leaves, num_class
    if S > MAX_SLOTS:
        raise ValueError(f"stream kernel supports at most {MAX_SLOTS} "
                         f"histogram slots per round, got {S}")
    if K > 1 and _ABLATE:
        raise ValueError("LGBTPU_KABLATE probes require num_class == 1")
    B = -(-bmax // 8) * 8
    u8_layout = bins_T.dtype == jnp.int8
    if bin_buckets is not None:
        if _ABLATE:
            raise ValueError("LGBTPU_KABLATE probes require the uniform "
                             "(non-bucketed) one-hot layout")
        if sum(gk for _, gk in bin_buckets) != G:
            raise ValueError(f"bin_buckets {bin_buckets} do not cover "
                             f"{G} groups")
        m_tot = sum(bucket_run_rows(bk, gk) for bk, gk in bin_buckets)
        m_rows = -(-m_tot // 128) * 128
    else:
        m_rows = G * B

    hist_dtype = jnp.int32 if int_weights else jnp.float32
    out_specs = [
        pl.BlockSpec((K, T), lambda b: (0, b)),
        pl.BlockSpec((m_rows, 2 * S * K), lambda b: (0, 0)),
        pl.BlockSpec((1, S * K), lambda b: (0, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((K, n_pad), jnp.int32),
        jax.ShapeDtypeStruct((m_rows, 2 * S * K), hist_dtype),
        jax.ShapeDtypeStruct((1, S * K), jnp.float32),
    ]
    if not with_hist:
        del out_specs[1], out_shape[1]
    outs = pl.pallas_call(
        functools.partial(_route_hist_kernel, T=T, G=G, B=B, S=S, L=L, GW=GW,
                          has_cat=has_cat, two_pass=two_pass,
                          int_weights=int_weights, f32_dots=_interp(),
                          u8_layout=u8_layout, with_hist=with_hist,
                          bin_buckets=bin_buckets, m_rows=m_rows, K=K),
        grid=(NB,),
        in_specs=[
            pl.BlockSpec((GW, T), lambda b: (0, b)),
            pl.BlockSpec((K, T), lambda b: (0, b)),
            pl.BlockSpec((w_T.shape[0], T), lambda b: (0, b)),
            pl.BlockSpec((NUM_TAB, K * L), lambda b: (0, 0)),
            pl.BlockSpec((B, K * L), lambda b: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interp(),
    )(bins_T, leaf_id, w_T, tabs, bits)

    def _cnt_out(cnt):
        return cnt.reshape(-1) if K == 1 else cnt.reshape(K, S)

    if not with_hist:
        new_leaf, cnt = outs
        shape4 = (S, G, bmax, 2) if K == 1 else (K, S, G, bmax, 2)
        return new_leaf, jnp.zeros(shape4, hist_dtype), _cnt_out(cnt)
    new_leaf, hist, cnt = outs
    if bin_buckets is not None:
        # per-run unpack: rows [roff, roff + Bk*Gk) -> (K, S, Gk, Bk, 2),
        # bins padded up to Bmax, runs concatenated in layout group order
        parts4 = []
        roff = 0
        for Bk, Gk in bin_buckets:
            Gk8 = bucket_group_pad(Gk)
            blk = hist[roff:roff + Bk * Gk8]
            h4 = blk.reshape(Bk, Gk8, K, 2, S)[:, :Gk].transpose(2, 4, 1, 0, 3)
            if Bk < bmax:
                h4 = jnp.pad(h4, ((0, 0), (0, 0), (0, 0),
                                  (0, bmax - Bk), (0, 0)))
            parts4.append(h4[:, :, :, :bmax, :])
            roff += Bk * Gk8
        hist4 = jnp.concatenate(parts4, axis=2)
        if K == 1:
            hist4 = hist4[0]
        return new_leaf, hist4, _cnt_out(cnt)
    # (B*G, 2*S*K) b-major rows -> (K, S, G, Bmax, 2); int histograms are
    # unscaled by the caller
    hist4 = hist.reshape(B, G, K, 2, S).transpose(2, 4, 1, 0, 3)[
        :, :, :, :bmax, :]
    if K == 1:
        hist4 = hist4[0]
    return new_leaf, hist4, _cnt_out(cnt)


def _route_replay_kernel(nr_ref, bins_ref, tabs_ref, newleaf_ref, *,
                         T: int, L: int, GW: int, u8_layout: bool,
                         f32_dots: bool):
    """Fused full-data route REPLAY (GOSS+stream fusion, docs/PERF.md):
    starting from leaf 0, apply every stored round table in sequence to
    this row block in ONE kernel launch — bins stream from HBM ONCE per
    tree instead of once per round.  The trip count is the tree's ACTUAL
    round count (scalar-prefetched), so replay compute matches the sum of
    the per-round route-only passes it replaces; the table buffer's unused
    zero rows are exact no-op steps (chosen=0 keeps every lid) and are
    never executed.  Routing math is the shared _route_step — bit-identical
    to the per-round passes by construction."""
    i32, f32 = jnp.int32, jnp.float32
    bf16 = f32 if f32_dots else jnp.bfloat16
    l_iota = jax.lax.broadcasted_iota(i32, (L, T), 0)
    bins32 = bins_ref[...].astype(i32) if u8_layout else None
    n_rounds = nr_ref[0]

    def step(r, lid):
        tab = tabs_ref[pl.ds(r * NUM_TAB, NUM_TAB), :]       # (NUM_TAB, L)
        leaf_oh = (l_iota == lid).astype(bf16)
        vals = jax.lax.dot_general(
            tab, leaf_oh, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                      # (NUM_TAB, T)
        iv = vals.astype(i32)
        chosen_i, newid, _, go_left_i, _, _, _ = _route_step(
            iv, bins_ref, bins32, GW, T, u8_layout)
        return jnp.where(chosen_i * (1 - go_left_i) > 0, newid, lid)

    lid0 = jnp.zeros((1, T), i32)
    newleaf_ref[0:1, :] = jax.lax.fori_loop(0, n_rounds, step, lid0)


@functools.partial(watched_jit, name="route_replay", warn_after=0,
                   static_argnames=("num_leaves", "block_rows",
                                    "rounds_buf"))
def route_replay(bins_T: jax.Array, tabs_buf: jax.Array, n_rounds: jax.Array,
                 num_leaves: int, block_rows: int = 1024,
                 rounds_buf: int = 0) -> jax.Array:
    """Replay the stored per-round route tables over ALL rows.

    bins_T: (GW_pad, N_pad) i32 / (G_pad, N_pad) i8 from pack_bins_T.
    tabs_buf: (rounds_buf * NUM_TAB, L) f32 — round r's build_route_tables
    block at rows [r*NUM_TAB, (r+1)*NUM_TAB); untouched rounds are zeros.
    n_rounds: () i32 — dynamic replay trip count (the grown tree's actual
    round count; scalar-prefetched into the kernel's fori_loop bound).

    Returns the final (N_pad,) i32 leaf id of every row — bit-identical to
    the chain of per-round route-only route_and_hist passes it fuses
    (categorical splits are not supported; the grow layer gates fusion off
    when the tree may contain one)."""
    GW, n_pad = bins_T.shape
    T = block_rows
    NB = n_pad // T
    L = num_leaves
    if rounds_buf <= 0:
        rounds_buf = tabs_buf.shape[0] // NUM_TAB
    u8_layout = bins_T.dtype == jnp.int8
    out = pl.pallas_call(
        functools.partial(_route_replay_kernel, T=T, L=L, GW=GW,
                          u8_layout=u8_layout, f32_dots=_interp()),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(NB,),
            in_specs=[
                pl.BlockSpec((GW, T), lambda b, nr: (0, b)),
                pl.BlockSpec((rounds_buf * NUM_TAB, L),
                             lambda b, nr: (0, 0)),
            ],
            out_specs=pl.BlockSpec((1, T), lambda b, nr: (0, b)),
        ),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=_interp(),
    )(jnp.asarray(n_rounds, jnp.int32).reshape(1), bins_T, tabs_buf)
    return out.reshape(-1)


def _leaf_gather_kernel(lid_ref, val_ref, out_ref, *, T, L):
    i32, f32 = jnp.int32, jnp.float32
    lid = lid_ref[0:1, :]
    l_iota = jax.lax.broadcasted_iota(i32, (L, T), 0)
    oh = (l_iota == lid).astype(f32)                         # (L, T)
    # exactly one nonzero (1.0 * v) term per output column, so the f32 dot
    # is BIT-EXACT — and at M=1 it is far off the critical path
    out_ref[0:1, :] = jax.lax.dot_general(
        val_ref[0:1, :], oh, (((1,), (0,)), ((), ())),
        preferred_element_type=f32)


@functools.partial(watched_jit, name="leaf_gather", warn_after=0,
                   static_argnames=("block_rows",))
def leaf_gather(leaf_id: jax.Array, values: jax.Array,
                block_rows: int = 1024) -> jax.Array:
    """values[leaf_id] as a streaming one-hot contraction (bit-exact).

    XLA lowers small-table gathers over millions of rows to its generic
    (slow, ~100M rows/s) gather; a (1, L) @ (L, T) one-hot dot runs at
    streaming bandwidth instead.  Each output picks exactly one 1.0*value
    product, so the f32 contraction reproduces values[leaf_id] exactly.
    Reference analog: ScoreUpdater::AddScore (score_updater.hpp)."""
    N = leaf_id.shape[0]
    L = values.shape[0]
    T = block_rows
    n_pad = -(-N // T) * T
    lid = jnp.pad(leaf_id.astype(jnp.int32), (0, n_pad - N)).reshape(1, -1)
    out = pl.pallas_call(
        functools.partial(_leaf_gather_kernel, T=T, L=L),
        grid=(n_pad // T,),
        in_specs=[
            pl.BlockSpec((1, T), lambda b: (0, b)),
            pl.BlockSpec((1, L), lambda b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, T), lambda b: (0, b)),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interp(),
    )(lid, values.reshape(1, L).astype(jnp.float32))
    return out.reshape(-1)[:N]


def build_route_tables(leaf_chosen, leaf_feat, leaf_thr, leaf_dir, leaf_newid,
                       slot_left1, slot_right1, slot_keep1, routing,
                       num_leaves: int):
    """Assemble the (NUM_TAB, L) f32 per-leaf split tables from this round's
    chosen splits; all inputs are (L,) arrays except `routing` (RoutingLayout).

    slot_*1 are histogram-slot indices +1 (0 means "no histogram")."""
    L = num_leaves
    f32 = jnp.float32
    feat = leaf_feat.astype(jnp.int32)
    grp = routing.feat_group[feat]
    word = grp >> 2
    shift = (grp & 3) << 3
    nan_bin = routing.nan_bin[feat]
    newid_lo, newid_hi = _digits(leaf_newid)
    word_lo, word_hi = _digits(word)
    rows = jnp.zeros((NUM_TAB, L), f32)
    rows = rows.at[T_CHOSEN].set(leaf_chosen.astype(f32))
    rows = rows.at[T_NEWID_LO].set(newid_lo).at[T_NEWID_HI].set(newid_hi)
    rows = rows.at[T_WORD_LO].set(word_lo).at[T_WORD_HI].set(word_hi)
    rows = rows.at[T_SHIFT].set(shift.astype(f32))
    rows = rows.at[T_SPAN].set(routing.span_start[feat].astype(f32))
    rows = rows.at[T_DEFBIN].set(routing.default_bin[feat].astype(f32))
    rows = rows.at[T_BUNDLED].set(routing.bundled[feat].astype(f32))
    rows = rows.at[T_HASNAN].set((nan_bin >= 0).astype(f32))
    rows = rows.at[T_NANBIN].set(jnp.maximum(nan_bin, 0).astype(f32))
    rows = rows.at[T_NBINS].set(routing.num_bins[feat].astype(f32))
    rows = rows.at[T_THR].set(leaf_thr.astype(f32))
    rows = rows.at[T_DEFLEFT].set(((leaf_dir & 1) != 0).astype(f32))
    rows = rows.at[T_ISCAT].set(((leaf_dir & 2) != 0).astype(f32))
    rows = rows.at[T_SLOT_L].set(slot_left1.astype(f32))
    rows = rows.at[T_SLOT_R].set(slot_right1.astype(f32))
    rows = rows.at[T_SLOT_KEEP].set(slot_keep1.astype(f32))
    mzb = (routing.mzero_bin[feat] if routing.mzero_bin is not None
           else jnp.full_like(feat, -1))
    rows = rows.at[T_HASMZ].set((mzb >= 0).astype(f32))
    rows = rows.at[T_MZBIN].set(jnp.maximum(mzb, 0).astype(f32))
    return rows
