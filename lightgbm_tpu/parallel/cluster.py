"""Local multi-process cluster launcher — the Dask orchestration analog.

Reference: python-package/lightgbm/dask.py — `_train` (dask.py:124-215)
discovers the workers holding data parts, assembles the `machines=` list,
opens ports, and runs `_train_part` on every worker; the model of one worker
becomes the result. The TPU-native redesign:

  * worker discovery / machines list  -> a free localhost port +
    `jax.distributed.initialize` (the process mesh IS the cluster)
  * `client.scatter` of data parts    -> sharded FILE ingest: every rank
    loads only its own row range (parallel/dist_data.py; queries stay
    whole on one rank for ranking)
  * `_train_part` per worker          -> the SAME SPMD `lgb.train` call in
    every process with `tree_learner=data|feature|voting`
  * result from one worker            -> rank 0 serializes the model (all
    ranks hold identical trees — histogram psum makes training replicated)

`train_distributed` below packages that recipe: it spawns N local worker
processes (one per CPU device group — the same topology the multi-host
tests and the driver's `dryrun_multichip` validate), trains over the file
shards, and returns the finished Booster in the parent process. On a real
TPU pod, run the body yourself instead: one process per host executing
`lgb.init_distributed()` + `lgb.train(...)` (see parallel/launcher.py) —
there is deliberately no pod-ssh automation here.

The sklearn-style `DaskLGBM{Classifier,Regressor,Ranker}` wrappers are NOT
mirrored: they exist to adapt dask collections to sklearn's fit(X, y), but
the scatter mechanism here is file sharding, so the natural unit is the
data path + params dict that `train_distributed` already takes.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..utils.log import LightGBMError, log_info

_WORKER = r"""
import json, os, sys
spec = json.load(open(sys.argv[1]))
rank = int(sys.argv[2])
os.environ.pop("XLA_FLAGS", None)
os.environ["JAX_PLATFORMS"] = spec["platform"]
import jax
jax.config.update("jax_platforms", spec["platform"])
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
jax.distributed.initialize(spec["coordinator"], num_processes=spec["nproc"],
                           process_id=rank)
if spec.get("cache_dir"):
    jax.config.update("jax_compilation_cache_dir", spec["cache_dir"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import lightgbm_tpu as lgb
ds = lgb.Dataset(spec["data"])
valid_sets = [lgb.Dataset(p, reference=ds) for p in spec["valid"]]
evals = {}
bst = lgb.train(spec["params"], ds, num_boost_round=spec["rounds"],
                valid_sets=valid_sets,
                valid_names=spec["valid_names"] or None,
                callbacks=[lgb.record_evaluation(evals)] if valid_sets else None)
if rank == 0:
    out = {"model": bst.model_to_string(), "evals": evals,
           "best_iteration": bst.best_iteration}
    import lightgbm_tpu.telemetry as _tel
    if _tel.enabled():   # however the params spelled it (aliases, sinks)
        out["telemetry"] = bst.telemetry_summary()
    json.dump(out, open(sys.argv[3], "w"))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def train_distributed(params: Dict[str, Any], data_path: str,
                      num_boost_round: int = 100,
                      num_processes: int = 2,
                      valid_paths: Optional[List[str]] = None,
                      valid_names: Optional[List[str]] = None,
                      platform: str = "cpu",
                      timeout: float = 1200.0,
                      python: str = sys.executable):
    """Train over `num_processes` local worker processes, each ingesting its
    own row shard of `data_path` (and of each `valid_paths` entry), and
    return the finished Booster.

    The dask.py `_train` analog for one machine: workers connect through
    `jax.distributed`, shard the file by rows (whole query groups per rank
    for ranking objectives), and run the standard data-parallel SPMD
    training program. Defaults to `tree_learner=data` when params don't
    choose one. `evals_result_` and `best_iteration` from rank 0 are set on
    the returned Booster."""
    if num_processes < 2:
        raise LightGBMError("train_distributed needs num_processes >= 2; "
                            "call lgb.train directly for one process")
    if not Path(data_path).exists():
        raise LightGBMError(f"data_path not found: {data_path}")
    params = dict(params)
    params.setdefault("tree_learner", "data")
    spec = {
        "coordinator": f"localhost:{_free_port()}",
        "nproc": num_processes,
        "platform": platform,
        "cache_dir": "/tmp/lgb_tpu_jax_cache",
        "params": params,
        "data": str(data_path),
        "valid": [str(p) for p in (valid_paths or [])],
        "valid_names": list(valid_names) if valid_names else None,
        "rounds": int(num_boost_round),
    }
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = platform
    repo = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    with tempfile.TemporaryDirectory(prefix="lgb_tpu_cluster_") as td:
        spec_path = os.path.join(td, "spec.json")
        out_path = os.path.join(td, "result.json")
        with open(spec_path, "w") as fh:
            json.dump(spec, fh)
        procs = [subprocess.Popen(
            [python, "-c", _WORKER, spec_path, str(r), out_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for r in range(num_processes)]
        outs = []
        try:
            for p in procs:
                outs.append(p.communicate(timeout=timeout)[0].decode())
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, o) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise LightGBMError(
                    f"worker {r}/{num_processes} failed "
                    f"(exit {p.returncode}):\n{o[-4000:]}")
        with open(out_path) as fh:
            result = json.load(fh)
    from ..basic import Booster
    bst = Booster(model_str=result["model"])
    bst.evals_result_ = result["evals"]
    if result.get("best_iteration"):
        bst.best_iteration = result["best_iteration"]
    if result.get("telemetry"):
        # rank 0's telemetry rollup (iteration records, straggler reports,
        # recompiles); Booster.telemetry_summary() answers from this when
        # set, since the driver process's own registry saw no training
        bst.telemetry_summary_ = result["telemetry"]
    log_info(f"train_distributed: {num_processes} workers done, "
             f"{bst.num_trees()} trees")
    return bst
