"""Local multi-process cluster launcher — the Dask orchestration analog.

Reference: python-package/lightgbm/dask.py — `_train` (dask.py:124-215)
discovers the workers holding data parts, assembles the `machines=` list,
opens ports, and runs `_train_part` on every worker; the model of one worker
becomes the result. The TPU-native redesign:

  * worker discovery / machines list  -> a free localhost port +
    `jax.distributed.initialize` (the process mesh IS the cluster)
  * `client.scatter` of data parts    -> sharded FILE ingest: every rank
    loads only its own row range (parallel/dist_data.py; queries stay
    whole on one rank for ranking)
  * `_train_part` per worker          -> the SAME SPMD `lgb.train` call in
    every process with `tree_learner=data|feature|voting`
  * result from one worker            -> rank 0 serializes the model (all
    ranks hold identical trees — histogram psum makes training replicated)

`train_distributed` below packages that recipe as a SUPERVISOR (the
reference Network layer survives flaky links; this survives flaky
processes, docs/ROBUSTNESS.md):

  * all worker processes are polled CONCURRENTLY — the first nonzero exit
    kills the peers and fails the attempt immediately instead of blocking
    on rank order until the full timeout;
  * every worker heartbeats a per-rank file each iteration
    (robustness/heartbeat.py); a stale beat past ``hang_timeout`` reaps a
    worker wedged inside a collective;
  * with ``dist_retries > 0`` a failed cohort is relaunched (backoff
    ``dist_backoff`` seconds, doubling per retry) from the NEWEST VALID
    snapshot rank 0 wrote (``snapshot_freq`` checkpoints), resuming
    bit-identically instead of losing the run.

On a real TPU pod, run the body yourself instead: one process per host
executing `lgb.init_distributed()` + `lgb.train(...)` (see
parallel/launcher.py) — there is deliberately no pod-ssh automation here.

The sklearn-style `DaskLGBM{Classifier,Regressor,Ranker}` wrappers are NOT
mirrored: they exist to adapt dask collections to sklearn's fit(X, y), but
the scatter mechanism here is file sharding, so the natural unit is the
data path + params dict that `train_distributed` already takes.
"""
from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

from ..config import resolve_aliases
from ..utils.log import LightGBMError, log_info, log_warning

_WORKER = r"""
import json, os, sys
spec = json.load(open(sys.argv[1]))
rank = int(sys.argv[2])
os.environ.pop("XLA_FLAGS", None)
os.environ["JAX_PLATFORMS"] = spec["platform"]
import jax
jax.config.update("jax_platforms", spec["platform"])
if spec["platform"] == "cpu":
    try:  # cross-process CPU collectives (older jax: option absent)
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
jax.distributed.initialize(spec["coordinator"], num_processes=spec["nproc"],
                           process_id=rank)
if spec.get("cache_dir"):
    jax.config.update("jax_compilation_cache_dir", spec["cache_dir"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
import lightgbm_tpu as lgb
from lightgbm_tpu.robustness.heartbeat import heartbeat_callback
ds = lgb.Dataset(spec["data"])
valid_sets = [lgb.Dataset(p, reference=ds) for p in spec["valid"]]
evals = {}
cbs = [lgb.record_evaluation(evals)] if valid_sets else []
cbs.append(heartbeat_callback(
    os.path.join(spec["heartbeat_dir"], "hb_%d" % rank)))
bst = lgb.train(spec["params"], ds, num_boost_round=spec["rounds"],
                valid_sets=valid_sets,
                valid_names=spec["valid_names"] or None,
                callbacks=cbs)
if rank == 0:
    out = {"model": bst.model_to_string(), "evals": evals,
           "best_iteration": bst.best_iteration}
    import lightgbm_tpu.telemetry as _tel
    if _tel.enabled():   # however the params spelled it (aliases, sinks)
        out["telemetry"] = bst.telemetry_summary()
    tmp = sys.argv[3] + ".tmp"
    json.dump(out, open(tmp, "w"))
    os.replace(tmp, sys.argv[3])
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _tail(path: str, n: int = 4000) -> str:
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - n))
            return fh.read().decode(errors="replace")
    except OSError:
        return "<no worker log>"


def _run_cohort(spec: Dict[str, Any], td: str, out_path: str, attempt: int,
                timeout: float, hang_timeout: Optional[float],
                startup_grace: float, python: str,
                env: Dict[str, str]) -> Optional[str]:
    """Launch one worker cohort and babysit it to completion.

    Returns None on success or a failure description.  All processes are
    polled together: the first nonzero exit — or a heartbeat gone stale
    past ``hang_timeout`` — kills every peer at once (the old behavior
    awaited rank 0 first, so a crashed rank 1 left the driver blocked for
    the full timeout)."""
    n = spec["nproc"]
    spec_path = os.path.join(td, f"spec_{attempt}.json")
    # atomic: a worker that starts early must never read a half-written spec
    from ..robustness.checkpoint import atomic_open
    with atomic_open(spec_path, "w") as fh:
        json.dump(spec, fh)
    for r in range(n):
        for stale in (out_path, os.path.join(td, f"hb_{r}")):
            if os.path.exists(stale):
                os.unlink(stale)
    log_paths = [os.path.join(td, f"worker_{r}.log") for r in range(n)]
    logs = [open(p, "ab") for p in log_paths]
    procs = [subprocess.Popen(
        [python, "-c", _WORKER, spec_path, str(r), out_path],
        env=env, stdout=logs[r], stderr=subprocess.STDOUT)
        for r in range(n)]
    start = time.monotonic()
    err: Optional[str] = None
    try:
        while True:
            rcs = [p.poll() for p in procs]
            bad = next(((r, rc) for r, rc in enumerate(rcs)
                        if rc not in (None, 0)), None)
            if bad is not None:
                err = (f"worker {bad[0]}/{n} failed (exit {bad[1]}):\n"
                       f"{_tail(log_paths[bad[0]])}")
                break
            if all(rc == 0 for rc in rcs):
                break
            elapsed = time.monotonic() - start
            if elapsed > timeout:
                err = f"cohort timed out after {timeout:.0f}s"
                break
            if hang_timeout is not None:
                now = time.time()
                for r in range(n):
                    if rcs[r] is not None:
                        continue
                    hb = os.path.join(td, f"hb_{r}")
                    if os.path.exists(hb):
                        age = now - os.path.getmtime(hb)
                        if age > hang_timeout:
                            err = (f"worker {r}/{n} heartbeat stale "
                                   f"({age:.0f}s > hang_timeout="
                                   f"{hang_timeout:.0f}s); presumed hung")
                            break
                    elif elapsed > max(startup_grace, hang_timeout):
                        err = (f"worker {r}/{n} produced no heartbeat "
                               f"within {elapsed:.0f}s; presumed hung "
                               "during startup")
                        break
                if err:
                    break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        for f in logs:
            f.close()
    return err


def train_distributed(params: Dict[str, Any], data_path: str,
                      num_boost_round: int = 100,
                      num_processes: int = 2,
                      valid_paths: Optional[List[str]] = None,
                      valid_names: Optional[List[str]] = None,
                      platform: str = "cpu",
                      timeout: float = 1200.0,
                      python: str = sys.executable,
                      hang_timeout: Optional[float] = None,
                      startup_grace: float = 180.0):
    """Train over `num_processes` local worker processes, each ingesting its
    own row shard of `data_path` (and of each `valid_paths` entry), and
    return the finished Booster.

    The dask.py `_train` analog for one machine, run under a supervisor:
    workers connect through `jax.distributed`, shard the file by rows
    (whole query groups per rank for ranking objectives), and run the
    standard data-parallel SPMD training program. Defaults to
    `tree_learner=data` when params don't choose one. `evals_result_` and
    `best_iteration` from rank 0 are set on the returned Booster.

    Fault tolerance (docs/ROBUSTNESS.md): `timeout` bounds each attempt;
    `hang_timeout` (seconds, None = off) reaps workers whose per-iteration
    heartbeat goes stale; params `dist_retries`/`dist_backoff` relaunch a
    failed cohort from the newest valid snapshot (rank 0 checkpoints every
    `snapshot_freq` iterations — defaulted on when retries are enabled)."""
    if num_processes < 2:
        raise LightGBMError("train_distributed needs num_processes >= 2; "
                            "call lgb.train directly for one process")
    if not Path(data_path).exists():
        raise LightGBMError(f"data_path not found: {data_path}")
    params = resolve_aliases(dict(params))
    params.setdefault("tree_learner", "data")
    retries = int(params.get("dist_retries", 0) or 0)
    backoff = float(params.get("dist_backoff", 2.0) or 0.0)
    if retries > 0:
        # retry without snapshots would replay the whole run — checkpoint
        # often enough that a relaunch loses at most ~10% of the work
        params.setdefault("snapshot_freq", max(1, num_boost_round // 10))
    td = tempfile.mkdtemp(prefix="lgb_tpu_cluster_")
    params.setdefault("output_model", os.path.join(td, "ckpt.txt"))
    output_model = str(params["output_model"])
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = platform
    env["PYTHONUNBUFFERED"] = "1"
    repo = str(Path(__file__).resolve().parents[2])
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    spec = {
        "nproc": num_processes,
        "platform": platform,
        "cache_dir": "/tmp/lgb_tpu_jax_cache",
        "params": dict(params),
        "data": str(data_path),
        "valid": [str(p) for p in (valid_paths or [])],
        "valid_names": list(valid_names) if valid_names else None,
        "rounds": int(num_boost_round),
        "heartbeat_dir": td,
    }
    out_path = os.path.join(td, "result.json")
    try:
        attempt = 0
        while True:
            # fresh port per attempt: the previous coordinator socket may
            # still be in TIME_WAIT
            spec["coordinator"] = f"localhost:{_free_port()}"
            err = _run_cohort(spec, td, out_path, attempt, timeout,
                              hang_timeout, startup_grace, python, env)
            if err is None:
                break
            attempt += 1
            if attempt > retries:
                raise LightGBMError(
                    f"train_distributed failed after {attempt} attempt(s) "
                    f"({retries} retries allowed): {err}")
            delay = backoff * (2 ** (attempt - 1))
            log_warning(f"train_distributed attempt {attempt}/{retries + 1} "
                        f"failed: {err.splitlines()[0]} — relaunching in "
                        f"{delay:.1f}s")
            if delay > 0:
                time.sleep(delay)
            from ..robustness.checkpoint import latest_valid_snapshot
            # params check included: a stale snapshot from an earlier run
            # with different training params would fail every worker's
            # load_checkpoint and burn all retries. Fall back to the
            # user's own resume_from (if any) when this run hasn't sealed
            # a newer snapshot yet — never silently discard a requested
            # continuation
            snap = (latest_valid_snapshot(output_model,
                                          params=spec["params"],
                                          expect_processes=num_processes)
                    or params.get("resume_from") or None)
            wp = dict(spec["params"])
            if snap is not None:
                wp["resume_from"] = snap
                log_info(f"train_distributed: cohort will resume from {snap}")
            else:
                wp.pop("resume_from", None)
                log_info("train_distributed: no valid snapshot; cohort "
                         "restarts from scratch")
            spec["params"] = wp
        with open(out_path) as fh:
            result = json.load(fh)
    finally:
        shutil.rmtree(td, ignore_errors=True)
    from ..basic import Booster
    bst = Booster(model_str=result["model"])
    bst.evals_result_ = result["evals"]
    if result.get("best_iteration"):
        bst.best_iteration = result["best_iteration"]
    if result.get("telemetry"):
        # rank 0's telemetry rollup (iteration records, straggler reports,
        # recompiles); Booster.telemetry_summary() answers from this when
        # set, since the driver process's own registry saw no training
        bst.telemetry_summary_ = result["telemetry"]
    log_info(f"train_distributed: {num_processes} workers done, "
             f"{bst.num_trees()} trees")
    return bst
