"""Data-parallel histogram comms: Reduce-Scatter + shard-local split finding.

Reference: src/treelearner/data_parallel_tree_learner.cpp:285-299 — the
data-parallel learner never all-reduces full histograms.  Each worker owns a
feature slice: histogram blocks are Reduce-Scattered, every worker finds the
best split over ITS features only, and the workers Allreduce nothing but tiny
SplitInfo records (gain, feature, threshold, default direction, left sums — a
few hundred bytes, vs the multi-MB histogram block).

GSPMD re-design (hist_comms=reduce_scatter, docs/DISTRIBUTED.md): inside the
same shard_map that runs the per-device streaming kernel,

  * the per-device histogram block is `jax.lax.psum_scatter` over the
    feature-GROUP axis, so each device receives only its G/D group slice —
    bitwise equal to the psum result restricted to the slice (XLA reduces
    contributions in rank order for both collectives);
  * split finding runs shard-locally on that slice through a per-shard
    static sub-FeatureLayout (built here, ordered by ascending global
    feature id so local argmax tie-breaks reproduce the global scan's
    lowest-feature-index rule);
  * only the per-shard best-split records are `all_gather`ed and combined
    with the exact (max gain, lowest feature id) tie-break — trees are
    BIT-IDENTICAL to the psum path.

`hist_comms_dtype=bf16_pair` additionally halves the wire payload: remote
contributions ride the HIGH half of the f32 high/low bf16 split (the same
two-pass trick the histogram kernel uses, pallas/hist_kernel._wsplit), each
device's own-slice contribution stays exact f32 (its low half never needed
the wire), and the cross-device accumulation runs in f32 — contributions are
quantized at most once and partial sums never round to bf16.  Opt-in: not
bit-identical to psum (the quantized-GBDT line of work shows histogram
payloads tolerate reduced wire precision).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.split import (EPS_HESS, NEG_INF, FeatureLayout,
                         categorical_left_bitset, find_best_splits,
                         gather_feature_histograms)

HIST_COMMS_MODES = ("psum", "reduce_scatter")
HIST_COMMS_DTYPES = ("f32", "bf16_pair")

_BIGF = jnp.int32(2 ** 30)


class ShardPlan(NamedTuple):
    """Static per-shard feature ownership for reduce_scatter comms.

    Groups are sliced contiguously: shard s owns groups [s*gs, (s+1)*gs) of
    the G axis padded to g_pad = d*gs; a feature belongs to the shard that
    owns its storage group (EFB bundles live entirely inside one group, so
    a feature never straddles shards).  The sub-layout stacks carry one row
    per shard (leading axis d), features sorted by ascending GLOBAL id and
    padded to fmax with never-matching entries."""
    d: int
    g_pad: int
    gs: int                    # groups per shard
    fmax: int                  # max features owned by any shard (>= 1)
    feat_gid: np.ndarray       # (d, fmax) i32 global feature id, -1 pad
    gather_idx: np.ndarray     # (d, fmax, Bmax) i32 into flat (gs * Bmax)
    valid_mask: np.ndarray     # (d, fmax, Bmax) bool
    residual_pos: np.ndarray   # (d, fmax) i32
    nan_bin: np.ndarray        # (d, fmax) i32
    is_cat: np.ndarray         # (d, fmax) bool
    num_bins: np.ndarray       # (d, fmax) i32
    mzero_bin: Optional[np.ndarray]  # (d, fmax) i32 or None


def build_shard_plan(layout: FeatureLayout, routing, num_groups: int,
                     bmax: int, d: int) -> ShardPlan:
    """Slice the training FeatureLayout into d per-shard sub-layouts."""
    try:
        gather_idx = np.asarray(layout.gather_idx)
        valid_mask = np.asarray(layout.valid_mask)
        residual_pos = np.asarray(layout.residual_pos)
        nan_bin = np.asarray(layout.nan_bin)
        is_cat = np.asarray(layout.is_cat)
        num_bins = np.asarray(layout.num_bins)
        mzero = (np.asarray(layout.mzero_bin)
                 if layout.mzero_bin is not None else None)
        feat_group = np.asarray(routing.feat_group)
    except Exception as e:  # traced layouts cannot be sliced statically
        raise ValueError(
            "hist_comms=reduce_scatter needs concrete (non-traced) feature "
            f"layouts: {e}") from e
    F = gather_idx.shape[0]
    gs = -(-num_groups // d)
    g_pad = gs * d
    shard_of = feat_group[:F] // gs
    fmax = 1
    per_shard = []
    for s in range(d):
        ids = np.where(shard_of == s)[0].astype(np.int32)  # ascending ids
        per_shard.append(ids)
        fmax = max(fmax, len(ids))

    def stack(src, pad, dtype):
        out = np.full((d, fmax) + src.shape[1:], pad, dtype)
        for s, ids in enumerate(per_shard):
            out[s, :len(ids)] = src[ids]
        return out

    # group-local gather: subtract the shard's flat offset (every entry of
    # feature f indexes inside group feat_group[f]'s Bmax span)
    local_gidx = gather_idx - (shard_of * gs * bmax)[:, None]
    return ShardPlan(
        d=d, g_pad=g_pad, gs=gs, fmax=fmax,
        feat_gid=stack(np.arange(F, dtype=np.int32), -1, np.int32),
        gather_idx=stack(local_gidx.astype(np.int32), 0, np.int32),
        valid_mask=stack(valid_mask, False, bool),
        residual_pos=stack(residual_pos.astype(np.int32), -1, np.int32),
        nan_bin=stack(nan_bin.astype(np.int32), -1, np.int32),
        is_cat=stack(is_cat, False, bool),
        num_bins=stack(num_bins.astype(np.int32), 1, np.int32),
        mzero_bin=(stack(mzero.astype(np.int32), -1, np.int32)
                   if mzero is not None else None),
    )


def _local_layout(plan: ShardPlan, gi, vm, rp, nb, ic, nbins, mz
                  ) -> FeatureLayout:
    return FeatureLayout(
        gather_idx=gi[0], valid_mask=vm[0], residual_pos=rp[0],
        nan_bin=nb[0], is_cat=ic[0], num_bins=nbins[0],
        mzero_bin=(mz[0] if mz is not None else None))


def _plan_args(plan: ShardPlan):
    args = [plan.feat_gid, plan.gather_idx, plan.valid_mask,
            plan.residual_pos, plan.nan_bin, plan.is_cat, plan.num_bins]
    if plan.mzero_bin is not None:
        args.append(plan.mzero_bin)
    return [jnp.asarray(a) for a in args]


def reduce_hist(h: jax.Array, axis: str, g_dim: int, plan: ShardPlan,
                dtype: str = "f32", chunks: int = 1) -> jax.Array:
    """Reduce-Scatter the per-device histogram block over the group axis.

    Called INSIDE shard_map: h is this device's local block with
    h.shape[g_dim] == num_groups; returns the device's reduced
    (g_pad / d)-group slice.  dtype="f32" is one `psum_scatter`, bitwise
    equal to `psum` restricted to the slice; "bf16_pair" exchanges remote
    contributions as the high bf16 half (half the wire bytes), keeps the
    own-slice contribution exact f32, and accumulates in f32.

    ``chunks`` > 1 DOUBLE-BUFFERS the exact-wire collective (f32 / int32
    psum_scatter; the bf16_pair path pipelines through its all_to_all
    instead and ignores the knob — the engine resolves chunks=1 there):
    the slot axis (dim 0 —
    the round's child-slot channels, independent of the scatter's group
    axis) is split into ``chunks`` independent ``psum_scatter`` calls, so
    the XLA latency-hiding scheduler can start chunk 0's wire transfer
    while chunk 1's operand copy/packing still runs, and downstream
    consumers of already-delivered chunks overlap the tail (the classic
    comms/compute pipeline of pjit training stacks).  Each element rides
    the SAME rank-ordered reduction either way, so any chunking is
    bitwise identical to chunks=1 (asserted by the A/B suite)."""
    G = h.shape[g_dim]
    if plan.g_pad != G:
        pad = [(0, 0)] * h.ndim
        pad[g_dim] = (0, plan.g_pad - G)
        h = jnp.pad(h, pad)
    if dtype == "f32" or jnp.issubdtype(h.dtype, jnp.integer):
        # int32 quantized-gradient histograms are already the compressed,
        # exactly-summable wire format — bf16_pair would only lose bits
        n_slots = h.shape[0]
        if chunks > 1 and n_slots >= 2 * chunks:
            cut = n_slots // chunks
            parts = []
            for c in range(chunks):
                lo = c * cut
                hi = n_slots if c == chunks - 1 else lo + cut
                with jax.named_scope(f"hist_reduce_scatter_c{c}"):
                    parts.append(jax.lax.psum_scatter(
                        h[lo:hi], axis, scatter_dimension=g_dim,
                        tiled=True))
            return jnp.concatenate(parts, axis=0)
        with jax.named_scope("hist_reduce_scatter"):
            return jax.lax.psum_scatter(h, axis, scatter_dimension=g_dim,
                                        tiled=True)
    # bf16_pair: chunk the group axis per destination shard, ship the high
    # bf16 half, restore the exact f32 own-chunk, reduce in f32 rank order
    shape = h.shape
    hr = h.reshape(shape[:g_dim] + (plan.d, plan.gs) + shape[g_dim + 1:])
    with jax.named_scope("hist_all_to_all_bf16"):
        recv = jax.lax.all_to_all(hr.astype(jnp.bfloat16), axis,
                                  split_axis=g_dim, concat_axis=g_dim)
    me = jax.lax.axis_index(axis)
    own = jax.lax.dynamic_slice_in_dim(hr, me, 1, axis=g_dim)
    contrib = jax.lax.dynamic_update_slice_in_dim(
        recv.astype(jnp.float32), own, me, axis=g_dim)
    return jnp.sum(contrib, axis=g_dim)


def pack_gh_wire(h: jax.Array, axis: str, width: int, d: int):
    """Quantize-and-pack an int32 (…, 2) grad/hess histogram block into ONE
    integer lane per pair for the cross-device collective (hist_packed_width;
    reference contract: gradient_discretizer.cpp keeps quality with 16-bit
    packed accumulators on the wire).

    Called INSIDE shard_map on each device's exact int32 partial sums.
    width=16 packs the pair into one int32 lane (grad in the signed high 16
    bits, hess in the unsigned low 16) — HALF the wire bytes of the two-lane
    int32 block; width=8 packs into one int16 lane (8+8) — a QUARTER.

    Requantization is a shared power-of-two right shift chosen from the
    cross-device abs-max (`pmax`) so that d device partials sum without
    overflowing their field, and the hess field's sum stays < 2**hbits —
    carry-free into the signed grad field above it (hessian grid sums are
    non-negative for every supported objective).  A pow2 shift of integers
    with round-half-away is deterministic regardless of stochastic_rounding
    upstream, and is exact (shift 0) whenever the block magnitudes fit the
    field — the documented-ulp contract of the packed widths.

    Returns (packed, scales) with scales=(s_g, s_h) f32 pow2 factors the
    matching :func:`unpack_gh_wire` multiplies back after the collective."""
    g = h[..., 0]
    hh = h[..., 1]
    gbits, hbits = (15, 16) if width == 16 else (7, 8)
    # -8 margin: the f32 log2 bound below may round the int32 max down
    cap_g = (2 ** gbits - 8) // d
    cap_h = (2 ** hbits - 8) // d
    mg = jnp.max(jnp.abs(g)).astype(jnp.float32)
    mh = jnp.max(hh).astype(jnp.float32)
    if axis is not None:
        mg = jax.lax.pmax(mg, axis)
        mh = jax.lax.pmax(mh, axis)

    def _shift(m, cap):
        sh = jnp.ceil(jnp.log2(jnp.maximum(m, 1.0) / cap))
        return jnp.maximum(sh, 0.0).astype(jnp.int32)

    def _rshift_round(v, sh):
        half = jnp.where(sh > 0, (1 << jnp.maximum(sh - 1, 0)), 0)
        q = (jnp.abs(v) + half) >> sh
        return jnp.sign(v) * q

    sh_g, sh_h = _shift(mg, cap_g), _shift(mh, cap_h)
    gq = _rshift_round(g, sh_g)
    hq = _rshift_round(hh, sh_h)
    if width == 16:
        packed = gq * 65536 + hq
    else:
        packed = (gq * 256 + hq).astype(jnp.int16)
    scales = jnp.stack([jnp.exp2(sh_g.astype(jnp.float32)),
                        jnp.exp2(sh_h.astype(jnp.float32))])
    return packed, scales


def unpack_gh_wire(packed: jax.Array, scales: jax.Array,
                   width: int) -> jax.Array:
    """Inverse of :func:`pack_gh_wire` AFTER the summing collective: split
    the carry-free fields back out (floored mod keeps the low field
    non-negative; the high field's floor division is exact) and multiply the
    pow2 scales back, returning the usual f32 (…, 2) grid-valued block."""
    base = 65536 if width == 16 else 256
    p = packed.astype(jnp.int32)
    hq = jnp.mod(p, base)
    gq = (p - hq) // base
    return jnp.stack([gq.astype(jnp.float32) * scales[0],
                      hq.astype(jnp.float32) * scales[1]], axis=-1)


def make_sharded_finder(mesh, axis: str, plan: ShardPlan, scan_kw: dict):
    """shard_map-wrapped shard-local split finder.

    Returns find(hist, parent_g, parent_h, parent_c, col_mask) where hist
    is the GLOBAL (R, g_pad, Bmax, 2) histogram array sharded over its
    group axis; the result is a replicated 7-tuple (gain, feature,
    threshold, dir_flags, left_g, left_h, left_c) equal field-for-field to
    the full-F find_best_splits scan: each shard scans only its own
    features, and the tiny per-shard best records are all_gathered and
    combined with the exact (max gain, lowest global feature id)
    tie-break."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows

    has_mz = plan.mzero_bin is not None
    fmax = plan.fmax

    def _local(hist_s, pg, ph, pc, col_mask, fg, gi, vm, rp, nb, ic,
               nbins, *mz):
        sub = _local_layout(plan, gi, vm, rp, nb, ic, nbins,
                            mz[0] if has_mz else None)
        fg0 = fg[0]                                      # (fmax,)
        R = hist_s.shape[0]
        cm = col_mask[jnp.maximum(fg0, 0)] & (fg0 >= 0)
        with jax.named_scope("find_splits_shard_local"):
            res = find_best_splits(
                hist_s, pg, ph, pc, layout=sub,
                col_mask=jnp.broadcast_to(cm[None, :], (R, fmax)),
                **scan_kw)
        has = res.gain > NEG_INF / 2
        gfeat = jnp.where(has, fg0[res.feature], _BIGF)
        fstack = jnp.stack([res.gain, res.left_sum_g, res.left_sum_h,
                            res.left_count], axis=0)     # (4, R) f32
        istack = jnp.stack([gfeat, res.threshold, res.dir_flags], axis=0)
        with jax.named_scope("best_split_allgather"):
            gf = jax.lax.all_gather(fstack, axis)        # (D, 4, R)
            gi_ = jax.lax.all_gather(istack, axis)       # (D, 3, R)
        gains, feats = gf[:, 0], gi_[:, 0]
        # exact global-scan tie-break: max gain, then lowest feature id
        maxg = jnp.max(gains, axis=0)                    # (R,)
        cand = gains == maxg
        fsel = jnp.min(jnp.where(cand, feats, _BIGF), axis=0)
        pick = cand & (feats == fsel)
        dsel = jnp.argmax(pick, axis=0)                  # (R,) owner shard
        ar = jnp.arange(gains.shape[1])
        gain = gf[dsel, 0, ar]
        none = gain <= NEG_INF / 2
        feature = jnp.where(none, 0, fsel)               # argmax-of-empty = 0
        return (gain, feature.astype(jnp.int32),
                gi_[dsel, 1, ar].astype(jnp.int32),
                gi_[dsel, 2, ar].astype(jnp.int32),
                gf[dsel, 1, ar], gf[dsel, 2, ar], gf[dsel, 3, ar])

    rep = P()
    n_plan = 8 if has_mz else 7
    wrapped = shard_map_rows(
        _local, mesh,
        (P(None, axis, None, None), rep, rep, rep, rep)
        + (P(axis),) * n_plan,
        (rep,) * 7)
    plan_args = _plan_args(plan)

    def find(hist, pg, ph, pc, col_mask):
        return wrapped(hist, pg, ph, pc, col_mask, *plan_args)

    return find


def make_sharded_bitset(mesh, axis: str, plan: ShardPlan, cat_smooth: float,
                        min_data_per_group: int):
    """shard_map-wrapped categorical left-bitset: the OWNER shard of each
    chosen split's feature recomputes the (Bmax,) membership mask from its
    local histogram slice — identical arithmetic to the replicated path —
    and a tiny masked psum replicates it (S * Bmax floats, vs shipping the
    whole histogram block to every device)."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows

    has_mz = plan.mzero_bin is not None

    def _local(hist_s, feat, thr, dirf, pg, ph, pc, fg, gi, vm, rp, nb,
               ic, nbins, *mz):
        sub = _local_layout(plan, gi, vm, rp, nb, ic, nbins,
                            mz[0] if has_mz else None)
        fg0 = fg[0]
        R = hist_s.shape[0]
        ar = jnp.arange(R)
        own_f = fg0[None, :] == feat[:, None]            # (R, fmax)
        owned = jnp.any(own_f, axis=1)
        lfi = jnp.argmax(own_f, axis=1)                  # local feature idx
        hf = gather_feature_histograms(hist_s, sub, pg, ph)
        hf_feat = hf[ar, lfi]                            # (R, Bmax, 2)
        bitset = categorical_left_bitset(
            hf_feat, thr, dirf, sub.valid_mask[lfi], cat_smooth,
            min_data_per_group, pc / jnp.maximum(ph, EPS_HESS))
        with jax.named_scope("cat_bitset_psum"):
            out = jax.lax.psum(
                jnp.where(owned[:, None] & bitset, 1.0, 0.0), axis)
        return out > 0.5

    rep = P()
    n_plan = 8 if has_mz else 7
    wrapped = shard_map_rows(
        _local, mesh,
        (P(None, axis, None, None),) + (rep,) * 6 + (P(axis),) * n_plan,
        rep)
    plan_args = _plan_args(plan)

    def bitset(hist, feat, thr, dirf, pg, ph, pc):
        return wrapped(hist, feat, thr, dirf, pg, ph, pc, *plan_args)

    return bitset


def make_sharded_hist(mesh, axis: str, backend: str, num_slots: int,
                      bmax: int, acc_dtype):
    """shard_map-wrapped LOCAL histogram build for the feature-parallel
    learner: bins is sharded over its GROUP axis (rows replicated), so each
    device builds the (S, G/D, Bmax, 3) block for its own feature groups
    with NO collective at all — the reference's feature-parallel workers
    each histogram only their feature subset
    (feature_parallel_tree_learner.cpp:25-83).  Per-group sums are
    independent of other groups, so every shard's block is bitwise equal
    to the corresponding slice of the serial build."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows
    from ..ops.histogram import build_histograms

    def _local(bins_s, slot, grad, hess, cnt):
        with jax.named_scope("hist_shard_local"):
            return build_histograms(bins_s, slot, grad, hess, cnt,
                                    num_slots, bmax, backend=backend,
                                    acc_dtype=acc_dtype)

    rep = P()
    return shard_map_rows(
        _local, mesh,
        (P(None, axis), rep, rep, rep, rep),
        P(None, axis, None, None))


def make_sharded_hist_2d(mesh, row_axis: str, feature_axis: str,
                         backend: str, num_slots: int, bmax: int,
                         acc_dtype, k_classes: int = 0):
    """shard_map-wrapped histogram build for the 2D (rows x feature-groups)
    mesh: bins is sharded over BOTH axes, so device (f, r) holds an
    (N / D_rows, G / D_feat) block.  Each device builds the full local
    block — ZERO feature-axis collective, exactly the feature-parallel
    build of :func:`make_sharded_hist` — and ONE ``psum_scatter`` over the
    ROW axis (PR 5's reduce, data_parallel_tree_learner.cpp:285-299)
    delivers its G / (D_rows * D_feat) group slice.  The feature-local
    group count is gs * D_rows by construction (the engine pads groups to
    a multiple of D_rows * D_feat), so the tiled scatter needs no
    in-kernel padding, and flat shard s = f * D_rows + r holds groups
    [s * gs, (s+1) * gs) — the ShardPlan's contiguous-slice convention
    under the compound ``(feature, data)`` spec.

    ``k_classes`` > 0 builds the batched-multiclass (K, S, G, Bmax, 3)
    block instead (slot/grad/hess are (K, N); cnt stays (N,))."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows
    from ..ops.histogram import build_histograms, build_histograms_k

    k_mode = k_classes > 0
    g_dim = 2 if k_mode else 1

    def _local(bins_s, slot, grad, hess, cnt):
        with jax.named_scope("hist_2d_local"):
            if k_mode:
                h = build_histograms_k(bins_s, slot, grad, hess, cnt,
                                       k_classes, num_slots, bmax,
                                       backend=backend,
                                       acc_dtype=acc_dtype)
            else:
                h = build_histograms(bins_s, slot, grad, hess, cnt,
                                     num_slots, bmax, backend=backend,
                                     acc_dtype=acc_dtype)
        with jax.named_scope("hist_2d_row_scatter"):
            return jax.lax.psum_scatter(h, row_axis,
                                        scatter_dimension=g_dim,
                                        tiled=True)

    row = P(row_axis)
    per_row = P(None, row_axis) if k_mode else row
    out_g = (feature_axis, row_axis)
    out_spec = (P(None, None, out_g, None, None) if k_mode
                else P(None, out_g, None, None))
    return shard_map_rows(
        _local, mesh,
        (P(row_axis, feature_axis), per_row, per_row, per_row, row),
        out_spec)


def make_sharded_bin_gather_2d(mesh, row_axis: str, feature_axis: str,
                               g_loc: int, batched: bool = False):
    """Per-row stored-bin fetch on the 2D mesh: the chosen split feature's
    bins column lives on ONE feature shard of each row block, so the owner
    reads its local column slice and a psum over the FEATURE axis only
    replicates the value across that row block — the row axis never
    communicates (every row lives on exactly one row shard, and the
    result stays row-sharded).  ``g_loc`` is the per-feature-shard group
    count G / D_feat; ``grp`` holds GLOBAL group indices.  ``batched``
    handles the (K, N) multiclass-lockstep shape (rows on dim 1)."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows

    def _local(bins_s, grp):
        me = jax.lax.axis_index(feature_axis)
        local = grp.astype(jnp.int32) - me * g_loc
        owned = (local >= 0) & (local < bins_s.shape[1])
        idx = jnp.clip(local, 0, bins_s.shape[1] - 1)
        if batched:
            vals = jnp.take_along_axis(bins_s, idx.T, axis=1).T
        else:
            vals = jnp.take_along_axis(bins_s, idx[:, None], axis=1)[:, 0]
        with jax.named_scope("route_bin_psum_2d"):
            return jax.lax.psum(
                jnp.where(owned, vals.astype(jnp.int32), 0), feature_axis)

    grp_spec = P(None, row_axis) if batched else P(row_axis)
    return shard_map_rows(_local, mesh,
                          (P(row_axis, feature_axis), grp_spec), grp_spec)


def make_sharded_bin_gather(mesh, axis: str, gs: int):
    """shard_map-wrapped per-row stored-bin fetch for feature-parallel
    routing: rows are replicated but the bins column of a chosen split
    feature lives only on its owner shard, so the owner reads its local
    column slice and a tiny (N,) psum replicates the values — the routing
    decision costs one int32 per row per round, never a histogram column.
    ``grp`` is the (N,) replicated GLOBAL group index per row."""
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_rows

    def _local(bins_s, grp):
        me = jax.lax.axis_index(axis)
        local = grp.astype(jnp.int32) - me * gs
        owned = (local >= 0) & (local < bins_s.shape[1])
        idx = jnp.clip(local, 0, bins_s.shape[1] - 1)
        vals = jnp.take_along_axis(bins_s, idx[:, None], axis=1)[:, 0]
        with jax.named_scope("route_bin_psum"):
            return jax.lax.psum(
                jnp.where(owned, vals.astype(jnp.int32), 0), axis)

    return shard_map_rows(_local, mesh, (P(None, axis), P()), P())


def feature_bytes_per_round(num_slots: int, d: int, bmax: int,
                            has_categorical: bool, n_rows: int = 0,
                            num_class: int = 1) -> int:
    """Analytic per-device payload DELIVERED per growth round under
    tree_learner=feature: ZERO histogram bytes — only the 7-field
    per-shard best-split records (all_gather), the owner-recomputed
    categorical bitset psum when categorical features exist, and the
    per-row route-bin psum (one int32 per row; pass n_rows=0 to count
    split-decision traffic only)."""
    rec = d * num_class * num_slots * 7 * 4
    if has_categorical:
        rec += num_class * num_slots * bmax * 4
    return rec + n_rows * 4


def voting_bytes_per_round(num_slots: int, num_features: int, top_k2: int,
                           bmax: int, num_class: int = 1) -> int:
    """Analytic per-device payload DELIVERED per growth round under
    tree_learner=voting (PV-Tree): the one-hot vote psum (S x F f32) plus
    ONLY the elected top-2k features' histogram columns (S x 2k x Bmax x
    3 channels) — O(2k·B) instead of the data-parallel O(F·B)
    (voting_parallel_tree_learner.cpp:104/396)."""
    votes = num_class * num_slots * num_features * 4
    elected = num_class * num_slots * top_k2 * bmax * 3 * 4
    return votes + elected


def hist_comms_bytes_per_round(num_slots: int, num_groups: int, bmax: int,
                               d: int, mode: str, dtype: str = "f32",
                               num_class: int = 1,
                               packed_width: int = 32,
                               d_feat: int = 1) -> int:
    """Analytic per-device histogram payload DELIVERED per growth round.

    Convention (docs/DISTRIBUTED.md): bytes of reduced histogram payload a
    device materializes out of the round's collective — psum delivers the
    whole (K, S, G, Bmax, 2) block to every device (unpadded: only rs pads
    the group axis to a multiple of d); reduce_scatter delivers only the
    G/D group slice (plus the all_gathered best-split records, counted
    too).  bf16_pair halves the per-element wire width of the slice.
    Distinct from link-level ring traffic, which the mode also cuts
    (all-reduce moves ~2x a reduce-scatter).

    ``packed_width`` (hist_packed_width under use_quantized_grad +
    stream): 16 packs each (grad, hess) int pair into ONE int32 lane (4
    bytes per pair instead of 8 — half), 8 packs the pair into ONE int16
    lane (2 bytes per pair — quarter).  The two scale scalars ride the
    best-split record exchange; their bytes are noise and not counted.

    ``d_feat`` > 1 is the 2D (rows x feature-groups) mesh: the feature
    axis ships ZERO histogram bytes (each feature shard builds only its
    own groups, like tree_learner=feature), the row axis psum_scatters
    each feature-local block so a device materializes only its
    G / (d * d_feat) group slice, and the best-split records all_gather
    over BOTH axes (d * d_feat shards).  The 2D path runs the exact-f32
    contraction build (no stream kernel per feature shard), so the wire
    is always 4-byte f32 there — hist_packed_width and bf16_pair resolve
    to 32-wide f32 (documented in docs/DISTRIBUTED.md "2D mesh")."""
    if d_feat > 1:
        gs = -(-num_groups // (d * d_feat))
        elems_slice = num_class * num_slots * gs * bmax * 2
        record_bytes = (d * d_feat) * num_class * num_slots * 7 * 4
        return elems_slice * 4 + record_bytes
    per_elem = {32: 4, 16: 2, 8: 1}[packed_width]
    if mode == "psum":
        return num_class * num_slots * num_groups * bmax * 2 * per_elem
    gs = -(-num_groups // d)
    elems_slice = num_class * num_slots * gs * bmax * 2
    width = 2 if dtype == "bf16_pair" else 4
    if packed_width != 32:
        width = per_elem
    # + per-shard best records: 7 fields x 4 bytes from each of d shards
    record_bytes = d * num_class * num_slots * 7 * 4
    return elems_slice * width + record_bytes


def make_rs_context(mesh, axis: str, layout: FeatureLayout, routing,
                    num_groups: int, bmax: int, params):
    """Everything a grow function needs for reduce_scatter comms: the
    static ShardPlan, a SplitResult-shaped shard-local finder, and the
    owner-shard categorical bitset (None without categorical features).
    Shared by grow_tree and grow_tree_k so the scan kwargs can never
    drift between the two growth paths.

    ``axis`` may be a TUPLE of mesh axis names (the 2D mesh passes
    ``(feature, data)``): the plan then slices groups over the COMBINED
    d = prod(sizes) shards, and every collective inside the finder /
    bitset (all_gather, psum) runs over the compound axis — jax orders
    tuple-axis collectives first-named-major, so flat shard
    f * D_rows + r matches the post-psum_scatter slice ownership."""
    from ..ops.split import SplitResult

    axes = axis if isinstance(axis, tuple) else (axis,)
    n_dev = int(np.prod([mesh.shape[a] for a in axes]))
    plan = build_shard_plan(layout, routing, num_groups, bmax, n_dev)
    scan_kw = dict(
        lambda_l1=params.lambda_l1, lambda_l2=params.lambda_l2,
        min_data_in_leaf=max(params.min_data_in_leaf, 1),
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf,
        min_gain_to_split=params.min_gain_to_split,
        cat_l2=params.cat_l2, cat_smooth=params.cat_smooth,
        max_cat_threshold=params.max_cat_threshold,
        max_cat_to_onehot=params.max_cat_to_onehot,
        min_data_per_group=params.min_data_per_group,
        enable_categorical=params.has_categorical,
        max_delta_step=params.max_delta_step)
    rs_find = make_sharded_finder(mesh, axis, plan, scan_kw)
    rs_bitset = (make_sharded_bitset(mesh, axis, plan, params.cat_smooth,
                                     params.min_data_per_group)
                 if params.has_categorical else None)

    def rs_split(hist_rows, pg, ph, pc, cmask):
        g, f, t, d_, lg, lh, lc = rs_find(hist_rows, pg, ph, pc, cmask)
        return SplitResult(gain=g, feature=f, threshold=t, dir_flags=d_,
                           left_sum_g=lg, left_sum_h=lh, left_count=lc,
                           right_sum_g=pg - lg, right_sum_h=ph - lh,
                           right_count=pc - lc)

    return plan, rs_split, rs_bitset
