"""Distributed (multi-process) dataset ingestion.

Reference: DatasetLoader::LoadFromFile(rank, num_machines) — each machine
parses its own row shard of the shared file, bin mappers are found from
per-rank samples and synchronized across machines (dataset_loader.cpp:211,
733-741, 1240-1248) so every rank bins identically, and training runs on the
union without any single host ever holding the full feature matrix.

TPU re-design: ranks are jax processes. Mapper sync = host-level allgather of
the per-rank samples (jax.experimental.multihost_utils) followed by a
DETERMINISTIC mapper computation on every process — equivalent to the
reference's mapper Allgather but without serializing mapper objects. The
binned shard is assembled into one global row-sharded device array with
jax.make_array_from_process_local_data; per-row metadata (label/weight/
position — O(N) scalars, not the O(N*F) features) is allgathered to every
host in shard-padded order so the whole existing engine works unchanged.

Row layout: every rank pads its shard to a common n_shard (a multiple of
2048 * local_device_count, covering the stream kernel's largest block), and
the global row space is the rank-ordered concatenation of padded shards.
Pad rows carry weight 0 and a 0 entry in the true-row mask, so they take no
part in histograms, counts, or metrics.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils.log import LightGBMError


def dist_context() -> Optional[Tuple[int, int]]:
    """(process_index, process_count) when running multi-process, else None."""
    import jax
    if jax.process_count() <= 1:
        return None
    return jax.process_index(), jax.process_count()


def allgather_np(x: np.ndarray) -> np.ndarray:
    """Allgather equal-shape host arrays; returns (P, *x.shape).

    64-bit dtypes ride as uint32 pairs — jax would silently downcast them
    to 32 bits (x64 disabled), which must not corrupt sample values that
    feed bin-boundary computation."""
    from jax.experimental import multihost_utils
    x = np.ascontiguousarray(np.asarray(x))
    wide = x.dtype in (np.dtype(np.float64), np.dtype(np.int64))
    if wide:
        orig = x.dtype
        x = x.view(np.uint32)        # last axis doubles
    g = np.asarray(multihost_utils.process_allgather(x))
    if wide:
        g = g.view(orig)
    return g


def shard_pad_base() -> int:
    """Per-shard row padding: covers the stream kernel's largest block per
    local device so the assembled global array splits evenly."""
    import jax
    return 4096 * max(jax.local_device_count(), 1)


def pad_rows(a: Optional[np.ndarray], n_shard: int, fill=0.0
             ) -> Optional[np.ndarray]:
    if a is None:
        return None
    pad = [(0, n_shard - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad, constant_values=fill)


def gather_padded(a: Optional[np.ndarray], n_shard: int, fill=0.0
                  ) -> Optional[np.ndarray]:
    """Pad the local per-row array to n_shard and allgather into the global
    shard-ordered layout (P * n_shard rows)."""
    if a is None:
        return None
    g = allgather_np(pad_rows(a, n_shard, fill))
    return g.reshape((-1,) + a.shape[1:])


def gather_sample(sample: np.ndarray) -> np.ndarray:
    """Allgather per-rank sample rows (padded to the largest rank's count)
    and return only the valid rows, rank-ordered — the input every process
    feeds to the deterministic mapper/EFB computation (reference:
    bin-mapper Allgather, dataset_loader.cpp:733-741)."""
    cnt = np.asarray([sample.shape[0]], np.int64)
    counts = allgather_np(cnt).reshape(-1)
    m = int(counts.max())
    padded = np.zeros((m,) + sample.shape[1:], sample.dtype)
    padded[:sample.shape[0]] = sample
    gathered = allgather_np(padded)
    return np.concatenate([gathered[r, :counts[r]]
                           for r in range(len(counts))], axis=0)


def sync_ingest_blob(blob: np.ndarray) -> np.ndarray:
    """The streaming loader's mapper sync: ONE host collective carrying
    each rank's serialized pass-1 state (fixed-width quantile sketches +
    the EFB bottom-k pool, ingest._pack_rank_blob) — the analog of the
    reference's bin-mapper Allgather (dataset_loader.cpp:733-741).
    Every rank merges the gathered blobs in rank order, so boundaries
    come out identical everywhere without a second round trip."""
    return allgather_np(np.ascontiguousarray(blob, np.int64))


def make_global_bins(local_bins: np.ndarray, mesh, row_axis: str):
    """Assemble per-process binned shards into one global row-sharded device
    array (the features never leave their host except to its own devices)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P(row_axis, None))
    return jax.make_array_from_process_local_data(sh, local_bins)


def check_uniform_features(num_feature: int) -> int:
    """LibSVM shards can infer different widths; agree on the max."""
    widths = allgather_np(np.asarray([num_feature], np.int64)).reshape(-1)
    return int(widths.max())
