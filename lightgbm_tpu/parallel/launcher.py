"""Multi-host launch helper — the Dask-module analog.

Reference: python-package/lightgbm/dask.py:196-215 (_train: machine list
assembly + LGBM_NetworkInit on every worker) and src/network/linkers_socket.cpp.

On TPU there is no socket layer to configure: `jax.distributed.initialize()`
connects the hosts, and the SAME SPMD training program spans all of them —
`tree_learner=data|feature|voting` shard over the global device mesh exactly
as they do over a single host's devices.

Typical multi-host run (one process per host, e.g. under `gcloud compute tpus
tpu-vm ssh --worker=all`):

    import lightgbm_tpu as lgb
    lgb.init_distributed()                      # TPU pod: args auto-detected
    # or, on CPU/GPU clusters:
    # lgb.init_distributed(coordinator_address="host0:1234",
    #                      num_processes=4, process_id=rank)
    bst = lgb.train({"tree_learner": "data", ...}, dset)

Every process must execute the same calls; passing a FILE PATH to Dataset
under multi-process training loads only this rank's row shard (bin mappers
sync automatically — see parallel/dist_data.py), so no host ever holds the
full feature matrix. In-memory arrays must still be identical everywhere.
"""
from __future__ import annotations

import os
from typing import Optional

from ..utils.log import LightGBMError, log_info


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     local_device_ids=None) -> None:
    """Connect this process to the multi-host training job (replaces
    LGBM_NetworkInit / the Dask machines= list).

    On TPU pods all arguments are auto-detected from the environment; on
    other platforms pass them explicitly."""
    import jax

    # NOTE: jax.process_count() would itself initialize the XLA backend,
    # after which distributed.initialize is rejected — probe the
    # distributed client state directly instead
    try:
        from jax._src import distributed as _dist
        already = _dist.global_state.client is not None
    except Exception:  # pragma: no cover - jax internals moved
        already = False
    if already:
        log_info("jax.distributed already initialized "
                 f"({jax.process_count()} processes)")
        return
    # the default CPU client refuses cross-process computations
    # ("Multiprocess computations aren't implemented on the CPU
    # backend"); the gloo collectives implementation is what makes
    # localhost-simulated multi-host runs work (parallel/cluster.py's
    # workers set the same; older jax: option absent, TPU: irrelevant)
    platforms = str(getattr(jax.config, "jax_platforms", None)
                    or os.environ.get("JAX_PLATFORMS", ""))
    if "cpu" in platforms:
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - option absent in old jax
            pass
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:  # pragma: no cover - depends on cluster env
        if "already initialized" in str(e).lower():
            # belt-and-braces for the private-state probe above: an
            # earlier explicit initialize is fine, keep the old no-op
            log_info("jax.distributed already initialized")
            return
        raise LightGBMError(
            f"jax.distributed.initialize failed: {e}; on non-TPU clusters "
            "pass coordinator_address/num_processes/process_id explicitly")
    log_info(f"distributed init OK: process {jax.process_index()}/"
             f"{jax.process_count()}, {jax.device_count()} global devices")
