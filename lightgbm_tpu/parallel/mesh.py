"""Device mesh + sharding policy — the distributed backend.

Reference: src/network/ (from-scratch socket/MPI collectives: Allreduce/ReduceScatter/
Allgather, network.cpp:72-307) and the three distributed learners in src/treelearner/
(feature_parallel_tree_learner.cpp, data_parallel_tree_learner.cpp,
voting_parallel_tree_learner.cpp).

TPU re-design: the entire collective layer is replaced by XLA GSPMD over a
jax.sharding.Mesh. The tree grower (ops/grow.py) is pure jnp, so:

  * tree_learner="data"    -> shard rows (N) across the mesh. The histogram build
    contracts over N, so XLA inserts an all-reduce of histogram blocks — exactly the
    reference's ReduceScatter+Allgather specialisation (data_parallel_tree_learner.
    cpp:285-299) chosen automatically, riding ICI instead of TCP.
  * tree_learner="feature" -> shard the feature-group axis (G). Each device builds
    histograms and split candidates for its feature slice; the argmax over features
    becomes an all-gather of per-shard bests (the reference Allreduces SplitInfo,
    feature_parallel_tree_learner.cpp:25-83).
  * tree_learner="voting"  -> planned as a comm optimisation of "data" for DCN-connected
    hosts (top-k vote before the histogram reduce, PV-Tree); round-2 work.

Multi-host: call jax.distributed.initialize() before building the mesh; the same
program runs SPMD across hosts (replaces LGBM_NetworkInit / machine_list entirely).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.log import LightGBMError, log_info

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def parse_mesh_shape(spec: str) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """Parse "data:4,feature:2" into axis names/sizes.

    Malformed specs raise LightGBMError naming the offending part instead
    of leaking a bare ValueError (e.g. "data:") or silently building a
    mesh with duplicate/empty axis names or non-positive sizes."""
    names, sizes = [], []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, size = part.partition(":")
        name = name.strip()
        if not sep or not name:
            raise LightGBMError(
                f"mesh_shape part {part!r} must be '<axis>:<size>' "
                f"(full spec: {spec!r})")
        try:
            n = int(size)
        except ValueError:
            raise LightGBMError(
                f"mesh_shape part {part!r} has a non-integer size "
                f"{size.strip()!r} (full spec: {spec!r})") from None
        if n <= 0:
            raise LightGBMError(
                f"mesh_shape part {part!r} has non-positive size {n} "
                f"(full spec: {spec!r})")
        if name in names:
            raise LightGBMError(
                f"mesh_shape {spec!r} repeats axis name {name!r}")
        names.append(name)
        sizes.append(n)
    if not names:
        raise LightGBMError(f"mesh_shape {spec!r} names no axes")
    return tuple(names), tuple(sizes)


def create_mesh(mesh_shape: str = "", tree_learner: str = "serial",
                num_machines: int = 1) -> Optional[Mesh]:
    """Build the device mesh for the configured parallelism (None = single device)."""
    devices = jax.devices()
    n = len(devices)
    if num_machines > 1 and jax.process_count() < num_machines:
        log_info(f"num_machines={num_machines} but only {jax.process_count()} "
                 "JAX process(es) are initialized; call jax.distributed.initialize() "
                 "on every host before training (replaces LGBM_NetworkInit). "
                 "Proceeding with the devices visible to this process.")
    if mesh_shape:
        names, sizes = parse_mesh_shape(mesh_shape)
        # combined 2-axis meshes: ONLY tree_learner=data consumes both
        # axes (histograms build shard-locally over feature groups and
        # psum_scatter over rows — docs/DISTRIBUTED.md "2D mesh"). The
        # feature and voting learners run their collectives on a single
        # axis, so a combined mesh would leave the second axis unconsumed
        # and the bins sharding and split collectives would disagree.
        # Trailing size-1 axes are harmless (their collectives are
        # identities) and stay allowed for sweep tooling.
        big = [f"{nm}:{sz}" for nm, sz in zip(names, sizes) if sz > 1]
        big_names = {nm for nm, sz in zip(names, sizes) if sz > 1}
        if len(big) > 1 and not (tree_learner == "data"
                                 and big_names <= {DATA_AXIS, FEATURE_AXIS}):
            raise LightGBMError(
                f"mesh_shape {mesh_shape!r} requests a combined "
                f"{' x '.join(big)} mesh; 2-axis sharding is only "
                f"supported as \"{DATA_AXIS}:R,{FEATURE_AXIS}:F\" with "
                "tree_learner=data (rows x feature-groups, docs/"
                "DISTRIBUTED.md \"2D mesh\") — other learners shard ONE "
                "axis (\"data:D\" with tree_learner=voting, or "
                "\"feature:D\" with tree_learner=feature)")
        if tree_learner == "feature" and FEATURE_AXIS not in names:
            raise LightGBMError(
                f"tree_learner=feature needs a mesh with a "
                f"{FEATURE_AXIS!r} axis but mesh_shape {mesh_shape!r} "
                f"names {names}; use e.g. \"feature:{n}\"")
        if tree_learner in ("data", "voting") and FEATURE_AXIS in names \
                and DATA_AXIS not in names:
            raise LightGBMError(
                f"tree_learner={tree_learner} shards rows but mesh_shape "
                f"{mesh_shape!r} names only the {FEATURE_AXIS!r} axis; use "
                f"e.g. \"{DATA_AXIS}:{n}\"")
        total = int(np.prod(sizes))
        if total > n:
            raise LightGBMError(f"mesh {mesh_shape} needs {total} devices, have {n}")
        dev = np.asarray(devices[:total]).reshape(sizes)
        return Mesh(dev, names)
    if tree_learner in ("data", "voting"):
        if n == 1:
            log_info("tree_learner=data with a single device: running serial")
            return None
        return Mesh(np.asarray(devices), (DATA_AXIS,))
    if tree_learner == "feature":
        if n == 1:
            log_info("tree_learner=feature with a single device: running serial")
            return None
        return Mesh(np.asarray(devices), (FEATURE_AXIS,))
    return None


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across the data axis (bins (N, G), grad/hess/leaf_id (N,))."""
    axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]
    return NamedSharding(mesh, P(axis))


def bins_sharding(mesh: Mesh, tree_learner: str) -> NamedSharding:
    if tree_learner == "feature" and FEATURE_AXIS not in mesh.axis_names:
        raise LightGBMError(
            f"tree_learner=feature needs a mesh with a {FEATURE_AXIS!r} "
            f"axis; this mesh names {tuple(mesh.axis_names)}")
    if tree_learner == "feature" or (FEATURE_AXIS in mesh.axis_names
                                     and DATA_AXIS not in mesh.axis_names):
        return NamedSharding(mesh, P(None, FEATURE_AXIS))
    axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]
    if FEATURE_AXIS in mesh.axis_names and (
            tree_learner != "data" or int(mesh.shape[FEATURE_AXIS]) > 1):
        # tree_learner=data with a real feature axis is the 2D mesh: bins
        # (N, G) shard over BOTH axes; a size-1 feature axis keeps the
        # rows-only spec so the 1D stream path is untouched.
        return NamedSharding(mesh, P(axis, FEATURE_AXIS))
    return NamedSharding(mesh, P(axis))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_rows(mesh: Optional[Mesh], *arrays):
    """Place row-dimension arrays on the mesh (no-op without a mesh)."""
    if mesh is None:
        return arrays if len(arrays) > 1 else arrays[0]
    sh = data_sharding(mesh)
    out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]


def pad_rows_for_mesh(n: int, mesh: Optional[Mesh], base: int = 256) -> int:
    """Row count padded so every shard is equal-sized and tile-aligned."""
    mult = base
    if mesh is not None:
        mult = base * int(np.prod(mesh.devices.shape))
    return -(-n // mult) * mult


def shard_map_rows(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map a per-device function over the mesh with the replication
    check OFF: pallas_call cannot annotate varying-mesh-axes on its outputs,
    so callers psum whatever must come back replicated (the reference's
    per-worker histogram construction + ReduceScatter,
    data_parallel_tree_learner.cpp:285-299). Handles the old/new shard_map
    API spellings (check_vma in current jax, check_rep in the older
    experimental shard_map)."""
    try:
        from jax import shard_map as _sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
    specs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    try:
        return _sm(fn, check_vma=False, **specs)
    except TypeError:   # older signature spells it check_rep
        try:
            return _sm(fn, check_rep=False, **specs)
        except TypeError:   # oldest: no replication-check kwarg at all
            return _sm(fn, **specs)
