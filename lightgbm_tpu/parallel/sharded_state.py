"""Permanently device-sharded training state for the fused iteration path.

Reference analog: the reference keeps ``scores_``/``gradients_``/
``bag_data_indices_`` resident in each worker's memory for the whole
training run (gbdt.cpp, data_partition.hpp) — nothing row-indexed ever
round-trips through a coordinator between iterations.

TPU re-design (docs/DISTRIBUTED.md "fused iteration & sharded state"):
every row-indexed array a boosting iteration touches — the score vector,
the last iteration's gradients/hessians, the tree's row->leaf routing,
the in-bag mask — lives in ONE pytree that the fused one-launch step
takes and returns with **explicit out-sharding equal to in-sharding**
(the pjit partition-rule pattern).  XLA therefore never inserts an
implicit re-shard or a host round trip between iterations, and the
engine's host loop only ever touches the tiny scalar tail (finished /
nan-ok flags, in-bag count, compaction-overflow counter) through the
batched once-per-``eval_fetch_freq`` fetch.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax


class ShardedTrainState(NamedTuple):
    """Row-sharded training state threaded through the fused iteration.

    Row-axis arrays (sharded over the mesh's data axis):
      * ``score``   — (N,) or (N, K) f32 training scores
      * ``grad``/``hess`` — like ``score``; the last iteration's RAW
        (unquantized, pre-sampling) gradients, kept for batched
        telemetry/debug fetches.  These are the iteration's own live
        buffers, not fresh allocations — holding them extends two N-row
        arrays' lifetime across the iteration gap (~8 bytes/row; drop
        them from the pytree if that headroom is ever needed)
      * ``leaf_id`` — (N,) or (K, N) i32, the last tree's row routing
      * ``mask``    — (N,) f32 in-bag mask of the last iteration

    Replicated scalar tail (read only by the batched flag fetch):
      * ``key``      — (2,) u32, mirrors the per-iteration RNG stream
        position (keys themselves derive from the iteration counter the
        checkpoint already stores)
      * ``sampled``  — () i32 global in-bag row count of ``mask``
      * ``overflow`` — () i32 iterations whose per-shard in-bag count
        exceeded the static compaction capacity (must stay 0; the poll
        disables compaction and warns when it moves)
      * ``finished`` — () bool, last tree grew no split
      * ``ok``       — () bool, nan_guard all-finite flag
    """
    score: jax.Array
    grad: jax.Array
    hess: jax.Array
    leaf_id: jax.Array
    mask: jax.Array
    key: jax.Array
    sampled: jax.Array
    overflow: jax.Array
    finished: jax.Array
    ok: jax.Array


def state_shardings(mesh, row_axis: Optional[str], num_class: int,
                    replicate_rows: bool = False
                    ) -> Optional[ShardedTrainState]:
    """The explicit sharding pytree for a :class:`ShardedTrainState` —
    used as BOTH the in- and out-sharding of the fused step so row-axis
    arrays stay pinned to their devices across iterations.  ``None``
    without a mesh (single-device runs let jit place everything).

    ``replicate_rows``: the FEATURE-parallel variant (tree_learner=
    feature) — the mesh shards bins' feature-group axis, so every per-row
    state array is pinned fully REPLICATED instead; mixing a replicated
    score with group-sharded bins is exactly the layout the fp grow
    program's shard_maps expect, and an accidental row sharding here
    would silently re-shard every iteration.

    2D mesh variant (tree_learner=data over ``data x feature`` axes,
    docs/DISTRIBUTED.md "2D mesh"): pass the 2D mesh with the row axis
    and ``replicate_rows=False`` — ``P(row_axis)`` on a multi-axis mesh
    shards rows over the data axis and REPLICATES them over the feature
    axis, which is exactly the placement the 2D grow program requires
    for every per-row array (score, grad/hess, leaf routing, bag/GOSS
    mask); only the bins matrix shards over both axes."""
    if mesh is None or (row_axis is None and not replicate_rows):
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    if replicate_rows:
        return ShardedTrainState(*([rep] * len(ShardedTrainState._fields)))
    row = NamedSharding(mesh, P(row_axis))
    if num_class == 1:
        score = grad = hess = row
        leaf = row
    else:
        score = grad = hess = NamedSharding(mesh, P(row_axis, None))
        leaf = NamedSharding(mesh, P(None, row_axis))
    return ShardedTrainState(
        score=score, grad=grad, hess=hess, leaf_id=leaf, mask=row,
        key=rep, sampled=rep, overflow=rep, finished=rep, ok=rep)
