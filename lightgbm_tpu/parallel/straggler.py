"""Multi-host straggler detection for data-parallel training.

A data-parallel step runs at the pace of the slowest host — one throttled
VM, one overloaded NIC, and the whole pod waits in the histogram
collective.  The reference's socket network makes this visible as wait
time inside Allreduce; under jax.distributed it is invisible unless
measured.

Every K iterations (param ``telemetry_straggler_every``) each host
contributes its recent per-iteration wall-time stats — and, since the
comms overhaul, its per-iteration BARRIER WAIT (the time it idled at the
post-iteration sync while stragglers caught up) — to a
``process_allgather``, and process 0 logs a skew report (max/median of
the per-host means).  The two columns separate the failure modes the
merged number conflated:

  * **slow device**: one host's local compute mean is far above the
    median, and every OTHER host shows a large barrier wait (they finish
    early and idle);
  * **slow link**: compute means are level but barrier waits are large
    everywhere — time is going into the collectives themselves.

A skew above ``telemetry_straggler_skew`` warns with the offending
host's process index and the bottleneck classification.  All hosts must
reach the check at the same iteration — the call sites key it off the
iteration counter, which is replicated by construction.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..utils.log import log_info, log_warning


def straggler_report(iter_times: Sequence[float],
                     warn_skew: float = 1.25,
                     comms_waits: Optional[Sequence[float]] = None,
                     _all_host_stats: Optional[np.ndarray] = None
                     ) -> Optional[Dict[str, Any]]:
    """Aggregate per-host iteration times; returns the report dict.

    ``iter_times`` — this host's recent per-iteration wall times (s) of
    the LOCAL step (compute + in-program collectives).
    ``comms_waits`` — matching per-iteration barrier waits (s); the comms
    phase split the telemetry iteration records carry (``comms_wait_s``).
    ``_all_host_stats`` — test hook: pre-gathered (H, 3) [n, mean, max]
    or (H, 4) [n, mean, max, comms_mean] rows standing in for the
    collective."""
    if not len(iter_times) and _all_host_stats is None:
        return None
    import jax

    t = np.asarray(iter_times, np.float64)
    w = np.asarray(comms_waits if comms_waits is not None else [],
                   np.float64)
    local = np.array([len(t), float(t.mean()) if len(t) else 0.0,
                      float(t.max()) if len(t) else 0.0,
                      float(w.mean()) if len(w) else 0.0], np.float64)
    if _all_host_stats is not None:
        stats = np.asarray(_all_host_stats, np.float64)
        if stats.ndim == 1:
            stats = stats.reshape(1, -1)
        if stats.shape[1] == 3:          # legacy 3-column test rows
            stats = np.concatenate(
                [stats, np.zeros((stats.shape[0], 1))], axis=1)
        pidx = 0
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        stats = np.asarray(multihost_utils.process_allgather(local))
        pidx = jax.process_index()
    else:
        stats = local[None]
        pidx = 0

    means = stats[:, 1]
    waits = stats[:, 3]
    median = float(np.median(means))
    slowest = int(np.argmax(means))
    worst = float(means[slowest])
    skew = worst / median if median > 0 else 1.0
    wait_median = float(np.median(waits))
    wait_frac = wait_median / median if median > 0 else 0.0
    # bottleneck classification (docs/DISTRIBUTED.md): a slow DEVICE shows
    # one host's compute far above the median (the others idle at the
    # barrier); a slow LINK shows level compute with everyone's barrier
    # wait high — the time is inside the collectives
    if skew >= warn_skew:
        bottleneck = "device"
    elif wait_frac >= (warn_skew - 1.0):
        bottleneck = "link"
    else:
        bottleneck = "balanced"
    report: Dict[str, Any] = {
        "event": "straggler_report",
        "hosts": int(stats.shape[0]),
        "window_iters": int(stats[:, 0].max()),
        "median_host_mean_s": round(median, 6),
        "max_host_mean_s": round(worst, 6),
        "max_host_max_s": round(float(stats[:, 2].max()), 6),
        "slowest_host": slowest,
        "skew": round(skew, 4),
        "median_comms_wait_s": round(wait_median, 6),
        "max_comms_wait_s": round(float(waits.max()), 6),
        "comms_wait_frac": round(wait_frac, 4),
        "bottleneck": bottleneck,
    }
    from ..telemetry import global_registry, global_tracer
    global_registry.record(report)
    global_registry.gauge("straggler/skew", skew)
    global_registry.gauge("straggler/comms_wait_frac", wait_frac)
    global_tracer.counter("straggler_skew", skew=skew)
    if pidx == 0 and stats.shape[0] > 1:
        if bottleneck == "device":
            log_warning(
                f"telemetry: straggler detected — host {slowest} averages "
                f"{worst * 1e3:.1f} ms/iter compute vs the "
                f"{median * 1e3:.1f} ms median across {stats.shape[0]} "
                f"hosts (skew {skew:.2f}x >= {warn_skew:.2f}x; slow "
                "DEVICE — the other hosts idle at the barrier)")
        elif bottleneck == "link":
            log_warning(
                f"telemetry: comms-bound — hosts spend a median "
                f"{wait_median * 1e3:.1f} ms/iter waiting at the barrier "
                f"({wait_frac:.0%} of the {median * 1e3:.1f} ms compute "
                "median) with level compute across hosts (slow LINK)")
        else:
            log_info(
                f"telemetry: {stats.shape[0]} hosts, median "
                f"{median * 1e3:.1f} ms/iter, max {worst * 1e3:.1f} ms "
                f"(host {slowest}, skew {skew:.2f}x, comms wait "
                f"{wait_median * 1e3:.1f} ms)")
    return report
