"""Multi-host straggler detection for data-parallel training.

A data-parallel step runs at the pace of the slowest host — one throttled
VM, one overloaded NIC, and the whole pod waits in the histogram psum.
The reference's socket network makes this visible as wait time inside
Allreduce; under jax.distributed it is invisible unless measured.

Every K iterations (param ``telemetry_straggler_every``) each host
contributes its recent per-iteration wall-time stats to a
``process_allgather``, and process 0 logs a skew report (max/median of
the per-host means). A skew above ``telemetry_straggler_skew`` warns
with the offending host's process index. All hosts must reach the
check at the same iteration — the call sites key it off the iteration
counter, which is replicated by construction.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..utils.log import log_info, log_warning


def straggler_report(iter_times: Sequence[float],
                     warn_skew: float = 1.25,
                     _all_host_stats: Optional[np.ndarray] = None
                     ) -> Optional[Dict[str, Any]]:
    """Aggregate per-host iteration times; returns the report dict.

    ``iter_times`` — this host's recent per-iteration wall times (s).
    ``_all_host_stats`` — test hook: pre-gathered (H, 3) [n, mean, max]
    rows standing in for the collective."""
    if not len(iter_times) and _all_host_stats is None:
        return None
    import jax

    t = np.asarray(iter_times, np.float64)
    local = np.array([len(t), float(t.mean()) if len(t) else 0.0,
                      float(t.max()) if len(t) else 0.0], np.float64)
    if _all_host_stats is not None:
        stats = np.asarray(_all_host_stats, np.float64).reshape(-1, 3)
        pidx = 0
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        stats = np.asarray(multihost_utils.process_allgather(local))
        pidx = jax.process_index()
    else:
        stats = local[None]
        pidx = 0

    means = stats[:, 1]
    median = float(np.median(means))
    slowest = int(np.argmax(means))
    worst = float(means[slowest])
    skew = worst / median if median > 0 else 1.0
    report: Dict[str, Any] = {
        "event": "straggler_report",
        "hosts": int(stats.shape[0]),
        "window_iters": int(stats[:, 0].max()),
        "median_host_mean_s": round(median, 6),
        "max_host_mean_s": round(worst, 6),
        "max_host_max_s": round(float(stats[:, 2].max()), 6),
        "slowest_host": slowest,
        "skew": round(skew, 4),
    }
    from ..telemetry import global_registry, global_tracer
    global_registry.record(report)
    global_registry.gauge("straggler/skew", skew)
    global_tracer.counter("straggler_skew", skew=skew)
    if pidx == 0 and stats.shape[0] > 1:
        if skew >= warn_skew:
            log_warning(
                f"telemetry: straggler detected — host {slowest} averages "
                f"{worst * 1e3:.1f} ms/iter vs the {median * 1e3:.1f} ms "
                f"median across {stats.shape[0]} hosts "
                f"(skew {skew:.2f}x >= {warn_skew:.2f}x)")
        else:
            log_info(
                f"telemetry: {stats.shape[0]} hosts, median "
                f"{median * 1e3:.1f} ms/iter, max {worst * 1e3:.1f} ms "
                f"(host {slowest}, skew {skew:.2f}x)")
    return report
