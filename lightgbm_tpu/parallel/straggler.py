"""Multi-host straggler detection for data-parallel training.

A data-parallel step runs at the pace of the slowest host — one throttled
VM, one overloaded NIC, and the whole pod waits in the histogram
collective.  The reference's socket network makes this visible as wait
time inside Allreduce; under jax.distributed it is invisible unless
measured.

Every K iterations (param ``telemetry_straggler_every``) each host
contributes its recent per-iteration wall-time stats — and, since the
comms overhaul, its per-iteration BARRIER WAIT (the time it idled at the
post-iteration sync while stragglers caught up) — to a
``process_allgather``, and process 0 logs a skew report (max/median of
the per-host means).  The two columns separate the failure modes the
merged number conflated:

  * **slow device**: one host's local compute mean is far above the
    median, and every OTHER host shows a large barrier wait (they finish
    early and idle);
  * **slow link**: compute means are level but barrier waits are large
    everywhere — time is going into the collectives themselves.

A skew above ``telemetry_straggler_skew`` warns with the offending
host's process index and the bottleneck classification.  All hosts must
reach the check at the same iteration — the call sites key it off the
iteration counter, which is replicated by construction.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..utils.log import log_info, log_warning


DISPATCH_LAUNCHES = 3.5   # launches/iter past this reads dispatch-bound


def straggler_report(iter_times: Sequence[float],
                     warn_skew: float = 1.25,
                     comms_waits: Optional[Sequence[float]] = None,
                     launches_per_iter: Optional[float] = None,
                     host_syncs_per_iter: Optional[float] = None,
                     _all_host_stats: Optional[np.ndarray] = None
                     ) -> Optional[Dict[str, Any]]:
    """Aggregate per-host iteration times; returns the report dict.

    ``iter_times`` — this host's recent per-iteration wall times (s) of
    the LOCAL step (compute + in-program collectives).
    ``comms_waits`` — matching per-iteration barrier waits (s); the comms
    phase split the telemetry iteration records carry (``comms_wait_s``).
    ``launches_per_iter`` / ``host_syncs_per_iter`` — this host's window
    mean of watched_jit dispatches and noted device->host transfers per
    iteration (telemetry.launch_count / host_sync_count diffs); they feed
    the ``bottleneck: dispatch`` classification — a loop that is neither
    device- nor link-skewed but still issues many launches (or syncs)
    per iteration is paying fixed dispatch latency, the regime the fused
    iteration path (docs/DISTRIBUTED.md) removes.
    ``_all_host_stats`` — test hook: pre-gathered (H, 3) [n, mean, max],
    (H, 4) [n, mean, max, comms_mean], or (H, 6) [..., launches/iter,
    host_syncs/iter] rows standing in for the collective."""
    if not len(iter_times) and _all_host_stats is None:
        return None
    import jax

    t = np.asarray(iter_times, np.float64)
    w = np.asarray(comms_waits if comms_waits is not None else [],
                   np.float64)
    local = np.array([len(t), float(t.mean()) if len(t) else 0.0,
                      float(t.max()) if len(t) else 0.0,
                      float(w.mean()) if len(w) else 0.0,
                      float(launches_per_iter or 0.0),
                      float(host_syncs_per_iter or 0.0)], np.float64)
    if _all_host_stats is not None:
        stats = np.asarray(_all_host_stats, np.float64)
        if stats.ndim == 1:
            stats = stats.reshape(1, -1)
        if stats.shape[1] < 6:           # legacy 3/4-column test rows
            stats = np.concatenate(
                [stats, np.zeros((stats.shape[0], 6 - stats.shape[1]))],
                axis=1)
        pidx = 0
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        stats = np.asarray(multihost_utils.process_allgather(local))
        pidx = jax.process_index()
    else:
        stats = local[None]
        pidx = 0

    means = stats[:, 1]
    waits = stats[:, 3]
    median = float(np.median(means))
    slowest = int(np.argmax(means))
    worst = float(means[slowest])
    skew = worst / median if median > 0 else 1.0
    wait_median = float(np.median(waits))
    wait_frac = wait_median / median if median > 0 else 0.0
    launches = float(np.median(stats[:, 4]))
    syncs = float(np.median(stats[:, 5]))
    # bottleneck classification (docs/DISTRIBUTED.md): a slow DEVICE shows
    # one host's compute far above the median (the others idle at the
    # barrier); a slow LINK shows level compute with everyone's barrier
    # wait high — the time is inside the collectives; a DISPATCH-bound
    # loop shows neither, but issues many launches (or per-iteration host
    # syncs) per step — each one fixed latency the fused iteration folds
    # away.  Rows without the counters (legacy 3/4-column test rows,
    # callers that never wired launches_per_iter) zero-pad to 0 and keep
    # their PRE-dispatch-era classification (device/link/balanced) — a
    # "balanced" verdict is only evidence of a fused loop when the
    # launches column is nonzero.
    if skew >= warn_skew:
        bottleneck = "device"
    elif wait_frac >= (warn_skew - 1.0):
        bottleneck = "link"
    elif launches > DISPATCH_LAUNCHES or syncs > DISPATCH_LAUNCHES:
        bottleneck = "dispatch"
    else:
        bottleneck = "balanced"
    report: Dict[str, Any] = {
        "event": "straggler_report",
        "hosts": int(stats.shape[0]),
        "window_iters": int(stats[:, 0].max()),
        "median_host_mean_s": round(median, 6),
        "max_host_mean_s": round(worst, 6),
        "max_host_max_s": round(float(stats[:, 2].max()), 6),
        "slowest_host": slowest,
        "skew": round(skew, 4),
        "median_comms_wait_s": round(wait_median, 6),
        "max_comms_wait_s": round(float(waits.max()), 6),
        "comms_wait_frac": round(wait_frac, 4),
        "launches_per_iter": round(launches, 3),
        "host_syncs_per_iter": round(syncs, 3),
        "bottleneck": bottleneck,
    }
    from ..telemetry import global_registry, global_tracer
    global_registry.record(report)
    global_registry.gauge("straggler/skew", skew)
    global_registry.gauge("straggler/comms_wait_frac", wait_frac)
    # the /metrics scrape surface (docs/OBSERVABILITY.md "Serving
    # observability") carries the training-side skew signal too, so one
    # Prometheus dashboard covers both halves of the train->serve loop
    global_registry.gauge("straggler/median_host_mean_s", median)
    global_registry.gauge("straggler/max_host_mean_s", worst)
    global_registry.gauge("straggler/launches_per_iter", launches)
    global_registry.gauge("straggler/host_syncs_per_iter", syncs)
    global_tracer.counter("straggler_skew", skew=skew)
    global_tracer.instant("straggler_report", bottleneck=bottleneck,
                          skew=round(skew, 4), hosts=int(stats.shape[0]))
    if pidx == 0 and stats.shape[0] > 1:
        if bottleneck == "device":
            log_warning(
                f"telemetry: straggler detected — host {slowest} averages "
                f"{worst * 1e3:.1f} ms/iter compute vs the "
                f"{median * 1e3:.1f} ms median across {stats.shape[0]} "
                f"hosts (skew {skew:.2f}x >= {warn_skew:.2f}x; slow "
                "DEVICE — the other hosts idle at the barrier)")
        elif bottleneck == "link":
            log_warning(
                f"telemetry: comms-bound — hosts spend a median "
                f"{wait_median * 1e3:.1f} ms/iter waiting at the barrier "
                f"({wait_frac:.0%} of the {median * 1e3:.1f} ms compute "
                "median) with level compute across hosts (slow LINK)")
        elif bottleneck == "dispatch":
            log_warning(
                f"telemetry: dispatch-bound — {launches:.1f} launches and "
                f"{syncs:.1f} host syncs per iteration at a level "
                f"{median * 1e3:.1f} ms/iter (each dispatch pays fixed "
                "latency; enable the fused iteration path, "
                "docs/DISTRIBUTED.md)")
        else:
            log_info(
                f"telemetry: {stats.shape[0]} hosts, median "
                f"{median * 1e3:.1f} ms/iter, max {worst * 1e3:.1f} ms "
                f"(host {slowest}, skew {skew:.2f}x, comms wait "
                f"{wait_median * 1e3:.1f} ms, {launches:.1f} launches/iter)")
    return report
