"""Voting-parallel tree learner (PV-Tree).

Reference: src/treelearner/voting_parallel_tree_learner.cpp — :104 (GlobalVoting:
each worker proposes its local top-k split features), :396 (only the globally
ELECTED features' histograms are allreduced; the best split is chosen among
them). This trades a tiny amount of split quality for communication volume
O(2k * B) instead of O(F * B) per round — the mode a DCN-connected TPU pod
uses when the feature count is large.

TPU re-design: the grower state keeps PER-DEVICE local histograms (leading
device axis sharded over the mesh via shard_map); each round
  1. every device builds local child histograms from its row shard (segsum),
  2. computes local per-feature best gains and votes for its top-k features,
  3. `psum` of the one-hot votes elects the global top-2k features,
  4. `psum` reduces ONLY the elected features' histogram columns,
  5. the best split among elected features is computed identically everywhere.
Scope: numeric features without EFB bundling (the reference's voting learner
also specializes the dense numeric path); the engine falls back to
tree_learner=data otherwise.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.split import leaf_output, leaf_term
from ..tree import TreeArrays
from ..utils.log import log_warning
from .mesh import DATA_AXIS

NEG_INF = -1e30


def _per_feature_best(hist, parent_g, parent_h, parent_c, lambda_l1, lambda_l2,
                      min_data_in_leaf, min_sum_hessian_in_leaf):
    """Numeric split scan returning PER-FEATURE bests: hist (S, F, B, 3) ->
    (gain (S,F), thr (S,F), left sums (S,F,3)). Simplified (no NaN bins/EFB:
    voting mode guards for that layout)."""
    cg = jnp.cumsum(hist[..., 0], axis=-1)
    ch = jnp.cumsum(hist[..., 1], axis=-1)
    cc = jnp.cumsum(hist[..., 2], axis=-1)
    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]

    rg, rh, rc = pg - cg, ph - ch, pc - cc
    gain = (leaf_term(cg, ch, lambda_l1, lambda_l2)
            + leaf_term(rg, rh, lambda_l1, lambda_l2)
            - leaf_term(pg, ph, lambda_l1, lambda_l2))
    ok = ((cc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
          (ch >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
    B = hist.shape[2]
    t_valid = jnp.arange(B)[None, None, :] < (B - 1)
    gain = jnp.where(ok & t_valid, gain, NEG_INF)
    thr = jnp.argmax(gain, axis=-1)                       # (S, F)
    bestg = jnp.take_along_axis(gain, thr[..., None], -1)[..., 0]
    lg = jnp.take_along_axis(cg, thr[..., None], -1)[..., 0]
    lh = jnp.take_along_axis(ch, thr[..., None], -1)[..., 0]
    lc = jnp.take_along_axis(cc, thr[..., None], -1)[..., 0]
    return bestg, thr, lg, lh, lc


def voting_split_round(bins_s, slot_s, grad_s, hess_s, cnt_s, parent_g,
                       parent_h, parent_c, col_mask, *, num_slots, bmax,
                       top_k, lambda_l1, lambda_l2, min_data_in_leaf,
                       min_sum_hessian_in_leaf, min_gain_to_split, axis):
    """One voting round, called INSIDE shard_map over the data axis.

    bins_s/slot_s/...: this device's row shard. parent sums are replicated.
    Returns replicated (gain, feature, threshold, left sums) for S slots."""
    S, B = num_slots, bmax
    n, F = bins_s.shape
    valid = slot_s >= 0
    s = jnp.where(valid, slot_s, 0)
    w = jnp.stack([grad_s, hess_s, cnt_s], -1) * valid[:, None]

    def per_feature(col):
        ids = s * B + col.astype(jnp.int32)
        h = jax.ops.segment_sum(w, ids, num_segments=S * B)
        return h.reshape(S, B, 3)

    hist_loc = jnp.transpose(jax.lax.map(per_feature, bins_s.T), (1, 0, 2, 3))

    # local parent sums for the vote gains (reference: local FindBestSplits)
    pg_loc = jax.ops.segment_sum(grad_s * valid, s, num_segments=S)
    ph_loc = jax.ops.segment_sum(hess_s * valid, s, num_segments=S)
    pc_loc = jax.ops.segment_sum(cnt_s * valid, s, num_segments=S)

    gain_loc, _, _, _, _ = _per_feature_best(
        hist_loc, pg_loc, ph_loc, pc_loc, lambda_l1, lambda_l2,
        min_data_in_leaf, min_sum_hessian_in_leaf)
    gain_loc = jnp.where(col_mask[None, :], gain_loc, NEG_INF)

    # ---- vote: local top-k features per slot (GlobalVoting, :104) ----
    k = min(top_k, F)
    top_gain, local_top = jax.lax.top_k(gain_loc, k)      # (S, k)
    # masked / splitless features carry NEG_INF gain; they must not receive
    # votes (the reference only proposes valid local splits)
    vote_w = (top_gain > NEG_INF / 2).astype(jnp.float32)
    votes = jnp.zeros((S, F)).at[
        jnp.arange(S)[:, None], local_top].add(vote_w)
    votes = jax.lax.psum(votes, axis)

    # ---- elect global top-2k and reduce ONLY their columns (:396) ----
    k2 = min(2 * k, F)
    _, elected = jax.lax.top_k(votes, k2)                 # (S, 2k)
    hist_elec = jnp.take_along_axis(
        hist_loc, elected[:, :, None, None], axis=1)      # (S, 2k, B, 3)
    hist_elec = jax.lax.psum(hist_elec, axis)

    gain_e, thr_e, lg_e, lh_e, lc_e = _per_feature_best(
        hist_elec, parent_g, parent_h, parent_c, lambda_l1, lambda_l2,
        min_data_in_leaf, min_sum_hessian_in_leaf)
    elected_mask = jnp.take_along_axis(
        jnp.broadcast_to(col_mask[None, :], (S, F)), elected, axis=1)
    gain_e = jnp.where(elected_mask, gain_e, NEG_INF)
    best = jnp.argmax(gain_e, axis=-1)                    # (S,)
    ar = jnp.arange(S)
    gain = gain_e[ar, best]
    gain = jnp.where(gain > min_gain_to_split, gain, NEG_INF)
    return (gain.astype(jnp.float32),
            elected[ar, best].astype(jnp.int32),
            thr_e[ar, best].astype(jnp.int32),
            lg_e[ar, best], lh_e[ar, best], lc_e[ar, best])


def make_voting_splitter(mesh: Mesh, num_slots: int, bmax: int, top_k: int,
                         cfg) -> "callable":
    """shard_map-wrapped voting split finder bound to the mesh."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]
    fn = functools.partial(
        voting_split_round, num_slots=num_slots, bmax=bmax, top_k=top_k,
        lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
        min_data_in_leaf=max(cfg.min_data_in_leaf, 1),
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
        min_gain_to_split=cfg.min_gain_to_split, axis=axis)
    row = P(axis)
    rep = P()
    kwargs = dict(mesh=mesh,
                  in_specs=(P(axis, None), row, row, row, row,
                            rep, rep, rep, rep),
                  out_specs=(rep, rep, rep, rep, rep, rep))
    try:
        return shard_map(fn, check_vma=False, **kwargs)
    except TypeError:
        try:
            return shard_map(fn, check_rep=False, **kwargs)
        except TypeError:
            return shard_map(fn, **kwargs)


def voting_supported(layout, routing) -> bool:
    """Numeric, unbundled layouts only (scope of the voting specialization)."""
    try:
        is_cat = np.asarray(layout.is_cat)
        bundled = np.asarray(routing.bundled)
        nan_bin = np.asarray(routing.nan_bin)
    except Exception:
        return False
    return (not is_cat.any()) and (not bundled.any()) and (nan_bin < 0).all()


class _VoteState(NamedTuple):
    leaf_id: jax.Array
    split_feature: jax.Array
    threshold_bin: jax.Array
    left_child: jax.Array
    right_child: jax.Array
    split_gain: jax.Array
    internal_value: jax.Array
    internal_weight: jax.Array
    internal_count: jax.Array
    sum_g: jax.Array
    sum_h: jax.Array
    cnt: jax.Array
    depth: jax.Array
    leaf_parent: jax.Array
    best_gain: jax.Array
    best_feat: jax.Array
    best_thr: jax.Array
    best_left_g: jax.Array
    best_left_h: jax.Array
    best_left_c: jax.Array
    num_leaves_cur: jax.Array
    progressed: jax.Array


def grow_tree_voting(bins, grad, hess, cnt_w, col_mask, splitter_root,
                     splitter, params) -> Tuple[TreeArrays, jax.Array]:
    """Voting-parallel batched leaf-wise growth (numeric/unbundled layouts).

    Unlike ops.grow.grow_tree there is NO global histogram state: every round
    re-derives child best-splits through the elected-feature voting reduce
    (reference: voting_parallel_tree_learner.cpp Train loop)."""
    N, F = bins.shape
    L = params.num_leaves
    S = min(params.max_splits_per_round, max(L - 1, 1))
    f32, i32 = jnp.float32, jnp.int32

    def leaf_out(g, h):
        return leaf_output(g, h, params.lambda_l1, params.lambda_l2,
                           params.max_delta_step)

    root_g, root_h, root_c = jnp.sum(grad), jnp.sum(hess), jnp.sum(cnt_w)
    g0, f0, t0, lg0, lh0, lc0 = splitter_root(
        bins, jnp.zeros(N, i32), grad, hess, cnt_w, root_g[None],
        root_h[None], root_c[None], col_mask)

    state = _VoteState(
        leaf_id=jnp.zeros(N, i32),
        split_feature=jnp.zeros(L, i32), threshold_bin=jnp.zeros(L, i32),
        left_child=jnp.zeros(L, i32), right_child=jnp.zeros(L, i32),
        split_gain=jnp.zeros(L, f32),
        internal_value=jnp.zeros(L, f32), internal_weight=jnp.zeros(L, f32),
        internal_count=jnp.zeros(L, f32),
        sum_g=jnp.zeros(L, f32).at[0].set(root_g),
        sum_h=jnp.zeros(L, f32).at[0].set(root_h),
        cnt=jnp.zeros(L, f32).at[0].set(root_c),
        depth=jnp.zeros(L, i32), leaf_parent=jnp.full(L, -1, i32),
        best_gain=jnp.full(L, NEG_INF, f32).at[0].set(g0[0]),
        best_feat=jnp.zeros(L, i32).at[0].set(f0[0]),
        best_thr=jnp.zeros(L, i32).at[0].set(t0[0]),
        best_left_g=jnp.zeros(L, f32).at[0].set(lg0[0]),
        best_left_h=jnp.zeros(L, f32).at[0].set(lh0[0]),
        best_left_c=jnp.zeros(L, f32).at[0].set(lc0[0]),
        num_leaves_cur=jnp.asarray(1, i32), progressed=jnp.asarray(True),
    )

    def cond(st):
        return st.progressed & (st.num_leaves_cur < L)

    def body(st):
        cur = st.num_leaves_cur
        remaining = L - cur
        drop = jnp.asarray(2 ** 30, i32)
        depth_ok = (params.max_depth <= 0) | (st.depth < jnp.asarray(
            params.max_depth if params.max_depth > 0 else 2 ** 30, i32))
        cand = jnp.where((st.best_gain > 0) & depth_ok, st.best_gain, NEG_INF)
        order = jnp.argsort(-cand)
        ranks = jnp.arange(L)
        chosen = (ranks < jnp.minimum(remaining, S)) & (cand[order] > 0)
        k = jnp.sum(chosen.astype(i32))
        pair_valid = jnp.arange(S) < k
        pair_old = jnp.where(pair_valid, order[:S], 0)
        pair_new = jnp.where(pair_valid, cur + jnp.arange(S), 0)
        pair_node = jnp.where(pair_valid, (cur - 1) + jnp.arange(S), 0)
        node_idx = jnp.where(pair_valid, pair_node, drop)
        new_idx = jnp.where(pair_valid, pair_new, drop)
        old_idx = jnp.where(pair_valid, pair_old, drop)

        feat = st.best_feat[pair_old]
        thr = st.best_thr[pair_old]
        gain = st.best_gain[pair_old]
        pg, ph, pc = st.sum_g[pair_old], st.sum_h[pair_old], st.cnt[pair_old]
        lg, lh, lc = (st.best_left_g[pair_old], st.best_left_h[pair_old],
                      st.best_left_c[pair_old])
        rg, rh, rc = pg - lg, ph - lh, pc - lc

        st2 = st._replace(
            split_feature=st.split_feature.at[node_idx].set(feat, mode="drop"),
            threshold_bin=st.threshold_bin.at[node_idx].set(thr, mode="drop"),
            split_gain=st.split_gain.at[node_idx].set(gain, mode="drop"),
            internal_value=st.internal_value.at[node_idx].set(
                leaf_out(pg, ph), mode="drop"),
            internal_weight=st.internal_weight.at[node_idx].set(ph, mode="drop"),
            internal_count=st.internal_count.at[node_idx].set(pc, mode="drop"),
            left_child=st.left_child.at[node_idx].set(~pair_old, mode="drop"),
            right_child=st.right_child.at[node_idx].set(~pair_new, mode="drop"),
        )
        parent_of_old = st.leaf_parent[pair_old]
        was_left = (st2.left_child[jnp.where(parent_of_old >= 0, parent_of_old,
                                             0)] == ~pair_old) & (parent_of_old >= 0)
        lp = jnp.where(pair_valid & (parent_of_old >= 0) & was_left,
                       parent_of_old, drop)
        rp = jnp.where(pair_valid & (parent_of_old >= 0) & ~was_left,
                       parent_of_old, drop)
        st2 = st2._replace(
            left_child=st2.left_child.at[lp].set(pair_node, mode="drop"),
            right_child=st2.right_child.at[rp].set(pair_node, mode="drop"),
            leaf_parent=(st2.leaf_parent.at[old_idx].set(pair_node, mode="drop")
                                        .at[new_idx].set(pair_node, mode="drop")))

        # route rows (numeric, unbundled: stored bin IS the feature bin)
        leaf_chosen = jnp.zeros(L, bool).at[old_idx].set(pair_valid, mode="drop")
        leaf_new = jnp.zeros(L, i32).at[old_idx].set(pair_new, mode="drop")
        leaf_feat = jnp.zeros(L, i32).at[old_idx].set(feat, mode="drop")
        leaf_thr = jnp.zeros(L, i32).at[old_idx].set(thr, mode="drop")
        r_feat = leaf_feat[st.leaf_id]
        gb = jnp.take_along_axis(bins, r_feat[:, None], axis=1)[:, 0]
        go_left = gb.astype(i32) <= leaf_thr[st.leaf_id]
        new_leaf = jnp.where(leaf_chosen[st.leaf_id] & ~go_left,
                             leaf_new[st.leaf_id], st.leaf_id)

        st2 = st2._replace(
            leaf_id=new_leaf,
            sum_g=st2.sum_g.at[old_idx].set(lg, mode="drop")
                          .at[new_idx].set(rg, mode="drop"),
            sum_h=st2.sum_h.at[old_idx].set(lh, mode="drop")
                          .at[new_idx].set(rh, mode="drop"),
            cnt=st2.cnt.at[old_idx].set(lc, mode="drop")
                      .at[new_idx].set(rc, mode="drop"),
            depth=st2.depth.at[new_idx].set(st.depth[pair_old] + 1, mode="drop")
                          .at[old_idx].set(st.depth[pair_old] + 1, mode="drop"))

        # children best splits through the voting reduce (2S slots)
        slot_map = jnp.full(L, -1, i32)
        slot_map = slot_map.at[old_idx].set(jnp.arange(S), mode="drop")
        slot_map = slot_map.at[new_idx].set(S + jnp.arange(S), mode="drop")
        slot2 = slot_map[new_leaf]
        ids2 = jnp.concatenate([pair_old, pair_new])
        valid2 = jnp.concatenate([pair_valid, pair_valid])
        g2, f2, t2, lg2, lh2, lc2 = splitter(
            bins, slot2, grad, hess, cnt_w, st2.sum_g[ids2], st2.sum_h[ids2],
            st2.cnt[ids2], col_mask)
        ids2_m = jnp.where(valid2, ids2, drop)
        st2 = st2._replace(
            best_gain=st2.best_gain.at[ids2_m].set(g2, mode="drop"),
            best_feat=st2.best_feat.at[ids2_m].set(f2, mode="drop"),
            best_thr=st2.best_thr.at[ids2_m].set(t2, mode="drop"),
            best_left_g=st2.best_left_g.at[ids2_m].set(lg2, mode="drop"),
            best_left_h=st2.best_left_h.at[ids2_m].set(lh2, mode="drop"),
            best_left_c=st2.best_left_c.at[ids2_m].set(lc2, mode="drop"))
        return st2._replace(num_leaves_cur=cur + k, progressed=k > 0)

    final = jax.lax.while_loop(cond, body, state)
    leaf_value = leaf_out(final.sum_g, final.sum_h)
    leaf_value = jnp.where(final.num_leaves_cur > 1, leaf_value, 0.0)
    Bmax = 1
    tree = TreeArrays(
        split_feature=final.split_feature, threshold_bin=final.threshold_bin,
        dir_flags=jnp.zeros(L, i32), left_child=final.left_child,
        right_child=final.right_child, split_gain=final.split_gain,
        internal_value=final.internal_value,
        internal_weight=final.internal_weight,
        internal_count=final.internal_count,
        cat_bitset=jnp.zeros((L, Bmax), bool),
        leaf_value=leaf_value, leaf_weight=final.sum_h, leaf_count=final.cnt,
        leaf_parent=final.leaf_parent, num_leaves=final.num_leaves_cur,
        leaf_depth=final.depth)
    return tree, final.leaf_id
