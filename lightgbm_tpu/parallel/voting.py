"""Voting-parallel tree learner (PV-Tree).

Reference: src/treelearner/voting_parallel_tree_learner.cpp — :104 (GlobalVoting:
each worker proposes its local top-k split features), :396 (only the globally
ELECTED features' histograms are allreduced; the best split is chosen among
them). This trades a tiny amount of split quality for communication volume
O(2k * B) instead of O(F * B) per round — the mode a DCN-connected TPU pod
uses when the feature count is large.

TPU re-design: per-device local histograms live inside shard_map over the
data axis; each round
  1. every device builds local GROUP histograms from its row shard (segsum)
     and gathers them to per-FEATURE histograms (EFB bundles residual-fill
     against the LOCAL per-slot parent sums — the fill is linear in both the
     histogram and the parent, so the psum of locally-filled histograms
     equals the globally-filled one),
  2. computes local per-feature best gains and votes for its top-k features,
  3. `psum` of the one-hot votes elects the global top-2k features per slot,
  4. `psum` reduces ONLY the elected features' histogram columns,
  5. the FULL split scan (ops.split.find_best_splits — NaN directions,
     scan-order tie-breaks, categorical one-hot/sorted-subset) runs on the
     elected subset, vmapped over slots with per-slot gathered sub-layouts,
     identically on every device.

All training layouts are supported — EFB bundles, NaN bins, and categorical
features ride the same scan as the serial learner (the reference's voting
learner handles every layout too).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.grow import RoutingLayout, feature_local_bin
from ..ops.split import (DIR_CATEGORICAL, DIR_DEFAULT_LEFT, FeatureLayout,
                         categorical_left_bitset, find_best_splits,
                         gather_feature_histograms, leaf_output, leaf_term,
                         round_int)
from ..tree import TreeArrays
from ..utils.log import log_warning
from .mesh import DATA_AXIS

NEG_INF = -1e30
EPS_HESS = 1e-15


def _local_feature_hists(bins_s, slot_s, grad_s, hess_s, cnt_s, layout,
                         num_slots, bmax):
    """This device's per-feature histograms (S, F, B, 3) with EFB residual
    fill against the LOCAL per-slot parent sums, plus those local parents."""
    S, B = num_slots, bmax
    valid = slot_s >= 0
    s = jnp.where(valid, slot_s, 0)
    w = jnp.stack([grad_s, hess_s, cnt_s], -1) * valid[:, None]

    def per_group(col):
        ids = s * B + col.astype(jnp.int32)
        h = jax.ops.segment_sum(w, ids, num_segments=S * B)
        return h.reshape(S, B, 3)

    hist_g = jnp.transpose(jax.lax.map(per_group, bins_s.T), (1, 0, 2, 3))
    pg = jax.ops.segment_sum(grad_s * valid, s, num_segments=S)
    ph = jax.ops.segment_sum(hess_s * valid, s, num_segments=S)
    pc = jax.ops.segment_sum(cnt_s * valid, s, num_segments=S)
    hist_f = gather_feature_histograms(hist_g, layout, pg, ph, pc)
    return hist_f, pg, ph, pc


def _vote_gain_scan(hist_f, pg, ph, pc, layout, lambda_l1, lambda_l2,
                    min_data_in_leaf, min_sum_hessian_in_leaf):
    """Per-feature best-gain scan for the local VOTES only (both missing
    directions for numeric features; categorical features vote with their
    best one-hot gain — the reference ranks votes by local best gain)."""
    hg, hh, hc = hist_f[..., 0], hist_f[..., 1], hist_f[..., 2]
    cg = jnp.cumsum(hg, -1)
    ch = jnp.cumsum(hh, -1)
    cc = jnp.cumsum(hc, -1)
    pgb = pg[:, None, None]
    phb = ph[:, None, None]
    pcb = pc[:, None, None]

    def gains(lg, lh, lc):
        rg, rh, rc = pgb - lg, phb - lh, pcb - lc
        g = (leaf_term(lg, lh, lambda_l1, lambda_l2)
             + leaf_term(rg, rh, lambda_l1, lambda_l2)
             - leaf_term(pgb, phb, lambda_l1, lambda_l2))
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf) &
              (lh >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
        return jnp.where(ok, g, NEG_INF)

    B = hg.shape[-1]
    S = hg.shape[0]
    nbins = layout.num_bins
    nan_bin = layout.nan_bin
    has_nan = (nan_bin >= 0)[None, :, None]
    nidx = jnp.maximum(nan_bin, 0)
    nan_g = jnp.where(has_nan, jnp.take_along_axis(
        hg, nidx[None, :, None].repeat(S, 0), -1), 0.0)
    nan_h = jnp.where(has_nan, jnp.take_along_axis(
        hh, nidx[None, :, None].repeat(S, 0), -1), 0.0)
    nan_c = jnp.where(has_nan, jnp.take_along_axis(
        hc, nidx[None, :, None].repeat(S, 0), -1), 0.0)
    data_bins = jnp.where(nan_bin[None, :, None] >= 0,
                          nbins[None, :, None] - 1, nbins[None, :, None])
    biota = jnp.arange(B)[None, None, :]
    g_rev = jnp.where(biota < data_bins - 1,
                      gains(cg + nan_g, ch + nan_h, cc + nan_c), NEG_INF)
    g_fwd = jnp.where(has_nan & (biota < data_bins),
                      gains(cg, ch, cc), NEG_INF)
    num_best = jnp.max(jnp.maximum(g_rev, g_fwd), axis=-1)       # (S, F)
    vm_res = layout.valid_mask | (
        (jnp.arange(B)[None, :] == layout.residual_pos[:, None])
        & (layout.residual_pos >= 0)[:, None])
    cat_best = jnp.max(jnp.where(vm_res[None],
                                 gains(hg, hh, hc), NEG_INF), axis=-1)
    return jnp.where(layout.is_cat[None, :], cat_best, num_best)


def voting_split_round(bins_s, slot_s, grad_s, hess_s, cnt_s, parent_g,
                       parent_h, parent_c, col_mask, *, layout, num_slots,
                       bmax, top_k, scan_kw, vote_kw, cat_kw, axis):
    """One voting round, called INSIDE shard_map over the data axis.

    Returns replicated per-slot winners: (gain, GLOBAL feature id,
    threshold, dir_flags, left g/h/c, cat bitset (B,))."""
    S, B = num_slots, bmax
    F = layout.gather_idx.shape[0]
    # validity incl. the residual-filled EFB default bin (the gathered
    # histograms carry it even though the stored layout does not)
    vm_res = layout.valid_mask | (
        (jnp.arange(B)[None, :] == layout.residual_pos[:, None])
        & (layout.residual_pos >= 0)[:, None])
    hist_loc, pg_loc, ph_loc, pc_loc = _local_feature_hists(
        bins_s, slot_s, grad_s, hess_s, cnt_s, layout, S, B)

    gain_loc = _vote_gain_scan(hist_loc, pg_loc, ph_loc, pc_loc, layout,
                               **vote_kw)
    gain_loc = jnp.where(col_mask[None, :], gain_loc, NEG_INF)

    # ---- vote: local top-k features per slot (GlobalVoting, :104) ----
    k = min(top_k, F)
    top_gain, local_top = jax.lax.top_k(gain_loc, k)      # (S, k)
    vote_w = (top_gain > NEG_INF / 2).astype(jnp.float32)
    votes = jnp.zeros((S, F)).at[
        jnp.arange(S)[:, None], local_top].add(vote_w)
    votes = jax.lax.psum(votes, axis)

    # ---- elect global top-2k and reduce ONLY their columns (:396) ----
    k2 = min(2 * k, F)
    _, elected = jax.lax.top_k(votes, k2)                 # (S, 2k)
    hist_elec = jnp.take_along_axis(
        hist_loc, elected[:, :, None, None], axis=1)      # (S, 2k, B, 3)
    hist_elec = jax.lax.psum(hist_elec, axis)

    # ---- full scan on the elected subset (vmapped per slot: each slot has
    # its own elected set, hence its own gathered sub-layout) ----
    iota_gather = (jnp.arange(k2, dtype=jnp.int32)[:, None] * B
                   + jnp.arange(B, dtype=jnp.int32)[None, :])

    def scan_one(h_e, pg1, ph1, pc1, e_s):
        # the elected histograms are ALREADY residual-filled (the local
        # gather filled EFB default bins before the psum), so the sub-layout
        # must mark the residual position VALID and not fill again
        sub = FeatureLayout(
            gather_idx=iota_gather,
            valid_mask=vm_res[e_s],
            residual_pos=jnp.full(k2, -1, jnp.int32),
            nan_bin=layout.nan_bin[e_s],
            is_cat=layout.is_cat[e_s],
            num_bins=layout.num_bins[e_s],
            mzero_bin=(layout.mzero_bin[e_s]
                       if layout.mzero_bin is not None else None))
        res = find_best_splits(
            h_e[None, :, :, :2], pg1[None], ph1[None], pc1[None],
            layout=sub, col_mask=col_mask[e_s][None], **scan_kw)
        return jax.tree.map(lambda a: a[0], res)

    res = jax.vmap(scan_one)(hist_elec, parent_g, parent_h, parent_c,
                             elected)
    ar = jnp.arange(S)
    feat_global = elected[ar, res.feature]

    # categorical winners: recompute the left-side bin membership from the
    # reduced histogram (identical on every device)
    hist_win = hist_elec[ar, res.feature, :, :2]           # (S, B, 2)
    vm_win = vm_res[feat_global]
    cnt_factor = parent_c / jnp.maximum(parent_h, EPS_HESS)
    bitset = categorical_left_bitset(
        hist_win, res.threshold, res.dir_flags, vm_win,
        cat_kw["cat_smooth"], cat_kw["min_data_per_group"], cnt_factor)

    return (res.gain.astype(jnp.float32), feat_global.astype(jnp.int32),
            res.threshold.astype(jnp.int32), res.dir_flags.astype(jnp.int32),
            res.left_sum_g, res.left_sum_h, res.left_count, bitset)


def make_voting_splitter(mesh: Mesh, num_slots: int, bmax: int, top_k: int,
                         cfg, layout=None) -> "callable":
    """shard_map-wrapped voting split finder bound to the mesh + layout."""
    from .mesh import shard_map_rows
    axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else mesh.axis_names[0]
    scan_kw = dict(
        lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
        min_data_in_leaf=max(cfg.min_data_in_leaf, 1),
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
        min_gain_to_split=cfg.min_gain_to_split,
        cat_l2=cfg.cat_l2, cat_smooth=cfg.cat_smooth,
        max_cat_threshold=cfg.max_cat_threshold,
        max_cat_to_onehot=cfg.max_cat_to_onehot,
        min_data_per_group=cfg.min_data_per_group)
    vote_kw = dict(
        lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
        min_data_in_leaf=max(cfg.min_data_in_leaf, 1),
        min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf)
    cat_kw = dict(cat_smooth=cfg.cat_smooth,
                  min_data_per_group=cfg.min_data_per_group)
    fn = functools.partial(
        voting_split_round, layout=layout, num_slots=num_slots, bmax=bmax,
        top_k=top_k, scan_kw=scan_kw, vote_kw=vote_kw, cat_kw=cat_kw,
        axis=axis)
    row = P(axis)
    rep = P()
    return shard_map_rows(
        fn, mesh,
        (P(axis, None), row, row, row, row, rep, rep, rep, rep),
        (rep,) * 8)


def voting_supported(layout, routing) -> bool:
    """Every training layout is supported (EFB / NaN / categorical)."""
    return True


def compact_views_sharded(bins, grad, hess, cnt_w, compact_rows: int,
                          mesh, row_axis):
    """Per-shard GOSS/bagging row compaction for the voting learner: every
    device stable-partitions its OWN row shard (in-bag rows first,
    original relative order) and truncates to the static ``compact_rows``
    capacity — no cross-device row movement.  The truncated tail carries
    exact-zero weights, so every shard-local histogram (and therefore
    every vote and every elected reduce) is bitwise identical to the
    dense-masked pass (the SamplePlan contract, ops/compact.py)."""
    from ..ops.compact import plan_sample_rows

    def _local(b, g, h, c):
        perm = plan_sample_rows(c, compact_rows).perm
        return (jnp.take(b, perm, axis=0), jnp.take(g, perm, axis=0),
                jnp.take(h, perm, axis=0), jnp.take(c, perm, axis=0))

    with jax.named_scope("voting_compact_rows"):
        if mesh is None:
            return _local(bins, grad, hess, cnt_w)
        from .mesh import shard_map_rows
        row = P(row_axis)
        return shard_map_rows(
            _local, mesh,
            (P(row_axis, None), row, row, row),
            (P(row_axis, None), row, row, row))(bins, grad, hess, cnt_w)


class _VoteState(NamedTuple):
    leaf_id: jax.Array
    # compacted-view leaf ids (GOSS/bagging row compaction; (1,) dummy
    # when compaction is off — histogram/vote passes route the compacted
    # rows, the full-data route keeps `leaf_id` current for every row)
    leaf_id_c: jax.Array
    split_feature: jax.Array
    threshold_bin: jax.Array
    dir_flags: jax.Array
    left_child: jax.Array
    right_child: jax.Array
    split_gain: jax.Array
    internal_value: jax.Array
    internal_weight: jax.Array
    internal_count: jax.Array
    cat_bitset: jax.Array
    sum_g: jax.Array
    sum_h: jax.Array
    cnt: jax.Array
    depth: jax.Array
    leaf_parent: jax.Array
    best_gain: jax.Array
    best_feat: jax.Array
    best_thr: jax.Array
    best_dir: jax.Array
    best_bits: jax.Array
    best_left_g: jax.Array
    best_left_h: jax.Array
    best_left_c: jax.Array
    num_leaves_cur: jax.Array
    progressed: jax.Array


def grow_tree_voting(bins, grad, hess, cnt_w, col_mask, splitter_root,
                     splitter, params, routing: RoutingLayout,
                     mesh=None, row_axis=None, compact_rows: int = 0
                     ) -> Tuple[TreeArrays, jax.Array]:
    """Voting-parallel batched leaf-wise growth (all layouts).

    Unlike ops.grow.grow_tree there is NO global histogram state: every round
    re-derives child best-splits through the elected-feature voting reduce
    (reference: voting_parallel_tree_learner.cpp Train loop).

    compact_rows: static PER-SHARD capacity for GOSS/bagging row
    compaction (0 = off): one stable partition per tree gathers each
    shard's in-bag rows to the front, every vote/histogram pass streams
    only ``compact_rows`` rows per shard, and a per-round full-data
    route-only pass keeps ``leaf_id`` current for all N rows (score
    update).  Bitwise identical to the dense-masked pass — the truncated
    tail carries exact-zero weights (ops/compact.SamplePlan contract)."""
    N, G = bins.shape
    L = params.num_leaves
    S = min(params.max_splits_per_round, max(L - 1, 1))
    f32, i32 = jnp.float32, jnp.int32
    Bmax = params_bmax = None

    def leaf_out(g, h):
        return leaf_output(g, h, params.lambda_l1, params.lambda_l2,
                           params.max_delta_step)

    use_compact = compact_rows > 0
    if use_compact:
        bins_h, grad_h, hess_h, cnt_h = compact_views_sharded(
            bins, grad, hess, cnt_w, compact_rows, mesh, row_axis)
    else:
        bins_h, grad_h, hess_h, cnt_h = bins, grad, hess, cnt_w
    Nh = bins_h.shape[0]

    root_g, root_h, root_c = jnp.sum(grad), jnp.sum(hess), jnp.sum(cnt_w)
    (g0, f0, t0, d0, lg0, lh0, lc0, b0) = splitter_root(
        bins_h, jnp.zeros(Nh, i32), grad_h, hess_h, cnt_h, root_g[None],
        root_h[None], root_c[None], col_mask)
    Bmax = b0.shape[-1]

    state = _VoteState(
        leaf_id=jnp.zeros(N, i32),
        leaf_id_c=jnp.zeros(Nh if use_compact else 1, i32),
        split_feature=jnp.zeros(L, i32), threshold_bin=jnp.zeros(L, i32),
        dir_flags=jnp.zeros(L, i32),
        left_child=jnp.zeros(L, i32), right_child=jnp.zeros(L, i32),
        split_gain=jnp.zeros(L, f32),
        internal_value=jnp.zeros(L, f32), internal_weight=jnp.zeros(L, f32),
        internal_count=jnp.zeros(L, f32),
        cat_bitset=jnp.zeros((L, Bmax), bool),
        sum_g=jnp.zeros(L, f32).at[0].set(root_g),
        sum_h=jnp.zeros(L, f32).at[0].set(root_h),
        cnt=jnp.zeros(L, f32).at[0].set(root_c),
        depth=jnp.zeros(L, i32), leaf_parent=jnp.full(L, -1, i32),
        best_gain=jnp.full(L, NEG_INF, f32).at[0].set(g0[0]),
        best_feat=jnp.zeros(L, i32).at[0].set(f0[0]),
        best_thr=jnp.zeros(L, i32).at[0].set(t0[0]),
        best_dir=jnp.zeros(L, i32).at[0].set(d0[0]),
        best_bits=jnp.zeros((L, Bmax), bool).at[0].set(b0[0]),
        best_left_g=jnp.zeros(L, f32).at[0].set(lg0[0]),
        best_left_h=jnp.zeros(L, f32).at[0].set(lh0[0]),
        best_left_c=jnp.zeros(L, f32).at[0].set(lc0[0]),
        num_leaves_cur=jnp.asarray(1, i32), progressed=jnp.asarray(True),
    )

    def cond(st):
        return st.progressed & (st.num_leaves_cur < L)

    def body(st):
        cur = st.num_leaves_cur
        remaining = L - cur
        drop = jnp.asarray(2 ** 30, i32)
        depth_ok = (params.max_depth <= 0) | (st.depth < jnp.asarray(
            params.max_depth if params.max_depth > 0 else 2 ** 30, i32))
        cand = jnp.where((st.best_gain > 0) & depth_ok, st.best_gain, NEG_INF)
        order = jnp.argsort(-cand)
        ranks = jnp.arange(L)
        chosen = (ranks < jnp.minimum(remaining, S)) & (cand[order] > 0)
        k = jnp.sum(chosen, dtype=i32)
        pair_valid = jnp.arange(S) < k
        pair_old = jnp.where(pair_valid, order[:S].astype(i32), 0)
        pair_new = jnp.where(pair_valid, cur + jnp.arange(S, dtype=i32), 0)
        pair_node = jnp.where(pair_valid, (cur - 1) + jnp.arange(S, dtype=i32),
                              0)
        node_idx = jnp.where(pair_valid, pair_node, drop)
        new_idx = jnp.where(pair_valid, pair_new, drop)
        old_idx = jnp.where(pair_valid, pair_old, drop)

        feat = st.best_feat[pair_old]
        thr = st.best_thr[pair_old]
        dirf = st.best_dir[pair_old]
        bits = st.best_bits[pair_old]
        gain = st.best_gain[pair_old]
        pg, ph, pc = st.sum_g[pair_old], st.sum_h[pair_old], st.cnt[pair_old]
        lg, lh, lc = (st.best_left_g[pair_old], st.best_left_h[pair_old],
                      st.best_left_c[pair_old])
        rg, rh, rc = pg - lg, ph - lh, pc - lc

        st2 = st._replace(
            split_feature=st.split_feature.at[node_idx].set(feat, mode="drop"),
            threshold_bin=st.threshold_bin.at[node_idx].set(thr, mode="drop"),
            dir_flags=st.dir_flags.at[node_idx].set(dirf, mode="drop"),
            split_gain=st.split_gain.at[node_idx].set(gain, mode="drop"),
            internal_value=st.internal_value.at[node_idx].set(
                leaf_out(pg, ph), mode="drop"),
            internal_weight=st.internal_weight.at[node_idx].set(ph, mode="drop"),
            internal_count=st.internal_count.at[node_idx].set(pc, mode="drop"),
            cat_bitset=st.cat_bitset.at[node_idx].set(bits, mode="drop"),
            left_child=st.left_child.at[node_idx].set(~pair_old, mode="drop"),
            right_child=st.right_child.at[node_idx].set(~pair_new, mode="drop"),
        )
        parent_of_old = st.leaf_parent[pair_old]
        was_left = (st2.left_child[jnp.where(parent_of_old >= 0, parent_of_old,
                                             0)] == ~pair_old) & (parent_of_old >= 0)
        lp = jnp.where(pair_valid & (parent_of_old >= 0) & was_left,
                       parent_of_old, drop)
        rp = jnp.where(pair_valid & (parent_of_old >= 0) & ~was_left,
                       parent_of_old, drop)
        st2 = st2._replace(
            left_child=st2.left_child.at[lp].set(pair_node, mode="drop"),
            right_child=st2.right_child.at[rp].set(pair_node, mode="drop"),
            leaf_parent=(st2.leaf_parent.at[old_idx].set(pair_node, mode="drop")
                                        .at[new_idx].set(pair_node, mode="drop")))

        # ---- route rows: EFB feature-local bins, NaN default direction,
        # categorical bitsets (same semantics as ops.grow's non-stream path)
        leaf_chosen = jnp.zeros(L, bool).at[old_idx].set(pair_valid, mode="drop")
        leaf_new = jnp.zeros(L, i32).at[old_idx].set(pair_new, mode="drop")
        leaf_feat = jnp.zeros(L, i32).at[old_idx].set(feat, mode="drop")
        leaf_thr = jnp.zeros(L, i32).at[old_idx].set(thr, mode="drop")
        leaf_dir = jnp.zeros(L, i32).at[old_idx].set(dirf, mode="drop")
        leaf_bits = jnp.zeros((L, Bmax), bool).at[old_idx].set(bits,
                                                               mode="drop")

        def route(bins_x, lid_x):
            r_chosen = leaf_chosen[lid_x]
            r_feat = leaf_feat[lid_x]
            r_grp = routing.feat_group[r_feat]
            gb = jnp.take_along_axis(bins_x, r_grp[:, None].astype(i32),
                                     axis=1)[:, 0]
            fb = feature_local_bin(gb, r_feat, routing)
            r_thr = leaf_thr[lid_x]
            r_dir = leaf_dir[lid_x]
            is_cat = (r_dir & DIR_CATEGORICAL) != 0
            default_left = (r_dir & DIR_DEFAULT_LEFT) != 0
            is_nan = (routing.nan_bin[r_feat] >= 0) \
                & (fb == routing.nan_bin[r_feat])
            mzb_r = (routing.mzero_bin[r_feat]
                     if routing.mzero_bin is not None
                     else jnp.full_like(r_feat, -1))
            is_miss = is_nan | ((mzb_r >= 0) & (fb == mzb_r))
            go_left_num = jnp.where(is_miss, default_left, fb <= r_thr)
            go_left_cat = leaf_bits.reshape(-1)[lid_x * Bmax + fb]
            go_left = jnp.where(is_cat, go_left_cat, go_left_num)
            return jnp.where(r_chosen & ~go_left, leaf_new[lid_x], lid_x)

        new_leaf = route(bins, st.leaf_id)
        new_leaf_c = (route(bins_h, st.leaf_id_c) if use_compact
                      else st.leaf_id_c)

        st2 = st2._replace(
            leaf_id=new_leaf,
            leaf_id_c=new_leaf_c,
            sum_g=st2.sum_g.at[old_idx].set(lg, mode="drop")
                          .at[new_idx].set(rg, mode="drop"),
            sum_h=st2.sum_h.at[old_idx].set(lh, mode="drop")
                          .at[new_idx].set(rh, mode="drop"),
            cnt=st2.cnt.at[old_idx].set(lc, mode="drop")
                      .at[new_idx].set(rc, mode="drop"),
            depth=st2.depth.at[new_idx].set(st.depth[pair_old] + 1, mode="drop")
                          .at[old_idx].set(st.depth[pair_old] + 1, mode="drop"))

        # children best splits through the voting reduce (2S slots)
        slot_map = jnp.full(L, -1, i32)
        slot_map = slot_map.at[old_idx].set(jnp.arange(S, dtype=i32),
                                            mode="drop")
        slot_map = slot_map.at[new_idx].set(S + jnp.arange(S, dtype=i32),
                                            mode="drop")
        slot2 = slot_map[new_leaf_c if use_compact else new_leaf]
        ids2 = jnp.concatenate([pair_old, pair_new])
        valid2 = jnp.concatenate([pair_valid, pair_valid])
        (g2, f2, t2, d2, lg2, lh2, lc2, b2) = splitter(
            bins_h, slot2, grad_h, hess_h, cnt_h, st2.sum_g[ids2],
            st2.sum_h[ids2], st2.cnt[ids2], col_mask)
        ids2_m = jnp.where(valid2, ids2, drop)
        st2 = st2._replace(
            best_gain=st2.best_gain.at[ids2_m].set(g2, mode="drop"),
            best_feat=st2.best_feat.at[ids2_m].set(f2, mode="drop"),
            best_thr=st2.best_thr.at[ids2_m].set(t2, mode="drop"),
            best_dir=st2.best_dir.at[ids2_m].set(d2, mode="drop"),
            best_bits=st2.best_bits.at[ids2_m].set(b2, mode="drop"),
            best_left_g=st2.best_left_g.at[ids2_m].set(lg2, mode="drop"),
            best_left_h=st2.best_left_h.at[ids2_m].set(lh2, mode="drop"),
            best_left_c=st2.best_left_c.at[ids2_m].set(lc2, mode="drop"))
        return st2._replace(num_leaves_cur=cur + k, progressed=k > 0)

    final = jax.lax.while_loop(cond, body, state)
    leaf_value = leaf_out(final.sum_g, final.sum_h)
    leaf_value = jnp.where(final.num_leaves_cur > 1, leaf_value, 0.0)
    tree = TreeArrays(
        split_feature=final.split_feature, threshold_bin=final.threshold_bin,
        dir_flags=final.dir_flags, left_child=final.left_child,
        right_child=final.right_child, split_gain=final.split_gain,
        internal_value=final.internal_value,
        internal_weight=final.internal_weight,
        internal_count=final.internal_count,
        cat_bitset=final.cat_bitset,
        leaf_value=leaf_value, leaf_weight=final.sum_h, leaf_count=final.cnt,
        leaf_parent=final.leaf_parent, num_leaves=final.num_leaves_cur,
        leaf_depth=final.depth)
    return tree, final.leaf_id
