"""Closed-loop freshness pipeline: ``task=pipeline``
(docs/ROBUSTNESS.md "Closed-loop freshness").

One CLI invocation runs the whole production loop:

1. **train**   — base model (skipped when ``input_model`` is given), with
   a final PR 3 checkpoint so the refit stage continues from a
   crash-consistent snapshot, not a bare model file.
2. **refit**   — continued training on ``pipeline_fresh_data`` (streamed
   via the ingest pipeline: fresh data never needs to fit in RAM), then
   the TPU-native leaf-value refit (``refit.refit_leaf_values``: stream
   kernel route replay + f64 segment sums, ``refit_decay_rate`` blend).
3. **gate**    — the candidate must pass nan_guard/corruption validation
   (``validate_candidate``), must not regress the holdout metric by more
   than ``pipeline_gate_margin`` vs the serving baseline, and must carry
   a regenerated quality-profile sidecar.
4. **promote** — atomic fleet-wide promotion through the ``promote.json``
   generation pointer; the promotion is a telemetry instant, replicas'
   convergence is awaited, and the train-vs-serve score drift of a probe
   batch is stamped into telemetry (zero tolerance: the fleet must serve
   ``Booster.predict`` bitwise).
5. **observe** — for ``pipeline_observe_s`` seconds the watcher polls the
   replicas' SLO and drift alerts; a burn triggers automatic rollback to
   the prior generation (``rollback_pointer``) without operator action.

Every fault injected by the chaos matrix (poison_refit, kill_refit,
torn_pointer, truncated candidate) must leave the fleet serving its old
sha256 — the pipeline only ever moves the pointer AFTER the gate, and
verifies its own pointer write before declaring success.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .utils.log import LightGBMError, log_info, log_warning

_PROMOTE_WAIT_S = 30.0


# ---------------------------------------------------------------------------
# fleet-dir plumbing (file-based: works with no in-process fleet handle)
# ---------------------------------------------------------------------------

def _replica_endpoints(fleet_dir: str) -> List[Tuple[int, str, int]]:
    """(rank, host, port) from the replica_<r>.json files the replicas
    publish; unreadable files (replica mid-restart) are skipped."""
    out: List[Tuple[int, str, int]] = []
    if not fleet_dir:
        return out
    import glob as _glob
    import re as _re
    for p in sorted(_glob.glob(os.path.join(fleet_dir, "replica_*.json"))):
        m = _re.match(r"replica_(\d+)\.json$", os.path.basename(p))
        if not m:
            continue
        try:
            with open(p) as fh:
                ep = json.load(fh)
            out.append((int(m.group(1)), str(ep["host"]), int(ep["port"])))
        except (OSError, ValueError, KeyError):
            continue
    return out


def _http(host: str, port: int, method: str, path: str, obj=None,
          timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    import http.client
    from .serving.front import http_json
    try:
        _, payload, _ = http_json(host, port, method, path, obj=obj,
                                  timeout=timeout)
        return payload
    except (OSError, http.client.HTTPException, ValueError):
        return None


def _tenant_state(state: Optional[Dict[str, Any]],
                  model_id: str) -> Dict[str, Any]:
    """The record carrying sha256/generation/alerts for the promotion's
    target: the per-model entry of a multi-tenant replica's /ready when
    ``model_id`` is set, the flat payload otherwise."""
    if state is None:
        return {}
    if model_id:
        return (state.get("models") or {}).get(model_id) or {}
    return state


def _wait_for_sha(fleet_dir: str, sha: str, generation: int,
                  timeout_s: float, model_id: str = "") -> Dict[str, Any]:
    """Poll replica /ready until every reachable replica serves ``sha``
    (for ``model_id``'s tenant when set — siblings are not consulted);
    returns the convergence record."""
    sha_key = "sha256" if model_id else "model_sha256"
    deadline = time.monotonic() + timeout_s
    converged: Dict[int, bool] = {}
    reachable = 0
    while True:
        eps = _replica_endpoints(fleet_dir)
        if not eps:
            # pointer-only promotion (no replica has published an endpoint
            # file): nothing to await — the pointer is the contract
            break
        states = {r: _http(h, p, "GET", "/ready") for r, h, p in eps}
        reachable = sum(1 for s in states.values() if s is not None)
        converged = {
            r: (str(_tenant_state(s, model_id).get(sha_key)) == sha
                and int(_tenant_state(s, model_id)
                        .get("seen_generation", 0)) >= 0)
            for r, s in states.items()}
        if reachable and all(converged.values()):
            break
        if time.monotonic() > deadline:
            break
        time.sleep(0.1)
    out = {"generation": int(generation), "sha256": sha,
           "reachable": reachable,
           "converged": sorted(r for r, ok in converged.items() if ok),
           "pending": sorted(r for r, ok in converged.items() if not ok)}
    if model_id:
        out["model_id"] = model_id
    return out


# ---------------------------------------------------------------------------
# stages
# ---------------------------------------------------------------------------

def _stage_train(params: Dict[str, Any], cfg: Config,
                 out_model: str) -> Tuple[Booster, Optional[Dataset], str]:
    """Base model: load ``input_model`` when given, else train on
    ``data=`` and force a final checkpoint (the refit stage continues
    from the snapshot, proving the PR 3 interplay end to end)."""
    from .engine import train as engine_train

    input_model = str(params.get("input_model", "") or "")
    if input_model:
        bst = Booster(model_file=input_model, params=dict(params))
        return bst, None, input_model
    data_path = params.get("data")
    if not data_path:
        raise LightGBMError(
            "task=pipeline requires data=<file> (or input_model=<file>)")
    ds = Dataset(str(data_path), params=dict(params))
    num_rounds = int(params.get("num_iterations", 100))
    bst = engine_train(params, ds, num_boost_round=num_rounds)
    bst.save_model(out_model)
    keep = int(params.get("snapshot_keep", -1) or -1)
    bst.checkpoint(out_model, keep=keep)
    return bst, ds, out_model


def _stage_refit(params: Dict[str, Any], cfg: Config, base_bst: Booster,
                 base_ds: Optional[Dataset], base_path: str,
                 out_model: str, candidate_path: str,
                 report: Dict[str, Any]) -> Booster:
    """Continued training on the fresh data + device leaf refit."""
    from .engine import train as engine_train
    from .refit import refit_leaf_values
    from .robustness.checkpoint import latest_valid_snapshot, load_checkpoint

    fresh = str(params.get("pipeline_fresh_data", "") or "")
    if not fresh:
        raise LightGBMError(
            "task=pipeline requires pipeline_fresh_data=<file> "
            "(alias fresh_data)")
    # resume source: the newest valid checkpoint of the base model when
    # one exists (crash-consistent, sha-sealed), else the model file
    init = base_bst
    snap = latest_valid_snapshot(out_model, params=dict(params))
    if snap is not None:
        model_str, manifest, _ = load_checkpoint(snap, params=dict(params))
        init = Booster(model_str=model_str, params=dict(params))
        report["refit_source"] = {"checkpoint": snap,
                                  "iteration": int(manifest["iteration"])}
    else:
        report["refit_source"] = {"model_file": base_path}
    fresh_ds = Dataset(fresh, params=dict(params), reference=base_ds)
    refit_iters = int(cfg.pipeline_refit_iterations)
    if refit_iters > 0:
        p2 = dict(params)
        # the candidate's own snapshots must not clobber the base run's,
        # and num_iterations= in the user params governs the BASE model,
        # not the continuation (engine.train lets it trump num_boost_round)
        p2["output_model"] = candidate_path
        p2["num_iterations"] = refit_iters
        p2.pop("snapshot_freq", None)
        cand = engine_train(p2, fresh_ds, num_boost_round=refit_iters,
                            init_model=init)
    else:
        cand = Booster(model_str=init.model_to_string(),
                       params=dict(params))
    report["refit"] = refit_leaf_values(cand, fresh_ds,
                                        decay_rate=cfg.refit_decay_rate)
    report["refit"]["continued_iterations"] = refit_iters
    stats = getattr(fresh_ds, "ingest_stats", None) or {}
    report["refit"]["ingest_mode"] = stats.get("mode", "inmem")
    cand.save_model(candidate_path)
    # chaos matrix: a candidate torn on disk (partial write, dying fs)
    # must die at the gate's parse/truncation check, never in the fleet
    from .robustness import chaos
    chaos.maybe_truncate_snapshot(candidate_path, 0)
    return cand


def _stage_gate(params: Dict[str, Any], cfg: Config, cand: Booster,
                candidate_path: str, baseline_path: str,
                report: Dict[str, Any]) -> bool:
    """All checks must pass before the candidate may touch the pointer."""
    from .metrics import create_metrics
    from .model_io import _objective_string
    from .serving.fleet import validate_candidate
    from .telemetry.quality import QUALITY_SUFFIX

    gate: Dict[str, Any] = {"checks": {}}
    report["gate"] = gate
    ok = True

    # 1) nan_guard + corruption/truncation: the exact validation every
    # promoter and replica runs (a poisoned or torn candidate dies here)
    try:
        gate["sha256"] = validate_candidate(candidate_path)
        gate["checks"]["nan_guard"] = "pass"
    except LightGBMError as e:
        gate["checks"]["nan_guard"] = f"FAIL: {e}"
        ok = False

    # 2) holdout metric vs the serving baseline
    vspec = str(params.get("valid", params.get("valid_data", "")) or "")
    valid_path = vspec.split(",")[0].strip() if vspec else ""
    if valid_path and ok:
        from .dataset_io import load_data_file
        Xv, yv, _ = load_data_file(valid_path, dict(params))
        if yv is None:
            raise LightGBMError(
                "pipeline gate needs a labeled holdout (valid=<file>)")
        obj_name = _objective_string(cand).split(" ")[0] or "regression"
        cfg2 = Config.from_params({**params, "objective": obj_name})
        metrics = create_metrics(cfg2, obj_name)
        base = Booster(model_file=baseline_path)

        def _eval(b: Booster):
            score = np.asarray(b.predict(Xv, raw_score=True))
            out = {}
            for m in metrics:
                m.init(yv, None)
                for name, val, hb in m.evaluate(score,
                                                b._convert_output_fn()):
                    out[name] = (float(val), bool(hb))
            return out

        cand_ev, base_ev = _eval(cand), _eval(base)
        margin = float(cfg.pipeline_gate_margin)
        worse = []
        for name, (cv, hb) in cand_ev.items():
            bv = base_ev.get(name, (cv, hb))[0]
            regressed = (cv < bv - margin) if hb else (cv > bv + margin)
            if regressed:
                worse.append(f"{name} {cv:.6g} vs baseline {bv:.6g}")
        gate["holdout"] = {"candidate": {k: v[0] for k, v in cand_ev.items()},
                           "baseline": {k: v[0] for k, v in base_ev.items()},
                           "margin": margin}
        if worse:
            gate["checks"]["holdout_metric"] = "FAIL: " + "; ".join(worse)
            ok = False
        else:
            gate["checks"]["holdout_metric"] = "pass"
    else:
        gate["checks"]["holdout_metric"] = ("skipped (no valid=)"
                                            if not valid_path else "skipped")

    # 3) quality-profile regeneration (PR 16): the sidecar must ride the
    # candidate so the fleet's drift monitor has a reference to compare to
    if bool(getattr(cfg, "quality_profile", True)):
        sidecar = candidate_path + QUALITY_SUFFIX
        if os.path.exists(sidecar):
            gate["checks"]["quality_profile"] = "pass"
        else:
            gate["checks"]["quality_profile"] = (
                "FAIL: sidecar missing (candidate saved without an engine "
                "or quality_profile write failed)")
            ok = False
    else:
        gate["checks"]["quality_profile"] = "skipped (quality_profile=false)"

    gate["pass"] = ok
    return ok


def _stage_promote(params: Dict[str, Any], cfg: Config, cand: Booster,
                   candidate_path: str, fleet_dir: str,
                   report: Dict[str, Any], model_id: str = "") -> bool:
    from . import telemetry
    from .robustness import chaos
    from .serving.fleet import promote_pointer, read_pointer

    # the chaos window the whole design exists for: gate passed, pointer
    # not yet written — a crash here must leave the fleet untouched
    chaos.maybe_kill_refit()
    pointer = promote_pointer(fleet_dir, candidate_path,
                              model_id=model_id)
    gen, sha = int(pointer["generation"]), str(pointer["sha256"])
    # verify our own write: a torn pointer (chaos or a dying filesystem)
    # reads back as None/garbage and must be reported as a FAILED
    # promotion, not waited on
    back = read_pointer(fleet_dir, model_id)
    if back is None or int(back.get("generation", -1)) != gen \
            or str(back.get("sha256")) != sha:
        report["promote"] = {"generation": gen, "sha256": sha,
                             "torn_pointer": True}
        telemetry.inc("pipeline/promotions_torn")
        log_warning("pipeline: pointer write did not read back; the fleet "
                    "keeps its old generation")
        return False
    telemetry.instant("pipeline:promote", generation=gen, sha256=sha,
                      path=candidate_path, model_id=model_id or "")
    telemetry.inc("pipeline/promotions")
    conv = _wait_for_sha(fleet_dir, sha, gen, _PROMOTE_WAIT_S,
                         model_id=model_id)
    report["promote"] = {"generation": gen, "sha256": sha,
                         "convergence": conv}
    if model_id:
        report["promote"]["model_id"] = model_id

    # train-vs-serve drift stamp: the served scores of a probe batch must
    # be bitwise Booster.predict of the PROMOTED ARTIFACT — reloaded from
    # the candidate file, because that is what the replicas loaded (the
    # in-memory engine booster differs in the serialization ulps)
    probe = _probe_rows(params, cand)
    if probe is not None and conv["converged"]:
        local = np.asarray(Booster(model_file=candidate_path).predict(probe),
                           np.float64)
        eps = _replica_endpoints(fleet_dir)
        drift = None
        mis_versioned = 0
        body: Dict[str, Any] = {"rows": probe.tolist()}
        if model_id:
            body["model_id"] = model_id
        for r, h, p in eps:
            resp = _http(h, p, "POST", "/predict", body, timeout=10.0)
            if resp is None or "predictions" not in resp:
                continue
            if str(resp.get("model_sha256")) != sha:
                mis_versioned += 1
                continue
            served = np.asarray(resp["predictions"], np.float64)
            d = float(np.max(np.abs(served - local))) if served.size else 0.0
            drift = d if drift is None else max(drift, d)
        if drift is not None:
            telemetry.gauge("pipeline/train_serve_drift_maxabs", drift)
            report["promote"]["train_serve_drift_maxabs"] = drift
            report["promote"]["mis_versioned"] = mis_versioned
    return True


def _probe_rows(params: Dict[str, Any],
                cand: Booster) -> Optional[np.ndarray]:
    """A small feature batch for the train-vs-serve drift stamp (holdout
    file first, fresh data second); None when neither loads."""
    from .dataset_io import load_data_file
    for key in ("valid", "pipeline_fresh_data"):
        spec = str(params.get(key, "") or "").split(",")[0].strip()
        if not spec:
            continue
        try:
            X, label, _ = load_data_file(spec, dict(params))
        except (LightGBMError, OSError):
            continue
        if X.shape[1] == cand.num_feature() - 1 and label is not None:
            X = np.column_stack([label, X])
        return np.asarray(X[: min(64, X.shape[0])], np.float64)
    return None


def _stage_observe(cfg: Config, fleet_dir: str,
                   report: Dict[str, Any], model_id: str = "") -> None:
    """Post-promotion rollback watcher: any replica reporting an SLO burn
    or a drift alert inside the observation window reverts the fleet to
    the prior generation — no operator in the loop.  When the promotion
    targeted one tenant, only THAT tenant's per-model alerts are watched
    and only its pointer is rolled back: a sibling's burn neither blames
    nor reverts this promotion."""
    from . import telemetry
    from .serving.fleet import read_pointer, rollback_pointer

    window = float(cfg.pipeline_observe_s)
    obs: Dict[str, Any] = {"window_s": window, "burned": False}
    report["observe"] = obs
    if window <= 0:
        obs["skipped"] = "pipeline_observe_s=0"
        return
    deadline = time.monotonic() + window
    poll = float(cfg.pipeline_observe_poll_s)
    while time.monotonic() < deadline:
        for r, h, p in _replica_endpoints(fleet_dir):
            st = _http(h, p, "GET", "/ready")
            rec = _tenant_state(st, model_id)
            if not rec:
                continue
            reasons = []
            if rec.get("slo_alert"):
                reasons.append("slo_burn")
            if rec.get("drift_alert"):
                reasons.append("drift_alert")
            if reasons:
                why = "+".join(reasons) + f" on replica {r}"
                if model_id:
                    why += f" (model {model_id})"
                telemetry.instant("pipeline:observe_burn", replica=r,
                                  reasons=",".join(reasons),
                                  model_id=model_id or "")
                pointer = rollback_pointer(fleet_dir, reason=why,
                                           model_id=model_id)
                conv = _wait_for_sha(fleet_dir, str(pointer["sha256"]),
                                     int(pointer["generation"]),
                                     _PROMOTE_WAIT_S, model_id=model_id)
                obs.update({"burned": True, "reason": why,
                            "rollback": {
                                "generation": int(pointer["generation"]),
                                "rollback_from": pointer.get("rollback_from"),
                                "sha256": pointer["sha256"],
                                "convergence": conv}})
                return
        time.sleep(poll)
    obs["healthy"] = True
    cur = read_pointer(fleet_dir, model_id)
    log_info(f"pipeline: observation window ({window:.1f}s) passed clean; "
             f"generation {cur['generation'] if cur else '?'} stands"
             + (f" (model {model_id})" if model_id else ""))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run_pipeline(params: Dict[str, Any]) -> Dict[str, Any]:
    """The closed loop, one invocation.  Returns the stage report;
    ``report["ok"]`` is the CLI exit status."""
    from . import telemetry

    cfg = Config.from_params(params)
    if cfg.telemetry:
        telemetry.configure(enabled=True)
    out_model = str(params.get("output_model", "LightGBM_model.txt"))
    fleet_dir = str(params.get("serve_fleet_dir", "") or "")
    # multi-tenant keying: pipeline_model_id targets ONE tenant's
    # promote_<id>.json — its generation counter, its candidate naming,
    # its rollback; sibling tenants keep serving their bytes untouched
    mid = str(cfg.pipeline_model_id or "")
    # generation-unique candidate path: a later pipeline run (even one
    # that fails its gate) must never overwrite the model file the
    # fleet's pointer currently targets
    tag = f".{mid}" if mid else ""
    if fleet_dir:
        from .serving.fleet import _current_generation
        candidate_path = (f"{out_model}{tag}.candidate_gen"
                          f"{_current_generation(fleet_dir, mid) + 1}")
    else:
        candidate_path = out_model + tag + ".candidate"
    report: Dict[str, Any] = {"ok": False, "candidate": candidate_path,
                              "fleet_dir": fleet_dir}
    if mid:
        report["model_id"] = mid

    with telemetry.global_tracer.span("pipeline/train"):
        base_bst, base_ds, base_path = _stage_train(params, cfg, out_model)
    report["base_model"] = base_path

    with telemetry.global_tracer.span("pipeline/refit"):
        cand = _stage_refit(params, cfg, base_bst, base_ds, base_path,
                            out_model, candidate_path, report)

    with telemetry.global_tracer.span("pipeline/gate"):
        # baseline for the gate: what the fleet serves NOW (pointer
        # target) when there is one, else the base model
        baseline = base_path
        if fleet_dir:
            from .serving.fleet import read_pointer
            p = read_pointer(fleet_dir, mid)
            if p and os.path.exists(str(p["path"])):
                baseline = str(p["path"])
        gate_ok = _stage_gate(params, cfg, cand, candidate_path, baseline,
                              report)
    if not gate_ok:
        telemetry.instant("pipeline:gate_failed",
                          checks=json.dumps(report["gate"]["checks"]))
        telemetry.inc("pipeline/gate_failures")
        log_warning(f"pipeline: gate FAILED ({report['gate']['checks']}); "
                    "the fleet keeps its current generation")
        _finish(params, report)
        return report

    if not fleet_dir or not bool(cfg.pipeline_promote):
        report["promote"] = {"skipped": ("no serve_fleet_dir" if not fleet_dir
                                         else "pipeline_promote=false")}
        report["ok"] = True
        _finish(params, report)
        return report

    with telemetry.global_tracer.span("pipeline/promote"):
        promoted = _stage_promote(params, cfg, cand, candidate_path,
                                  fleet_dir, report, model_id=mid)
    if not promoted:
        _finish(params, report)
        return report

    with telemetry.global_tracer.span("pipeline/observe"):
        _stage_observe(cfg, fleet_dir, report, model_id=mid)

    report["ok"] = True
    _finish(params, report)
    return report


def _finish(params: Dict[str, Any], report: Dict[str, Any]) -> None:
    from . import telemetry

    if telemetry.enabled():
        telemetry.gauge("pipeline/ok", 1.0 if report["ok"] else 0.0)
        trace_out = str(params.get("trace_out", "") or "")
        if trace_out:
            try:
                telemetry.export_trace(trace_out)
            except OSError as e:
                log_warning(f"pipeline: trace export failed: {e}")
    log_info(f"pipeline: {'OK' if report['ok'] else 'FAILED'} "
             f"(candidate {report['candidate']})")
