"""Plotting utilities (matplotlib/graphviz gated).

Reference: python-package/lightgbm/plotting.py — plot_importance, plot_metric,
plot_split_value_histogram, plot_tree / create_tree_digraph.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster
from .sklearn import LGBMModel
from .utils.log import LightGBMError


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:
        raise ImportError("You must install matplotlib to plot") from e


def _to_booster(booster) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be a Booster or LGBMModel")


def plot_importance(booster, ax=None, height: float = 0.2, xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance", ylabel: str = "Features",
                    importance_type: str = "auto", max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None, grid: bool = True,
                    precision: Optional[int] = 3, **kwargs):
    plt = _check_matplotlib()
    bst = _to_booster(booster)
    if importance_type == "auto":
        importance_type = "split"
    importance = bst.feature_importance(importance_type)
    feature_names = bst.feature_name()
    tuples = sorted(zip(feature_names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] != 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Cannot plot trees with zero importance")
    labels, values = zip(*tuples)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(int(x)),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict, Any], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None, xlim=None,
                ylim=None, title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    elif isinstance(booster, LGBMModel):
        eval_results = booster.evals_result_
    else:
        raise TypeError("booster must be a dict from record_evaluation or LGBMModel")
    if not eval_results:
        raise ValueError("eval results are empty")
    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    msg = None
    for name in dataset_names:
        metrics = eval_results[name]
        if metric is None:
            metric = next(iter(metrics.keys()))
        if metric not in metrics:
            raise ValueError(f"metric {metric} not found for {name}")
        results = metrics[metric]
        ax.plot(range(len(results)), results, label=name)
    ax.legend(loc="best")
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", metric or ""))
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None, width_coef=0.8,
                               xlim=None, ylim=None,
                               title="Split value histogram for feature with @index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    plt = _check_matplotlib()
    bst = _to_booster(booster)
    feature_names = bst.feature_name()
    if isinstance(feature, str):
        fidx = feature_names.index(feature)
    else:
        fidx = int(feature)
    values = []
    for t in bst._all_trees():
        for i in range(t.num_leaves - 1):
            if int(t.split_feature[i]) == fidx and not (int(t.decision_type[i]) & 1):
                values.append(float(t.threshold[i]))
    if not values:
        raise ValueError("Cannot plot split value histogram, "
                         f"because feature {feature} was not used in splitting")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, bin_edges = np.histogram(values, bins=bins or "auto")
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2
    width = width_coef * (bin_edges[1] - bin_edges[0]) if len(bin_edges) > 1 else 1.0
    ax.bar(centers, hist, width=width, **kwargs)
    ax.set_title(title.replace("@index/name@", "name" if isinstance(feature, str)
                               else "index").replace("@feature@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: Optional[int] = 3, orientation: str = "horizontal",
                        **kwargs):
    try:
        import graphviz
    except ImportError as e:
        raise ImportError("You must install graphviz to plot tree") from e
    bst = _to_booster(booster)
    trees = bst._all_trees()
    if tree_index >= len(trees):
        raise IndexError(f"tree_index {tree_index} out of range")
    t = trees[tree_index]
    show_info = show_info or []
    graph = graphviz.Digraph(**kwargs)
    graph.attr(rankdir="LR" if orientation == "horizontal" else "TB")
    fnames = bst.feature_name()

    def add(node: int, parent: Optional[str], decision: Optional[str]):
        if node < 0:
            leaf = ~node
            name = f"leaf{leaf}"
            label = f"leaf {leaf}: {t.leaf_value[leaf]:.{precision}f}"
            if "leaf_count" in show_info:
                label += f"\ncount: {int(t.leaf_count[leaf])}"
            if "leaf_weight" in show_info:
                label += f"\nweight: {t.leaf_weight[leaf]:.{precision}f}"
            graph.node(name, label=label)
        else:
            name = f"split{node}"
            f = int(t.split_feature[node])
            dt = int(t.decision_type[node])
            if dt & 1:
                label = f"{fnames[f]} in cat set {int(t.threshold_bin[node])}"
            else:
                label = f"{fnames[f]} <= {t.threshold[node]:.{precision}f}"
            if "split_gain" in show_info:
                label += f"\ngain: {t.split_gain[node]:.{precision}f}"
            if "internal_value" in show_info:
                label += f"\nvalue: {t.internal_value[node]:.{precision}f}"
            if "internal_count" in show_info:
                label += f"\ncount: {int(t.internal_count[node])}"
            graph.node(name, label=label)
            add(int(t.left_child[node]), name, "yes")
            add(int(t.right_child[node]), name, "no")
        if parent is not None:
            graph.edge(parent, name, decision)

    add(0 if t.num_leaves > 1 else ~0, None, None)
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: Optional[int] = 3,
              orientation: str = "horizontal", **kwargs):
    plt = _check_matplotlib()
    try:
        import importlib
        image_mod = importlib.import_module("PIL.Image")
    except ImportError as e:
        raise ImportError("You must install Pillow to plot tree") from e
    import io
    graph = create_tree_digraph(booster, tree_index=tree_index, show_info=show_info,
                                precision=precision, orientation=orientation)
    s = io.BytesIO(graph.pipe(format="png"))
    img = image_mod.open(s)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.imshow(img)
    ax.axis("off")
    return ax
