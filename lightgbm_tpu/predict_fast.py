"""Low-latency single-row prediction with FastConfig-style pre-binding.

Reference analog: include/LightGBM/c_api.h:1399-1428
(LGBM_BoosterPredictForMatSingleRowFastInit / ...Fast + the
FastConfigHandle it documents): serving paths pre-bind everything that is
per-model — tree arrays, iteration slice, output transform — so each call
does only the per-row tree walks.

Here the pre-bind packs the model's trees into contiguous arrays once and
each call runs one C tree-walk over them (native/binner.cpp
lgbt_predict_row, loaded via ctypes), with a pure-NumPy per-tree fallback
when the native toolchain is unavailable.  No device dispatch, no jit —
sub-millisecond end-to-end on serving-sized models.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional

import numpy as np


class SingleRowFastPredictor:
    """Pre-bound predictor; call with one raw feature row.

    ``start_iteration``/``num_iteration`` slice the model at PRE-BIND
    time (reference: the FastConfig carries the iteration window, so the
    per-call walk never re-slices): ``trees`` is the full iteration-major
    list and the window is cut here.  The ``best_iteration`` fallback for
    ``num_iteration=None`` is Booster knowledge and stays in
    ``Booster.predict_single_row_fast_init``."""

    def __init__(self, trees: List, num_class: int, num_features: int,
                 average_factor: float = 1.0, convert_fn=None,
                 start_iteration: int = 0,
                 num_iteration: Optional[int] = None):
        self.num_class = int(num_class)
        self.num_features = int(num_features)
        self.average_factor = float(average_factor)
        self.convert_fn = convert_fn
        k = max(self.num_class, 1)
        if start_iteration or (num_iteration is not None
                               and num_iteration > 0):
            n_total = len(trees) // k
            start = max(int(start_iteration), 0)
            end = (min(start + int(num_iteration), n_total)
                   if num_iteration is not None and num_iteration > 0
                   else n_total)
            trees = trees[start * k:end * k]
        self._trees = trees      # NumPy fallback path
        self._has_linear = any(getattr(t, "is_linear", False) for t in trees)

        nt = len(trees)
        tree_off = np.zeros(nt + 1, np.int32)
        leaf_off = np.zeros(nt + 1, np.int32)
        cat_off = np.zeros(nt + 1, np.int32)   # word offset per tree
        catb_parts, catt_parts = [], []
        for i, t in enumerate(trees):
            tree_off[i + 1] = tree_off[i] + max(t.num_leaves - 1, 0)
            leaf_off[i + 1] = leaf_off[i] + max(t.num_leaves, 1)
            catt_parts.append(np.asarray(t.cat_threshold, np.uint32))
            # per-tree cat_boundaries are word offsets; rebase onto the
            # concatenated word array
            cb = np.asarray(t.cat_boundaries, np.int32)
            catb_parts.append(cb[:-1] + cat_off[i] if len(cb) > 1
                              else np.zeros(0, np.int32))
            cat_off[i + 1] = cat_off[i] + len(catt_parts[-1])

        def cat_field(name, dtype):
            return (np.concatenate([np.asarray(getattr(t, name), dtype)
                                    for t in trees])
                    if nt else np.zeros(0, dtype))

        self.tree_off = tree_off
        self.leaf_off = leaf_off[:-1].copy()
        self.split_feature = cat_field("split_feature", np.int32)
        self.threshold = cat_field("threshold", np.float64)
        self.decision_type = cat_field("decision_type", np.uint8)
        self.left = cat_field("left_child", np.int32)
        self.right = cat_field("right_child", np.int32)
        self.leaf_value = cat_field("leaf_value", np.float64)
        # threshold_bin holds each categorical node's per-tree cat ordinal;
        # rebase it so ordinals index the concatenated boundary table
        tb_parts = []
        cat_count = 0
        for t in trees:
            tb = np.asarray(t.threshold_bin, np.int32).copy()
            is_cat = (np.asarray(t.decision_type, np.uint8) & 1) != 0
            tb[is_cat] += cat_count
            cat_count += max(len(t.cat_boundaries) - 1, 0) \
                if len(np.asarray(t.cat_threshold)) else 0
            tb_parts.append(tb)
        self.threshold_bin = (np.concatenate(tb_parts) if nt
                              else np.zeros(0, np.int32))
        self.cat_boundaries = (np.concatenate(catb_parts + [cat_off[-1:]])
                               .astype(np.int32))
        self.cat_threshold = (np.concatenate(catt_parts) if nt
                              else np.zeros(0, np.uint32))

        self._lib = None
        if not self._has_linear:
            from .native import get_lib
            self._lib = get_lib()
        if self._lib is not None:
            c = ctypes
            self._pd = lambda a: a.ctypes.data_as(c.POINTER(c.c_double))
            self._pi = lambda a: a.ctypes.data_as(c.POINTER(c.c_int32))

    def raw_predict(self, row: np.ndarray) -> np.ndarray:
        """Raw scores (num_class,) for one row; no output transform.
        Thread-safe: per-call buffers, the packed model arrays are only
        read."""
        row = np.asarray(row, np.float64).reshape(-1)
        if row.shape[0] != self.num_features:
            # the native walk indexes row[split_feature] unchecked — a
            # short row would read past the buffer
            from .basic import LightGBMError
            raise LightGBMError(
                f"single-row predict expects {self.num_features} features, "
                f"got {row.shape[0]}")
        if self._lib is not None:
            rb = np.ascontiguousarray(row, np.float64)
            ob = np.zeros(self.num_class, np.float64)
            c = ctypes
            self._lib.lgbt_predict_row(
                self._pd(rb), self._pi(self.tree_off),
                len(self.tree_off) - 1, self._pi(self.split_feature),
                self._pd(self.threshold), self._pi(self.threshold_bin),
                self.decision_type.ctypes.data_as(c.POINTER(c.c_uint8)),
                self._pi(self.left), self._pi(self.right),
                self._pi(self.leaf_off), self._pd(self.leaf_value),
                self._pi(self.cat_boundaries),
                self.cat_threshold.ctypes.data_as(c.POINTER(c.c_uint32)),
                self.num_class, self._pd(ob))
            score = ob
        else:
            X = np.asarray(row, np.float64).reshape(1, -1)
            score = np.zeros(self.num_class, np.float64)
            for i, t in enumerate(self._trees):
                score[i % self.num_class] += t.predict_raw(X)[0]
        return score * self.average_factor

    def __call__(self, row, raw_score: bool = False):
        score = self.raw_predict(row)   # validates the row length
        if not raw_score and self.convert_fn is not None:
            score = np.asarray(self.convert_fn(score))
        return score if self.num_class > 1 else float(score[0])
