"""Ranking objectives: LambdaRank-NDCG and XE-NDCG.

Reference: src/objective/rank_objective.hpp — RankingObjective (:26, per-query OpenMP
loops), LambdarankNDCG (:139, pairwise lambdas with delta-NDCG weighting, truncation,
sigmoid table, per-query normalisation), RankXENDCG (:385).

TPU re-design: queries are bucketed by size into padded (Q_bucket, M) blocks host-side;
each bucket's gradient is one jitted dense computation — LambdaRank materialises the
(chunked) all-pairs (q, M, M) tensors on the VPU instead of scalar double loops; the
sigmoid lookup table is unnecessary. Outputs scatter back to the flat document order.
"""
from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .objectives import ObjectiveFunction
from .telemetry.watchdog import watched_jit
from .utils.log import LightGBMError, log_warning


def default_label_gain(max_label: int = 31) -> np.ndarray:
    return (2.0 ** np.arange(max_label + 1)) - 1.0


def query_spans(query_boundaries) -> Tuple[np.ndarray, np.ndarray]:
    """(starts, sizes) from either 1-D cumulative boundaries or (nq, 2)
    [start, size] spans (the distributed shard-padded layout, which has pad
    gaps between ranks' queries — see Dataset.get_query_boundaries)."""
    qb = np.asarray(query_boundaries, np.int64)
    if qb.ndim == 2:
        return qb[:, 0], qb[:, 1]
    return qb[:-1], np.diff(qb)


class _QueryBuckets(NamedTuple):
    sizes: List[int]                  # padded M per bucket
    doc_index: List[np.ndarray]       # (Qb, M) flat doc indices, -1 = pad
    inv_max_dcg: List[np.ndarray]     # (Qb,) per query
    query_ids: List[np.ndarray]       # (Qb,) original query index


def _bucketize(query_boundaries: np.ndarray, labels: np.ndarray,
               label_gain: np.ndarray, truncation_level: int) -> _QueryBuckets:
    starts, sizes = query_spans(query_boundaries)
    nq = len(starts)
    max_m = int(sizes.max()) if nq else 1
    bucket_sizes: List[int] = []
    m = 8
    while m < max_m:
        bucket_sizes.append(m)
        m *= 2
    bucket_sizes.append(max(m, 8))

    # per-query 1/maxDCG@truncation (reference: DCGCalculator::CalMaxDCGAtK)
    inv_max = np.zeros(nq)
    gains = label_gain[np.clip(labels.astype(np.int64), 0, len(label_gain) - 1)]
    disc_all = 1.0 / np.log2(np.arange(max_m) + 2.0)
    for qi in range(nq):
        g = np.sort(gains[starts[qi]:starts[qi] + sizes[qi]])[::-1][:truncation_level]
        md = float(np.sum(g * disc_all[:len(g)]))
        inv_max[qi] = 1.0 / md if md > 0 else 0.0

    which = np.searchsorted(bucket_sizes, sizes)
    out_sizes, out_idx, out_inv, out_qids = [], [], [], []
    for bi, m in enumerate(bucket_sizes):
        qsel = np.where(which == bi)[0]
        if len(qsel) == 0:
            continue
        idx = np.full((len(qsel), m), -1, np.int64)
        for r, qi in enumerate(qsel):
            s, z = starts[qi], sizes[qi]
            idx[r, :z] = np.arange(s, s + z)
        out_sizes.append(m)
        out_idx.append(idx)
        out_inv.append(inv_max[qsel])
        out_qids.append(qsel)
    return _QueryBuckets(out_sizes, out_idx, out_inv, out_qids)


def _contiguous_span(idx: np.ndarray):
    """(offset, true_size) when every query in the bucket has the same true
    size and their rows are consecutive in the flat doc order — then the
    bucket's (Q, M) padded gather collapses to slice+reshape+pad, and the
    gradient scatter to one contiguous slice-add.  Real ranking sets are
    close to uniform (MSLR ~120 docs/query), so this removes two random
    N-sized gathers per boosting iteration (~105M rows/s on TPU =
    ~20 ms/iter at MSLR scale)."""
    q, m = idx.shape
    valid = idx >= 0
    z = int(valid[0].sum())
    if z == 0 or not (valid.sum(axis=1) == z).all() or not valid[:, :z].all():
        return None
    off = int(idx[0, 0])
    expect = off + np.arange(q * z, dtype=np.int64).reshape(q, z)
    if not np.array_equal(idx[:, :z], expect):
        return None
    return off, z


def _bucket_scores(score, idx, span):
    """Per-bucket (Q, M) padded scores: slice+reshape+pad on contiguous
    uniform buckets, generic gather otherwise."""
    if span is not None:
        off, z = span
        q, m = idx.shape
        s = jax.lax.dynamic_slice(score, (off,), (q * z,)).reshape(q, z)
        return jnp.pad(s, ((0, 0), (0, m - z))) if z < m else s
    return score[idx.reshape(-1)].reshape(idx.shape)


def _bucket_scatter_add(vec, vals, idx, valid, span, n):
    """Accumulate per-bucket (Q, M) grads back into the flat (N,) vector."""
    if span is not None:
        off, z = span
        q = idx.shape[0]
        return vec.at[off:off + q * z].add(
            vals[:, :z].reshape(-1).astype(vec.dtype))
    flat_idx = jnp.where(valid.reshape(-1), idx.reshape(-1), n)
    return vec.at[flat_idx].add(vals.reshape(-1).astype(vec.dtype),
                                mode="drop")


@functools.partial(watched_jit, name="lambdarank_bucket", warn_after=0,
                   static_argnames=("sigma", "norm", "trunc", "chunk"))
def _lambdarank_bucket(scores, labels_q, valid, inv_max_dcg, gains_q,
                       sigma: float, norm: bool, trunc: int, chunk: int = 256):
    """Pairwise lambdas for one padded bucket.

    scores/labels_q/valid: (Q, M); inv_max_dcg: (Q,). Returns (grad, hess) (Q, M)."""
    Q, M = scores.shape
    NEG = -1e30
    K = min(trunc, M)

    def one_chunk(args):
        # Sorted-space top-K pair formulation (reference:
        # rank_objective.hpp:180 GetGradientsForOneQuery iterates
        # `for i < min(truncation_level, cnt): for j in (i, cnt)` over docs
        # sorted by score desc).  Forming only those (K, M) pairs — instead
        # of all (M, M) pairs masked down — cuts the pairwise tensor work
        # by M/K (~4x at the MSLR shapes M~128, truncation 30), and the
        # positional discounts become a static vector.
        s, lab, v, imd, gain = args                       # (q, M) ...
        masked = jnp.where(v, s, NEG)
        # multi-operand stable sort carries every per-doc array into sorted
        # space in ONE pass, and a second sort on the carried original
        # position unsorts the results.  take_along_axis gathers here were
        # 2x the cost of the whole pairwise computation (TPU random gather
        # ~105M rows/s vs sort ~230M rows/s).
        iota = jnp.broadcast_to(
            jnp.arange(M, dtype=jnp.int32), masked.shape)
        neg_ss, labs, gains_s, vf, orig_pos = jax.lax.sort(
            (-masked, lab, gain, v.astype(jnp.float32), iota),
            dimension=-1, num_keys=1, is_stable=True)
        ss = -neg_ss
        vs = vf > 0.5                                     # valid = prefix
        disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)
        best = jnp.max(masked, axis=-1, keepdims=True)
        worst = jnp.min(jnp.where(v, s, -NEG), axis=-1, keepdims=True)
        has_range = (best != worst)

        sk, labk, gk, vk = ss[:, :K], labs[:, :K], gains_s[:, :K], vs[:, :K]
        sd = sk[:, :, None] - ss[:, None, :]              # (q, K, M)
        sgn = jnp.sign(labk[:, :, None] - labs[:, None, :])
        upper = (jnp.arange(M)[None, :] > jnp.arange(K)[:, None])  # j > a
        pair_valid = (vk[:, :, None] & vs[:, None, :] & (sgn != 0)
                      & upper[None])
        delta = (jnp.abs(gk[:, :, None] - gains_s[:, None, :])
                 * jnp.abs(disc[:K][None, :, None] - disc[None, None, :])
                 * imd[:, None, None])
        if norm:
            delta = jnp.where(has_range[..., None],
                              delta / (0.01 + jnp.abs(sd)), delta)
        # p = sigmoid(-sigma * (s_high - s_low)); the higher-labelled doc of
        # the pair is position a when sgn>0 else position j
        p = jax.nn.sigmoid(-sigma * sgn * sd)
        lam = -sigma * p * delta                          # lambda for the high doc
        hs = sigma * sigma * p * (1.0 - p) * delta
        lam = jnp.where(pair_valid, lam, 0.0)
        hs = jnp.where(pair_valid, hs, 0.0)
        slam = sgn * lam                                  # signed for pos a
        # high doc += lam, low doc -= lam (in sorted space), then unsort
        g_sorted = (-jnp.sum(slam, axis=1)).at[:, :K].add(jnp.sum(slam, axis=2))
        h_sorted = jnp.sum(hs, axis=1).at[:, :K].add(jnp.sum(hs, axis=2))
        sum_lambdas = -2.0 * jnp.sum(lam, axis=(1, 2))
        if norm:
            factor = jnp.where(sum_lambdas > 0,
                               jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, 1e-20),
                               1.0)
            g_sorted = g_sorted * factor[:, None]
            h_sorted = h_sorted * factor[:, None]
        _, g, h = jax.lax.sort((orig_pos, g_sorted, h_sorted),
                               dimension=-1, num_keys=1, is_stable=True)
        return g, h

    pad_q = -(-Q // chunk) * chunk - Q
    if pad_q:
        scores = jnp.pad(scores, ((0, pad_q), (0, 0)))
        labels_q = jnp.pad(labels_q, ((0, pad_q), (0, 0)))
        valid = jnp.pad(valid, ((0, pad_q), (0, 0)))
        inv_max_dcg = jnp.pad(inv_max_dcg, (0, pad_q))
        gains_q = jnp.pad(gains_q, ((0, pad_q), (0, 0)))
    nb = scores.shape[0] // chunk
    xs = tuple(a.reshape((nb, chunk) + a.shape[1:])
               for a in (scores, labels_q, valid, inv_max_dcg, gains_q))
    g, h = jax.lax.map(one_chunk, xs)
    g = g.reshape(-1, M)[:Q]
    h = h.reshape(-1, M)[:Q]
    return g, h


class LambdarankNDCG(ObjectiveFunction):
    """reference: rank_objective.hpp:139."""
    name = "lambdarank"
    is_ranking = True

    def init(self, label, weight, query_boundaries=None, position=None, n=0):
        super().init(label, weight)
        if query_boundaries is None:
            raise LightGBMError("lambdarank requires query information (set group)")
        c = self.config
        lg = c.label_gain
        if lg is None:
            lg = default_label_gain(max(int(np.max(label)) if len(label) else 1, 31))
        self.label_gain_np = np.asarray(lg, np.float64)
        max_label = int(np.max(label)) if len(label) else 0
        if max_label >= len(self.label_gain_np):
            raise LightGBMError(f"label {max_label} exceeds label_gain size")
        self.qb = np.asarray(query_boundaries, np.int64)
        self.buckets = _bucketize(self.qb, np.asarray(label), self.label_gain_np,
                                  c.lambdarank_truncation_level)
        self.n = n
        self._dev_idx = [jnp.asarray(np.maximum(ix, 0)) for ix in self.buckets.doc_index]
        self._dev_valid = [jnp.asarray(ix >= 0) for ix in self.buckets.doc_index]
        self._spans = [_contiguous_span(ix) for ix in self.buckets.doc_index]
        self._dev_inv = [jnp.asarray(v, jnp.float32) for v in self.buckets.inv_max_dcg]
        lab = np.asarray(label)
        gains = self.label_gain_np[np.clip(lab.astype(np.int64), 0,
                                           len(self.label_gain_np) - 1)]
        self._dev_lab = [jnp.asarray(lab[np.maximum(ix, 0)], jnp.float32)
                         for ix in self.buckets.doc_index]
        self._dev_gain = [jnp.asarray(gains[np.maximum(ix, 0)], jnp.float32)
                          for ix in self.buckets.doc_index]
        # position-debiased lambdarank (reference: rank_objective.hpp:44-66
        # score adjustment + :303 UpdatePositionBiasFactors Newton step)
        self._positions = None
        if position is not None:
            # the per-iteration Newton bias update stays traceable: pos_biases
            # is declared in state_attrs(), so the fused gradient jit threads
            # it in as an argument and returns the new value (GBDT._boost_padded)
            pos = np.asarray(position, np.int64).reshape(-1)
            if len(pos) != n:
                raise LightGBMError(
                    f"position has {len(pos)} entries for {n} rows")
            self.num_position_ids = int(pos.max()) + 1 if len(pos) else 0
            self._positions = jnp.asarray(pos, jnp.int32)
            self.pos_biases = jnp.zeros(self.num_position_ids, jnp.float32)
            self._pos_counts = jnp.asarray(
                np.bincount(pos, minlength=self.num_position_ids), jnp.float32)
            self._pos_reg = float(c.lambdarank_position_bias_regularization)
            self._pos_lr = float(c.learning_rate)

    def data_bound_attrs(self):
        return ("label", "weight", "_dev_idx", "_dev_valid", "_dev_inv",
                "_dev_lab", "_dev_gain", "_positions", "_pos_counts")

    def state_attrs(self):
        return ("pos_biases",) if self._positions is not None else ()

    def get_gradients(self, score):
        c = self.config
        n = score.shape[0]
        if self._positions is not None:
            score = score + self.pos_biases[self._positions]
        grad = jnp.zeros(n, jnp.float32)
        hess = jnp.zeros(n, jnp.float32)
        for bi in range(len(self.buckets.sizes)):
            idx = self._dev_idx[bi]
            span = self._spans[bi]
            s = _bucket_scores(score, idx, span)
            g, h = _lambdarank_bucket(
                s, self._dev_lab[bi], self._dev_valid[bi], self._dev_inv[bi],
                self._dev_gain[bi], sigma=float(c.sigmoid),
                norm=bool(c.lambdarank_norm),
                trunc=int(c.lambdarank_truncation_level))
            grad = _bucket_scatter_add(grad, g, idx, self._dev_valid[bi],
                                       span, n)
            hess = _bucket_scatter_add(hess, h, idx, self._dev_valid[bi],
                                       span, n)
        grad, hess = self._apply_weight(grad, hess)
        if self._positions is not None:
            self._update_position_bias(grad, hess)
        return grad, hess

    def _update_position_bias(self, grad, hess) -> None:
        """Newton-Raphson step on the per-position bias factors (reference:
        rank_objective.hpp:303 UpdatePositionBiasFactors); stays on device —
        host readbacks are expensive on a tunneled TPU."""
        P = self.num_position_ids
        d1 = -jax.ops.segment_sum(grad, self._positions, num_segments=P)
        d2 = -jax.ops.segment_sum(hess, self._positions, num_segments=P)
        d1 = d1 - self.pos_biases * self._pos_reg * self._pos_counts
        d2 = d2 - self._pos_reg * self._pos_counts
        self.pos_biases = (self.pos_biases
                           + self._pos_lr * d1 / (jnp.abs(d2) + 0.001))


@functools.partial(watched_jit, name="xendcg_bucket", warn_after=0,
                   static_argnames=())
def _xendcg_bucket(scores, phi, valid):
    """XE-NDCG gradients for one padded bucket (reference: rank_objective.hpp:401-452)."""
    NEG = -1e30
    masked = jnp.where(valid, scores, NEG)
    rho = jax.nn.softmax(masked, axis=-1)
    rho = jnp.where(valid, rho, 0.0)
    inv_denom = 1.0 / jnp.maximum(jnp.sum(phi * valid, axis=-1, keepdims=True), 1e-15)
    l1 = -phi * inv_denom + rho
    params1 = jnp.where(valid, l1 / jnp.maximum(1.0 - rho, 1e-15), 0.0)
    sum_l1 = jnp.sum(params1, axis=-1, keepdims=True)
    l2 = rho * (sum_l1 - params1)
    params2 = jnp.where(valid, l2 / jnp.maximum(1.0 - rho, 1e-15), 0.0)
    sum_l2 = jnp.sum(params2, axis=-1, keepdims=True)
    l3 = rho * (sum_l2 - params2)
    grad = jnp.where(valid, l1 + l2 + l3, 0.0)
    hess = jnp.where(valid, rho * (1.0 - rho), 0.0)
    return grad, hess


class RankXENDCG(ObjectiveFunction):
    """reference: rank_objective.hpp:385 (XE-NDCG, arxiv 1911.09798)."""
    name = "rank_xendcg"
    is_ranking = True
    jit_safe_gradients = False   # fresh host RNG draw every iteration

    def init(self, label, weight, query_boundaries=None, position=None, n=0):
        super().init(label, weight)
        if query_boundaries is None:
            raise LightGBMError("rank_xendcg requires query information (set group)")
        c = self.config
        self.qb = np.asarray(query_boundaries, np.int64)
        self.buckets = _bucketize(self.qb, np.asarray(label),
                                  default_label_gain(
                                      max(int(np.max(label)) if len(label) else 1, 31)),
                                  c.lambdarank_truncation_level)
        self.n = n
        self._label_np = np.asarray(label)
        self._dev_idx = [jnp.asarray(np.maximum(ix, 0)) for ix in self.buckets.doc_index]
        self._dev_valid = [jnp.asarray(ix >= 0) for ix in self.buckets.doc_index]
        self._spans = [_contiguous_span(ix) for ix in self.buckets.doc_index]
        self._iter = 0
        self._rng = np.random.RandomState(c.objective_seed)

    def get_gradients(self, score):
        n = score.shape[0]
        grad = jnp.zeros(n, jnp.float32)
        hess = jnp.zeros(n, jnp.float32)
        # fresh gammas each iteration (reference: rands_ per query)
        gamma = self._rng.rand(n)
        phi_flat = np.power(2.0, self._label_np.astype(np.int64)) - gamma
        self._iter += 1
        for bi in range(len(self.buckets.sizes)):
            idx = self._dev_idx[bi]
            span = self._spans[bi]
            s = _bucket_scores(score, idx, span)
            phi = jnp.asarray(
                phi_flat[np.maximum(self.buckets.doc_index[bi], 0)], jnp.float32)
            g, h = _xendcg_bucket(s, phi, self._dev_valid[bi])
            grad = _bucket_scatter_add(grad, g, idx, self._dev_valid[bi],
                                       span, n)
            hess = _bucket_scatter_add(hess, h, idx, self._dev_valid[bi],
                                       span, n)
        return self._apply_weight(grad, hess)
