"""TPU-native leaf-value refit (reference: TreeLearner::FitByExistingTree,
tree_learner.h:28-115; GBDT::RefitTree, gbdt.cpp).

The host-side ``Booster.refit`` walks every tree over every row on the
host — O(N * depth) Python/NumPy work per tree.  This module computes the
SAME leaf values with device passes:

* **Leaf assignment** is ONE route-only replay of the streaming kernel
  per tree (``pallas.stream_kernel.route_replay``): the tree's splits are
  re-encoded as per-round route tables (the exact encoding the grower
  streams during training) and every row is routed through all rounds in
  a single kernel launch.  Binning the refit data with the TRAINING bin
  mappers makes the bin-space comparison ``bin(v) <= thr_bin`` exactly
  equivalent to the host's real-threshold walk ``v <= upper_bound[thr_bin]``
  (searchsorted round-trip), so leaf assignment is bitwise identical.
* **Leaf sums** are float64 ``segment_sum``s on device (bitwise equal to
  the sequential ``np.bincount`` accumulation of the host reference on
  row-ordered updates); the decay blend
  ``decay * old + (1 - decay) * (-sum_g / (sum_h + l2)) * shrinkage``
  mirrors FitByExistingTree.

Trees the replay kernel cannot route (categorical splits) fall back to
the device tree walk used by the score rebuild (``ops.predict``) — still
no host O(N * depth) pass.  Telemetry counts both:
``refit/route_replay_passes`` / ``refit/walk_fallback_passes``.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .tree import Tree
from .utils.log import LightGBMError, log_debug, log_info


def _x64():
    """Scoped float64 (the repo never enables x64 globally)."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx()


# ---------------------------------------------------------------------------
# replay-schedule reconstruction: finished Tree -> per-round route tables
# ---------------------------------------------------------------------------

def _replay_schedule(tree: Tree, mappers) -> Optional[Tuple[List[List[Tuple[int, int, int, int, int]]], np.ndarray]]:
    """Recover a grow-order replay schedule from a finished tree.

    BFS from the root: replay leaf-id 0 is the root; each split at replay
    id ``l`` keeps ``l`` for its left child and assigns the next fresh id
    to the right child (exactly the grower's id assignment, so the round
    tables' newid encoding is in range).  All splits at BFS depth ``d``
    form replay round ``d`` — sibling splits at one depth touch disjoint
    replay ids, so batching them into one table round routes identically
    to any sequential order.

    Returns ``(rounds, iperm)`` where ``rounds[d]`` is a list of
    ``(replay_lid, feature, thr_bin, dir_flags, newid)`` and
    ``iperm[replay_lid]`` is the tree's true leaf index — or ``None``
    when the tree cannot be replayed (categorical splits: the stream
    kernel does not route them)."""
    L = tree.num_leaves
    if L < 2 or tree.num_cat > 0:
        return None
    iperm = np.zeros(L, np.int64)
    rounds: List[List[Tuple[int, int, int, int, int]]] = []
    next_id = 1
    frontier: List[Tuple[int, int]] = [(0, 0)]       # (node, replay_lid)
    while frontier:
        this_round: List[Tuple[int, int, int, int, int]] = []
        nxt: List[Tuple[int, int]] = []
        for node, lid in frontier:
            f = int(tree.split_feature[node])
            dt = int(tree.decision_type[node])
            if dt & Tree._CAT_MASK:
                return None
            # DIR_DEFAULT_LEFT=1 / DIR_CATEGORICAL=2 (ops.split flags),
            # recovered from the LightGBM decision_type bit layout the
            # same way _tree_to_device does
            dirf = 1 if dt & Tree._DEFAULT_LEFT_MASK else 0
            m = mappers[f]
            thr_bin = int(np.searchsorted(m.upper_bounds,
                                          tree.threshold[node], side="left"))
            newid = next_id
            next_id += 1
            this_round.append((lid, f, thr_bin, dirf, newid))
            for child, clid in ((int(tree.left_child[node]), lid),
                                (int(tree.right_child[node]), newid)):
                if child < 0:
                    iperm[clid] = ~child
                else:
                    nxt.append((child, clid))
        rounds.append(this_round)
        frontier = nxt
    return rounds, iperm


def _tree_depth(tree: Tree) -> int:
    """Max root-to-leaf edge count (bound for the fallback device walk)."""
    if tree.num_leaves < 2:
        return 1
    depth = {0: 1}
    best = 1
    for node in range(len(tree.split_feature)):
        d = depth.get(node, 1)
        best = max(best, d)
        for child in (int(tree.left_child[node]), int(tree.right_child[node])):
            if child >= 0:
                depth[child] = d + 1
    return best


def _build_tabs_buf(rounds, routing, L_pad: int, R_buf: int) -> jax.Array:
    """Stack per-round build_route_tables blocks into the (R_buf*NUM_TAB,
    L_pad) replay buffer; untouched rounds stay zeros (exact no-op steps:
    chosen=0 keeps every row's leaf id)."""
    from .pallas.stream_kernel import NUM_TAB, build_route_tables

    zeros = jnp.zeros(L_pad, jnp.float32)
    blocks = []
    for splits in rounds:
        chosen = np.zeros(L_pad, np.float32)
        feat = np.zeros(L_pad, np.int64)
        thr = np.zeros(L_pad, np.int64)
        dirf = np.zeros(L_pad, np.int64)
        newid = np.zeros(L_pad, np.int64)
        for lid, f, t, d, nid in splits:
            chosen[lid] = 1.0
            feat[lid] = f
            thr[lid] = t
            dirf[lid] = d
            newid[lid] = nid
        blocks.append(build_route_tables(
            jnp.asarray(chosen), jnp.asarray(feat), jnp.asarray(thr),
            jnp.asarray(dirf), jnp.asarray(newid),
            zeros, zeros, zeros,            # route-only: no histogram slots
            routing, L_pad))
    buf = jnp.concatenate(blocks, axis=0) if blocks \
        else jnp.zeros((0, L_pad), jnp.float32)
    pad_rows = R_buf * NUM_TAB - buf.shape[0]
    if pad_rows > 0:
        buf = jnp.pad(buf, ((0, pad_rows), (0, 0)))
    return buf


# ---------------------------------------------------------------------------
# device leaf assignment
# ---------------------------------------------------------------------------

def device_leaf_ids(trees: List[Tree], dataset, mesh=None,
                    row_axis: Optional[str] = None):
    """Leaf index per row for every tree, computed on device.

    Replayable trees share ONE route_replay compile (one leaf budget, one
    rounds buffer, dynamic trip count); categorical trees fall back to
    the score-rebuild walk.  Yields ``(true_leaf_ids_i32_device, kind)``
    per tree, ``kind`` in {"replay", "walk"}."""
    from . import telemetry
    from .pallas.stream_kernel import pack_bins_T, stream_block_rows

    dd = dataset.device_data()
    mappers = dataset.bin_mappers()
    N = dd.num_data
    schedules = [_replay_schedule(t, mappers) for t in trees]
    out: List[Tuple[jax.Array, str]] = []

    L_max = max([t.num_leaves for t in trees] + [2])
    L_pad = max(8, -(-L_max // 8) * 8)
    R_buf = max([len(s[0]) for s in schedules if s is not None] + [1])
    T_rows = stream_block_rows(dd.max_bins, dd.num_groups)
    bins_T = pack_bins_T(dd.bins, T_rows, max_bins=dd.max_bins).bins_T

    def _replay(tabs_buf, n_rounds):
        from .pallas.stream_kernel import route_replay
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            from .parallel.mesh import DATA_AXIS, shard_map_rows
            ax = row_axis or DATA_AXIS
            rep = shard_map_rows(
                lambda bT, tb, nr: route_replay(
                    bT, tb, nr, L_pad, block_rows=T_rows,
                    rounds_buf=R_buf)[None],
                mesh, (P(None, ax), P(None, None), P()), P(None, ax))
            return rep(bins_T, tabs_buf, n_rounds)[0]
        return route_replay(bins_T, tabs_buf, n_rounds, L_pad,
                            block_rows=T_rows, rounds_buf=R_buf)

    walk_budget = max(L_max, 2)
    for tree, sched in zip(trees, schedules):
        if tree.num_leaves < 2:
            out.append((jnp.zeros(N, jnp.int32), "trivial"))
            continue
        if sched is not None:
            rounds, iperm = sched
            tabs_buf = _build_tabs_buf(rounds, dd.routing, L_pad, R_buf)
            lids = _replay(tabs_buf, jnp.int32(len(rounds)))[:N]
            true_leaf = jnp.asarray(iperm, jnp.int32)[lids]
            telemetry.inc("refit/route_replay_passes")
            out.append((true_leaf, "replay"))
        else:
            from .models.gbdt import _tree_to_device
            from .ops.predict import _walk_one_tree
            ta = _tree_to_device(tree, walk_budget, dd.max_bins, dataset)
            fields = (ta.split_feature, ta.threshold_bin, ta.dir_flags,
                      ta.left_child, ta.right_child, ta.cat_bitset)
            lids = _walk_one_tree(fields, dd.bins, dd.routing,
                                  _tree_depth(tree))[:N]
            telemetry.inc("refit/walk_fallback_passes")
            out.append((lids.astype(jnp.int32), "walk"))
    return out


# ---------------------------------------------------------------------------
# the refit loop (mirrors model_io.refit_model / FitByExistingTree)
# ---------------------------------------------------------------------------

def refit_leaf_values(booster, dataset, decay_rate: float = 0.9,
                      mesh=None) -> Dict[str, Any]:
    """Refit ``booster``'s leaf values IN PLACE on ``dataset`` (constructed,
    labeled; binned with the training mappers via ``reference=`` for exact
    routing).  Sequential over trees like the reference: tree ``i``'s
    gradients are taken at the score of the already-refitted prefix.

    Returns a report with the per-kind pass counters (the acceptance
    gate's proof that leaf assignment reused the stream kernel)."""
    from .config import Config
    from .model_io import _objective_string
    from .objectives import create_objective
    from .robustness import chaos
    from . import telemetry

    dataset.construct()
    y = dataset.get_label()
    if y is None:
        raise LightGBMError("refit requires labeled data")
    y = np.asarray(y, np.float64)
    w = dataset.get_weight()
    n = dataset.num_data()

    trees = (list(booster.engine.models) if booster._engine is not None
             else list(booster._loaded_trees.trees))
    k = booster.num_model_per_iteration()
    cfg = booster.config if booster._engine is not None else None
    cfg = cfg or Config()
    obj_name = _objective_string(booster).split(" ")[0]
    cfg2 = copy.copy(cfg)
    cfg2.objective = obj_name if obj_name else "regression"
    try:
        obj = create_objective(cfg2)
        obj.init(y, w, n=n)
    except Exception as e:
        log_debug(f"refit: objective unavailable ({e}); leaf values kept")
        obj = None

    report = {"trees": len(trees), "route_replay_passes": 0,
              "walk_fallback_passes": 0, "trivial": 0,
              "decay_rate": float(decay_rate)}
    with telemetry.global_tracer.span("refit/leaf_assignment"):
        leaf_ids = device_leaf_ids(trees, dataset, mesh=mesh)

    score = np.zeros((n, k), np.float64)
    for i, (tree, (leaf_dev, kind)) in enumerate(zip(trees, leaf_ids)):
        report["route_replay_passes" if kind == "replay" else
               "walk_fallback_passes" if kind == "walk" else "trivial"] += 1
        kk = i % k
        leaf = np.asarray(leaf_dev)
        if obj is not None and tree.num_leaves >= 1:
            g, h = obj.get_gradients(
                jnp.asarray(score if k > 1 else score[:, 0], np.float32))
            g = np.asarray(g)
            h = np.asarray(h)
            if k > 1:
                g, h = g[:, kk], h[:, kk]
            # float64 device segment sums: identical accumulation order to
            # the host reference's np.bincount (row-ordered updates)
            with _x64():
                seg = jnp.asarray(leaf_dev, jnp.int32)
                sum_g = np.asarray(jax.ops.segment_sum(
                    jnp.asarray(g, jnp.float64), seg,
                    num_segments=tree.num_leaves))
                sum_h = np.asarray(jax.ops.segment_sum(
                    jnp.asarray(h, jnp.float64), seg,
                    num_segments=tree.num_leaves))
                cnt = np.asarray(jax.ops.segment_sum(
                    jnp.ones(n, jnp.float64), seg,
                    num_segments=tree.num_leaves))
            new_vals = (-sum_g / (sum_h + cfg2.lambda_l2 + 1e-15)
                        * tree.shrinkage)
            has_data = cnt > 0
            new_leaf = np.where(has_data,
                                decay_rate * tree.leaf_value
                                + (1 - decay_rate) * new_vals,
                                tree.leaf_value)
            tree.leaf_value = chaos.inject_nan_refit(new_leaf, i + 1)
        score[:, kk] += tree.leaf_value[leaf]
    booster._fast1_cache = None
    log_info(f"refit: {report['route_replay_passes']} stream-replay + "
             f"{report['walk_fallback_passes']} walk-fallback + "
             f"{report['trivial']} trivial trees "
             f"(decay_rate={decay_rate})")
    return report
