"""Fault tolerance: crash-consistent checkpoints, non-finite guards, chaos.

The reference survives production failure modes through its Network layer
(socket retries, linkers_socket.cpp) and `snapshot_freq` model snapshots
(gbdt.cpp:259-263).  This package is the TPU reproduction's equivalent
reflex arc (docs/ROBUSTNESS.md):

  * :mod:`.checkpoint` — atomic snapshot writes (tmp + ``os.replace``),
    a JSON manifest with content checksums and a params hash, engine
    state capture (score vector, host RNG streams, objective state) so
    ``lgb.train(..., resume_from=...)`` continues **bit-identically** to
    an uninterrupted run, and retention pruning (``snapshot_keep``);
  * :mod:`.guards` — the ``nan_guard`` non-finite gradient/hessian guard
    and finite checks for loaded init scores and model trees;
  * :mod:`.heartbeat` — per-worker liveness files the supervising
    launcher (parallel/cluster.py) watches for hang detection;
  * :mod:`.chaos` — the deterministic fault-injection harness driven by
    ``LGBTPU_CHAOS`` (kill a worker at iteration N, delay heartbeats,
    truncate a snapshot, poison one gradient batch).  Every hook is a
    no-op when the env var is unset.
"""
from . import chaos
from .checkpoint import (latest_valid_snapshot, list_snapshots,
                         load_checkpoint, validate_checkpoint,
                         write_checkpoint)
from .guards import NanGuard, check_finite_init, check_model_trees
from .heartbeat import heartbeat_callback, read_heartbeat

__all__ = [
    "chaos",
    "write_checkpoint", "load_checkpoint", "validate_checkpoint",
    "list_snapshots", "latest_valid_snapshot",
    "NanGuard", "check_finite_init", "check_model_trees",
    "heartbeat_callback", "read_heartbeat",
]
