"""Deterministic fault injection for the robustness test matrix.

Faults are declared in the ``LGBTPU_CHAOS`` environment variable and fire
at exact, reproducible points of the training loop — the same strategy the
reference uses for its network tests (tests/distributed simulates worker
loss with localhost process kills), generalized into one harness the unit
tests and manual experiments share.

Grammar (directives separated by ``;``, options by ``,``)::

    LGBTPU_CHAOS="kill:iter=5,rank=1,once=/tmp/m"   # os._exit after iter 5
    LGBTPU_CHAOS="nan_grad:iter=3,count=8"          # NaN one gradient batch
    LGBTPU_CHAOS="truncate_snapshot"                # corrupt snapshot files
    LGBTPU_CHAOS="hang:iter=3,rank=1,once=/tmp/m"   # stop heartbeating
    LGBTPU_CHAOS="heartbeat_delay:seconds=2"        # slow every heartbeat

Closed-loop pipeline faults (docs/ROBUSTNESS.md "Closed-loop
freshness"; ``iter`` for ``poison_refit`` is the 1-based tree index of
the refit loop)::

    LGBTPU_CHAOS="poison_refit:iter=1,count=4"      # NaN refit leaf values
    LGBTPU_CHAOS="kill_refit:once=/tmp/m"           # die between gate and pointer
    LGBTPU_CHAOS="torn_pointer:once=/tmp/m"         # truncated promote.json write

Serving-fleet faults (docs/SERVING.md fleet architecture; ``rank`` here
is the REPLICA rank — the supervisor exports ``LGBTPU_REPLICA_RANK`` to
every replica process and rank matching prefers it over
``jax.process_index``; ``iter`` is the replica's heartbeat-loop beat
number, one beat every ~0.25 s)::

    LGBTPU_CHAOS="kill_replica:iter=8,rank=0,once=/tmp/m"  # SIGKILL-like exit
    LGBTPU_CHAOS="hang_replica:iter=12,rank=1,once=/tmp/m" # wedge the replica
    LGBTPU_CHAOS="slow_replica:seconds=0.5"                # delay every request
    LGBTPU_CHAOS="drop_conn:count=3"                       # reset 3 connections

Options:

* ``iter=N``   — fire at boosting iteration N (1-based); omitted = every.
* ``rank=R``   — only in the process with ``jax.process_index() == R``
  (or ``LGBTPU_REPLICA_RANK == R`` in serving replicas).
* ``once=P``   — marker-file latch: fire only if P does not exist, and
  create P first, so a relaunched/resumed cohort is not killed again.
* ``seconds=S``/``count=N`` — directive-specific magnitudes.

Every hook re-reads the env var (cheap dict lookup + cached parse), so
tests can monkeypatch it per-case; with the variable unset every hook is
an exact no-op.  Run ``python -m lightgbm_tpu.robustness.chaos`` to print
the parsed directive table.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import List, Optional

from ..utils.log import log_warning

ENV_VAR = "LGBTPU_CHAOS"


@dataclass
class Directive:
    name: str
    iteration: Optional[int] = None
    rank: Optional[int] = None
    once: Optional[str] = None
    seconds: Optional[float] = None
    count: Optional[int] = None


def _parse(text: str) -> List[Directive]:
    out: List[Directive] = []
    for raw in text.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        name, _, opts = raw.partition(":")
        d = Directive(name=name.strip())
        for tok in opts.split(","):
            tok = tok.strip()
            if not tok:
                continue
            key, _, val = tok.partition("=")
            key = key.strip()
            if key in ("iter", "iteration"):
                d.iteration = int(val)
            elif key == "rank":
                d.rank = int(val)
            elif key == "once":
                d.once = val
            elif key == "seconds":
                d.seconds = float(val)
            elif key == "count":
                d.count = int(val)
            else:
                raise ValueError(
                    f"{ENV_VAR}: unknown option {key!r} in directive {raw!r}")
        out.append(d)
    return out


_cache_text: Optional[str] = None
_cache: List[Directive] = []


def directives() -> List[Directive]:
    """Parsed directives for the CURRENT env value (re-read every call)."""
    global _cache_text, _cache
    text = os.environ.get(ENV_VAR, "")
    if text != _cache_text:
        _cache = _parse(text)
        _cache_text = text
    return _cache


def active() -> bool:
    return bool(directives())


def has(name: str) -> bool:
    return any(d.name == name for d in directives())


def _rank_matches(d: Directive) -> bool:
    if d.rank is None:
        return True
    # serving replicas carry their rank in the environment (set by the
    # fleet supervisor); importing jax for process_index would be both
    # wrong (replicas are single-process jax) and expensive here
    env_rank = os.environ.get("LGBTPU_REPLICA_RANK")
    if env_rank is not None:
        try:
            return int(env_rank) == d.rank
        except ValueError:
            return False
    import jax
    return jax.process_index() == d.rank


def _fire_once(d: Directive) -> bool:
    """Marker-file latch: created BEFORE firing so even an os._exit cannot
    re-arm the directive for the relaunched cohort."""
    if d.once is None:
        return True
    if os.path.exists(d.once):
        return False
    try:
        with open(d.once, "w") as fh:
            fh.write(f"fired {d.name} at {time.time()}\n")
    except OSError:
        pass
    return True


def _matches(d: Directive, name: str, iteration: Optional[int]) -> bool:
    if d.name != name:
        return False
    if d.iteration is not None and d.iteration != iteration:
        return False
    return _rank_matches(d)


def maybe_kill(iteration: int) -> None:
    """Simulate a hard crash/preemption right after ``iteration``: exits the
    process with no cleanup (``os._exit``), like SIGKILL would."""
    for d in directives():
        if _matches(d, "kill", iteration) and _fire_once(d):
            log_warning(f"chaos: killing process at iteration {iteration}")
            os._exit(137)


def inject_nan_grad(grad, iteration: int):
    """Poison the first ``count`` gradient rows with NaN at the matching
    iteration (1-based: pass ``iter_ + 1``); identity otherwise."""
    for d in directives():
        if _matches(d, "nan_grad", iteration) and _fire_once(d):
            import jax.numpy as jnp
            n = min(d.count or 8, grad.shape[0])
            log_warning(f"chaos: injecting NaN into {n} gradient rows at "
                        f"iteration {iteration}")
            return grad.at[:n].set(jnp.nan)
    return grad


def maybe_truncate_snapshot(path: str, iteration: Optional[int] = None) -> None:
    """Corrupt a just-written snapshot (cut the file in half) to exercise
    the manifest-checksum rejection path at resume time."""
    for d in directives():
        if _matches(d, "truncate_snapshot", iteration) and _fire_once(d):
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(size // 2, 1))
            log_warning(f"chaos: truncated snapshot {path} "
                        f"({size} -> {max(size // 2, 1)} bytes)")


def heartbeat_hook(iteration: int) -> None:
    """Called by the worker heartbeat callback before each beat: ``hang``
    stops beating (sleeps ~forever, the supervisor's hang detector must
    reap the worker); ``heartbeat_delay`` just slows the beat down."""
    for d in directives():
        if _matches(d, "hang", iteration) and _fire_once(d):
            log_warning(f"chaos: hanging worker at iteration {iteration}")
            time.sleep(d.seconds or 3600.0)
        elif _matches(d, "heartbeat_delay", iteration):
            time.sleep(d.seconds or 1.0)


# ---------------------------------------------------------------------------
# closed-loop pipeline faults (docs/ROBUSTNESS.md "Closed-loop freshness")
# ---------------------------------------------------------------------------

def inject_nan_refit(values: "np.ndarray", tree_index: int):
    """Poison the first ``count`` refitted leaf values of tree
    ``tree_index`` (1-based) with NaN — the validation gate's nan_guard
    must refuse the candidate; identity otherwise."""
    for d in directives():
        if _matches(d, "poison_refit", tree_index) and _fire_once(d):
            import numpy as np
            n = min(d.count or 4, values.shape[0])
            log_warning(f"chaos: poisoning {n} refit leaf values of tree "
                        f"{tree_index}")
            out = np.array(values, np.float64, copy=True)
            out[:n] = np.nan
            return out
    return values


def maybe_kill_refit() -> None:
    """Simulate the pipeline process dying BETWEEN gate-pass and the
    promotion pointer write (``os._exit``, like SIGKILL): the fleet must
    keep serving the old generation because the pointer never moved."""
    for d in directives():
        if _matches(d, "kill_refit", None) and _fire_once(d):
            log_warning("chaos: killing pipeline between gate and "
                        "pointer write")
            os._exit(137)


def maybe_tear_pointer(fleet_dir: str, pointer_text: str,
                       name: str = "promote.json") -> bool:
    """Replace the atomic promotion-pointer write with a NON-atomic
    truncated write (first half of the JSON) — simulates a promoter dying
    mid-write on a filesystem without atomic rename.  ``name`` selects
    the pointer file (per-tenant pointers are ``promote_<id>.json``).
    Replicas must treat the torn pointer as unreadable and keep serving.
    Returns True when fired (the caller must then skip its own pointer
    write)."""
    for d in directives():
        if _matches(d, "torn_pointer", None) and _fire_once(d):
            path = os.path.join(fleet_dir, name)
            torn = pointer_text[:max(len(pointer_text) // 2, 1)]
            with open(path, "w") as fh:
                fh.write(torn)
            log_warning(f"chaos: tore pointer write at {path} "
                        f"({len(pointer_text)} -> {len(torn)} bytes)")
            return True
    return False


# ---------------------------------------------------------------------------
# serving-fleet faults (docs/SERVING.md "Fleet architecture")
# ---------------------------------------------------------------------------

class DropConnection(Exception):
    """Raised by :func:`request_hook` when ``drop_conn`` fires; the HTTP
    handler closes the client socket without a response, so the client
    sees a connection reset — the fanout front must absorb it as a
    retryable transport error."""


# a wedged replica stays wedged: once hang_replica fires, EVERY later
# request (and the beat loop) blocks, like a process stuck in a lock
_replica_hung = False

# drop_conn with count=N resets only the first N matching requests; the
# latch is per-process (each replica counts its own drops)
_drops_fired = 0


def replica_hung() -> bool:
    return _replica_hung


def replica_beat_hook(beat: int) -> None:
    """Called by the fleet replica's heartbeat loop before each beat
    (one beat every ~0.25 s; ``iter`` matches the beat number).

    ``kill_replica`` exits the process with no cleanup (SIGKILL-like);
    ``hang_replica`` wedges the whole replica: the beat loop blocks (the
    supervisor's stale-heartbeat detector must reap it) and every request
    thread blocks too (the front's deadline/breaker must route around
    it)."""
    global _replica_hung
    for d in directives():
        if _matches(d, "kill_replica", beat) and _fire_once(d):
            log_warning(f"chaos: killing serving replica at beat {beat}")
            os._exit(137)
        elif _matches(d, "hang_replica", beat) and _fire_once(d):
            log_warning(f"chaos: hanging serving replica at beat {beat}")
            _replica_hung = True
            time.sleep(d.seconds or 3600.0)


def request_hook() -> None:
    """Called by the serving request path before any work.

    ``slow_replica`` delays the request by ``seconds``; ``drop_conn``
    raises :class:`DropConnection` (``count`` bounds how many requests
    are reset); a replica wedged by ``hang_replica`` blocks here forever
    — a hung process answers nothing, not just its heartbeat."""
    global _drops_fired
    if _replica_hung:
        time.sleep(3600.0)
    for d in directives():
        if _matches(d, "slow_replica", None) and _fire_once(d):
            time.sleep(d.seconds or 0.5)
        elif _matches(d, "drop_conn", None):
            if d.count is not None and _drops_fired >= d.count:
                continue
            if not _fire_once(d):
                continue
            _drops_fired += 1
            log_warning("chaos: dropping serving connection "
                        f"({_drops_fired}{'/' + str(d.count) if d.count else ''})")
            raise DropConnection()


def main() -> int:
    ds = directives()
    if not ds:
        print(f"{ENV_VAR} is unset or empty: all chaos hooks are no-ops")
        return 0
    print(f"{ENV_VAR}={os.environ.get(ENV_VAR, '')!r}")
    print(f"{'directive':<18}{'iter':<8}{'rank':<8}{'seconds':<10}"
          f"{'count':<8}once")
    for d in ds:
        print(f"{d.name:<18}{str(d.iteration):<8}{str(d.rank):<8}"
              f"{str(d.seconds):<10}{str(d.count):<8}{d.once}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
