"""Crash-consistent checkpoint/resume (docs/ROBUSTNESS.md).

The reference writes a model snapshot every ``snapshot_freq`` iterations
(gbdt.cpp:259-263) but leaves resumption to the user via continued
training.  Here a snapshot is a *checkpoint*: the model text plus the
engine state a bit-identical continuation needs (score vector, host RNG
streams, objective state), each written via tmp-file + ``os.replace`` and
sealed by a JSON manifest with content checksums — the manifest is written
LAST, so its presence certifies a complete checkpoint and a crash mid-write
can never produce a snapshot that validates.

Layout for ``output_model=M`` at iteration ``N``::

    M.snapshot_iter_N                 model text (LightGBM v4 format)
    M.snapshot_iter_N.state.npz       score + RNG/objective state
    M.snapshot_iter_N.manifest.json   iteration, params hash, checksums

``lgb.train(..., resume_from=M.snapshot_iter_N)`` (CLI: ``resume=``)
validates the manifest, loads the trees as the init model, restores the
state, and continues from iteration N byte-identically to a run that was
never interrupted.
"""
from __future__ import annotations

import contextlib
import glob
import hashlib
import io
import json
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..config import resolve_aliases
from ..utils.log import LightGBMError, log_debug, log_info
from . import chaos
from .guards import check_model_trees

MANIFEST_SUFFIX = ".manifest.json"
STATE_SUFFIX = ".state.npz"
FORMAT_VERSION = 1

# params with no bearing on the trained model: IO paths, orchestration, and
# observability knobs may differ between the checkpointing run and the
# resuming run (e.g. CLI vs API) without breaking bit-identity
_VOLATILE_PARAMS = frozenset({
    "config", "task", "data", "valid", "num_iterations", "verbosity",
    "input_model", "output_model", "output_result", "saved_feature_importance_type",
    "snapshot_freq", "snapshot_keep", "resume_from", "save_binary",
    "num_machines", "machines", "machine_list_filename", "local_listen_port",
    "time_out", "dist_retries", "dist_backoff",
    # comms-mode A/B knobs: trees are bit-identical across hist_comms and
    # across any psum_scatter chunking, so a run may resume under a
    # different collective layout (hist_comms_dtype is NOT volatile —
    # bf16_pair changes the arithmetic); eval_fetch_freq only re-times
    # host polls
    "hist_comms", "hist_comms_pipeline", "eval_fetch_freq",
    # the binned cache is a pure IO shortcut: a cache hit restores the
    # exact binned matrix the raw parse would have produced (params-hash
    # gated), so a resumed run may toggle it freely (ingest_mode /
    # ingest_chunk_rows / ingest_sketch_size are NOT volatile — they can
    # change sampling or compressed-sketch boundaries)
    "ingest_cache", "ingest_cache_path",
    "telemetry", "telemetry_out", "trace_out", "telemetry_recompile_threshold",
    "telemetry_straggler_every", "telemetry_straggler_skew",
    "telemetry_cost", "profile_out",
    "serve_host", "serve_port", "serve_max_batch", "serve_max_delay_ms",
    "serve_queue_size", "serve_buckets", "serve_warmup", "serve_heartbeat",
    "serve_replicas", "serve_fleet_mode", "serve_fleet_dir",
    "serve_binary_port", "serve_binary_accept_threads",
    "serve_deadline_ms", "serve_retries", "serve_retry_backoff_ms",
    "serve_breaker_failures", "serve_breaker_cooldown_s",
    "serve_restart_backoff_s", "serve_hang_timeout_s",
    "serve_trace_sample", "serve_trace_tail", "serve_access_log",
    "serve_slo_availability", "serve_slo_p99_ms", "serve_slo_window_s",
    "serve_slo_burn",
    # quality observability: the sidecar + drift monitor read the model,
    # they never shape it
    "quality_profile", "quality_sample", "quality_audit_sample",
    "quality_min_rows", "quality_topk", "drift_threshold",
    "drift_window_s",
    # closed-loop pipeline orchestration: these shape WHEN a candidate is
    # built/promoted, never the trees inside a checkpoint
    "pipeline_fresh_data", "pipeline_refit_iterations",
    "pipeline_gate_margin", "pipeline_observe_s",
    "pipeline_observe_poll_s", "pipeline_promote",
})


# ---------------------------------------------------------------------------
# atomic writes
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def atomic_open(path: str, mode: str = "w", **open_kw):
    """Open a same-directory tmp file for writing; on clean ``with`` exit
    it is fsynced and ``os.replace``d onto ``path``, on exception it is
    unlinked — callers stream arbitrary content (binary datasets, GB-scale
    CSV results) and a crash/preemption mid-write never leaves a partial
    file at ``path``.  The one blessed write primitive (lgbtlint LGB005):
    every atomic_write_* helper below rides it.

    Truncating-write modes only: append/update modes would start from an
    EMPTY tmp file and ``os.replace`` would silently discard everything
    already at ``path`` — fail loudly instead."""
    if "a" in mode or "+" in mode or "r" in mode:
        raise ValueError(
            f"atomic_open mode {mode!r} unsupported: the tmp file starts "
            "empty, so append/update modes would truncate the destination; "
            "use 'w'/'wb'/'x'/'xb'")
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, mode, **open_kw) as fh:
            yield fh
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write via a same-directory tmp file + fsync + ``os.replace`` so a
    crash/preemption mid-write never leaves a partial file at ``path``."""
    with atomic_open(path, "wb") as fh:
        fh.write(data)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_lines(path: str, lines) -> None:
    """Streaming variant: writes an iterable of text chunks straight to
    the same-directory tmp file (constant memory — CLI predict outputs
    can be GBs) before the fsync + ``os.replace``."""
    with atomic_open(path, "w", encoding="utf-8") as fh:
        for chunk in lines:
            fh.write(chunk)


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# params identity
# ---------------------------------------------------------------------------

def canonical_params(params: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Alias-resolved params minus IO/orchestration keys, JSON-normalized
    (numpy scalars -> python, everything non-JSON stringified)."""
    resolved = resolve_aliases(dict(params or {}))
    kept = {k: v for k, v in resolved.items() if k not in _VOLATILE_PARAMS}
    return json.loads(json.dumps(kept, sort_keys=True, default=str))


def params_hash(params: Optional[Dict[str, Any]]) -> str:
    return _sha256_bytes(
        json.dumps(canonical_params(params), sort_keys=True).encode())


# ---------------------------------------------------------------------------
# engine state capture / restore
# ---------------------------------------------------------------------------

def _pack_rng(prefix: str, rng, out: Dict[str, np.ndarray]) -> None:
    name, keys, pos, has_gauss, cached = rng.get_state(legacy=True)
    if name != "MT19937":  # pragma: no cover - numpy only has one legacy gen
        raise LightGBMError(f"cannot checkpoint RNG of type {name}")
    out[f"{prefix}__keys"] = np.asarray(keys, np.uint32)
    out[f"{prefix}__meta"] = np.asarray([pos, has_gauss], np.int64)
    out[f"{prefix}__gauss"] = np.asarray([cached], np.float64)


def _unpack_rng(prefix: str, rng, state: Dict[str, np.ndarray]) -> None:
    if f"{prefix}__keys" not in state:
        return
    meta = state[f"{prefix}__meta"]
    rng.set_state(("MT19937", np.asarray(state[f"{prefix}__keys"], np.uint32),
                   int(meta[0]), int(meta[1]),
                   float(state[f"{prefix}__gauss"][0])))


def _full_score_host(engine) -> np.ndarray:
    """The PADDED global score as host numpy.  Multi-process global arrays
    allgather their per-rank shards in rank-major row order (the global
    layout) — every rank ends up with the same full copy, so rank 0 can
    write it and every rank can restore it."""
    score = engine.score
    if getattr(engine, "_dist_mode", False):
        from jax.experimental import multihost_utils
        shards = sorted(score.addressable_shards,
                        key=lambda sh: sh.index[0].start or 0)
        local = np.concatenate([np.asarray(sh.data) for sh in shards])
        full = np.asarray(multihost_utils.process_allgather(local))
        return full.reshape((-1,) + tuple(score.shape[1:]))
    return np.asarray(score)


def capture_state(booster) -> Dict[str, np.ndarray]:
    """Everything beyond the trees that a bit-identical continuation needs.
    Collective-safe: in multi-process runs every rank must call this at the
    same point (the score capture allgathers)."""
    engine = booster.engine
    engine._flush_models()
    state: Dict[str, np.ndarray] = {
        "score": np.asarray(_full_score_host(engine), np.float32)}
    if getattr(engine, "_rng", None) is not None:
        _pack_rng("rng_feature", engine._rng, state)
    if getattr(engine, "_drop_rng", None) is not None:   # DART
        _pack_rng("rng_drop", engine._drop_rng, state)
    obj = engine.objective
    if obj is not None:
        if getattr(obj, "_rng", None) is not None:       # rank_xendcg
            _pack_rng("rng_objective", obj._rng, state)
        for a in obj.state_attrs():
            v = getattr(obj, a, None)
            if v is not None:
                state[f"obj_state__{a}"] = np.asarray(v)
    return state


def restore_state(booster, state: Dict[str, np.ndarray]) -> None:
    """Inverse of :func:`capture_state` on a freshly seeded engine (after
    ``load_init_model``): the restored float32 score replaces the tree-walk
    reconstruction so the resumed run's gradients are bit-identical."""
    import jax
    import jax.numpy as jnp

    engine = booster.engine
    score = np.asarray(state["score"], np.float32)
    if tuple(score.shape) != tuple(engine.score.shape):
        raise LightGBMError(
            f"checkpoint score shape {tuple(score.shape)} does not match this "
            f"run's {tuple(engine.score.shape)} — dataset, num_class, or "
            "process topology changed since the snapshot was written")
    if getattr(engine, "_dist_mode", False):
        engine.score = jax.make_array_from_callback(
            score.shape, engine.score.sharding, lambda idx: score[idx])
    else:
        engine.score = engine._shard_row_array(jnp.asarray(score))
    if getattr(engine, "_rng", None) is not None:
        _unpack_rng("rng_feature", engine._rng, state)
    if getattr(engine, "_drop_rng", None) is not None:
        _unpack_rng("rng_drop", engine._drop_rng, state)
    obj = engine.objective
    if obj is not None:
        if getattr(obj, "_rng", None) is not None:
            _unpack_rng("rng_objective", obj._rng, state)
        dist = getattr(engine, "_dist_mode", False)
        for a in obj.state_attrs():
            key = f"obj_state__{a}"
            if key in state:
                v = np.asarray(state[key])
                setattr(obj, a, v if dist else jnp.asarray(v))


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------

def snapshot_path(output_model: str, iteration: int) -> str:
    return f"{output_model}.snapshot_iter_{iteration}"


def write_checkpoint(booster, output_model: str, iteration: int,
                     keep: int = -1, fleet_dir: str = "") -> str:
    """Write the iteration-``N`` checkpoint for ``output_model`` and prune
    to the ``keep`` newest (``keep <= 0`` keeps all).  Multi-process: every
    rank participates in state capture (collective), rank 0 writes."""
    import jax

    path = snapshot_path(str(output_model), int(iteration))
    model_str = booster.model_to_string()
    state = capture_state(booster)
    if jax.process_index() != 0:
        return path
    atomic_write_text(path, model_str)
    buf = io.BytesIO()
    np.savez(buf, **state)
    state_bytes = buf.getvalue()
    atomic_write_bytes(path + STATE_SUFFIX, state_bytes)
    manifest = {
        "format_version": FORMAT_VERSION,
        "iteration": int(iteration),
        "num_trees": booster.num_trees(),
        "num_tree_per_iteration": booster.num_model_per_iteration(),
        "model_file": os.path.basename(path),
        "model_sha256": _sha256_bytes(model_str.encode("utf-8")),
        "state_file": os.path.basename(path + STATE_SUFFIX),
        # hash the in-memory bytes: re-reading the multi-MB npz it just
        # wrote would be a redundant full-file read on the training path
        "state_sha256": _sha256_bytes(state_bytes),
        "params_hash": params_hash(getattr(booster, "params", {})),
        "params": canonical_params(getattr(booster, "params", {})),
        "num_processes": jax.process_count(),
        "created_unix": time.time(),
    }
    atomic_write_text(path + MANIFEST_SUFFIX,
                      json.dumps(manifest, indent=1, sort_keys=True))
    chaos.maybe_truncate_snapshot(path, int(iteration))
    if keep and keep > 0:
        prune_snapshots(str(output_model), keep, fleet_dir=fleet_dir)
    return path


def promoted_paths(fleet_dir: str) -> set:
    """Real paths a live ``promote.json`` generation points at — the
    currently served model AND its rollback target (``prev``).  Read
    directly (not via serving.fleet) so the checkpoint layer stays
    import-light; a torn/unreadable pointer pins nothing."""
    pinned: set = set()
    if not fleet_dir:
        return pinned
    try:
        with open(os.path.join(fleet_dir, "promote.json")) as fh:
            p = json.load(fh)
    except (OSError, ValueError):
        return pinned
    for rec in (p, p.get("prev") or {}):
        target = rec.get("path")
        if target:
            pinned.add(os.path.realpath(str(target)))
    return pinned


def prune_snapshots(output_model: str, keep: int,
                    fleet_dir: str = "") -> None:
    """Delete all but the ``keep`` newest snapshots — EXCEPT any snapshot
    a live promotion generation (current or rollback target) points at:
    pruning the fleet's serving model out from under it would break every
    replica restart and the rollback path."""
    pinned = promoted_paths(fleet_dir)
    for it, path in list_snapshots(output_model)[:-keep]:
        if os.path.realpath(path) in pinned:
            log_debug(f"snapshot {path} pinned by a live promotion; "
                      "not pruned")
            continue
        for p in (path, path + STATE_SUFFIX, path + MANIFEST_SUFFIX):
            try:
                os.unlink(p)
            except OSError:
                pass
        log_debug(f"pruned snapshot {path} (snapshot_keep={keep})")


def list_snapshots(output_model: str) -> List[Tuple[int, str]]:
    """(iteration, path) for every on-disk snapshot, oldest first."""
    pat = re.compile(re.escape(os.path.basename(output_model))
                     + r"\.snapshot_iter_(\d+)$")
    out = []
    for p in glob.glob(glob.escape(output_model) + ".snapshot_iter_*"):
        m = pat.match(os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


# ---------------------------------------------------------------------------
# validate / load
# ---------------------------------------------------------------------------

def read_manifest(path: str) -> Dict[str, Any]:
    mpath = path + MANIFEST_SUFFIX
    if not os.path.exists(mpath):
        raise LightGBMError(
            f"checkpoint {path!r} has no manifest ({mpath} missing) — either "
            "the file is not a checkpoint or its write never completed")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except ValueError as e:
        raise LightGBMError(f"checkpoint manifest {mpath} is not valid "
                            f"JSON: {e}")
    if manifest.get("format_version") != FORMAT_VERSION:
        raise LightGBMError(
            f"checkpoint {path!r} has manifest format_version="
            f"{manifest.get('format_version')!r}; this build reads "
            f"{FORMAT_VERSION}")
    return manifest


def validate_checkpoint(path: str,
                        params: Optional[Dict[str, Any]] = None,
                        expect_processes: Optional[int] = None
                        ) -> Dict[str, Any]:
    """Full validation chain: manifest present, checksums match, model text
    parses completely, trees are finite, tree count matches, and (when
    ``params`` is given) the model-relevant params match the manifest's.
    ``expect_processes`` is the topology the RESUMING job will have —
    defaults to this process's world size; a supervisor validating on
    behalf of a worker cohort passes the cohort size."""
    return _validate_and_read(path, params, expect_processes)[0]


def _validate_and_read(path: str, params: Optional[Dict[str, Any]],
                       expect_processes: Optional[int]):
    """validate_checkpoint's body, returning the verified model text too so
    resume parses the exact bytes that were checksummed (no second read)."""
    import jax
    from ..model_io import load_model_string

    path = str(path)
    manifest = read_manifest(path)
    if not os.path.exists(path):
        raise LightGBMError(f"checkpoint model file missing: {path}")
    model_str = open(path, encoding="utf-8").read()
    if _sha256_bytes(model_str.encode("utf-8")) != manifest["model_sha256"]:
        raise LightGBMError(
            f"checkpoint {path!r} failed its content checksum — the model "
            "file is truncated or corrupt; resume from an older snapshot")
    lm = load_model_string(model_str)   # raises on truncated tree blocks
    if len(lm.trees) != int(manifest["num_trees"]):
        raise LightGBMError(
            f"checkpoint {path!r} holds {len(lm.trees)} trees but its "
            f"manifest recorded {manifest['num_trees']}")
    check_model_trees(lm.trees, what=f"checkpoint {path!r}")
    spath = path + STATE_SUFFIX
    if not os.path.exists(spath):
        raise LightGBMError(f"checkpoint state file missing: {spath}")
    if _sha256_file(spath) != manifest["state_sha256"]:
        raise LightGBMError(
            f"checkpoint state {spath!r} failed its content checksum")
    want_procs = (int(expect_processes) if expect_processes is not None
                  else jax.process_count())
    if int(manifest.get("num_processes", 1)) != want_procs:
        raise LightGBMError(
            f"checkpoint {path!r} was written by "
            f"{manifest.get('num_processes')} process(es) but this run has "
            f"{want_procs} — resume needs the same topology for "
            "bit-identical continuation")
    if params is not None:
        want = canonical_params(params)
        have = manifest.get("params", {})
        if want != have:
            diff = sorted(set(want) ^ set(have)
                          | {k for k in set(want) & set(have)
                             if want[k] != have[k]})
            raise LightGBMError(
                f"checkpoint {path!r} was written with different training "
                f"parameters (differing keys: {', '.join(diff) or '?'}); "
                "resume with the original params or pass params=None to "
                "skip the check")
    return manifest, model_str


def load_checkpoint(path: str,
                    params: Optional[Dict[str, Any]] = None
                    ) -> Tuple[str, Dict[str, Any], Dict[str, np.ndarray]]:
    """Validate and load: returns (model text, manifest, state arrays)."""
    manifest, model_str = _validate_and_read(path, params, None)
    with np.load(str(path) + STATE_SUFFIX) as z:
        state = {k: z[k] for k in z.files}
    return model_str, manifest, state


def latest_valid_snapshot(output_model: str,
                          params: Optional[Dict[str, Any]] = None,
                          expect_processes: Optional[int] = None
                          ) -> Optional[str]:
    """Newest snapshot of ``output_model`` that passes full validation;
    invalid/corrupt ones are skipped with a log line."""
    for it, path in reversed(list_snapshots(output_model)):
        try:
            validate_checkpoint(path, params=params,
                                expect_processes=expect_processes)
            return path
        except LightGBMError as e:
            log_info(f"skipping invalid snapshot {path}: {e}")
    return None
