"""Non-finite guards (param ``nan_guard``, docs/ROBUSTNESS.md).

A single NaN gradient silently poisons every subsequent tree: the leaf sums
go NaN, the split scan picks garbage, and the score vector never recovers.
The GBDT loop runs one cheap jitted all-finite check over the gradient and
hessian blocks each iteration and, when it trips, zeroes them — an all-zero
gradient grows an exact single-leaf no-op tree, so the poisoned iteration
is *skipped* without perturbing any later iteration's RNG streams.  The
same policy knob covers loaded init scores and the split gains / leaf
values of models used to seed continued training.

Modes: ``warn`` (default — log + skip + count), ``skip`` (silent skip),
``raise`` (abort with :class:`LightGBMError`), ``none`` (guard off).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils.log import LightGBMError, log_warning

VALID_MODES = ("warn", "skip", "raise", "none")


def resolve_mode(mode: str) -> str:
    m = str(mode or "warn").strip().lower()
    if m not in VALID_MODES:
        raise LightGBMError(
            f"nan_guard={mode!r} is not one of {', '.join(VALID_MODES)}")
    return m


class NanGuard:
    """Per-engine guard state: counts poisoned iterations and applies the
    configured policy.  Device flags from the fused TPU path are resolved
    lazily (``defer=True``) so the guard never forces an extra host sync
    on the one-launch fast path; ``raise`` mode always reads eagerly."""

    def __init__(self, mode: str, objective_name: str = ""):
        self.mode = resolve_mode(mode)
        self.enabled = self.mode != "none"
        self.objective_name = objective_name or "none"
        self.hits = 0
        self._pending: List[Tuple[int, object]] = []

    def note(self, ok_dev, iteration: int, defer: bool = False) -> None:
        """Record this iteration's device-side all-finite flag."""
        if not self.enabled or ok_dev is None:
            return
        if defer and self.mode != "raise":
            self._pending.append((iteration, ok_dev))
            if len(self._pending) >= 64:
                self.poll()
            return
        if not bool(ok_dev):
            self._record(iteration)

    def take_pending(self) -> List[Tuple[int, object]]:
        """Hand the deferred backlog to a caller that will fetch the
        device flags inside ITS OWN batched transfer (the engine's
        _poll_device_flags rides everything on one device_get); pair
        with :meth:`resolve`."""
        pending, self._pending = self._pending, []
        return pending

    def resolve(self, pending: List[Tuple[int, object]], values) -> None:
        """Apply host values fetched for a :meth:`take_pending` batch."""
        for (iteration, _), ok in zip(pending, values):
            if not bool(ok):
                self._record(iteration)

    def poll(self) -> None:
        """Resolve deferred flags (called at the finished-flag polls and at
        the end of training) — the whole backlog rides ONE device_get, not
        one blocking bool() per flag."""
        pending = self.take_pending()
        if not pending:
            return
        import jax
        from .. import telemetry as _tel
        got = jax.device_get([ok for _, ok in pending])
        _tel.note_host_sync()
        self.resolve(pending, got)

    def _record(self, iteration: int) -> None:
        self.hits += 1
        from .. import telemetry as _tel
        _tel.inc("train/nan_skipped")
        msg = (f"non-finite gradients/hessians at iteration {iteration + 1} "
               f"(objective={self.objective_name})")
        if self.mode == "raise":
            raise LightGBMError(f"nan_guard=raise: {msg}")
        if self.mode == "warn":
            log_warning(f"nan_guard: {msg}; skipping the poisoned iteration")


def check_finite_init(arr: np.ndarray, what: str,
                      mode: str) -> Optional[np.ndarray]:
    """Guard a loaded init-score array: non-finite entries are zeroed
    (``warn``/``skip``) or fatal (``raise``); ``none`` passes through."""
    mode = resolve_mode(mode)
    if mode == "none" or arr is None:
        return arr
    a = np.asarray(arr)
    bad = ~np.isfinite(a)
    nbad = int(bad.sum())
    if nbad == 0:
        return arr
    if mode == "raise":
        raise LightGBMError(
            f"nan_guard=raise: {what} contains {nbad} non-finite value(s)")
    if mode == "warn":
        log_warning(f"nan_guard: {what} contains {nbad} non-finite value(s); "
                    "replacing with 0")
    out = a.copy()
    out[bad] = 0.0
    return out


def check_model_trees(trees, what: str = "model") -> None:
    """Reject models with poisoned trees before they seed continued
    training or a resume: NaN/inf leaf values or NaN split gains mean the
    source run was already corrupt and every further tree would inherit
    it.  (Thresholds may legitimately be +/-inf — last-bin boundaries.)"""
    for i, t in enumerate(trees):
        lv = np.asarray(t.leaf_value, np.float64)
        if not np.all(np.isfinite(lv)):
            raise LightGBMError(
                f"non-finite leaf values in {what} (tree {i}); refusing to "
                "continue training from a poisoned model")
        sg = np.asarray(t.split_gain, np.float64)
        if sg.size and np.any(np.isnan(sg)):
            raise LightGBMError(
                f"non-finite split gains in {what} (tree {i}); refusing to "
                "continue training from a poisoned model")
