"""Per-worker heartbeat files for the supervising launcher.

Each distributed worker touches a small JSON file after every boosting
iteration; the supervisor (parallel/cluster.py) watches the files' mtimes
and declares a worker hung when its beat goes stale — the analog of the
reference Network layer's socket timeouts (``time_out``), but observable
from OUTSIDE the process, which is what a supervisor needs when a worker
is wedged inside a collective.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

from . import chaos


def write_heartbeat(path: str, iteration: int) -> None:
    """Atomically (tmp + ``os.replace``) refresh the heartbeat file; the
    supervisor keys off the file mtime, the payload is for humans."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"iteration": iteration, "time": time.time(),
                   "pid": os.getpid()}, fh)
    os.replace(tmp, path)


def heartbeat_age(path: str) -> Optional[float]:
    """Seconds since the heartbeat file was last touched (mtime — the
    field supervisors and the serving ``/health`` probe key off), or
    ``None`` when no beat has been written yet."""
    try:
        return max(time.time() - os.path.getmtime(path), 0.0)
    except OSError:
        return None


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def heartbeat_callback(path: str, every: int = 1) -> Callable:
    """Training callback beating ``path`` every ``every`` iterations.

    No beat is written before the first iteration completes: the first
    iteration includes the full XLA compile, so an early beat would start
    the supervisor's stale-mtime clock mid-compile and defeat its
    ``startup_grace`` (which governs exactly as long as no file exists)."""
    def _callback(env) -> None:
        if every > 0 and (env.iteration + 1) % every == 0:
            chaos.heartbeat_hook(env.iteration + 1)
            write_heartbeat(path, env.iteration + 1)
    _callback.order = 50  # type: ignore[attr-defined]
    return _callback
