"""Online serving launcher: ``python -m lightgbm_tpu.serve``.

Same ``key=value`` grammar as the training CLI (config files compose the
same way), e.g.::

    python -m lightgbm_tpu.serve input_model=model.txt serve_port=12600 \\
        serve_max_batch=256 serve_max_delay_ms=2

Equivalent to ``python -m lightgbm_tpu task=serve ...``; see
docs/SERVING.md for endpoints and tuning.
"""
from __future__ import annotations

import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__)
        return 1
    from .cli import _coerce, parse_args
    from .config import resolve_aliases
    from .serving.server import run_server

    params = _coerce(resolve_aliases(parse_args(list(argv))))
    params.setdefault("task", "serve")
    return run_server(params)


if __name__ == "__main__":
    sys.exit(main())
