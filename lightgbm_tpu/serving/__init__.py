"""Online inference serving (docs/SERVING.md).

Four layers on top of the trained-model stack:

  * :mod:`.registry` — versioned, pre-bound models with validated atomic
    hot-reload (sha256 manifest + model_io corruption checks + finite
    guard) and drain-by-reference swaps;
  * :mod:`.compiled` — the shape-bucketed compiled predictor: batches pad
    to a fixed row-count ladder so every post-warmup dispatch reuses an
    already-traced XLA program, while exact integer-key comparisons keep
    scores bitwise identical to ``Booster.predict``;
  * :mod:`.batcher` — dynamic micro-batching under
    ``serve_max_batch``/``serve_max_delay_ms`` with admission control
    (structured overload rejection) and a native single-row fast path;
  * :mod:`.server` — the stdlib-HTTP JSON front end
    (``/predict /health /ready /reload /stats``) with graceful SIGTERM
    drain, launched via ``python -m lightgbm_tpu.serve`` or CLI
    ``task=serve``;
  * :mod:`.fleet` + :mod:`.front` — the replica-pool supervisor
    (restart-with-backoff, heartbeat liveness, shared-directory
    fleet-wide promotion keyed ``(model_id, generation)``) and the
    fanout front (deadline/retry/backoff, per-replica circuit breaker,
    load shedding); ``serve_replicas > 1`` serves through the fleet;
  * :mod:`.multimodel` — the HBM-resident multi-model cache behind
    ``serve_models``: byte-accounted LRU residency, per-tenant
    registries, and stacked dispatch of same-shape tenants through ONE
    compiled ``serve_predict_multi`` program (docs/SERVING.md
    "Multi-tenant serving").
"""
from .batcher import DeadlineError, MicroBatcher, OverloadError, PredictResult
from .compiled import (CompiledPredictor, bucket_ladder, raw_scores_stacked,
                       shape_envelope)
from .front import CircuitBreaker, FanoutFront
from .fleet import ServingFleet, run_fleet
from .multimodel import MultiModelRegistry, parse_model_roster
from .registry import ModelRegistry, ServingModel
from .server import (ServingApp, reuseport_available, run_server,
                     serve_from_params)
from .slo import SLOMonitor
from .wire import (OP_EXPLAIN, OP_PREDICT, BinaryClient, BinaryServer,
                   FleetBinaryClient, WireError)

__all__ = [
    "CompiledPredictor", "bucket_ladder", "shape_envelope",
    "raw_scores_stacked",
    "ModelRegistry", "ServingModel",
    "MultiModelRegistry", "parse_model_roster",
    "MicroBatcher", "OverloadError", "DeadlineError", "PredictResult",
    "ServingApp", "run_server", "serve_from_params",
    "ServingFleet", "run_fleet", "FanoutFront", "CircuitBreaker",
    "SLOMonitor", "reuseport_available",
    "BinaryServer", "BinaryClient", "FleetBinaryClient", "WireError",
    "OP_PREDICT", "OP_EXPLAIN",
]
