"""Dynamic micro-batching: coalesce requests into one device dispatch.

Clipper-style adaptive batching (Crankshaw et al., NSDI 2017): a bounded
queue feeds one worker thread that drains whatever arrived, keeps
pulling until ``serve_max_batch`` rows are gathered or the oldest
request's ``serve_max_delay_ms`` deadline expires, and runs ONE bucketed
device call for the coalesced matrix.  Per-request tails (averaging +
output transform) are applied to each request's row slice, so every
response is bitwise identical to predicting that request alone.

Two escape hatches keep tail latency honest:

  * **singleton fast path** — ``submit(..., fast=True)`` executes a
    one-row request synchronously on the caller thread through the
    pre-bound :class:`SingleRowFastPredictor` native walk (no queue wait,
    no device dispatch) — the latency-critical path of the reference's
    ``LGBM_BoosterPredictForMatSingleRowFast``;
  * **admission control** — a full queue rejects immediately with a
    structured :class:`OverloadError` (HTTP 503 upstream) instead of
    buffering unboundedly; shedding at the door keeps the p99 of
    admitted requests bounded.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.log import LightGBMError, log_debug, log_warning
from .registry import ModelRegistry, ServingModel

# value-histogram bounds for batch-size / queue-depth distributions
DEPTH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class OverloadError(LightGBMError):
    """Queue-full rejection carrying the structured overload payload."""

    def __init__(self, queue_depth: int, queue_size: int):
        self.queue_depth = int(queue_depth)
        self.queue_size = int(queue_size)
        super().__init__(
            f"serving queue full ({self.queue_depth}/{self.queue_size} "
            "requests); retry with backoff")

    def payload(self) -> Dict[str, Any]:
        return {"error": "overload", "queue_depth": self.queue_depth,
                "queue_size": self.queue_size}


@dataclass
class PredictResult:
    """What a resolved request future carries."""
    values: np.ndarray       # converted (or raw) scores for this request
    model_version: int       # the version that actually scored it
    batched_rows: int        # total rows of the coalesced dispatch
    queue_wait_s: float      # enqueue -> dispatch latency


@dataclass
class _Request:
    rows: np.ndarray
    raw_score: bool
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)


class MicroBatcher:
    """Bounded queue + one coalescing worker thread over a registry."""

    def __init__(self, registry: ModelRegistry, *, max_batch: int = 256,
                 max_delay_ms: float = 2.0, queue_size: int = 512,
                 heartbeat_path: str = ""):
        self.registry = registry
        self.max_batch = max(int(max_batch), 1)
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1e3
        self.queue_size = max(int(queue_size), 1)
        self.heartbeat_path = str(heartbeat_path or "")
        self._q: "queue.Queue[_Request]" = queue.Queue(self.queue_size)
        self._stop = threading.Event()
        # serializes enqueue against stop(): _stop is SET under this lock
        # and checked under it before every put, so no request can enter
        # the queue after the drain decision — the worker only exits once
        # _stop is set AND the queue is empty, so everything admitted
        # before the flag is guaranteed to be served
        self._submit_lock = threading.Lock()
        self._drain = True
        self._worker: Optional[threading.Thread] = None
        self.batches = 0
        self.served = 0
        self.rejected = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(target=self._run,
                                            name="lgbtpu-serve-batcher",
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful stop: with ``drain`` the worker finishes everything
        already queued before exiting (SIGTERM semantics); without it,
        queued futures are cancelled."""
        self._drain = bool(drain)
        with self._submit_lock:
            self._stop.set()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)
        if not drain:
            while True:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    break
                req.future.cancel()

    @property
    def worker_alive(self) -> bool:
        return self._worker is not None and self._worker.is_alive()

    def queue_depth(self) -> int:
        return self._q.qsize()

    # -- submission --------------------------------------------------------
    def submit(self, rows, raw_score: bool = False,
               fast: bool = False) -> "Future[PredictResult]":
        """Enqueue one request; returns a Future resolving to
        :class:`PredictResult`.  Raises :class:`OverloadError` at once
        when the queue is full, :class:`LightGBMError` on shape errors."""
        from .. import telemetry

        model = self.registry.current()
        X = model.validate_rows(rows)
        if self._stop.is_set():
            raise OverloadError(self._q.qsize(), self.queue_size)
        if fast and X.shape[0] == 1:
            # latency-critical singleton: pre-bound native walk, caller
            # thread, zero queueing — still version-stamped
            t0 = time.perf_counter()
            values = model.predict(X, raw_score=raw_score)
            telemetry.observe("serve/latency_s",
                              time.perf_counter() - t0)
            telemetry.inc("serve/requests_fast")
            # the fast path runs on the CALLER thread and races the worker
            # thread's batch-counter updates (lgbtlint LGB006)
            with self._submit_lock:
                self.served += 1
            fut: "Future[PredictResult]" = Future()
            fut.set_result(PredictResult(values, model.version, 1, 0.0))
            return fut
        req = _Request(np.ascontiguousarray(X), bool(raw_score))
        with self._submit_lock:
            if self._stop.is_set():
                raise OverloadError(self._q.qsize(), self.queue_size)
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.rejected += 1
                telemetry.inc("serve/rejected")
                raise OverloadError(self._q.qsize(), self.queue_size)
        telemetry.observe("serve/queue_depth", float(self._q.qsize()),
                          bounds=DEPTH_BOUNDS)
        return req.future

    # -- worker ------------------------------------------------------------
    def _collect(self) -> List[_Request]:
        """One coalescing round: block for the first request, then gather
        batch-mates until the row budget or the delay deadline."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        rows = first.rows.shape[0]
        deadline = time.perf_counter() + self.max_delay_s
        while rows < self.max_batch:
            left = deadline - time.perf_counter()
            try:
                nxt = (self._q.get_nowait() if left <= 0
                       else self._q.get(timeout=left))
            except queue.Empty:
                break
            batch.append(nxt)
            rows += nxt.rows.shape[0]
            if left <= 0:
                break
        return batch

    def _process(self, batch: List[_Request]) -> None:
        from .. import telemetry

        model = self.registry.current()   # pinned for the WHOLE batch
        good = [r for r in batch
                if r.rows.shape[1] == model.num_features]
        for r in batch:
            if r.rows.shape[1] != model.num_features:
                # the model was hot-swapped to a different feature count
                # between submit-time validation and dispatch
                r.future.set_exception(LightGBMError(
                    f"model v{model.version} expects "
                    f"{model.num_features} features, request has "
                    f"{r.rows.shape[1]}"))
        if not good:
            return
        t0 = time.perf_counter()
        X = (good[0].rows if len(good) == 1
             else np.concatenate([r.rows for r in good], axis=0))
        n = X.shape[0]
        if n == 1 and len(good) == 1:
            # a lone singleton skips the device: native single-row walk
            values = model.predict(good[0].rows, raw_score=good[0].raw_score)
            good[0].future.set_result(PredictResult(
                values, model.version, 1,
                t0 - good[0].t_enqueue))
        else:
            raw = model.raw_scores(X)
            off = 0
            for r in good:
                m = r.rows.shape[0]
                r.future.set_result(PredictResult(
                    model.finish(raw[off:off + m], r.raw_score),
                    model.version, n, t0 - r.t_enqueue))
                off += m
        dt = time.perf_counter() - t0
        with self._submit_lock:
            self.batches += 1
            self.served += len(good)
        telemetry.inc("serve/requests", len(good))
        telemetry.inc("serve/rows", n)
        telemetry.inc("serve/batches")
        telemetry.observe("serve/dispatch_s", dt)
        telemetry.observe("serve/batch_rows", float(n),
                          bounds=DEPTH_BOUNDS)
        for r in good:
            telemetry.observe("serve/latency_s",
                              time.perf_counter() - r.t_enqueue)
        if self.heartbeat_path:
            from ..robustness.heartbeat import write_heartbeat
            try:
                write_heartbeat(self.heartbeat_path, self.batches)
            except OSError as e:   # liveness file must never kill serving
                log_debug(f"serve heartbeat write failed: {e}")

    def _run(self) -> None:
        while True:
            if self._stop.is_set() and (not self._drain or self._q.empty()):
                break
            batch: List[_Request] = []
            try:
                batch = self._collect()
                if batch:
                    self._process(batch)
            except BaseException as e:  # noqa: BLE001 — worker must survive
                log_warning(f"serve batcher error: {type(e).__name__}: {e}")
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(
                            e if isinstance(e, LightGBMError)
                            else LightGBMError(f"serving failure: {e}"))
        log_debug("serve batcher worker exited")
