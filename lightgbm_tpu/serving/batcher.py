"""Dynamic micro-batching: coalesce requests into one device dispatch.

Clipper-style adaptive batching (Crankshaw et al., NSDI 2017): a bounded
queue feeds one worker thread that drains whatever arrived, keeps
pulling until ``serve_max_batch`` rows are gathered or the oldest
request's ``serve_max_delay_ms`` deadline expires, and runs ONE bucketed
device call for the coalesced matrix.  Per-request tails (averaging +
output transform) are applied to each request's row slice, so every
response is bitwise identical to predicting that request alone.

Three escape hatches keep tail latency honest:

  * **singleton fast path** — ``submit(..., fast=True)`` executes a
    one-row request synchronously on the caller thread through the
    pre-bound :class:`SingleRowFastPredictor` native walk (no queue wait,
    no device dispatch) — the latency-critical path of the reference's
    ``LGBM_BoosterPredictForMatSingleRowFast``;
  * **admission control** — a full queue rejects immediately with a
    structured :class:`OverloadError` (HTTP 503 + ``Retry-After``
    upstream) instead of buffering unboundedly; shedding at the door
    keeps the p99 of admitted requests bounded;
  * **deadline propagation** — ``submit(..., deadline=t)`` carries the
    client's remaining budget (an absolute ``time.perf_counter`` point):
    an already-expired request is shed at admission, and the worker
    re-checks right before dispatch so the device NEVER works on a
    request whose client has already given up (:class:`DeadlineError`,
    structured 503 upstream).
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..telemetry.tracer import _NULL_SPAN as _NULL_DISPATCH
from ..utils.log import LightGBMError, log_debug, log_warning
from .registry import ModelRegistry, ServingModel

# value-histogram bounds for batch-size / queue-depth distributions
DEPTH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class OverloadError(LightGBMError):
    """Load-shed rejection carrying the structured 503 payload.

    ``reason`` names WHY the request was shed ("queue_full",
    "draining", "deadline_expired", "no_ready_replicas", ...) and
    ``retry_after_s`` is the server's estimate of when retrying is
    worthwhile — surfaced upstream both in the JSON body and as the
    HTTP ``Retry-After`` header."""

    def __init__(self, queue_depth: int, queue_size: int,
                 reason: str = "queue_full",
                 retry_after_s: float = 1.0):
        self.queue_depth = int(queue_depth)
        self.queue_size = int(queue_size)
        self.reason = str(reason)
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"serving request shed ({self.reason}; queue "
            f"{self.queue_depth}/{self.queue_size}); retry with backoff")

    def payload(self) -> Dict[str, Any]:
        return {"error": "overload", "reason": self.reason,
                "queue_depth": self.queue_depth,
                "queue_size": self.queue_size,
                "retry_after_s": round(self.retry_after_s, 3)}


class DeadlineError(OverloadError):
    """The request's propagated deadline expired before (or while)
    queued — shed without touching the device."""

    def __init__(self, queue_depth: int, queue_size: int):
        super().__init__(queue_depth, queue_size,
                         reason="deadline_expired", retry_after_s=0.0)

    def payload(self) -> Dict[str, Any]:
        out = super().payload()
        out["error"] = "deadline_expired"
        return out


@dataclass
class PredictResult:
    """What a resolved request future carries."""
    values: np.ndarray       # converted (or raw) scores for this request
    model_version: int       # the version that actually scored it
    batched_rows: int        # total rows of the coalesced dispatch
    queue_wait_s: float      # enqueue -> dispatch latency
    model_id: str = ""       # multi-tenant routing key ("" single-model)
    sha256: str = ""         # exact bytes that scored this request


@dataclass
class _Request:
    rows: np.ndarray
    raw_score: bool
    model: Optional[ServingModel] = None  # pinned at submit: an eviction
    #                                       or hot-swap mid-flight drains
    #                                       on this old reference
    deadline: Optional[float] = None      # absolute time.perf_counter point
    trace: Any = None                     # telemetry.TraceContext or None
    future: Future = field(default_factory=Future)
    t_enqueue: float = field(default_factory=time.perf_counter)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now or time.perf_counter()) >= self.deadline)

    def resolve(self, result=None, error: Optional[BaseException] = None):
        """Set the future's outcome, tolerating a caller that already
        cancelled it (deadline handlers give up on queued requests)."""
        try:
            if error is not None:
                self.future.set_exception(error)
            else:
                self.future.set_result(result)
        except InvalidStateError:
            pass


class MicroBatcher:
    """Bounded queue + one coalescing worker thread over a registry."""

    def __init__(self, registry: ModelRegistry, *, max_batch: int = 256,
                 max_delay_ms: float = 2.0, queue_size: int = 512,
                 heartbeat_path: str = "", mode: str = "predict"):
        if mode not in ("predict", "explain"):
            raise LightGBMError(f"batcher mode {mode!r} must be predict "
                                "or explain")
        self.registry = registry
        self.mode = mode
        self.max_batch = max(int(max_batch), 1)
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1e3
        self.queue_size = max(int(queue_size), 1)
        self.heartbeat_path = str(heartbeat_path or "")
        # explain lane: SHAP dispatches pad to their OWN bucket ladder so
        # the device contribution kernel sees shape-stable batches
        from .compiled import bucket_ladder
        self._explain_buckets = (bucket_ladder(self.max_batch)
                                 if mode == "explain" else None)
        self._q: "queue.Queue[_Request]" = queue.Queue(self.queue_size)
        self._stop = threading.Event()
        # serializes enqueue against stop(): _stop is SET under this lock
        # and checked under it before every put, so no request can enter
        # the queue after the drain decision — the worker only exits once
        # _stop is set AND the queue is empty, so everything admitted
        # before the flag is guaranteed to be served
        self._submit_lock = threading.Lock()
        self._drain = True
        self._worker: Optional[threading.Thread] = None
        # optional QualityMonitor (set by ServingApp): drift accumulation
        # + shadow-audit capture on the dispatch path, both behind their
        # own sampling draws — None keeps the hot path untouched.  A
        # multi-tenant app sets quality_lookup (model_id -> monitor) so
        # each tenant accumulates into ITS OWN drift window.
        self.quality = None
        self.quality_lookup = None
        self.batches = 0
        self.served = 0
        self.rejected = 0
        self.expired = 0
        # EWMA of per-batch dispatch seconds, seeding the Retry-After
        # estimate before the first batch completes
        self._dispatch_ewma = self.max_delay_s + 0.005

    def retry_after_s(self) -> float:
        """How long a shed client should back off: the estimated time to
        drain the CURRENT queue (pending batches x recent dispatch time),
        clamped to a sane [0.05 s, 5 s] window."""
        batches_pending = max(self._q.qsize() / self.max_batch, 1.0)
        return min(max(batches_pending * self._dispatch_ewma, 0.05), 5.0)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._worker is None or not self._worker.is_alive():
            self._stop.clear()
            self._worker = threading.Thread(target=self._run,
                                            name="lgbtpu-serve-batcher",
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful stop: with ``drain`` the worker finishes everything
        already queued before exiting (SIGTERM semantics); without it,
        queued futures are cancelled."""
        self._drain = bool(drain)
        with self._submit_lock:
            self._stop.set()
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)
        if not drain:
            while True:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    break
                req.future.cancel()

    @property
    def worker_alive(self) -> bool:
        return self._worker is not None and self._worker.is_alive()

    def queue_depth(self) -> int:
        return self._q.qsize()

    # -- submission --------------------------------------------------------
    def submit(self, rows, raw_score: bool = False,
               fast: bool = False,
               deadline: Optional[float] = None,
               trace=None,
               model_id: Optional[str] = None) -> "Future[PredictResult]":
        """Enqueue one request; returns a Future resolving to
        :class:`PredictResult`.  Raises :class:`OverloadError` at once
        when the queue is full (or ``deadline`` — an absolute
        ``time.perf_counter`` point — has already passed),
        :class:`LightGBMError` on shape errors or an unknown
        ``model_id``.  The resolved model is PINNED into the request: a
        hot-swap or LRU eviction mid-flight drains on the old
        reference."""
        from .. import telemetry

        model = self.registry.current(model_id) if model_id \
            else self.registry.current()
        X = model.validate_rows(rows)
        if self._stop.is_set():
            raise OverloadError(self._q.qsize(), self.queue_size,
                                reason="draining",
                                retry_after_s=self.retry_after_s())
        if deadline is not None and time.perf_counter() >= deadline:
            # expired before admission: shed at the door, zero queue work
            with self._submit_lock:
                self.expired += 1
            telemetry.inc("serve/deadline_expired")
            raise DeadlineError(self._q.qsize(), self.queue_size)
        if fast and X.shape[0] == 1 and self.mode == "predict":
            # latency-critical singleton: pre-bound native walk, caller
            # thread, zero queueing — still version-stamped
            t0 = time.perf_counter()
            values = model.predict(X, raw_score=raw_score)
            telemetry.observe("serve/latency_s",
                              time.perf_counter() - t0)
            telemetry.inc("serve/requests_fast")
            # the fast path runs on the CALLER thread and races the worker
            # thread's batch-counter updates (lgbtlint LGB006)
            with self._submit_lock:
                self.served += 1
            fut: "Future[PredictResult]" = Future()
            fut.set_result(PredictResult(values, model.version, 1, 0.0,
                                         model.model_id, model.sha256))
            return fut
        req = _Request(np.ascontiguousarray(X), bool(raw_score),
                       model=model, deadline=deadline, trace=trace)
        with self._submit_lock:
            if self._stop.is_set():
                raise OverloadError(self._q.qsize(), self.queue_size,
                                    reason="draining",
                                    retry_after_s=self.retry_after_s())
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.rejected += 1
                telemetry.inc("serve/rejected")
                telemetry.inc("serve/shed")
                raise OverloadError(self._q.qsize(), self.queue_size,
                                    reason="queue_full",
                                    retry_after_s=self.retry_after_s())
        telemetry.observe("serve/queue_depth", float(self._q.qsize()),
                          bounds=DEPTH_BOUNDS)
        return req.future

    # -- worker ------------------------------------------------------------
    def _expire(self, req: _Request) -> bool:
        """Resolve an already-expired request with :class:`DeadlineError`
        (the client gave up; the device must not score it)."""
        from .. import telemetry

        if not req.expired():
            return False
        with self._submit_lock:
            self.expired += 1
        telemetry.inc("serve/deadline_expired")
        req.resolve(error=DeadlineError(self._q.qsize(), self.queue_size))
        return True

    def _collect(self) -> List[_Request]:
        """One coalescing round: block for the first request, then gather
        batch-mates until the row budget or the delay deadline.  Requests
        whose propagated deadline lapsed while queued are expired here
        instead of joining the batch."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        if self._expire(first):
            return []
        batch = [first]
        rows = first.rows.shape[0]
        deadline = time.perf_counter() + self.max_delay_s
        while rows < self.max_batch:
            left = deadline - time.perf_counter()
            try:
                nxt = (self._q.get_nowait() if left <= 0
                       else self._q.get(timeout=left))
            except queue.Empty:
                break
            if self._expire(nxt):
                continue
            batch.append(nxt)
            rows += nxt.rows.shape[0]
            if left <= 0:
                break
        return batch

    def _quality_for(self, model: ServingModel):
        if self.quality_lookup is not None:
            return self.quality_lookup(model.model_id)
        return self.quality

    def _dispatch(self, jobs) -> List[np.ndarray]:
        """Score every (model, rows) job of one window.  Predict mode
        routes multi-tenant windows through the registry's grouped
        (model-axis-stacked) dispatch when it has one; explain mode pads
        each job to the lane's own bucket ladder for the SHAP kernel."""
        if self.mode == "explain":
            outs = []
            for model, X in jobs:
                m = X.shape[0]
                b = next((b for b in self._explain_buckets if m <= b),
                         self._explain_buckets[-1])
                if m < b:
                    Xp = np.zeros((b, X.shape[1]), np.float64)
                    Xp[:m] = X
                else:
                    Xp = X
                outs.append(model.explain_raw(Xp)[:m])
            return outs
        if len(jobs) > 1:
            grouped = getattr(self.registry, "raw_scores_grouped", None)
            if grouped is not None:
                return grouped(jobs)
        return [model.raw_scores(X) for model, X in jobs]

    def _process(self, batch: List[_Request]) -> None:
        from .. import telemetry

        # final pre-dispatch deadline check: the coalescing window may
        # have outlived a tight budget — the device never scores a
        # request whose client already gave up
        batch = [r for r in batch if not self._expire(r)]
        if not batch:
            return
        # group by the model PINNED at submit time: a hot-swap or LRU
        # eviction mid-flight drains on the old reference, and a
        # multi-tenant window carries several models at once
        order: List[ServingModel] = []
        by_model: Dict[int, List[_Request]] = {}
        for r in batch:
            if r.model is None:     # legacy direct caller: pin per batch
                r.model = self.registry.current()
            if r.rows.shape[1] != r.model.num_features:
                # the model was hot-swapped to a different feature count
                # between submit-time validation and dispatch
                r.resolve(error=LightGBMError(
                    f"model v{r.model.version} expects "
                    f"{r.model.num_features} features, request has "
                    f"{r.rows.shape[1]}"))
                continue
            key = id(r.model)
            if key not in by_model:
                by_model[key] = []
                order.append(r.model)
            by_model[key].append(r)
        good = [r for m in order for r in by_model[id(m)]]
        if not good:
            return
        t0 = time.perf_counter()
        # distributed tracing: each head-sampled request gets its queue
        # wait as a cross-thread complete event, and the coalesced
        # device dispatch is one span carrying every sampled trace id
        sampled = [r.trace.trace_id for r in good
                   if r.trace is not None and r.trace.sampled]
        for r in good:
            telemetry.request_complete(
                r.trace, "serve/queue_wait", r.t_enqueue,
                t0 - r.t_enqueue, rows=int(r.rows.shape[0]))
        jobs = []
        for model in order:
            reqs = by_model[id(model)]
            jobs.append((model, reqs[0].rows if len(reqs) == 1
                         else np.concatenate([r.rows for r in reqs],
                                             axis=0)))
        n = sum(x.shape[0] for _, x in jobs)
        dispatch_span = (telemetry.span("serve/dispatch", rows=n,
                                        requests=len(good),
                                        models=len(jobs),
                                        trace_ids=sampled)
                         if sampled else _NULL_DISPATCH)
        with dispatch_span:
            if (self.mode == "predict" and n == 1 and len(good) == 1):
                # a lone singleton skips the device: native single-row walk
                # (raw_scores has the pre-bound n==1 path — this is the
                # model.predict code path with submit-time validation)
                raws = [jobs[0][0].raw_scores(jobs[0][1])]
            else:
                with (telemetry.span("serve/device", rows=n,
                                     trace_ids=sampled)
                      if sampled else _NULL_DISPATCH):
                    raws = self._dispatch(jobs)
            for (model, _), raw in zip(jobs, raws):
                off = 0
                for r in by_model[id(model)]:
                    m = r.rows.shape[0]
                    values = (raw[off:off + m] if self.mode == "explain"
                              else model.finish(raw[off:off + m],
                                                r.raw_score))
                    r.resolve(PredictResult(
                        values, model.version, n, t0 - r.t_enqueue,
                        model.model_id, model.sha256))
                    off += m
        if self.mode == "predict":
            for (model, Xm), raw in zip(jobs, raws):
                q = self._quality_for(model)
                if q is None:
                    continue
                # drift accumulation + shadow-audit capture; each call
                # does its own sampling draw, and neither may ever break
                # serving
                try:
                    off = 0
                    for r in by_model[id(model)]:
                        m = r.rows.shape[0]
                        q.offer_audit(model, r.rows, raw[off:off + m],
                                      r.raw_score,
                                      r.trace.trace_id
                                      if r.trace is not None else None)
                        off += m
                    q.observe_batch(model, Xm, raw)
                except Exception as e:   # noqa: BLE001
                    log_debug(f"serve quality hook failed: {e}")
        dt = time.perf_counter() - t0
        with self._submit_lock:
            self.batches += 1
            self.served += len(good)
            # EWMA feeds the Retry-After estimate for shed responses
            self._dispatch_ewma = 0.8 * self._dispatch_ewma + 0.2 * dt
        if self.mode == "explain":
            telemetry.inc("serve/explain/requests", len(good))
            telemetry.inc("serve/explain/rows", n)
            telemetry.inc("serve/explain/batches")
            telemetry.observe("serve/explain/dispatch_s", dt)
        else:
            telemetry.inc("serve/requests", len(good))
            telemetry.inc("serve/rows", n)
            telemetry.inc("serve/batches")
            telemetry.observe("serve/dispatch_s", dt)
        telemetry.observe("serve/batch_rows", float(n),
                          bounds=DEPTH_BOUNDS)
        for r in good:
            telemetry.observe("serve/latency_s",
                              time.perf_counter() - r.t_enqueue)
        if self.heartbeat_path:
            from ..robustness.heartbeat import write_heartbeat
            try:
                write_heartbeat(self.heartbeat_path, self.batches)
            except OSError as e:   # liveness file must never kill serving
                log_debug(f"serve heartbeat write failed: {e}")

    def _run(self) -> None:
        while True:
            if self._stop.is_set() and (not self._drain or self._q.empty()):
                break
            batch: List[_Request] = []
            try:
                batch = self._collect()
                if batch:
                    self._process(batch)
            except BaseException as e:  # noqa: BLE001 — worker must survive
                log_warning(f"serve batcher error: {type(e).__name__}: {e}")
                for r in batch:
                    if not r.future.done():
                        r.resolve(error=(
                            e if isinstance(e, LightGBMError)
                            else LightGBMError(f"serving failure: {e}")))
        log_debug("serve batcher worker exited")
