"""Shape-bucketed compiled predictor: one traced XLA program per bucket.

Serving traffic arrives in arbitrary batch sizes; tracing a fresh XLA
program per size would turn every odd-shaped request into a multi-second
compile stall (the exact failure mode the telemetry recompile watchdog
exists to catch).  Incoming batches are therefore padded to a fixed
ladder of row-count buckets (powers of two by default, capped at
``serve_max_batch``) so after one warmup pass every dispatch hits an
already-compiled program — Clipper-style (Crankshaw et al., NSDI 2017)
"compile once per shape, amortize forever".

Bit-identity with ``Booster.predict`` is non-negotiable for serving (a
hot-reload A/B must never change scores), but the device is float32 and
model thresholds are float64.  The walk therefore never compares floats
on device: each float64 value ``v`` is mapped on the host to a MONOTONE
64-bit integer key (sign-flip trick: ``bits ^ (bits < 0 ? ~0 : 1<<63)``,
with -0.0 normalized to +0.0) carried as two uint32 lanes, and ``v <=
threshold`` becomes an exact lexicographic integer compare.

Score accumulation ALSO runs on device: the per-tree leaf-value table
rides into the program as a float64 argument (under a scoped
``jax.experimental.enable_x64``), and one sequential ``fori_loop``
replays the host batch loop's exact tree order — per row, the same
IEEE-754 float64 adds in the same order — so the returned scores are
bitwise equal to ``Booster.predict`` without the host ever touching a
per-tree Python loop (the pre-PR-13 hot path burned ~40% of serving CPU
there).  Backends without real float64 (probed once at import of the
first predictor; ``LGBTPU_SERVE_ACCUM=host`` forces it) keep the old
host-side float64 accumulation over device leaf indices — same bits,
more host work.  The only models the device path refuses entirely are
linear trees (raw-feature float64 dot products per leaf).

Missing handling mirrors tree.py ``predict_raw`` exactly: NaN rows carry
a host-computed mask; the ``zero_as_missing`` band ``|v| < 1e-35`` is an
exact key-range test; categorical values use a host-truncated int32 and
the model's category bitset words.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import LightGBMError, log_info

# monotone keys of +/-1e-35 — the reference's kZeroThreshold band used by
# zero-as-missing routing (tree.py predict_raw: np.abs(v) < 1e-35)
def _key64(v: np.ndarray) -> np.ndarray:
    """Float64 -> monotone uint64 key; total order matches <= on reals
    (±0 collapse to +0 first so the two zeros compare equal)."""
    v = np.ascontiguousarray(np.where(v == 0.0, 0.0, v), np.float64)
    b = v.view(np.uint64)
    return np.where(b >> np.uint64(63), ~b, b | np.uint64(1 << 63))


def _split_key(key: np.ndarray):
    return ((key >> np.uint64(32)).astype(np.uint32),
            (key & np.uint64(0xFFFFFFFF)).astype(np.uint32))


_ZLO = _split_key(_key64(np.asarray([-1e-35])))   # ([hi], [lo]) of -1e-35
_ZHI = _split_key(_key64(np.asarray([1e-35])))
_ZLO = (int(_ZLO[0][0]), int(_ZLO[1][0]))
_ZHI = (int(_ZHI[0][0]), int(_ZHI[1][0]))


class PackedServingTrees(NamedTuple):
    """Model arrays rectangularized to (T, M) for the jitted walk; passed
    as traced ARGUMENTS (not closure constants) so a hot-reloaded model of
    the same shape reuses the compiled program."""
    split_feature: object   # (T, M) i32
    thr_hi: object          # (T, M) u32 — monotone key lanes of threshold
    thr_lo: object          # (T, M) u32
    decision_type: object   # (T, M) i32 — LightGBM bits (cat/dleft/missing)
    left_child: object      # (T, M) i32
    right_child: object     # (T, M) i32
    cat_ord: object         # (T, M) i32 — row into cat_words, -1 numeric
    cat_words: object       # (C, W) u32 — per-cat-node bitset words


def _x64_scope():
    """Scoped float64 (the repo-wide pattern: models/gbdt.py _x64_scope) —
    the global x64 flag stays off; serving traces/dispatches its scored
    programs inside the scope so the f64 leaf table and accumulator are
    real IEEE doubles on capable backends."""
    import jax
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:   # moved under jax.experimental in recent releases
        from jax.experimental import enable_x64 as ctx
    return ctx()


_DEVICE_F64: Optional[bool] = None


def device_accumulation_supported() -> bool:
    """Can this backend hold float64 arrays and add them with IEEE-754
    semantics?  Probed ONCE: a pair whose low word vanishes under any
    f32 emulation (1.0 + 1e-16 == 1.0 in f32) must survive bitwise.
    ``LGBTPU_SERVE_ACCUM=host`` forces the host-accumulation fallback;
    ``=device`` raises if the probe fails (no silent downgrade)."""
    global _DEVICE_F64
    mode = os.environ.get("LGBTPU_SERVE_ACCUM", "auto").strip().lower()
    if mode not in ("auto", "device", "host"):
        raise LightGBMError(
            f"LGBTPU_SERVE_ACCUM={mode!r} must be auto, device, or host")
    if mode == "host":
        return False
    if _DEVICE_F64 is None:
        try:
            import jax.numpy as jnp
            want = np.float64(1.0) + np.float64(1e-16)
            with _x64_scope():
                a = jnp.asarray(np.asarray([1.0, 1e-16], np.float64))
                ok = a.dtype == jnp.float64
                if ok:
                    # eager device add (no bare jit): any f32 emulation
                    # loses the 1e-16 and fails the bit compare
                    got = np.asarray(a[0] + a[1])
                    ok = (got.dtype == np.float64
                          and got.view(np.uint64) ==
                          np.float64(want).view(np.uint64))
            _DEVICE_F64 = bool(ok)
        except Exception as e:  # noqa: BLE001 — probe must never kill serving
            log_info(f"serving: device float64 probe failed ({e}); "
                     "leaf accumulation stays on the host")
            _DEVICE_F64 = False
    if mode == "device" and not _DEVICE_F64:
        raise LightGBMError(
            "LGBTPU_SERVE_ACCUM=device but this backend has no IEEE "
            "float64 — unset it to fall back to host accumulation")
    return _DEVICE_F64


def _walk_impl(pack: PackedServingTrees, keys_hi, keys_lo, nan_mask, iv,
               max_depth: int):
    """(T, n) leaf index per tree per row — integer ops only."""
    import jax
    import jax.numpy as jnp

    n = keys_hi.shape[0]
    W = pack.cat_words.shape[1]
    rows = jnp.arange(n)

    def lex_le(ahi, alo, bhi, blo):
        return (ahi < bhi) | ((ahi == bhi) & (alo <= blo))

    def lex_lt(ahi, alo, bhi, blo):
        return (ahi < bhi) | ((ahi == bhi) & (alo < blo))

    zlo_hi = jnp.uint32(_ZLO[0])
    zlo_lo = jnp.uint32(_ZLO[1])
    zhi_hi = jnp.uint32(_ZHI[0])
    zhi_lo = jnp.uint32(_ZHI[1])

    def one_tree(tf):
        sf, thi, tlo, dt, lc, rc, co = tf

        def step(_, node):
            active = node >= 0
            ni = jnp.maximum(node, 0)
            f = sf[ni]
            khi = keys_hi[rows, f]
            klo = keys_lo[rows, f]
            isn = nan_mask[rows, f]
            cv = iv[rows, f]
            d = dt[ni]
            is_cat = (d & 1) != 0
            def_left = (d & 2) != 0
            zero_missing = ((d >> 2) & 3) == 1
            le = lex_le(khi, klo, thi[ni], tlo[ni])
            near_zero = (lex_lt(zlo_hi, zlo_lo, khi, klo)
                         & lex_lt(khi, klo, zhi_hi, zhi_lo))
            miss = isn | (zero_missing & near_zero)
            word = cv >> 5
            row_ix = co[ni]
            cvalid = (cv >= 0) & (word < W) & (row_ix >= 0)
            w = pack.cat_words[jnp.maximum(row_ix, 0),
                               jnp.clip(word, 0, W - 1)]
            bit = (w >> (cv & 31).astype(jnp.uint32)) & jnp.uint32(1)
            gl_cat = cvalid & (bit == 1)
            go_left = jnp.where(is_cat, gl_cat,
                                jnp.where(miss, def_left, le))
            nxt = jnp.where(go_left, lc[ni], rc[ni])
            return jnp.where(active, nxt, node)

        node = jax.lax.fori_loop(0, max_depth, step, jnp.zeros(n, jnp.int32))
        # trivial/padded trees loop on node 0 forever: resolve to leaf 0,
        # matching the host path's single-leaf output (tree.py:113)
        return jnp.where(node < 0, ~node, 0)

    return jax.lax.map(one_tree, tuple(pack[:7]))


def _score_impl(pack: PackedServingTrees, leaf_values, keys_hi, keys_lo,
                nan_mask, iv, max_depth: int, num_class: int):
    """Walk + on-device float64 accumulation in the host loop's exact
    tree order (traced under enable_x64; bitwise == Booster.predict).

    ``leaf_values`` is (T, L) float64.  num_class == 1: one fori_loop
    ``score += lv[t][leaf[t]]`` — per element the identical IEEE add
    sequence as the host ``for t: score += lv[leaves[t]]`` loop.
    num_class > 1: trees iterate round-major (tree i feeds column i % k),
    so looping rounds r and adding the (k, n) gather keeps every COLUMN's
    adds in ascending tree order — again the host loop's order."""
    import jax
    import jax.numpy as jnp

    leaves = _walk_impl(pack, keys_hi, keys_lo, nan_mask, iv, max_depth)
    n = keys_hi.shape[0]
    T = leaf_values.shape[0]
    if num_class == 1:
        def body(t, s):
            return s + leaf_values[t][leaves[t]]
        return jax.lax.fori_loop(0, T, body, jnp.zeros(n, jnp.float64))
    k = num_class
    lv3 = leaf_values.reshape(T // k, k, leaf_values.shape[1])
    lf3 = leaves.reshape(T // k, k, n)

    def body(r, s):
        return s + jnp.take_along_axis(lv3[r], lf3[r], axis=1).T

    return jax.lax.fori_loop(0, T // k, body,
                             jnp.zeros((n, k), jnp.float64))


def _score_multi_impl(pack: PackedServingTrees, leaf_values, keys_hi,
                      keys_lo, nan_mask, iv, max_depth: int, num_class: int):
    """Model-axis-stacked scoring: every argument carries a leading model
    axis G and slot ``g`` is scored with slot ``g``'s pack — a vmap of
    ``_score_impl``, so per slot the walk and the float64 accumulation
    are the IDENTICAL element-wise IEEE-754 op sequence as the
    single-model program (bitwise equal to each member's own
    ``Booster.predict``).  One dispatch serves a whole multi-tenant
    micro-batch window with zero cross-model launches."""
    import jax

    def one(p, lv, kh, kl, nm, i):
        return _score_impl(PackedServingTrees(*p), lv, kh, kl, nm, i,
                           max_depth, num_class)

    return jax.vmap(one)(tuple(pack), leaf_values, keys_hi, keys_lo,
                         nan_mask, iv)


_serve_walk = None    # lazily-built watched_jits (import must stay jax-free)
_serve_score = None
_serve_score_multi = None


def _get_walk():
    global _serve_walk
    if _serve_walk is None:
        from ..telemetry import watched_jit
        # leaf-index-only program: the host-accumulation fallback and the
        # leaves() introspection surface (buckets legitimately
        # re-specialize per ladder shape: count, never warn)
        _serve_walk = watched_jit(_walk_impl, name="serve_leaves",
                                  warn_after=0,
                                  static_argnames=("max_depth",))
    return _serve_walk


def _get_score():
    global _serve_score
    if _serve_score is None:
        from ..telemetry import watched_jit
        # the serving hot path: walk + f64 accumulation in ONE program.
        # Keeps the historical entry name — every zero-recompiles gate
        # (tests, BENCH_SERVE, /stats) keys off "serve_predict"
        _serve_score = watched_jit(_score_impl, name="serve_predict",
                                   warn_after=0,
                                   static_argnames=("max_depth",
                                                    "num_class"))
    return _serve_score


def _get_score_multi():
    global _serve_score_multi
    if _serve_score_multi is None:
        from ..telemetry import watched_jit
        # the multi-tenant hot path: same program vmapped over a model
        # axis; model-count/bucket ladders legitimately re-specialize
        _serve_score_multi = watched_jit(_score_multi_impl,
                                         name="serve_predict_multi",
                                         warn_after=0,
                                         static_argnames=("max_depth",
                                                          "num_class"))
    return _serve_score_multi


def bucket_ladder(max_batch: int, spec: str = "",
                  floor: int = 8) -> List[int]:
    """Row-count buckets, ascending.  Default: powers of two from
    ``floor`` up to (and including) the next power >= max_batch; an
    explicit comma ``spec`` overrides the whole ladder."""
    if spec and str(spec).strip():
        try:
            out = sorted({int(tok) for tok in str(spec).split(",")
                          if str(tok).strip()})
        except ValueError:
            raise LightGBMError(f"serve_buckets={spec!r} must be a "
                                "comma-separated list of integers")
        if not out or out[0] < 1:
            raise LightGBMError(f"serve_buckets={spec!r} must list "
                                "positive row counts")
        return out
    cap = max(int(max_batch), floor)
    out, b = [], floor
    while b < cap:
        out.append(b)
        b *= 2
    out.append(b)   # first power of two >= cap
    return out


class CompiledPredictor:
    """Pre-packed model + bucket ladder; every call pads to a bucket and
    dispatches one already-traced program that returns FINISHED float64
    raw scores (device accumulation), or leaf indices on f64-less
    backends (host accumulation fallback)."""

    def __init__(self, trees: Sequence, num_class: int, num_features: int,
                 max_batch: int = 256, buckets: Optional[Sequence[int]] = None,
                 envelope: Optional[Tuple[int, int, int, int]] = None):
        for t in trees:
            if getattr(t, "is_linear", False):
                # linear leaves need raw-feature dot products in float64 —
                # host path (registry falls back to Booster.predict)
                raise LightGBMError(
                    "linear trees are not supported by the compiled "
                    "serving predictor")
        self.num_class = int(num_class)
        self.num_features = int(num_features)
        self.buckets = (sorted(int(b) for b in buckets) if buckets
                        else bucket_ladder(max_batch))
        self._leaf_values = [np.asarray(t.leaf_value, np.float64)
                             for t in trees]
        nt = len(trees)
        # envelope = (leaves-1, cat rows, cat words, depth) MINIMUMS: pad
        # the pack out to a shared rounded shape (shape_envelope) so
        # same-family models of a multi-tenant cache land on identical
        # traced shapes and reuse ONE compiled serve_predict program.
        # Padding only widens never-visited node/bitset slots and no-op
        # walk iterations (a settled leaf is inactive), so scores are
        # bit-identical to the unpadded pack.
        env_m, env_c, env_w, env_d = (int(x) for x in envelope) \
            if envelope is not None else (0, 0, 0, 0)
        M = max(max((t.num_leaves - 1 for t in trees), default=0), 1, env_m)

        sf = np.zeros((nt, M), np.int32)
        thr = np.zeros((nt, M), np.float64)
        dt = np.zeros((nt, M), np.int32)
        lc = np.zeros((nt, M), np.int32)
        rc = np.zeros((nt, M), np.int32)
        co = np.full((nt, M), -1, np.int32)
        cat_rows: List[np.ndarray] = []
        from ..pallas.predict_kernel import tree_max_depth
        maxd = 1
        for ti, t in enumerate(trees):
            ni = max(t.num_leaves - 1, 0)
            if ni == 0:
                continue
            maxd = max(maxd, tree_max_depth(t))
            sf[ti, :ni] = np.asarray(t.split_feature[:ni], np.int32)
            thr[ti, :ni] = np.asarray(t.threshold[:ni], np.float64)
            d = np.asarray(t.decision_type[:ni], np.uint8).astype(np.int32)
            dt[ti, :ni] = d
            lc[ti, :ni] = np.asarray(t.left_child[:ni], np.int32)
            rc[ti, :ni] = np.asarray(t.right_child[:ni], np.int32)
            for i in np.nonzero(d & 1)[0]:
                k = int(t.threshold_bin[i])
                s, e = int(t.cat_boundaries[k]), int(t.cat_boundaries[k + 1])
                co[ti, i] = len(cat_rows)
                cat_rows.append(np.asarray(t.cat_threshold[s:e], np.uint32))
        self.max_depth = max(int(maxd), env_d)
        W = max([1, env_w] + [len(r) for r in cat_rows])
        cw = np.zeros((max(len(cat_rows), 1, env_c), W), np.uint32)
        for ri, r in enumerate(cat_rows):
            cw[ri, :len(r)] = r

        import jax.numpy as jnp
        thi, tlo = _split_key(_key64(thr))
        # host copies kept only in envelope (multi-tenant) mode — the
        # stacked serve_predict_multi dispatch stacks them per call
        self._host_pack = (sf, thi, tlo, dt, lc, rc, co, cw) \
            if envelope is not None else None
        self._host_lv = None
        self._pack = PackedServingTrees(
            split_feature=jnp.asarray(sf), thr_hi=jnp.asarray(thi),
            thr_lo=jnp.asarray(tlo), decision_type=jnp.asarray(dt),
            left_child=jnp.asarray(lc), right_child=jnp.asarray(rc),
            cat_ord=jnp.asarray(co), cat_words=jnp.asarray(cw))
        # (T, L) float64 leaf-value table for the on-device accumulation;
        # created under the x64 scope so the device array is real f64
        self.device_accum = (device_accumulation_supported()
                             and (self.num_class == 1
                                  or nt % self.num_class == 0))
        self._lv_dev = None
        if self.device_accum:
            lvt = np.zeros((max(nt, 1), M + 1), np.float64)
            for ti, t in enumerate(trees):
                nlv = min(t.num_leaves, M + 1)
                lvt[ti, :nlv] = np.asarray(t.leaf_value[:nlv], np.float64)
            if envelope is not None:
                self._host_lv = lvt
            with _x64_scope():
                self._lv_dev = jnp.asarray(lvt)
        # pinned per-bucket pad buffers: one (bucket, F) set per bucket,
        # filled in place per chunk — the hot path never np.pad-allocates.
        # One dispatch at a time per predictor (the micro-batcher's single
        # worker is the expected caller; direct concurrent callers
        # serialize on this lock rather than corrupt each other's pads)
        self._buf_lock = threading.Lock()
        self._pads: Dict[int, Tuple[np.ndarray, ...]] = {}

    @property
    def shape_signature(self) -> Tuple:
        """Everything a traced serve_predict program specializes on:
        models with equal signatures share compiled programs (and may be
        dispatched together by ``raw_scores_stacked``)."""
        T, M = self._pack.split_feature.shape
        C, W = self._pack.cat_words.shape
        return (int(T), int(M), int(C), int(W), self.max_depth,
                self.num_class, self.num_features, bool(self.device_accum),
                tuple(self.buckets))

    def device_bytes(self) -> int:
        """Bytes of device residency this model pins (pack + f64 leaf
        table) — the multi-tenant cache's HBM accounting unit."""
        n = 0
        for a in self._pack:
            n += int(np.prod(a.shape)) * int(np.dtype(a.dtype).itemsize)
        if self._lv_dev is not None:
            n += int(np.prod(self._lv_dev.shape)) * 8
        return n

    # -- host-side row encoding -------------------------------------------
    def _encode(self, X: np.ndarray):
        X = np.ascontiguousarray(X, np.float64)
        nan = np.isnan(X)
        khi, klo = _split_key(_key64(X))
        # categorical int: truncate-toward-zero like the host walk's
        # astype(int64); NaN -> -1 (routes right), huge values clamp into
        # the always-invalid range beyond any bitset
        iv = np.where(nan, -1.0, X)
        iv = np.clip(iv, -1.0, float(2 ** 31 - 1)).astype(np.int64)
        return khi, klo, nan, iv.astype(np.int32)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _pad_buffers(self, bucket: int) -> Tuple[np.ndarray, ...]:
        """The pinned (bucket, F) khi/klo/nan/iv pad set (caller holds
        ``_buf_lock``).  Pad rows keep whatever the previous chunk left —
        their walk output is sliced away, so stale contents are unread."""
        bufs = self._pads.get(bucket)
        if bufs is None:
            F = self.num_features
            bufs = (np.zeros((bucket, F), np.uint32),
                    np.zeros((bucket, F), np.uint32),
                    np.zeros((bucket, F), bool),
                    np.zeros((bucket, F), np.int32))
            self._pads[bucket] = bufs
        return bufs

    def _fill(self, bucket: int, khi, klo, nan, iv, s: int, m: int):
        bufs = self._pad_buffers(bucket)
        for buf, src in zip(bufs, (khi, klo, nan, iv)):
            buf[:m] = src[s:s + m]
        return bufs

    def leaves(self, X: np.ndarray) -> np.ndarray:
        """(T, n) leaf indices; internally chunks to the largest bucket
        and pads each chunk, so any n works without a fresh trace.
        Introspection / host-accumulation surface — the serving hot path
        is :meth:`raw_scores`."""
        import jax.numpy as jnp
        n = X.shape[0]
        khi, klo, nan, iv = self._encode(X)
        cap = self.buckets[-1]
        walk = _get_walk()
        outs = []
        with self._buf_lock:
            for s in range(0, n, cap) if n else []:
                m = min(cap, n - s)
                b = self.bucket_for(m)
                bufs = self._fill(b, khi, klo, nan, iv, s, m)
                out = walk(self._pack, jnp.asarray(bufs[0]),
                           jnp.asarray(bufs[1]), jnp.asarray(bufs[2]),
                           jnp.asarray(bufs[3]), max_depth=self.max_depth)
                outs.append(np.asarray(out)[:, :m])
        if not outs:
            return np.zeros((len(self._leaf_values), 0), np.int32)
        return np.concatenate(outs, axis=1)

    def raw_scores(self, X: np.ndarray) -> np.ndarray:
        """Pre-average raw scores, (n,) or (n, K) float64 — bitwise
        identical to the ``Booster.predict`` host loop.  Device path:
        walk + float64 leaf accumulation inside one compiled program per
        bucket.  Fallback (f64-less backend / LGBTPU_SERVE_ACCUM=host):
        device walk to leaf indices, host float64 loop in tree order."""
        n = X.shape[0]
        k = self.num_class
        if self._lv_dev is None:
            return self._raw_scores_host(X)
        import jax.numpy as jnp
        khi, klo, nan, iv = self._encode(X)
        cap = self.buckets[-1]
        score = _get_score()
        outs = []
        with self._buf_lock, _x64_scope():
            for s in range(0, n, cap) if n else []:
                m = min(cap, n - s)
                b = self.bucket_for(m)
                bufs = self._fill(b, khi, klo, nan, iv, s, m)
                out = score(self._pack, self._lv_dev, jnp.asarray(bufs[0]),
                            jnp.asarray(bufs[1]), jnp.asarray(bufs[2]),
                            jnp.asarray(bufs[3]), max_depth=self.max_depth,
                            num_class=k)
                outs.append(np.asarray(out)[:m])
        if not outs:
            return np.zeros((0,) if k == 1 else (0, k), np.float64)
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def _raw_scores_host(self, X: np.ndarray) -> np.ndarray:
        """Host float64 accumulation over device leaf indices, in the
        exact order of the Booster.predict host loop."""
        n = X.shape[0]
        k = self.num_class
        leaves = self.leaves(X)
        if k == 1:
            score = np.zeros(n, np.float64)
            for i, lv in enumerate(self._leaf_values):
                score += lv[leaves[i]]
            return score
        score = np.zeros((n, k), np.float64)
        for i, lv in enumerate(self._leaf_values):
            score[:, i % k] += lv[leaves[i]]
        return score

    def warmup(self) -> int:
        """Trace every bucket once (called by the registry BEFORE the
        version swap, so live traffic never pays a compile). Returns the
        number of buckets primed."""
        for b in self.buckets:
            self.raw_scores(np.zeros((b, self.num_features), np.float64))
        return len(self.buckets)


def shape_envelope(trees: Sequence) -> Tuple[int, int, int, int]:
    """Deterministic rounded-up pack minimums (leaves-1, cat rows, cat
    words, depth) for :class:`CompiledPredictor`'s ``envelope`` argument.
    Same-family models (same feature count / class count / tree count /
    similar size) round to the SAME envelope without any cross-model
    coordination, so every member of a multi-tenant cache group shares
    one compiled program per bucket — zero cross-model recompile churn."""
    from ..pallas.predict_kernel import tree_max_depth
    m = c = w = 0
    d = 1
    for t in trees:
        ni = max(t.num_leaves - 1, 0)
        m = max(m, ni)
        if ni == 0:
            continue
        d = max(d, tree_max_depth(t))
        dts = np.asarray(t.decision_type[:ni], np.uint8)
        for i in np.nonzero(dts & 1)[0]:
            k = int(t.threshold_bin[i])
            c += 1
            w = max(w, int(t.cat_boundaries[k + 1])
                    - int(t.cat_boundaries[k]))

    def up(v: int, step: int) -> int:
        return max(step, ((int(v) + step - 1) // step) * step)

    return (up(m, 16), up(c, 8), up(w, 4), up(d, 4))


def raw_scores_stacked(preds: Sequence["CompiledPredictor"],
                       X_list: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Score several SAME-SHAPE models in ONE ``serve_predict_multi``
    dispatch: member ``g``'s pack and rows ride slot ``g`` of a
    model-axis stack (models padded to a power-of-two slot count, rows
    padded to a shared bucket).  Returns per-member float64 raw scores,
    bitwise equal to each member's own :meth:`raw_scores`.  Requires
    every member built with the same ``envelope`` (identical
    ``shape_signature``) and device accumulation."""
    if len(preds) != len(X_list) or not preds:
        raise LightGBMError("raw_scores_stacked: one row block per model")
    lead = preds[0]
    sig = lead.shape_signature
    for p in preds[1:]:
        if p.shape_signature != sig:
            raise LightGBMError("stacked dispatch requires identical "
                                "pack shapes (same envelope group)")
    if lead._lv_dev is None or any(p._host_pack is None for p in preds):
        raise LightGBMError("stacked dispatch requires device "
                            "accumulation and envelope packing")
    rows = [np.ascontiguousarray(x, np.float64) for x in X_list]
    m_max = max(x.shape[0] for x in rows)
    if m_max > lead.buckets[-1]:
        raise LightGBMError("stacked dispatch rows exceed the bucket "
                            "ladder; use per-model raw_scores")
    b = lead.bucket_for(max(m_max, 1))
    g_pad = 1
    while g_pad < len(preds):
        g_pad *= 2
    F = lead.num_features
    khi = np.zeros((g_pad, b, F), np.uint32)
    klo = np.zeros((g_pad, b, F), np.uint32)
    nan = np.zeros((g_pad, b, F), bool)
    iv = np.zeros((g_pad, b, F), np.int32)
    for g, (p, x) in enumerate(zip(preds, rows)):
        if x.shape[0] == 0:
            continue
        h, lo, nm, i32 = p._encode(x)
        m = x.shape[0]
        khi[g, :m], klo[g, :m], nan[g, :m], iv[g, :m] = h, lo, nm, i32
    # pad slots replicate member 0's pack (their rows are zeros whose
    # walk output is sliced away)
    order = list(range(len(preds))) + [0] * (g_pad - len(preds))
    import jax.numpy as jnp
    stacked = [np.stack([preds[i]._host_pack[j] for i in order])
               for j in range(8)]
    lv = np.stack([preds[i]._host_lv for i in order])
    score = _get_score_multi()
    k = lead.num_class
    with _x64_scope():
        pack = PackedServingTrees(*(jnp.asarray(a) for a in stacked))
        out = np.asarray(score(
            pack, jnp.asarray(lv), jnp.asarray(khi), jnp.asarray(klo),
            jnp.asarray(nan), jnp.asarray(iv),
            max_depth=lead.max_depth, num_class=k))
    return [out[g, :x.shape[0]] for g, x in enumerate(rows)]
