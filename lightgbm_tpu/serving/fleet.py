"""Serving fleet: replica pool supervisor with fleet-wide promotion.

PR 4's server is one Python process — one crash, hang, or hot-reload
hiccup takes 100% of traffic down.  This module turns it into a FLEET
(docs/SERVING.md "Fleet architecture"):

  * **replica pool** — N single-replica :class:`ServingApp` processes,
    each importing jax on its own, so a wedged XLA dispatch or a killed
    interpreter costs 1/N of capacity, not all of it.  Where the kernel
    supports ``SO_REUSEPORT`` the replicas can share one listen port
    (kernel load-balancing, ``serve_fleet_mode=reuseport``); everywhere
    else — and whenever retry/breaker routing is wanted — the tiny
    fanout front (:mod:`.front`) is the client-facing port
    (``serve_fleet_mode=front``, the default);
  * **liveness + restart** — every replica heartbeats a per-rank file
    (the existing :mod:`..robustness.heartbeat` machinery) every
    ``_BEAT_S``; the supervisor polls process exits AND heartbeat ages,
    SIGKILLs replicas wedged past ``hang_timeout_s``, and restarts dead
    ones with jittered exponential backoff (doubling per consecutive
    restart, decaying after a healthy period);
  * **fleet-wide promotion** — a shared registry directory holds a
    ``promote.json`` pointer (generation, model path, sha256).  Any
    ``/reload`` — on the front or on any replica — VALIDATES the
    candidate first (manifest sha256, truncation parse, finite trees),
    then atomically replaces the pointer; every replica's watcher thread
    re-validates (pointer sha256 + the full registry checks) before its
    own atomic swap.  A replica that fails validation keeps serving its
    old version and reports itself degraded via ``/ready``; the fleet
    never half-applies a poisoned candidate.

The supervisor owns only the replica processes and the state directory —
request routing, deadlines, retries and circuit breaking live in
:mod:`.front`.

State directory layout (``serve_fleet_dir``; a private tmpdir when
unset)::

    promote.json       {"generation", "path", "sha256", "promoted_unix"}
    promote_<id>.json  per-tenant pointer of a multi-tenant fleet —
                       promotion is keyed (model_id, generation); one
                       tenant's pointer advances without its siblings
                       reloading anything (docs/SERVING.md "Multi-tenant
                       serving")
    replica_<r>.json   {"rank", "host", "port", "pid", "started_unix"}
    hb_<r>             heartbeat file (mtime = liveness)
    replica_<r>.log    stdout/stderr of the replica process
"""
from __future__ import annotations

import json
import os
import random
import re
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from ..robustness.checkpoint import atomic_write_text
from ..robustness.heartbeat import heartbeat_age, write_heartbeat
from ..utils.log import LightGBMError, log_debug, log_info, log_warning

PROMOTE_NAME = "promote.json"
_MID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def pointer_name(model_id: str = "") -> str:
    """Pointer file for one tenant: the flat ``promote.json`` when
    ``model_id`` is empty (single-model fleets; also the boot gate of a
    multi-model fleet), ``promote_<id>.json`` per tenant otherwise —
    promotion is keyed by ``(model_id, generation)`` so one tenant's
    pointer advances without its siblings ever re-validating, reloading,
    or recompiling anything."""
    if not model_id:
        return PROMOTE_NAME
    if not _MID_RE.match(model_id):
        raise LightGBMError(
            f"model_id {model_id!r} is not a valid tenant id "
            "(1-64 chars of [A-Za-z0-9._-])")
    return f"promote_{model_id}.json"
_BEAT_S = 0.25           # replica heartbeat-loop period (chaos beat unit)
_SUPERVISE_S = 0.2       # supervisor poll period
_RESTART_CAP_S = 30.0    # backoff ceiling
_HEALTHY_DECAY_S = 60.0  # a replica alive this long forgets its restarts


# ---------------------------------------------------------------------------
# candidate validation + the shared promotion pointer
# ---------------------------------------------------------------------------

def validate_candidate(path: str) -> str:
    """The promotion pre-flight every promoter runs BEFORE touching the
    pointer: manifest sha256 (when a sidecar exists), truncation/
    corruption parse, finite-tree guard.  Returns the candidate's sha256.

    Replicas re-run the same checks (plus a sha match against the
    pointer) before their own swap — promotion is validated twice by
    design: once so a garbage file never enters the pointer, once so a
    file that changed on disk between pointer write and replica read is
    rejected per-replica instead of served."""
    from ..model_io import load_model_string
    from ..robustness.guards import check_model_trees
    from .registry import _check_manifest

    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError as e:
        raise LightGBMError(f"cannot read serving candidate {path!r}: {e}")
    sha = _check_manifest(str(path), data)
    try:
        loaded = load_model_string(data.decode("utf-8"))
    except UnicodeDecodeError as e:
        raise LightGBMError(f"serving candidate {path!r} is not a text "
                            f"model file: {e}")
    check_model_trees(loaded.trees, what=f"serving candidate {path!r}")
    return sha


def read_pointer(fleet_dir: str,
                 model_id: str = "") -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(fleet_dir, pointer_name(model_id))) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


HISTORY_NAME = "generations.jsonl"


def generation_history(fleet_dir: str,
                       model_id: Optional[str] = None
                       ) -> List[Dict[str, Any]]:
    """Append-only promotion audit trail (one JSON line per pointer
    write, every tenant interleaved in promotion order).  Survives a
    torn/corrupt pointer file: the next promoter recovers the generation
    counter from here instead of resetting to 1 (which the monotonicity
    guard would then refuse fleet-wide).  ``model_id=None`` returns the
    full interleaved trail; ``""`` filters to the flat (single-model)
    pointer's entries, a tenant id to that tenant's."""
    out: List[Dict[str, Any]] = []
    try:
        with open(os.path.join(fleet_dir, HISTORY_NAME)) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue   # torn final line of a killed writer
                if model_id is None \
                        or str(rec.get("model_id", "")) == model_id:
                    out.append(rec)
    except OSError:
        pass
    return out


def write_pointer(fleet_dir: str, path: str, sha: str, generation: int,
                  prev: Optional[Dict[str, Any]] = None,
                  rollback_from: Optional[int] = None,
                  model_id: str = "") -> Dict[str, Any]:
    """Atomically replace the promotion pointer (tmp + ``os.replace``:
    a replica's watcher never reads a half-written pointer).  ``prev``
    records the generation being replaced (the rollback target);
    ``rollback_from`` marks an intentional downgrade so replicas accept
    the backwards generation; ``model_id`` selects a tenant's pointer
    file (generation counters are per-tenant)."""
    pointer: Dict[str, Any] = {
        "generation": int(generation), "path": str(path),
        "sha256": sha, "promoted_unix": time.time()}
    if model_id:
        pointer["model_id"] = str(model_id)
    if prev:
        pointer["prev"] = {"generation": int(prev["generation"]),
                           "path": str(prev["path"]),
                           "sha256": prev["sha256"]}
    if rollback_from is not None:
        pointer["rollback_from"] = int(rollback_from)
    # history first, pointer second: a writer killed in between leaves a
    # history entry with no pointer — harmless — while the reverse order
    # could leave a served generation with no audit trail
    try:
        with open(os.path.join(fleet_dir, HISTORY_NAME), "a") as fh:
            fh.write(json.dumps(pointer) + "\n")
    except OSError as e:
        log_warning(f"fleet: generation history append failed: {e}")
    from ..robustness import chaos
    text = json.dumps(pointer)
    if chaos.maybe_tear_pointer(fleet_dir, text,
                                name=pointer_name(model_id)):
        return pointer
    atomic_write_text(os.path.join(fleet_dir, pointer_name(model_id)),
                      text)
    return pointer


def _current_generation(fleet_dir: str, model_id: str = "") -> int:
    """Last written generation of one tenant's pointer (the flat pointer
    when ``model_id`` is empty): the pointer file, or (torn/missing
    pointer) that tenant's newest history entry."""
    cur = read_pointer(fleet_dir, model_id)
    if cur is not None:
        return int(cur["generation"])
    hist = generation_history(fleet_dir, model_id)
    return int(hist[-1]["generation"]) if hist else 0


def promote_pointer(fleet_dir: str, path: str,
                    sha: Optional[str] = None,
                    model_id: str = "") -> Dict[str, Any]:
    """Validate ``path`` and advance the shared pointer one generation —
    only ``model_id``'s pointer when set, so promoting one tenant never
    touches (or re-validates) its siblings.  Any process with the fleet
    directory can promote — the supervisor, a replica's ``/reload``, or
    an external deploy tool."""
    checked = validate_candidate(path)
    if sha is not None and sha != checked:
        raise LightGBMError(
            f"serving candidate {path!r} sha256 mismatch (expected "
            f"{sha[:12]}..., file {checked[:12]}...)")
    cur = read_pointer(fleet_dir, model_id)
    gen = _current_generation(fleet_dir, model_id) + 1
    return write_pointer(fleet_dir, path, checked, gen, prev=cur,
                         model_id=model_id)


def rollback_pointer(fleet_dir: str, reason: str = "",
                     model_id: str = "") -> Dict[str, Any]:
    """Revert one tenant (the flat pointer when ``model_id`` is empty)
    to its previous generation: re-validate the prior target and write
    it back with a ``rollback_from`` marker (the only thing that lets a
    replica accept a backwards generation).  The target comes from the
    current pointer's ``prev`` record, or — when the pointer is torn —
    the tenant's history trail."""
    from .. import telemetry

    cur = read_pointer(fleet_dir, model_id)
    target = (cur or {}).get("prev")
    cur_gen = _current_generation(fleet_dir, model_id)
    if target is None:
        hist = generation_history(fleet_dir, model_id)
        for rec in reversed(hist):
            if int(rec.get("generation", 0)) < cur_gen:
                target = rec
                break
    if target is None:
        raise LightGBMError(
            f"fleet dir {fleet_dir!r} has no prior generation to roll "
            "back to" + (f" for model {model_id!r}" if model_id else ""))
    sha = validate_candidate(str(target["path"]))
    if sha != target.get("sha256"):
        raise LightGBMError(
            f"rollback target {target['path']!r} sha256 changed since its "
            f"promotion ({sha[:12]}... != "
            f"{str(target.get('sha256'))[:12]}...)")
    pointer = write_pointer(fleet_dir, str(target["path"]), sha,
                            int(target["generation"]),
                            rollback_from=cur_gen, model_id=model_id)
    telemetry.instant("fleet:rollback", generation=pointer["generation"],
                      rollback_from=cur_gen, sha256=sha,
                      model_id=model_id or "",
                      reason=reason or "unspecified")
    telemetry.inc("fleet/rollbacks")
    log_warning(f"fleet: rolled back "
                + (f"model {model_id!r} " if model_id else "")
                + f"generation {cur_gen} -> {pointer['generation']} "
                f"({reason or 'unspecified'})")
    return pointer


# ---------------------------------------------------------------------------
# replica process
# ---------------------------------------------------------------------------

def pointer_transition(applied: int, pointer: Optional[Dict[str, Any]]
                       ) -> str:
    """The promotion watcher's decision for a freshly read pointer, given
    the generation this replica last applied: ``"apply"``, ``"ignore"``
    (unreadable/unchanged), or ``"refuse"`` (backwards generation with no
    ``rollback_from`` marker — a stale or duplicate promoter must not
    silently downgrade the fleet; only ``rollback_pointer`` writes the
    marker that makes a downgrade intentional)."""
    if pointer is None:
        return "ignore"
    gen = int(pointer["generation"])
    if gen == applied:
        return "ignore"
    if gen < applied and pointer.get("rollback_from") is None:
        return "refuse"
    return "apply"


def _replica_main(spec_path: str, rank: int) -> int:
    """Entry point of one replica process (spawned by the supervisor as
    ``python -m lightgbm_tpu.serving.fleet --replica <spec> <rank>``)."""
    from .. import telemetry
    from ..robustness import chaos
    from .server import ServingApp

    with open(spec_path) as fh:
        spec = json.load(fh)
    # a replica serving blind (no latency histograms, no /metrics, no
    # trace spans) is undebuggable from the fleet — telemetry is on in
    # every replica; per-request span emission still follows the
    # propagated head-sampling decision (serve_trace_sample)
    telemetry.configure(enabled=True)
    if spec.get("cache_dir"):
        # shared persistent compile cache: replica warmups after the
        # first pay file reads, not XLA compiles
        import jax
        jax.config.update("jax_compilation_cache_dir", spec["cache_dir"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    fleet_dir = spec["fleet_dir"]
    hb_path = os.path.join(fleet_dir, f"hb_{rank}")
    stop = threading.Event()

    # the heartbeat loop starts BEFORE the model loads: a replica stuck
    # waiting for a valid pointer (below) must look alive to the
    # supervisor, not wedged
    def _beat() -> None:
        n = 0
        while not stop.is_set():
            n += 1
            chaos.replica_beat_hook(n)
            try:
                write_heartbeat(hb_path, n)
            except OSError as e:
                log_debug(f"replica {rank} heartbeat write failed: {e}")
            if stop.wait(_BEAT_S):
                break

    beat_thread = threading.Thread(target=_beat,
                                   name=f"lgbtpu-replica{rank}-beat",
                                   daemon=True)
    beat_thread.start()

    # boot from the CURRENT pointer(s), but only after the same
    # re-validation the promotion watcher performs — a candidate the
    # fleet rejected (file tampered after promotion) must not be served
    # just because this replica restarted; wait for a pointer that
    # validates instead of crash-looping on a dead one.  A multi-tenant
    # spec carries a model roster: every tenant boots from ITS OWN
    # promote_<id>.json (the supervisor writes them before spawning).
    roster: Dict[str, str] = {str(k): str(v)
                              for k, v in (spec.get("models") or {}).items()}
    default_mid = str(spec.get("default_model", "") or "")
    if roster and not default_mid:
        default_mid = next(iter(roster))
    applied: Dict[str, int] = {}
    pointer = None
    if roster:
        boot_roster: Dict[str, str] = {}
        for mid in roster:
            while mid not in boot_roster:
                p = read_pointer(fleet_dir, mid)
                if p is None:
                    # shared dir predating this tenant: serve the spec
                    # roster path; generation 0 until someone promotes
                    boot_roster[mid] = roster[mid]
                    applied[mid] = 0
                    break
                try:
                    sha = validate_candidate(str(p["path"]))
                    if sha != p.get("sha256"):
                        raise LightGBMError(
                            f"model {mid!r} pointer generation "
                            f"{p['generation']} sha256 mismatch "
                            f"({sha[:12]}... != "
                            f"{str(p.get('sha256'))[:12]}...) — the file "
                            "changed after promotion")
                    boot_roster[mid] = str(p["path"])
                    applied[mid] = int(p["generation"])
                except LightGBMError as e:
                    log_warning(f"replica {rank}: promoted model failed "
                                f"boot validation ({e}); waiting for a "
                                "valid promotion")
                    if stop.wait(1.0):
                        return 0
    else:
        while pointer is None:
            p = read_pointer(fleet_dir)
            if p is None:
                raise LightGBMError(
                    f"fleet dir {fleet_dir!r} has no promotion pointer; "
                    "the supervisor writes it before spawning replicas")
            try:
                sha = validate_candidate(str(p["path"]))
                if sha != p.get("sha256"):
                    raise LightGBMError(
                        f"pointer generation {p['generation']} sha256 "
                        f"mismatch ({sha[:12]}... != "
                        f"{str(p.get('sha256'))[:12]}...) — the file "
                        "changed after promotion")
                pointer = p
            except LightGBMError as e:
                log_warning(f"replica {rank}: promoted model failed boot "
                            f"validation ({e}); waiting for a valid "
                            "promotion")
                if stop.wait(1.0):
                    return 0
        applied[""] = int(pointer["generation"])
    reuseport = bool(spec.get("reuseport"))
    access_dir = str(spec.get("access_log_dir", "") or "")
    app = ServingApp(
        str(pointer["path"]) if pointer is not None else "",
        host=spec["host"],
        port=int(spec["shared_port"]) if reuseport else 0,
        max_batch=int(spec["max_batch"]),
        max_delay_ms=float(spec["max_delay_ms"]),
        queue_size=int(spec["queue_size"]),
        buckets_spec=str(spec.get("buckets", "")),
        warmup=bool(spec.get("warmup", True)),
        heartbeat_path=hb_path,
        deadline_ms=float(spec.get("deadline_ms", 0.0)),
        reuse_port=reuseport,
        trace_sample=float(spec.get("trace_sample", 0.01)),
        trace_tail=int(spec.get("trace_tail", 256)),
        access_log=(os.path.join(access_dir,
                                 f"access_replica_{rank}.jsonl")
                    if access_dir else ""),
        slo_availability=float(spec.get("slo_availability", 0.999)),
        slo_p99_ms=float(spec.get("slo_p99_ms", 0.0)),
        slo_window_s=float(spec.get("slo_window_s", 60.0)),
        slo_burn=float(spec.get("slo_burn", 14.4)),
        # binary wire: every replica opens its OWN ephemeral wire port
        # (published in replica_<r>.json below) — replica-aware clients
        # (wire.FleetBinaryClient) discover and route around failures
        binary_port=(0 if int(spec.get("binary_port", -1)) >= 0 else -1),
        binary_accept_threads=int(spec.get("binary_accept_threads", 2)),
        quality_sample=float(spec.get("quality_sample", 0.01)),
        quality_audit_sample=float(spec.get("quality_audit_sample", 0.01)),
        drift_threshold=float(spec.get("drift_threshold", 0.2)),
        drift_window_s=float(spec.get("drift_window_s", 60.0)),
        quality_min_rows=int(spec.get("quality_min_rows", 200)),
        quality_topk=int(spec.get("quality_topk", 5)),
        models=(boot_roster if roster else None),
        hbm_budget_mb=float(spec.get("hbm_budget_mb", 0.0)),
        default_model_id=default_mid,
        explain_max_batch=int(spec.get("explain_max_batch", 16)),
        explain_queue_size=int(spec.get("explain_queue_size", 64)),
        explain_max_delay_ms=float(spec.get("explain_max_delay_ms", 2.0)))
    app.replica_rank = rank
    # per-replica drift snapshot export (merged by `python -m
    # lightgbm_tpu.telemetry.quality report <fleet_dir>`)
    app.drift_export_path = os.path.join(fleet_dir,
                                         f"drift_replica_{rank}.json")
    app.generation = applied[default_mid if roster else ""]
    app.seen_generation = app.generation
    if roster:
        for mid, gen in applied.items():
            reg = app.registry.tenant(mid)
            reg.generation = gen
            reg.seen_generation = gen

    # the watcher polls ONE pointer per tenant (the flat promote.json in
    # single-model mode): a promotion of tenant A swaps A's registry and
    # NOTHING else — siblings keep their device arrays, compiled
    # programs and version counters bitwise untouched
    sources: List[str] = list(roster) if roster else [""]
    tenant_degraded: Dict[str, str] = {}

    def _apply_pointer(mid: str) -> None:
        p = read_pointer(fleet_dir, mid)
        decision = pointer_transition(applied[mid], p)
        if decision == "ignore":
            return
        gen = int(p["generation"])
        who = f"model {mid!r} " if mid else ""
        if decision == "refuse":
            log_warning(
                f"replica {rank}: refusing {who}pointer generation "
                f"{gen} < applied {applied[mid]} without a "
                "rollback_from marker (stale promoter?)")
            return
        if gen < applied[mid]:
            log_warning(f"replica {rank}: {who}rollback generation "
                        f"{gen} (from {p['rollback_from']})")
        applied[mid] = gen
        reg = app.registry.tenant(mid) if roster else None
        try:
            # re-validate against the POINTER's sha first: a file
            # swapped after promotion must not be served even if it
            # parses
            sha = validate_candidate(str(p["path"]))
            if sha != p.get("sha256"):
                raise LightGBMError(
                    f"candidate {p['path']!r} does not match the "
                    f"promoted sha256 ({sha[:12]}... != "
                    f"{str(p.get('sha256'))[:12]}...) — the file "
                    "changed after promotion")
            if roster:
                app.registry.load(str(p["path"]), mid)
            else:
                app.registry.load(str(p["path"]))
        except LightGBMError as e:
            msg = f"{who}candidate generation {gen} rejected: {e}"
            tenant_degraded[mid] = msg
            app.degraded = "; ".join(tenant_degraded.values())
            if reg is not None:
                reg.seen_generation = gen
            if not mid or mid == default_mid:
                app.seen_generation = gen
            log_warning(f"replica {rank}: {msg}; still serving "
                        f"{who}generation "
                        f"{reg.generation if reg is not None else app.generation}")
            return
        if reg is not None:
            reg.generation = gen
            reg.seen_generation = gen
        if not mid or mid == default_mid:
            app.generation = gen
            app.seen_generation = gen
        tenant_degraded.pop(mid, None)
        app.degraded = "; ".join(tenant_degraded.values()) or None
        log_info(f"replica {rank}: promoted {who}to generation {gen} "
                 f"(sha {str(p['sha256'])[:12]})")

    def _watch_promotions() -> None:
        while not stop.wait(float(spec.get("poll_s", _BEAT_S))):
            for mid in sources:
                _apply_pointer(mid)

    def _promote_fn(path: str, model_id: str = ""):
        # any replica's /reload promotes FLEET-WIDE through the shared
        # pointer (its own watcher applies the swap like everyone else's);
        # in a multi-tenant fleet an un-addressed reload targets the
        # default tenant's pointer
        mid = str(model_id or "") or (default_mid if roster else "")
        if roster and mid not in roster:
            raise LightGBMError(f"unknown model_id {mid!r} (roster: "
                                f"{', '.join(sorted(roster))})")
        p = promote_pointer(fleet_dir, path, model_id=mid)
        out = {"promoted_generation": p["generation"],
               "sha256": p["sha256"], "fleet_wide": True}
        if mid:
            out["model_id"] = mid
        return out

    app.promote_fn = _promote_fn
    app.start()
    atomic_write_text(
        os.path.join(fleet_dir, f"replica_{rank}.json"),
        json.dumps({"rank": rank, "host": app.host, "port": app.port,
                    "binary_port": app.binary_port,
                    "pid": os.getpid(), "started_unix": time.time()}))
    threading.Thread(target=_watch_promotions,
                     name=f"lgbtpu-replica{rank}-promote",
                     daemon=True).start()

    def _graceful(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    log_info(f"replica {rank} serving on http://{app.host}:{app.port} "
             f"(generation {app.generation}, pid {os.getpid()})")
    while not stop.wait(0.2):
        pass
    app.shutdown(drain=True)
    # leave this process's span shard behind for the cross-process
    # collector (python -m lightgbm_tpu.telemetry.collect <fleet_dir>) —
    # unless the fleet dir is a private tmpdir the supervisor removes on
    # stop, where the shard would be destroyed moments after the write
    if not spec.get("ephemeral_dir"):
        try:
            telemetry.export_trace(
                os.path.join(fleet_dir, f"trace_replica_{rank}.json"))
        except OSError as e:
            log_debug(f"replica {rank} trace export failed: {e}")
    return 0


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

class ServingFleet:
    """N replica processes + state dir + (front mode) the fanout front.

    ``start()`` spawns everything and blocks until the fleet answers;
    ``promote()`` advances the shared pointer and waits for replicas to
    converge; ``stop()`` drains and reaps.  The supervisor thread
    restarts dead/hung replicas with jittered exponential backoff."""

    def __init__(self, model_path: str, *, replicas: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 mode: str = "front", fleet_dir: str = "",
                 max_batch: int = 256, max_delay_ms: float = 2.0,
                 queue_size: int = 512, buckets_spec: str = "",
                 warmup: bool = True, deadline_ms: float = 0.0,
                 retries: int = 2, retry_backoff_ms: float = 25.0,
                 breaker_failures: int = 5, breaker_cooldown_s: float = 2.0,
                 restart_backoff_s: float = 0.5,
                 hang_timeout_s: float = 10.0,
                 startup_timeout_s: float = 180.0,
                 trace_sample: float = 0.01, trace_tail: int = 256,
                 access_log: str = "",
                 slo_availability: float = 0.999, slo_p99_ms: float = 0.0,
                 slo_window_s: float = 60.0, slo_burn: float = 14.4,
                 binary_port: int = -1, binary_accept_threads: int = 2,
                 quality_sample: float = 0.01,
                 quality_audit_sample: float = 0.01,
                 drift_threshold: float = 0.2, drift_window_s: float = 60.0,
                 quality_min_rows: int = 200, quality_topk: int = 5,
                 models=None, hbm_budget_mb: float = 0.0,
                 default_model_id: str = "",
                 explain_max_batch: int = 16,
                 explain_queue_size: int = 64,
                 explain_max_delay_ms: float = 2.0,
                 python: str = sys.executable):
        from .server import reuseport_available

        if replicas < 1:
            raise LightGBMError("serve_replicas must be >= 1")
        if mode not in ("front", "reuseport"):
            raise LightGBMError(
                f"serve_fleet_mode must be 'front' or 'reuseport', "
                f"got {mode!r}")
        if mode == "reuseport" and not reuseport_available():
            log_warning("SO_REUSEPORT is unavailable on this platform; "
                        "the fleet falls back to the fanout front")
            mode = "front"
        self.mode = mode
        self.replicas = int(replicas)
        self.host = str(host)
        self.port = int(port)
        if self.mode == "reuseport" and self.port == 0:
            # port 0 would hand every replica its OWN kernel-assigned
            # port — SO_REUSEPORT shares nothing and the fleet has no
            # addressable endpoint; pick one concrete free port for the
            # whole group instead
            import socket
            with socket.socket() as s:
                s.bind((self.host, 0))
                self.port = s.getsockname()[1]
            log_info(f"fleet: reuseport mode picked shared port "
                     f"{self.port}")
        self.deadline_ms = float(deadline_ms or 0.0)
        self.retries = int(retries)
        self.retry_backoff_ms = float(retry_backoff_ms)
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.restart_backoff_s = max(float(restart_backoff_s), 0.05)
        self.hang_timeout_s = float(hang_timeout_s or 0.0)
        self.startup_timeout_s = float(startup_timeout_s)
        self._python = python
        self._own_dir = not fleet_dir
        self.dir = fleet_dir or tempfile.mkdtemp(prefix="lgb_tpu_fleet_")
        os.makedirs(self.dir, exist_ok=True)
        # multi-tenant fleet: the roster maps model_id -> model file;
        # every tenant gets its OWN promote_<id>.json generation counter
        self.roster: Dict[str, str] = {}
        self.default_model_id = str(default_model_id or "")
        if models:
            from .multimodel import parse_model_roster
            self.roster = dict(parse_model_roster(models))
            if not self.default_model_id:
                self.default_model_id = next(iter(self.roster))
            if self.default_model_id not in self.roster:
                raise LightGBMError(
                    f"default model_id {self.default_model_id!r} is not "
                    f"in the roster ({', '.join(sorted(self.roster))})")
            if not model_path:
                model_path = self.roster[self.default_model_id]
        elif not model_path:
            raise LightGBMError(
                "ServingFleet needs a model_path or a model roster")
        # gen 1 (or continue a pre-existing shared dir's count): the
        # pointer(s) exist BEFORE any replica starts, so every replica
        # boots on the same validated version.  The flat promote.json is
        # always written (single-model fleets, plus back-compat tooling
        # that reads it); a roster adds one pointer per tenant
        sha = validate_candidate(model_path)
        cur = read_pointer(self.dir)
        gen = _current_generation(self.dir) + 1
        self._pointer = write_pointer(self.dir, model_path, sha, gen,
                                      prev=cur)
        for mid, mpath in self.roster.items():
            msha = validate_candidate(mpath)
            mcur = read_pointer(self.dir, mid)
            if mcur is not None and str(mcur.get("sha256")) == msha:
                continue   # shared dir already points at these bytes
            mgen = _current_generation(self.dir, mid) + 1
            write_pointer(self.dir, mpath, msha, mgen, prev=mcur,
                          model_id=mid)
        # observability knobs ride to every replica via the spec; the
        # access log treats the configured path as a DIRECTORY in fleet
        # mode (access_front.jsonl + access_replica_<r>.jsonl inside)
        self.trace_sample = float(trace_sample)
        self.slo_params = {"slo_availability": float(slo_availability),
                           "slo_p99_ms": float(slo_p99_ms),
                           "slo_window_s": float(slo_window_s),
                           "slo_burn": float(slo_burn)}
        self.access_dir = str(access_log or "")
        if self.access_dir:
            os.makedirs(self.access_dir, exist_ok=True)
        self._spec = {
            "fleet_dir": self.dir, "host": self.host,
            "shared_port": self.port, "reuseport": mode == "reuseport",
            "max_batch": int(max_batch),
            "max_delay_ms": float(max_delay_ms),
            "queue_size": int(queue_size), "buckets": str(buckets_spec),
            "warmup": bool(warmup), "deadline_ms": self.deadline_ms,
            "poll_s": _BEAT_S, "cache_dir": "/tmp/lgb_tpu_jax_cache",
            "trace_sample": self.trace_sample,
            "trace_tail": int(trace_tail),
            "access_log_dir": self.access_dir,
            # a private tmpdir is rmtree'd on stop — exporting trace
            # shards into it would be wasted work destroyed moments
            # later; set serve_fleet_dir to keep shards for the
            # collector (docs/OBSERVABILITY.md)
            "ephemeral_dir": self._own_dir,
            "binary_port": int(binary_port),
            "binary_accept_threads": int(binary_accept_threads),
            # data/model quality knobs ride to every replica; the
            # .quality.json sidecar itself travels with the model path,
            # so promotion carries it without fleet help
            "quality_sample": float(quality_sample),
            "quality_audit_sample": float(quality_audit_sample),
            "drift_threshold": float(drift_threshold),
            "drift_window_s": float(drift_window_s),
            "quality_min_rows": int(quality_min_rows),
            "quality_topk": int(quality_topk),
            # multi-tenant serving: replicas boot every tenant from its
            # own pointer; the roster here is only the fallback for a
            # tenant whose pointer a shared dir does not have yet
            "models": self.roster,
            "default_model": self.default_model_id,
            "hbm_budget_mb": float(hbm_budget_mb),
            "explain_max_batch": int(explain_max_batch),
            "explain_queue_size": int(explain_queue_size),
            "explain_max_delay_ms": float(explain_max_delay_ms),
            **self.slo_params,
        }
        self._spec_path = os.path.join(self.dir, "replica_spec.json")
        # atomic: a replica that races the supervisor must never read a
        # half-written spec
        atomic_write_text(self._spec_path, json.dumps(self._spec))
        self._lock = threading.Lock()
        self._procs: Dict[int, subprocess.Popen] = {}
        self._restarts: Dict[int, int] = {}
        self._last_spawn: Dict[int, float] = {}
        self._restart_due: Dict[int, float] = {}
        self.restarts_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.front = None
        # jitter keeps a mass-restart from thundering-herding the model
        # load; seeded per-fleet so runs are reproducible
        self._rng = random.Random(0xF1EE7 ^ self.replicas)

    # -- process plumbing --------------------------------------------------
    def _endpoint_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"replica_{rank}.json")

    def endpoint(self, rank: int) -> Optional[Dict[str, Any]]:
        try:
            with open(self._endpoint_path(rank)) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def endpoints(self) -> Dict[int, Dict[str, Any]]:
        """rank -> endpoint record for replicas with a LIVE process."""
        out: Dict[int, Dict[str, Any]] = {}
        with self._lock:
            live = [r for r, p in self._procs.items() if p.poll() is None]
        for r in live:
            ep = self.endpoint(r)
            if ep is not None:
                out[r] = ep
        return out

    def binary_endpoints(self) -> Dict[int, Any]:
        """rank -> (host, binary_port) of live replicas with an open
        binary wire — the discovery hook wire.FleetBinaryClient routes
        off (re-read per call: a restarted replica publishes a NEW port)."""
        out: Dict[int, Any] = {}
        for r, ep in self.endpoints().items():
            bp = ep.get("binary_port")
            if bp:
                out[r] = (ep["host"], int(bp))
        return out

    def _spawn(self, rank: int) -> None:
        for stale in (self._endpoint_path(rank),
                      os.path.join(self.dir, f"hb_{rank}")):
            if os.path.exists(stale):
                os.unlink(stale)
        env = dict(os.environ)
        env["LGBTPU_REPLICA_RANK"] = str(rank)
        env["PYTHONUNBUFFERED"] = "1"
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(self.dir, f"replica_{rank}.log")
        with open(log_path, "ab") as logf:
            proc = subprocess.Popen(
                [self._python, "-m", "lightgbm_tpu.serving.fleet",
                 "--replica", self._spec_path, str(rank)],
                env=env, stdout=logf, stderr=subprocess.STDOUT)
        with self._lock:
            self._procs[rank] = proc
            self._last_spawn[rank] = time.monotonic()
        log_debug(f"fleet: spawned replica {rank} (pid {proc.pid})")

    def _schedule_restart(self, rank: int, why: str) -> None:
        from .. import telemetry

        with self._lock:
            healthy_for = time.monotonic() - self._last_spawn.get(rank, 0.0)
            if healthy_for > _HEALTHY_DECAY_S:
                self._restarts[rank] = 0
            n = self._restarts.get(rank, 0)
            self._restarts[rank] = n + 1
            self.restarts_total += 1
            delay = min(self.restart_backoff_s * (2 ** n), _RESTART_CAP_S)
            delay *= 0.75 + 0.5 * self._rng.random()   # +/-25% jitter
            self._restart_due[rank] = time.monotonic() + delay
        telemetry.inc("fleet/restarts")
        log_warning(f"fleet: replica {rank} {why}; restart "
                    f"{self._restarts[rank]} in {delay:.2f}s")

    def _tail_log(self, rank: int, n: int = 2000) -> str:
        try:
            with open(os.path.join(self.dir, f"replica_{rank}.log"),
                      "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - n))
                return fh.read().decode(errors="replace")
        except OSError:
            return "<no replica log>"

    def _supervise(self) -> None:
        """The babysitter: poll exits + heartbeat ages, reap hung
        replicas, respawn dead ones once their backoff elapses."""
        from .. import telemetry

        while not self._stop.wait(_SUPERVISE_S):
            now = time.monotonic()
            with self._lock:
                snapshot = dict(self._procs)
                due = dict(self._restart_due)
            alive = 0
            for rank, proc in snapshot.items():
                rc = proc.poll()
                telemetry.gauge(f"fleet/replica/{rank}/up",
                                1.0 if rc is None else 0.0)
                if rc is not None:
                    if rank not in due:
                        self._schedule_restart(rank, f"exited (rc {rc})")
                    continue
                alive += 1
                if self.hang_timeout_s > 0:
                    age = heartbeat_age(os.path.join(self.dir, f"hb_{rank}"))
                    if age is not None:
                        telemetry.gauge(
                            f"fleet/replica/{rank}/heartbeat_age_s", age)
                    started = self._last_spawn.get(rank, now)
                    if age is None:
                        # no beat yet: give the interpreter+jax import
                        # the startup window before declaring it wedged
                        if now - started > max(self.startup_timeout_s,
                                               self.hang_timeout_s):
                            log_warning(f"fleet: replica {rank} never "
                                        "heartbeat; killing")
                            proc.kill()
                    elif age > self.hang_timeout_s:
                        log_warning(f"fleet: replica {rank} heartbeat "
                                    f"stale ({age:.1f}s > "
                                    f"{self.hang_timeout_s:.1f}s); killing")
                        proc.kill()
            telemetry.gauge("fleet/replicas_alive", float(alive))
            for rank, when in due.items():
                if now >= when:
                    with self._lock:
                        self._restart_due.pop(rank, None)
                    self._spawn(rank)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingFleet":
        for r in range(self.replicas):
            self._spawn(r)
        deadline = time.monotonic() + self.startup_timeout_s
        pending = set(range(self.replicas))
        while pending:
            for r in sorted(pending):
                proc = self._procs.get(r)
                if proc is not None and proc.poll() is not None:
                    raise LightGBMError(
                        f"fleet replica {r} died during startup "
                        f"(rc {proc.returncode}):\n{self._tail_log(r)}")
                if self.endpoint(r) is not None:
                    pending.discard(r)
            if not pending:
                break
            if time.monotonic() > deadline:
                raise LightGBMError(
                    f"fleet replicas {sorted(pending)} not up within "
                    f"{self.startup_timeout_s:.0f}s")
            time.sleep(0.1)
        self._thread = threading.Thread(target=self._supervise,
                                        name="lgbtpu-fleet-supervisor",
                                        daemon=True)
        self._thread.start()
        if self.mode == "front":
            from .front import FanoutFront
            self.front = FanoutFront(
                self, host=self.host, port=self.port,
                retries=self.retries,
                retry_backoff_ms=self.retry_backoff_ms,
                breaker_failures=self.breaker_failures,
                breaker_cooldown_s=self.breaker_cooldown_s,
                deadline_ms=self.deadline_ms,
                trace_sample=self.trace_sample,
                trace_tail=int(self._spec["trace_tail"]),
                access_log=(os.path.join(self.access_dir,
                                         "access_front.jsonl")
                            if self.access_dir else ""),
                slo_availability=self.slo_params["slo_availability"],
                slo_p99_ms=self.slo_params["slo_p99_ms"],
                slo_window_s=self.slo_params["slo_window_s"],
                slo_burn=self.slo_params["slo_burn"]).start()
            self.port = self.front.port
        else:
            self.port = int(self._spec["shared_port"])
        log_info(f"fleet: {self.replicas} replicas up "
                 f"({self.mode} mode, http://{self.host}:{self.port}, "
                 f"dir {self.dir})")
        return self

    def _pointer_mid(self, model_id: Optional[str]) -> str:
        """Resolve a promote/rollback target to a pointer key: the named
        tenant in a roster fleet (un-addressed calls hit the DEFAULT
        tenant's pointer — the flat promote.json is not watched by
        multi-tenant replicas), the flat pointer otherwise."""
        mid = str(model_id or "")
        if self.roster:
            mid = mid or self.default_model_id
            if mid not in self.roster:
                raise LightGBMError(
                    f"unknown model_id {mid!r} (roster: "
                    f"{', '.join(sorted(self.roster))})")
            return mid
        if mid:
            raise LightGBMError(
                "model_id promotion needs a multi-tenant fleet "
                "(serve_models)")
        return ""

    @property
    def generation(self) -> int:
        p = read_pointer(self.dir, self._pointer_mid(None))
        return int(p["generation"]) if p else 0

    def current_pointer(self, model_id: Optional[str] = None
                        ) -> Optional[Dict[str, Any]]:
        return read_pointer(self.dir, self._pointer_mid(model_id))

    def _replica_gen_state(self, st: Optional[Dict[str, Any]],
                           mid: str) -> Dict[str, Any]:
        """(seen_generation, generation, degraded) of one tenant in one
        replica's /ready payload — the per-model record when addressing
        a roster tenant, the flat fields otherwise."""
        if st is None:
            return {}
        if mid:
            return (st.get("models") or {}).get(mid) or {}
        return st

    def promote(self, path: str, timeout_s: float = 60.0,
                model_id: Optional[str] = None) -> Dict[str, Any]:
        """Validate + write one tenant's pointer, then wait for every
        live replica to process the new generation.  Returns the
        per-replica outcome; raises only when the CANDIDATE fails
        validation (the fleet is untouched in that case).  Sibling
        tenants are never touched — their registries, versions and
        compiled programs stay bitwise identical through the promotion."""
        mid = self._pointer_mid(model_id)
        pointer = promote_pointer(self.dir, path, model_id=mid)
        gen = int(pointer["generation"])
        deadline = time.monotonic() + timeout_s
        promoted: Dict[int, bool] = {}
        rejected: Dict[int, str] = {}
        while time.monotonic() < deadline:
            states = self._ready_states()
            pending = False
            for rank, st in states.items():
                rec = self._replica_gen_state(st, mid)
                if not rec or int(rec.get("seen_generation", 0)) < gen:
                    pending = True
                    continue
                if int(rec.get("generation", 0)) == gen:
                    promoted[rank] = True
                    rejected.pop(rank, None)
                else:
                    rejected[rank] = str((st or {}).get("degraded",
                                                        "rejected"))
            if not pending and states:
                break
            time.sleep(0.1)
        unreachable = [
            r for r, st in self._ready_states().items()
            if int(self._replica_gen_state(st, mid)
                   .get("seen_generation", 0)) < gen]
        out = {"generation": gen, "sha256": pointer["sha256"],
               "promoted": sorted(promoted),
               "rejected": {str(r): m for r, m in sorted(rejected.items())},
               "unreachable": sorted(set(unreachable) - set(promoted))}
        if mid:
            out["model_id"] = mid
        return out

    def rollback(self, reason: str = "", timeout_s: float = 60.0,
                 model_id: Optional[str] = None) -> Dict[str, Any]:
        """Revert one tenant to its previous generation and wait for the
        live replicas to converge on the rollback target's sha256 (the
        generation number moves DOWN, so the promote() wait — which keys
        on seen_generation advancing — does not apply)."""
        mid = self._pointer_mid(model_id)
        pointer = rollback_pointer(self.dir, reason=reason, model_id=mid)
        sha = str(pointer["sha256"])
        deadline = time.monotonic() + timeout_s
        reverted: Dict[int, bool] = {}
        while time.monotonic() < deadline:
            states = self._ready_states()
            reverted = {
                r: (str(self._replica_gen_state(st, mid)
                        .get("sha256" if mid else "model_sha256")) == sha)
                for r, st in states.items()}
            if states and all(reverted.values()):
                break
            time.sleep(0.1)
        out = {"generation": int(pointer["generation"]),
               "rollback_from": pointer.get("rollback_from"),
               "sha256": sha,
               "reverted": sorted(r for r, ok in reverted.items() if ok)}
        if mid:
            out["model_id"] = mid
        return out

    def _ready_states(self) -> Dict[int, Optional[Dict[str, Any]]]:
        """rank -> /ready payload (None when unreachable) for every live
        replica."""
        from .front import http_json

        import http.client

        out: Dict[int, Optional[Dict[str, Any]]] = {}
        for rank, ep in self.endpoints().items():
            try:
                _, obj, _ = http_json(ep["host"], ep["port"], "GET",
                                      "/ready", timeout=1.0)
                out[rank] = obj
            except (OSError, http.client.HTTPException):
                # a replica dying mid-response (IncompleteRead) must read
                # as unreachable, not abort a promote()/describe() whose
                # pointer already advanced
                out[rank] = None
        return out

    def describe(self, states: Optional[Dict[int, Optional[Dict[str, Any]]]]
                 = None) -> Dict[str, Any]:
        """Fleet snapshot.  ``states`` lets a caller that already holds
        fresh /ready payloads (the front's background cache) avoid N
        synchronous per-replica probes per /stats scrape."""
        if states is None:
            states = self._ready_states()
        with self._lock:
            restarts = dict(self._restarts)
            total = self.restarts_total
        reps: List[Dict[str, Any]] = []
        for rank in range(self.replicas):
            st = states.get(rank)
            rec: Dict[str, Any] = {"rank": rank,
                                   "reachable": st is not None,
                                   "restarts": restarts.get(rank, 0)}
            if st:
                rec.update({k: st[k] for k in
                            ("ready", "queue_depth", "model_version",
                             "model_sha256", "generation", "degraded",
                             "heartbeat_age_s") if k in st})
            reps.append(rec)
        return {"mode": self.mode, "replicas": reps,
                "generation": self.generation,
                "restarts_total": total, "dir": self.dir}

    def stop(self, timeout_s: float = 30.0) -> None:
        from .. import telemetry

        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(5.0)
        if self.front is not None:
            self.front.stop()
        if telemetry.global_tracer.enabled and not self._own_dir:
            # this process's shard (front routing + supervisor events);
            # replicas export theirs during their SIGTERM drain below.
            # A private tmpdir fleet is skipped — it is rmtree'd at the
            # end of this method; set serve_fleet_dir to collect shards
            try:
                telemetry.export_trace(
                    os.path.join(self.dir, "trace_front.json"))
            except OSError as e:
                log_debug(f"fleet front trace export failed: {e}")
        with self._lock:
            procs = dict(self._procs)
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()        # SIGTERM: replicas drain
        deadline = time.monotonic() + timeout_s
        for proc in procs.values():
            left = max(deadline - time.monotonic(), 0.1)
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        if self._own_dir:
            shutil.rmtree(self.dir, ignore_errors=True)


def fleet_from_params(params: Dict[str, Any]) -> ServingFleet:
    """Build (not start) a ServingFleet from resolved CLI/conf params."""
    from ..config import Config

    cfg = Config.from_params(params)
    model_path = str(params.get("input_model", "") or "")
    if not model_path and not cfg.serve_models:
        raise LightGBMError("task=serve requires input_model=<model file> "
                            "(or serve_models=<id=path,...>)")
    return ServingFleet(
        model_path, replicas=cfg.serve_replicas,
        host=cfg.serve_host, port=cfg.serve_port,
        mode=cfg.serve_fleet_mode, fleet_dir=cfg.serve_fleet_dir,
        max_batch=cfg.serve_max_batch, max_delay_ms=cfg.serve_max_delay_ms,
        queue_size=cfg.serve_queue_size, buckets_spec=cfg.serve_buckets,
        warmup=cfg.serve_warmup, deadline_ms=cfg.serve_deadline_ms,
        retries=cfg.serve_retries,
        retry_backoff_ms=cfg.serve_retry_backoff_ms,
        breaker_failures=cfg.serve_breaker_failures,
        breaker_cooldown_s=cfg.serve_breaker_cooldown_s,
        restart_backoff_s=cfg.serve_restart_backoff_s,
        hang_timeout_s=cfg.serve_hang_timeout_s,
        trace_sample=cfg.serve_trace_sample,
        trace_tail=cfg.serve_trace_tail,
        access_log=cfg.serve_access_log,
        slo_availability=cfg.serve_slo_availability,
        slo_p99_ms=cfg.serve_slo_p99_ms,
        slo_window_s=cfg.serve_slo_window_s,
        slo_burn=cfg.serve_slo_burn,
        binary_port=cfg.serve_binary_port,
        binary_accept_threads=cfg.serve_binary_accept_threads,
        quality_sample=cfg.quality_sample,
        quality_audit_sample=cfg.quality_audit_sample,
        drift_threshold=cfg.drift_threshold,
        drift_window_s=cfg.drift_window_s,
        quality_min_rows=cfg.quality_min_rows,
        quality_topk=cfg.quality_topk,
        models=cfg.serve_models or None,
        hbm_budget_mb=cfg.serve_hbm_budget_mb,
        default_model_id=cfg.serve_default_model,
        explain_max_batch=cfg.serve_explain_max_batch,
        explain_queue_size=cfg.serve_explain_queue_size,
        explain_max_delay_ms=cfg.serve_explain_max_delay_ms)


def run_fleet(params: Dict[str, Any]) -> int:
    """Blocking CLI entry: serve the fleet until SIGTERM/SIGINT."""
    from .. import telemetry

    if not telemetry.enabled():
        telemetry.configure(enabled=True,
                            metrics_out=str(params.get("telemetry_out", ""))
                            or None)
    fleet = fleet_from_params(params).start()
    stop = threading.Event()

    def _graceful(signum, frame):
        log_info(f"signal {signum}: draining serving fleet")
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        stop.wait()
    finally:
        fleet.stop()
        log_info("serving fleet stopped")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) == 3 and argv[0] == "--replica":
        return _replica_main(argv[1], int(argv[2]))
    print("usage: python -m lightgbm_tpu.serving.fleet --replica "
          "<spec.json> <rank>\n(the fleet supervisor spawns this; start "
          "a fleet with: python -m lightgbm_tpu.serve "
          "input_model=model.txt serve_replicas=3)")
    return 1


if __name__ == "__main__":
    sys.exit(main())
