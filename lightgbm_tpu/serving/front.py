"""Fanout front: the client-facing port of a serving fleet.

A tiny stdlib-HTTP reverse proxy over the replica pool with the three
request-resilience mechanisms the single-process server cannot provide
(docs/SERVING.md "Fleet architecture"):

  * **deadline-aware bounded retry** — the client's budget
    (``deadline_ms``, defaulting to ``serve_deadline_ms``) is split
    across up to ``serve_retries + 1`` attempts on DIFFERENT replicas,
    with jittered exponential backoff between attempts.  Transport
    failures (connection reset, timeout — what a killed or hung replica
    produces) and replica 5xx/503 responses retry; 4xx client errors
    pass through untouched.  The remaining budget rides to the replica
    in the forwarded body, so a request never queues past its own
    expiry downstream;
  * **per-replica circuit breaker** — consecutive errors/timeouts past
    ``serve_breaker_failures`` trip the replica's breaker OPEN: it gets
    no traffic for ``serve_breaker_cooldown_s``, then ONE half-open
    probe; success closes it, failure re-opens.  A wedged replica costs
    its first few victims a per-attempt timeout, then nothing;
  * **load shedding** — when no ready replica remains (all breakers
    open, none ready, or the budget ran out before an attempt), the
    front answers a fast structured 503 with ``Retry-After`` instead of
    queueing into collapse.

Routing keys off replica READINESS (``/ready``, polled in the
background), not liveness: a draining or model-less replica gets no
traffic but is not presumed dead.
"""
from __future__ import annotations

import http.client
import itertools
import json
import math
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..utils.log import LightGBMError, log_debug, log_info

_READY_POLL_S = 0.5       # background readiness sweep period
_READY_TIMEOUT_S = 1.0    # per-replica /ready probe timeout
_MIN_TRY_S = 0.05         # floor on a per-attempt forward timeout
_FALLBACK_BUDGET_S = 30.0  # budget when neither client nor config set one


def http_json(host: str, port: int, method: str, path: str,
              obj: Optional[Dict[str, Any]] = None,
              timeout: float = 10.0,
              headers: Optional[Dict[str, str]] = None
              ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
    """One JSON request; raises OSError-family on transport failure."""
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        body = json.dumps(obj) if obj is not None else None
        hdrs = {"Content-Type": "application/json"} if body else {}
        hdrs.update(headers or {})
        conn.request(method, path, body, hdrs)
        r = conn.getresponse()
        raw = r.read()
        try:
            payload = json.loads(raw) if raw else {}
        except ValueError:
            raise ConnectionError(
                f"non-JSON reply ({r.status}) from {host}:{port}{path}")
        return r.status, payload, dict(r.getheaders())
    finally:
        conn.close()


class CircuitBreaker:
    """closed -> open (after N consecutive failures) -> half-open (one
    probe after the cooldown) -> closed|open.  Thread-safe; the clock is
    injectable so tests drive the state machine deterministically."""

    def __init__(self, failures: int = 5, cooldown_s: float = 2.0,
                 clock=time.monotonic):
        self.failures = max(int(failures), 1)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self.trips = 0            # closed/half-open -> open transitions

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown_s:
            return "half_open"
        return "open"

    def peek(self) -> bool:
        """Non-consuming routability check (candidate filtering): True
        unless the breaker is open or a half-open probe is in flight."""
        with self._lock:
            st = self._state_locked()
            return st == "closed" or (st == "half_open"
                                      and not self._probing)

    def allow(self) -> bool:
        """May a request be routed here right now?  In half-open, only
        ONE in-flight probe is allowed at a time — calling this CLAIMS
        the probe slot, so only call it for the replica actually being
        dispatched to (use :meth:`peek` for filtering)."""
        with self._lock:
            st = self._state_locked()
            if st == "closed":
                return True
            if st == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            # a failed half-open probe re-opens; consecutive failures
            # past the threshold trip a closed breaker; failures landing
            # while already open (stragglers) leave the cooldown clock
            # alone
            if self._probing or (self._opened_at is None
                                 and self._consecutive >= self.failures):
                self._opened_at = self._clock()
                self._probing = False
                self.trips += 1

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._state_locked(),
                    "consecutive_failures": self._consecutive,
                    "trips": self.trips}


class FanoutFront:
    """The fleet's public HTTP endpoint: routes ``/predict`` across the
    replica pool, aggregates ``/ready``/``/stats``, and turns ``/reload``
    into a fleet-wide promotion."""

    def __init__(self, fleet, *, host: str = "127.0.0.1", port: int = 0,
                 retries: int = 2, retry_backoff_ms: float = 25.0,
                 breaker_failures: int = 5, breaker_cooldown_s: float = 2.0,
                 deadline_ms: float = 0.0, trace_sample: float = 0.01,
                 trace_tail: int = 256, access_log: str = "",
                 slo_availability: float = 0.999, slo_p99_ms: float = 0.0,
                 slo_window_s: float = 60.0, slo_burn: float = 14.4):
        from ..telemetry import AccessLog, TailRing
        from .slo import SLOMonitor

        self.fleet = fleet
        self.retries = max(int(retries), 0)
        self.retry_backoff_s = max(float(retry_backoff_ms), 0.0) / 1e3
        self.deadline_ms = float(deadline_ms or 0.0)
        self._breaker_failures = int(breaker_failures)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        self._breakers: Dict[int, CircuitBreaker] = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._ready: Dict[int, Dict[str, Any]] = {}
        self._ready_swept = False
        self.shed = 0
        self.retried = 0
        self.forwarded = 0
        # fleet-edge observability: the front mints the trace id + head-
        # sampling decision for every request entering the fleet, owns
        # the client-visible SLO monitor (the only place that sees final
        # outcomes across retries), the access log, and the tail ring
        self.trace_sample = max(float(trace_sample), 0.0)
        self.tail = TailRing(trace_tail)
        self.access_log = AccessLog(access_log) if access_log else None
        self.slo = SLOMonitor(availability_target=slo_availability,
                              p99_target_ms=slo_p99_ms,
                              window_s=slo_window_s,
                              burn_threshold=slo_burn)
        self._rng = random.Random(0xF407)
        self._stop = threading.Event()
        self._httpd = ThreadingHTTPServer((host, int(port)), _FrontHandler)
        self._httpd.daemon_threads = True
        self._httpd.front = self
        self._threads: List[threading.Thread] = []
        self.t0 = time.time()

    # -- plumbing ----------------------------------------------------------
    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "FanoutFront":
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="lgbtpu-fleet-front", daemon=True),
            threading.Thread(target=self._poll_ready,
                             name="lgbtpu-front-ready", daemon=True),
        ]
        for t in self._threads:
            t.start()
        log_info(f"fleet front on http://{self.host}:{self.port} "
                 f"({self.fleet.replicas} replicas)")
        return self

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        for t in self._threads:
            if t.is_alive():
                t.join(5.0)
        if self.access_log is not None:
            self.access_log.close()

    def breaker(self, rank: int) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(rank)
            if br is None:
                br = CircuitBreaker(self._breaker_failures,
                                    self._breaker_cooldown_s)
                self._breakers[rank] = br
            return br

    # -- readiness ---------------------------------------------------------
    def _poll_ready(self) -> None:
        from .. import telemetry

        while True:   # first sweep runs immediately, not a period late
            snapshot: Dict[int, Dict[str, Any]] = {}
            for rank, ep in self.fleet.endpoints().items():
                try:
                    st, obj, _ = http_json(ep["host"], ep["port"], "GET",
                                           "/ready",
                                           timeout=_READY_TIMEOUT_S)
                    obj["_reachable"] = True
                    obj["ready"] = bool(obj.get("ready")) and st == 200
                except (OSError, http.client.HTTPException) as e:
                    # a replica killed mid-response raises IncompleteRead
                    # (an HTTPException, NOT an OSError) — either way
                    # this sweep must survive, or the readiness cache
                    # freezes forever
                    obj = {"_reachable": False, "ready": False,
                           "error": f"{type(e).__name__}: {e}"}
                snapshot[rank] = obj
            with self._lock:
                self._ready = snapshot
                self._ready_swept = True
            telemetry.gauge("fleet/replicas_ready",
                            float(sum(1 for o in snapshot.values()
                                      if o.get("ready"))))
            # the poll loop doubles as the SLO heartbeat: burn gauges
            # stay fresh and alerts CLEAR even when traffic goes idle
            self.slo.tick()
            if self._stop.wait(_READY_POLL_S):
                break

    def _candidates(self) -> List[Tuple[int, Dict[str, Any]]]:
        """(rank, endpoint) targets in round-robin order: ready replicas
        whose breaker LOOKS routable (:meth:`CircuitBreaker.peek` —
        the probe slot is only claimed for the replica actually picked).
        Before the first readiness sweep completes, every live replica
        is optimistically a candidate."""
        eps = self.fleet.endpoints()
        with self._lock:
            ready, swept = dict(self._ready), self._ready_swept
        ranks = [r for r in sorted(eps)
                 if (not swept or ready.get(r, {}).get("ready"))
                 and self.breaker(r).peek()]
        if not ranks:
            return []
        start = next(self._rr) % len(ranks)
        return [(r, eps[r]) for r in ranks[start:] + ranks[:start]]

    # -- request handling --------------------------------------------------
    def handle_predict(self, body: Dict[str, Any],
                       headers: Optional[Dict[str, str]] = None
                       ) -> Tuple[int, Dict[str, Any],
                                  Optional[Dict[str, str]]]:
        """Route one client request.  The front is where a request's
        trace context is born (or accepted from the client's
        ``X-LGBTPU-Trace`` header) and where its FINAL outcome — across
        all retries — is judged against the SLO and logged."""
        from .. import telemetry

        t0 = time.perf_counter()
        want = telemetry.TRACE_HEADER.lower()
        hval = next((v for k, v in (headers or {}).items()
                     if k.lower() == want), None)
        ctx = telemetry.TraceContext.from_header(hval)
        if ctx is None:
            ctx = telemetry.TraceContext.mint(self.trace_sample)
        try:
            budget_ms = float(body.get("deadline_ms",
                                       self.deadline_ms) or 0.0)
        except (TypeError, ValueError):
            budget_ms = 0.0
            code, obj, hdrs = 400, {"error": "deadline_ms must be "
                                             "a number"}, None
        else:
            with telemetry.request_span(ctx, "front/request"):
                code, obj, hdrs = self._route_predict(body, ctx, t0,
                                                      budget_ms)
        latency_ms = (time.perf_counter() - t0) * 1e3
        obj.setdefault("trace_id", ctx.trace_id)
        hdrs = dict(hdrs or {})
        hdrs[telemetry.TRACE_HEADER] = ctx.header_value()
        self._note_outcome(ctx, code, obj, latency_ms, budget_ms)
        return code, obj, hdrs

    # shed reasons that mean "the fleet could not be reached", not "the
    # fleet chose to shed": these burn the AVAILABILITY budget (recorded
    # as 599 against the SLO — the client still sees an honest 503)
    _OUTAGE_REASONS = ("no_ready_replicas", "retries_exhausted")

    def _note_outcome(self, ctx, code: int, obj: Dict[str, Any],
                      latency_ms: float, deadline_ms: float) -> None:
        from ..telemetry.context import note_outcome

        slo_status = None
        if code == 503:
            reason = str(obj.get("reason", ""))
            if (reason in self._OUTAGE_REASONS
                    or "unreachable" in reason):
                slo_status = 599
        note_outcome(ctx=ctx, status=code, latency_ms=latency_ms,
                     deadline_ms=deadline_ms, obj=obj, slo=self.slo,
                     tail=self.tail, access_log=self.access_log,
                     retries=max(int(obj.get("attempts", 1)) - 1, 0),
                     extra={"replica": obj.get("replica")},
                     slo_status=slo_status)

    def _route_predict(self, body: Dict[str, Any], ctx, t0: float,
                       budget_ms: float
                       ) -> Tuple[int, Dict[str, Any],
                                  Optional[Dict[str, str]]]:
        from .. import telemetry

        budget_s = budget_ms / 1e3 if budget_ms > 0 else _FALLBACK_BUDGET_S
        deadline = t0 + budget_s
        attempts = self.retries + 1
        last: Optional[Tuple[int, Dict[str, Any]]] = None
        retry_after = 0.5
        tried = 0      # attempts actually forwarded — every outcome
        #                (success, shed, pass-through) reports it, so the
        #                access log's retry count is honest for failures

        def shed(reason: str, retry_after_s: float):
            code, obj, hdrs = self._shed(reason, retry_after_s)
            obj["attempts"] = tried
            return code, obj, hdrs

        for attempt in range(attempts):
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                return shed("deadline_expired", 0.0)
            picked = None
            for rank, ep in self._candidates():
                # allow() claims the half-open probe slot; only the
                # replica actually dispatched to may consume it
                if self.breaker(rank).allow():
                    picked = (rank, ep)
                    break
            if picked is None:
                return shed("no_ready_replicas", retry_after)
            rank, ep = picked
            tried = attempt + 1
            per_try = max(remaining / (attempts - attempt), _MIN_TRY_S)
            fwd = dict(body)
            fwd["deadline_ms"] = per_try * 1e3
            br = self.breaker(rank)
            telemetry.request_instant(ctx, "front/attempt",
                                      attempt=attempt + 1, replica=rank)
            try:
                st, obj, _ = http_json(
                    ep["host"], ep["port"], "POST", "/predict", fwd,
                    timeout=per_try,
                    headers={telemetry.TRACE_HEADER: ctx.header_value()})
            except (OSError, http.client.HTTPException,
                    ConnectionError) as e:
                # killed replica -> reset; hung replica -> timeout: both
                # are breaker food and retry on another replica
                trips0 = br.trips
                br.record_failure()
                if br.trips > trips0:
                    telemetry.inc("fleet/breaker_trips")
                    telemetry.request_instant(ctx, "front/breaker_trip",
                                              replica=rank)
                last = (503, {"error": "overload",
                              "reason": f"replica {rank} unreachable: "
                                        f"{type(e).__name__}"})
                log_debug(f"front: attempt {attempt + 1} replica {rank} "
                          f"failed: {type(e).__name__}: {e}")
            else:
                if st >= 500 and st != 503:
                    # replica-side error: breaker food, retry a sibling
                    trips0 = br.trips
                    br.record_failure()
                    if br.trips > trips0:
                        telemetry.inc("fleet/breaker_trips")
                        telemetry.request_instant(ctx, "front/breaker_trip",
                                                  replica=rank)
                    last = (st, obj)
                else:
                    # ANY prompt response proves the replica is alive —
                    # including a 503 shed (overloaded is not broken);
                    # this also releases a claimed half-open probe slot
                    br.record_success()
                    if st == 200:
                        with self._lock:
                            self.forwarded += 1
                        obj["attempts"] = attempt + 1
                        obj["latency_ms"] = round(
                            (time.perf_counter() - t0) * 1e3, 3)
                        return 200, obj, None
                    if st != 503:
                        # client errors (400/404/409) are not the
                        # replica's fault: pass through, never retry
                        obj.setdefault("attempts", tried)
                        return st, obj, None
                    # overload/deadline shed: try a sibling
                    retry_after = float(obj.get("retry_after_s",
                                                retry_after) or retry_after)
                    last = (st, obj)
            if attempt + 1 < attempts:
                with self._lock:
                    self.retried += 1
                telemetry.inc("fleet/retries")
                telemetry.request_instant(ctx, "front/retry",
                                          attempt=attempt + 1,
                                          replica=rank)
                backoff = self.retry_backoff_s * (2 ** attempt) \
                    * (0.5 + self._rng.random())
                backoff = min(backoff,
                              max(deadline - time.perf_counter(), 0.0))
                if backoff > 0:
                    time.sleep(backoff)
        if last is not None and last[0] == 503:
            return shed(str(last[1].get("reason",
                                        last[1].get("error",
                                                    "overload"))),
                        retry_after)
        return shed("retries_exhausted", retry_after)

    def _shed(self, reason: str, retry_after_s: float
              ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        from .. import telemetry

        with self._lock:
            self.shed += 1
        telemetry.inc("fleet/shed")
        retry_after_s = min(max(retry_after_s, 0.0), 5.0)
        return 503, {"error": "overload", "reason": reason,
                     "retry_after_s": round(retry_after_s, 3)}, \
            {"Retry-After": str(max(int(math.ceil(retry_after_s)), 0))}

    def handle_reload(self, body: Dict[str, Any]
                      ) -> Tuple[int, Dict[str, Any]]:
        # model_id keys the promotion to ONE tenant's pointer in a
        # multi-tenant fleet; un-addressed reloads hit the default tenant
        mid = str(body.get("model_id", "") or "") or None
        path = str(body.get("path", "") or "")
        try:
            if not path:
                p = self.fleet.current_pointer(mid)
                if p is None:
                    return 409, {"error": "fleet has no promoted model"}
                path = str(p["path"])
            outcome = self.fleet.promote(path, model_id=mid)
        except LightGBMError as e:
            # candidate failed validation: nothing was promoted anywhere
            return 409, {"error": str(e),
                         "generation": self.fleet.generation}
        if not outcome["promoted"]:
            return 409, {"error": "no replica accepted the candidate; "
                                  "fleet stays on its previous version",
                         **outcome}
        return 200, outcome

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            ready = dict(self._ready)
            counters = {"shed": self.shed, "retried": self.retried,
                        "forwarded": self.forwarded}
        breakers = {str(r): self.breaker(r).describe()
                    for r in sorted(self.fleet.endpoints())}
        # the cached /ready payloads stand in for fresh per-replica
        # probes — a /stats scrape must not fan out N blocking HTTP
        # calls when the background poller refreshes them anyway
        cached = {r: (st if st.get("_reachable") else None)
                  for r, st in ready.items()} or None
        return {"uptime_s": round(time.time() - self.t0, 3),
                **counters,
                "breakers": breakers,
                "replicas": {str(r): {k: v for k, v in st.items()
                                      if not k.startswith("_")}
                             for r, st in sorted(ready.items())},
                "slo": self.slo.state(),
                "trace_tail": self.tail.snapshot(last=20),
                "trace_sample": self.trace_sample,
                # binary-wire discovery for REMOTE clients: the per-
                # replica wire ports live in fleet-dir files a network
                # client cannot read — wire.FleetBinaryClient can poll
                # this /stats field instead (docs/SERVING.md "Binary
                # wire protocol")
                "binary_endpoints": {
                    str(r): {"host": hp[0], "port": hp[1]}
                    for r, hp in sorted(getattr(
                        self.fleet, "binary_endpoints", dict)().items())},
                "fleet": self.fleet.describe(states=cached)}

    def metrics_text(self, fleet_scope: bool = False) -> str:
        """Prometheus exposition for this process (front + supervisor
        share it), optionally aggregating every reachable replica's
        registry snapshot under ``replica="<r>"`` labels.

        The aggregate fans out one ``/metrics?format=json`` scrape per
        live replica with a short timeout — scrape cadence is tens of
        seconds, so unlike ``/stats`` this path accepts N blocking
        probes in exchange for a single-scrape fleet view."""
        from ..telemetry import global_registry
        from ..telemetry.prometheus import render_parts

        parts: List[Tuple[Dict[str, str], Dict[str, Any]]] = [
            ({"role": "front"}, global_registry.snapshot())]
        if fleet_scope:
            for rank, ep in sorted(self.fleet.endpoints().items()):
                try:
                    st, snap, _ = http_json(ep["host"], ep["port"], "GET",
                                            "/metrics?format=json",
                                            timeout=_READY_TIMEOUT_S)
                except (OSError, http.client.HTTPException):
                    continue
                if st == 200 and isinstance(snap, dict):
                    parts.append(({"role": "replica",
                                   "replica": str(rank)}, snap))
        return render_parts(parts)

    def drift_payload(self) -> Dict[str, Any]:
        """Fleet-aggregate drift view: one ``/drift`` scrape per live
        replica, merged like the report CLI (same cadence tradeoff as
        ``/metrics/fleet`` — a view endpoint, not a hot path)."""
        replicas: Dict[str, Any] = {}
        any_alerting = False
        available = False
        audit_rows = audit_mismatches = 0
        for rank, ep in sorted(self.fleet.endpoints().items()):
            try:
                st, snap, _ = http_json(ep["host"], ep["port"], "GET",
                                        "/drift",
                                        timeout=_READY_TIMEOUT_S)
            except (OSError, http.client.HTTPException):
                continue
            if st != 200 or not isinstance(snap, dict):
                continue
            replicas[str(rank)] = snap
            available = available or bool(snap.get("available"))
            any_alerting = any_alerting or bool(snap.get("alerting"))
            audit_rows += int(snap.get("audit", {}).get("rows", 0))
            audit_mismatches += int(
                snap.get("audit", {}).get("mismatches", 0))
        return {"available": available, "any_alerting": any_alerting,
                "audit": {"rows": audit_rows,
                          "mismatches": audit_mismatches},
                "replicas": replicas}

    def ready_payload(self) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            ready = dict(self._ready)
        rows = []
        n_ready = 0
        for rank in sorted(ready):
            st = ready[rank]
            ok = bool(st.get("ready"))
            n_ready += int(ok)
            rows.append({
                "rank": rank, "ready": ok,
                "breaker": self.breaker(rank).state,
                **{k: st[k] for k in ("queue_depth", "model_version",
                                      "model_sha256", "generation",
                                      "seen_generation", "degraded",
                                      "heartbeat_age_s") if k in st}})
        return (200 if n_ready else 503), {
            "ready": n_ready > 0, "replicas_ready": n_ready,
            "generation": self.fleet.generation, "replicas": rows}


class _FrontHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log_debug("fleet front http: " + fmt % args)

    @property
    def front(self) -> FanoutFront:
        return self.server.front

    def _send(self, code: int, obj: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            obj = json.loads(raw.decode("utf-8") or "{}")
        except ValueError as e:
            raise LightGBMError(f"request body is not valid JSON: {e}")
        if not isinstance(obj, dict):
            raise LightGBMError("request body must be a JSON object")
        return obj

    def do_GET(self):   # noqa: N802 — http.server API
        path = self.path.split("?")[0]
        if path == "/health":
            alive = sum(1 for _ in self.front.fleet.endpoints())
            self._send(200 if alive else 503,
                       {"status": "ok" if alive else "dead",
                        "replicas_alive": alive,
                        "uptime_s": round(time.time() - self.front.t0, 3)})
        elif path == "/ready":
            self._send(*self.front.ready_payload())
        elif path == "/stats":
            self._send(200, self.front.describe())
        elif path == "/drift":
            self._send(200, self.front.drift_payload())
        elif path in ("/metrics", "/metrics/fleet"):
            from ..telemetry.prometheus import CONTENT_TYPE
            body = self.front.metrics_text(
                fleet_scope=path.endswith("/fleet"))
            raw = body.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)
        else:
            self._send(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):   # noqa: N802
        path = self.path.split("?")[0]
        headers: Optional[Dict[str, str]] = None
        try:
            body = self._read_json()
            if path == "/predict":
                code, obj, headers = self.front.handle_predict(
                    body, dict(self.headers))
            elif path == "/reload":
                code, obj = self.front.handle_reload(body)
            else:
                code, obj = 404, {"error": f"unknown path {self.path!r}"}
        except LightGBMError as e:
            code, obj = 400, {"error": str(e)}
        except Exception as e:  # noqa: BLE001 — the front must answer
            code, obj = 500, {"error": f"{type(e).__name__}: {e}"}
        self._send(code, obj, headers)
