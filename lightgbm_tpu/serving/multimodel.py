"""HBM-resident multi-model serving cache (docs/SERVING.md
"Multi-tenant serving").

Production traffic is per-segment/per-country model FAMILIES, not one
booster (the reference C API is explicitly multi-booster: 98 ``LGBM_*``
handles over reader-writer-locked Booster wrappers).  This module holds
N tenants behind one serving surface:

  * each tenant is a full :class:`ModelRegistry` (manifest-verified
    loads, atomic hot-reload, quality sidecar, per-tenant version/sha
    history) keyed by a caller-chosen ``model_id``;
  * every tenant packs with the DETERMINISTIC rounded shape envelope
    (``compiled.shape_envelope``), so same-family models land on
    identical ``(T, M, C, W, depth)`` traced shapes and SHARE one
    compiled ``serve_predict`` program per bucket — admitting, evicting,
    or promoting a tenant never traces anything new;
  * mixed-tenant micro-batch windows dispatch as ONE model-axis-stacked
    ``serve_predict_multi`` program (``compiled.raw_scores_stacked``)
    instead of a per-tenant launch train;
  * residency is byte-accounted against ``serve_hbm_budget_mb`` with LRU
    eviction.  Evicting drops only the tenant's device arrays — compiled
    programs are keyed by shape and stay cached, so readmission rebuilds
    from the manifest-verified FILE (re-verifying sha256 and re-attaching
    the quality sidecar) and warms with zero recompiles.  In-flight
    requests that pinned the evicting :class:`ServingModel` drain on
    their old reference, exactly like a hot-reload swap.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import LightGBMError, log_info
from .compiled import raw_scores_stacked
from .registry import ModelRegistry, ServingModel

# stacked dispatch caps the model axis here; wider windows chunk.  Keeps
# the (model-slots, bucket) specialization lattice small enough that
# warmup covers it entirely (zero recompiles under live traffic).
MAX_STACK = 8


def parse_model_roster(spec) -> "OrderedDict[str, str]":
    """``serve_models`` parser: ``id=path[,id=path...]`` (or an already
    parsed mapping).  Ids must be short ASCII tokens — they ride a
    length-prefixed field of the binary wire frame."""
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for tok in str(spec or "").split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise LightGBMError(
                    f"serve_models entry {tok!r} must be model_id=path")
            mid, path = tok.split("=", 1)
            items.append((mid.strip(), path.strip()))
    out: "OrderedDict[str, str]" = OrderedDict()
    for mid, path in items:
        if not mid or len(mid) > 64 or not all(
                c.isalnum() or c in "._-" for c in mid):
            raise LightGBMError(
                f"model_id {mid!r} must be 1-64 chars of [A-Za-z0-9._-]")
        if not path:
            raise LightGBMError(f"model_id {mid!r} has an empty path")
        if mid in out:
            raise LightGBMError(f"duplicate model_id {mid!r}")
        out[mid] = path
    if not out:
        raise LightGBMError("serve_models lists no models")
    return out


class MultiModelRegistry:
    """N tenant registries behind the single-model registry surface
    (``current``/``load``/``stats``/``sha_for_version``) plus LRU
    residency and stacked multi-tenant dispatch."""

    def __init__(self, models, *, max_batch: int = 256,
                 buckets_spec: str = "", warmup: bool = True,
                 hbm_budget_mb: float = 0.0,
                 default_id: Optional[str] = None):
        from .. import telemetry

        roster = parse_model_roster(models)
        self._lock = threading.Lock()        # LRU order + counters
        self._max_batch = int(max_batch)
        self._warmup = bool(warmup)
        self.budget_bytes = int(float(hbm_budget_mb) * (1 << 20))
        self.default_id = default_id or next(iter(roster))
        if self.default_id not in roster:
            raise LightGBMError(
                f"default model_id {self.default_id!r} is not in "
                "serve_models")
        self._tenants: "OrderedDict[str, ModelRegistry]" = OrderedDict()
        self._admit_locks: Dict[str, threading.Lock] = {}
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self.readmissions = 0
        for mid, path in roster.items():
            self._admit_locks[mid] = threading.Lock()
            reg = ModelRegistry(path, max_batch=self._max_batch,
                                buckets_spec=buckets_spec,
                                warmup=self._warmup, envelope="auto",
                                model_id=mid)
            self._tenants[mid] = reg
            with self._lock:
                self._lru[mid] = None
        if self._warmup:
            self.warmup_stacked()
        self._enforce_budget()
        telemetry.gauge("serve/cache/models", len(self._tenants))
        log_info(f"multi-model cache: {len(self._tenants)} tenants, "
                 f"{self.resident_bytes()} device bytes resident, budget "
                 f"{self.budget_bytes or 'unlimited'}")

    # -- residency accounting ---------------------------------------------
    def model_ids(self) -> List[str]:
        return list(self._tenants)

    def tenant(self, model_id: Optional[str] = None) -> ModelRegistry:
        mid = model_id or self.default_id
        reg = self._tenants.get(mid)
        if reg is None:
            raise LightGBMError(f"unknown model_id {mid!r}")
        return reg

    def resident_bytes(self) -> int:
        total = 0
        for reg in self._tenants.values():
            model = reg.peek()
            if model is not None:
                total += model.device_bytes()
        return total

    def _touch(self, mid: str) -> None:
        with self._lock:
            self._lru.pop(mid, None)
            self._lru[mid] = None

    def _enforce_budget(self, keep: Optional[str] = None) -> None:
        """Evict least-recently-used tenants until the residency fits the
        byte budget (never evicting ``keep`` or the last resident)."""
        from .. import telemetry
        if self.budget_bytes <= 0:
            return
        while self.resident_bytes() > self.budget_bytes:
            victim = None
            with self._lock:
                for mid in self._lru:
                    if mid == keep:
                        continue
                    if self._tenants[mid].peek() is not None:
                        victim = mid
                        break
            if victim is None:
                return          # nothing evictable (budget < one model)
            self._tenants[victim].evict()
            telemetry.inc(f"model/{victim}/evictions")
            telemetry.gauge("serve/cache/resident_bytes",
                            self.resident_bytes())

    # -- the registry surface ---------------------------------------------
    def current(self, model_id: Optional[str] = None) -> ServingModel:
        """The tenant's resident model, readmitting (manifest-verified
        rebuild) when it was evicted.  Touches the LRU."""
        reg = self.tenant(model_id)
        mid = reg.model_id
        model = reg.peek()
        if model is None:
            with self._admit_locks[mid]:
                model = reg.peek()
                if model is None:
                    model = reg.readmit()
                    from .. import telemetry
                    with self._lock:
                        self.readmissions += 1
                    telemetry.inc(f"model/{mid}/readmissions")
        self._touch(mid)
        self._enforce_budget(keep=mid)
        return model

    def peek(self, model_id: Optional[str] = None) -> Optional[ServingModel]:
        return self.tenant(model_id).peek()

    def load(self, path: str, model_id: Optional[str] = None) -> ServingModel:
        """Hot-reload ONE tenant (promotion path): validate + build +
        warm off to the side, atomic per-tenant swap — sibling tenants
        keep serving their old versions bitwise untouched."""
        reg = self.tenant(model_id)
        model = reg.load(path)
        self._touch(reg.model_id)
        self._enforce_budget(keep=reg.model_id)
        return model

    @property
    def version(self) -> int:
        return self.tenant().version

    def sha_for_version(self, version: int) -> Optional[str]:
        return self.tenant().sha_for_version(version)

    @property
    def reloads_ok(self) -> int:
        return sum(r.reloads_ok for r in self._tenants.values())

    @property
    def reloads_failed(self) -> int:
        return sum(r.reloads_failed for r in self._tenants.values())

    @property
    def evictions(self) -> int:
        return sum(r.evictions for r in self._tenants.values())

    def stats(self) -> Dict[str, Any]:
        models = {mid: reg.stats() for mid, reg in self._tenants.items()}
        with self._lock:
            lru = list(self._lru)
        out: Dict[str, Any] = {
            "reloads_ok": self.reloads_ok,
            "reloads_failed": self.reloads_failed,
            "models": models,
            "cache": {
                "tenants": len(self._tenants),
                "resident": [mid for mid, reg in self._tenants.items()
                             if reg.peek() is not None],
                "lru": lru,
                "resident_bytes": self.resident_bytes(),
                "budget_bytes": self.budget_bytes,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
            },
        }
        cur = self.tenant().peek()
        if cur is not None:
            out["model"] = cur.describe()
        return out

    # -- stacked multi-tenant dispatch ------------------------------------
    @staticmethod
    def _stackable(model: ServingModel, n_rows: int) -> bool:
        c = model._compiled
        return (c is not None and c._host_pack is not None
                and c._lv_dev is not None and 0 < n_rows <= c.buckets[-1])

    def raw_scores_grouped(self, jobs: Sequence[Tuple[ServingModel,
                                                      np.ndarray]]
                           ) -> List[np.ndarray]:
        """Score one micro-batch window of (model, rows) jobs.  Jobs
        whose models share a pack shape dispatch together as ONE
        ``serve_predict_multi`` program (chunked at MAX_STACK models);
        everything else falls back to the per-model path.  Output order
        matches input order; every value is bitwise equal to the job's
        own ``model.raw_scores(rows)``."""
        from .. import telemetry

        out: List[Optional[np.ndarray]] = [None] * len(jobs)
        groups: Dict[Tuple, List[int]] = {}
        for i, (model, X) in enumerate(jobs):
            if self._stackable(model, X.shape[0]):
                key = model._compiled.shape_signature
                groups.setdefault(key, []).append(i)
            else:
                out[i] = model.raw_scores(jobs[i][1])
        for idxs in groups.values():
            for s in range(0, len(idxs), MAX_STACK):
                chunk = idxs[s:s + MAX_STACK]
                if len(chunk) == 1:
                    i = chunk[0]
                    out[i] = jobs[i][0].raw_scores(jobs[i][1])
                    continue
                scores = raw_scores_stacked(
                    [jobs[i][0]._compiled for i in chunk],
                    [jobs[i][1] for i in chunk])
                for i, sc in zip(chunk, scores):
                    out[i] = sc
                telemetry.inc("serve/multi/stacked_dispatches")
                telemetry.inc("serve/multi/stacked_models", len(chunk))
        return out  # type: ignore[return-value]

    def warmup_stacked(self) -> int:
        """Trace every (model-slots, bucket) combination live traffic can
        hit, grouped by pack shape — called at boot BEFORE the budget
        sweep so compiled programs outlive any later eviction."""
        traced = 0
        groups: Dict[Tuple, List[ServingModel]] = {}
        for reg in self._tenants.values():
            model = reg.peek()
            if model is not None and self._stackable(model, 1):
                groups.setdefault(model._compiled.shape_signature,
                                  []).append(model)
        for members in groups.values():
            if len(members) < 2:
                continue
            cap = min(len(members), MAX_STACK)
            g = 2           # slot counts 2, 4, ... up to round-up(cap)
            while True:
                use = members[:min(g, cap)]
                for b in use[0]._compiled.buckets:
                    raw_scores_stacked(
                        [m._compiled for m in use],
                        [np.zeros((b, m.num_features), np.float64)
                         for m in use])
                    traced += 1
                if g >= cap:
                    break
                g *= 2
        return traced
