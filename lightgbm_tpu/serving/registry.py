"""Versioned model registry with validated, atomic hot-reload.

Reference analog: the FastConfig pre-binding of c_api.h:1399-1428 —
everything per-model (packed tree arrays, jitted bucket programs, the
single-row native predictor, the output transform) is bound ONCE at load
time so the request hot path does no setup work.

Hot-reload discipline (the serving half of docs/ROBUSTNESS.md):

  1. the candidate file is validated BEFORE anything is swapped — sha256
     against the robustness manifest sidecar when one exists
     (``<model>.manifest.json``, written by the checkpoint subsystem),
     then the model_io truncation/corruption parse checks, then the
     finite-tree guard;
  2. the full serving state (packed arrays + warmed bucket traces) is
     built off to the side;
  3. the swap is a single reference rebind under a lock — in-flight
     requests that already resolved the old :class:`ServingModel` finish
     against it (drain-by-reference), new requests see the new version.

A failed reload therefore never degrades serving: the old model keeps
answering and the error surfaces to the caller (HTTP 409 on ``/reload``).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..robustness.checkpoint import MANIFEST_SUFFIX
from ..utils.log import LightGBMError, log_info, log_warning
from .compiled import CompiledPredictor, bucket_ladder


class ServingModel:
    """One immutable, fully pre-bound model version."""

    def __init__(self, path: str, model_str: str, sha256: str,
                 max_batch: int = 256,
                 buckets: Optional[List[int]] = None,
                 envelope=None):
        from ..basic import Booster
        from ..predict_fast import SingleRowFastPredictor
        from ..robustness.guards import check_model_trees

        self.path = str(path)
        self.sha256 = sha256
        self.version = 0            # assigned by the registry at swap time
        self.model_id = ""          # assigned by the multi-tenant cache
        self.loaded_unix = time.time()
        booster = Booster(model_str=model_str)   # raises on truncation
        check_model_trees(booster._all_trees(),
                          what=f"serving model {path!r}")
        self._booster = booster
        self._trees = booster._all_trees()
        self.num_trees = len(self._trees)
        self.num_class = booster.num_model_per_iteration()
        self.num_features = booster.num_feature()
        self._average = booster._average_output()
        self._convert = booster._convert_output_np_fn()
        # single-row hot path: native C walk, no device dispatch (factor 1
        # + generic tail below == the Booster.predict n==1 path exactly)
        self._fast = SingleRowFastPredictor(self._trees, self.num_class,
                                            self.num_features)
        # training-time quality reference profile (attached by the
        # registry from the .quality.json sidecar; None when the sidecar
        # is missing/corrupt/mismatched — drift reports available:false)
        self.quality = None
        if envelope == "auto":
            # deterministic rounded-up pack dims: same-family models land
            # on identical traced shapes with no cross-model coordination
            from .compiled import shape_envelope
            envelope = shape_envelope(self._trees)
        try:
            self._compiled: Optional[CompiledPredictor] = CompiledPredictor(
                self._trees, self.num_class, self.num_features,
                max_batch=max_batch, buckets=buckets, envelope=envelope)
        except LightGBMError as e:
            log_warning(f"serving model {path!r}: {e}; batches fall back "
                        "to the host predictor")
            self._compiled = None

    # -- prediction (bitwise identical to Booster.predict) ----------------
    def raw_scores(self, X: np.ndarray) -> np.ndarray:
        """Pre-average raw scores for validated float64 rows."""
        n = X.shape[0]
        k = self.num_class
        if n == 1:
            raw = self._fast.raw_predict(X[0])
            return raw[:1] if k == 1 else raw.reshape(1, k)
        if self._compiled is not None:
            return self._compiled.raw_scores(X)
        # host fallback (linear trees): the exact Booster.predict loop
        if k == 1:
            score = np.zeros(n, np.float64)
            for t in self._trees:
                score += t.predict_raw(X)
            return score
        score = np.zeros((n, k), np.float64)
        for i, t in enumerate(self._trees):
            score[:, i % k] += t.predict_raw(X)
        return score

    def finish(self, score: np.ndarray, raw_score: bool) -> np.ndarray:
        """The Booster.predict tail: averaging + output transform."""
        if self._average and self.num_trees:
            score = score / max(self.num_trees // max(self.num_class, 1), 1)
        if raw_score:
            return score
        return np.asarray(self._convert(score))

    def validate_rows(self, X) -> np.ndarray:
        try:
            X = np.ascontiguousarray(np.asarray(X, np.float64))
        except (ValueError, TypeError) as e:
            # ragged / non-numeric request payloads are client errors
            # (HTTP 400), not server faults
            raise LightGBMError(f"predict rows are not a numeric "
                                f"matrix: {e}")
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.ndim != 2:
            raise LightGBMError(f"predict rows must be 1-D or 2-D, "
                                f"got ndim={X.ndim}")
        if X.shape[1] != self.num_features:
            raise LightGBMError(
                f"The number of features in data ({X.shape[1]}) is not the "
                f"same as it was in training data ({self.num_features})")
        return X

    def predict(self, data, raw_score: bool = False) -> np.ndarray:
        X = self.validate_rows(data)
        if X.shape[0] == 0:
            k = self.num_class
            return np.zeros((0,) if k == 1 else (0, k), np.float64)
        return self.finish(self.raw_scores(X), raw_score)

    def explain_raw(self, X: np.ndarray) -> np.ndarray:
        """SHAP contributions for validated float64 rows — the exact
        ``Booster.predict(pred_contrib=True)`` contract: (n, F+1) per
        class with the expected value last, multiclass flattened to
        (n, k*(F+1)).  No averaging/transform tail applies."""
        from ..shap import predict_contrib
        return predict_contrib(self._trees, X, self.num_class)

    def device_bytes(self) -> int:
        """Device residency this version pins (0 for host-fallback
        models) — the multi-tenant cache's HBM accounting unit."""
        return (self._compiled.device_bytes()
                if self._compiled is not None else 0)

    def describe(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "model_id": self.model_id,
            "device_bytes": self.device_bytes(),
            "path": self.path,
            "sha256": self.sha256,
            "num_trees": self.num_trees,
            "num_class": self.num_class,
            "num_features": self.num_features,
            "compiled": self._compiled is not None,
            "buckets": list(self._compiled.buckets) if self._compiled else [],
            "loaded_unix": self.loaded_unix,
            "quality": self.quality is not None,
        }


def _check_manifest(path: str, data: bytes) -> Optional[str]:
    """Verify ``data`` against the robustness manifest sidecar when one
    exists; returns the sha256 hex of ``data`` either way."""
    sha = hashlib.sha256(data).hexdigest()
    mpath = path + MANIFEST_SUFFIX
    if os.path.exists(mpath):
        try:
            with open(mpath) as fh:
                manifest = json.load(fh)
        except ValueError as e:
            raise LightGBMError(
                f"serving model manifest {mpath!r} is not valid JSON: {e}")
        # "model_sha256" is the field write_checkpoint seals into the
        # manifest (robustness/checkpoint.py)
        want = manifest.get("model_sha256")
        if want and want != sha:
            raise LightGBMError(
                f"serving model {path!r} failed its manifest sha256 check "
                f"(manifest {want[:12]}..., file {sha[:12]}...) — the file "
                "was modified or truncated after the manifest was sealed")
    return sha


class ModelRegistry:
    """Holds the live :class:`ServingModel` plus monotone version numbers;
    ``load`` is both first load and hot-reload."""

    def __init__(self, path: Optional[str] = None, *,
                 max_batch: int = 256, buckets_spec: str = "",
                 warmup: bool = True, envelope=None, model_id: str = ""):
        self._lock = threading.Lock()
        self._current: Optional[ServingModel] = None
        self._version = 0
        self._max_batch = int(max_batch)
        self._buckets = (bucket_ladder(max_batch, buckets_spec)
                         if buckets_spec else None)
        self._warmup = bool(warmup)
        self._envelope = envelope
        self.model_id = str(model_id)
        self._path = str(path) if path else None
        self.reloads_ok = 0
        self.reloads_failed = 0
        self.evictions = 0
        # fleet promotion keying: the (model_id, generation) a replica
        # last applied for THIS tenant (stamped by the fleet's pointer
        # watcher; None for standalone registries)
        self.generation: Optional[int] = None
        self.seen_generation: Optional[int] = None
        # version -> sha256 for every model this registry ever served:
        # responses stamp both, so a fleet front (or an auditor) can map
        # any response to the exact bytes that scored it even across
        # replica-local version counters
        self._sha_by_version: Dict[int, str] = {}
        if path:
            self.load(path)

    def load(self, path: str) -> ServingModel:
        """Validate + build + warm a candidate, then atomically swap it
        in.  Raises (keeping the old model) on any validation failure."""
        from .. import telemetry

        t0 = time.perf_counter()
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            sha = _check_manifest(str(path), data)
            model = ServingModel(str(path), data.decode("utf-8"), sha,
                                 max_batch=self._max_batch,
                                 buckets=self._buckets,
                                 envelope=self._envelope)
            model.model_id = self.model_id
            if self._warmup and model._compiled is not None:
                model._compiled.warmup()
            # quality sidecar rides the model path, so hot-reload and
            # fleet promotion carry it for free; a bad sidecar degrades
            # to quality=None, never a load failure
            from ..telemetry.quality import QualityProfile
            model.quality = QualityProfile.load_for_model(str(path), sha)
        except (OSError, UnicodeDecodeError) as e:
            # counters mutate under the lock: /reload handler threads and
            # an embedding caller can race here (lgbtlint LGB006)
            with self._lock:
                self.reloads_failed += 1
            telemetry.inc("serve/reload_failed")
            raise LightGBMError(f"cannot load serving model {path!r}: {e}")
        except LightGBMError:
            with self._lock:
                self.reloads_failed += 1
            telemetry.inc("serve/reload_failed")
            raise
        with self._lock:
            self._version += 1
            model.version = self._version
            self._current = model
            self._path = str(path)
            self._sha_by_version[model.version] = sha
            self.reloads_ok += 1
        telemetry.inc("serve/reloads")
        telemetry.instant("serve:reload", version=model.version,
                          sha256=sha[:12])
        log_info(f"serving model v{model.version} loaded from {path} "
                 f"({model.num_trees} trees, sha256 {sha[:12]}, "
                 f"{time.perf_counter() - t0:.2f}s incl. warmup)")
        return model

    def current(self, model_id: Optional[str] = None) -> ServingModel:
        if model_id and model_id != self.model_id:
            # single-model registry: any explicit foreign id is a client
            # routing error, never silently served by the wrong model
            raise LightGBMError(f"unknown model_id {model_id!r}")
        with self._lock:
            if self._current is None:
                raise LightGBMError("model registry is empty — load a "
                                    "model before serving")
            return self._current

    def peek(self) -> Optional[ServingModel]:
        """The resident model WITHOUT readmission side effects (None when
        empty/evicted) — maintenance loops use this so a 1 Hz tick never
        thrashes the multi-tenant LRU."""
        with self._lock:
            return self._current

    def evict(self) -> Optional[ServingModel]:
        """Drop the resident model reference (the multi-tenant cache's
        LRU eviction).  In-flight requests that already pinned the old
        :class:`ServingModel` drain against it (drain-by-reference);
        readmission goes back through :meth:`load`, which re-verifies the
        manifest sha256 and re-attaches the quality sidecar from the
        file — an evicted entry can never be resurrected from stale
        state."""
        with self._lock:
            model, self._current = self._current, None
            if model is not None:
                self.evictions += 1
        return model

    def readmit(self) -> ServingModel:
        """Rebuild from the last-served path (manifest-verified, sidecar
        re-attached, fresh version number)."""
        if not self._path:
            raise LightGBMError("model registry has no path to readmit")
        return self.load(self._path)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def sha_for_version(self, version: int) -> Optional[str]:
        with self._lock:
            return self._sha_by_version.get(int(version))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            cur = self._current
            out = {"reloads_ok": self.reloads_ok,
                   "reloads_failed": self.reloads_failed,
                   "evictions": self.evictions,
                   "resident": cur is not None}
        if cur is not None:
            out["model"] = cur.describe()
        return out
