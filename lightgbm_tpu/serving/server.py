"""JSON-over-HTTP serving front end (stdlib ``http.server`` only).

HTTP/1.1 with keep-alive: a client reusing its connection pays the TCP
handshake once, not per request.  ``serve_binary_port >= 0`` additionally
opens the persistent-connection binary row wire (:mod:`.wire`) next to
HTTP — same registry and micro-batcher, length-prefixed f32 frames
instead of JSON (docs/SERVING.md "Binary wire protocol") — the 10k+ QPS
path.

Endpoints:

  ``POST /predict``  body {"rows": [[...], ...]} or {"row": [...]},
                     optional "raw_score" (bool), "fast" (bool — run a
                     single row synchronously on the native walk, no
                     queueing) and "model_id" (multi-tenant routing;
                     unknown ids reply 400); replies {"predictions",
                     "model_version", "batched_rows", "latency_ms"} plus
                     "model_id"/"model_sha256".  A full queue replies
                     503 with the structured overload payload; shape
                     errors reply 400.
  ``POST /explain``  same body shape (no "fast"); replies per-row SHAP
                     contributions under "contributions" — exactly the
                     reference's ``pred_contrib`` layout, k*(n_features
                     +1) values per row with the expected value last per
                     class.  Runs on its OWN micro-batcher lane
                     (``serve_explain_*`` knobs) so heavy explanation
                     traffic cannot starve the predict path.
  ``GET  /health``   LIVENESS only: is the process up and the batch
                     worker thread alive (503 when the worker died).
  ``GET  /ready``    READINESS: queue depth, active model version +
                     sha256, promotion generation, degraded state and
                     heartbeat age — what a fleet front or supervisor
                     keys routing off (503 while draining / dead /
                     model-less).
  ``POST /reload``   {"path": optional} — validated atomic hot-swap; a
                     rejected candidate replies 409 and the old version
                     keeps serving.
  ``GET  /stats``    latency/queue-depth percentiles from the telemetry
                     registry, request counters, recompile watchdog
                     counts, model + registry info, SLO burn state and
                     the tail-capture ring.
  ``GET  /metrics``  Prometheus text exposition of the process metrics
                     registry (counters/gauges/cumulative-bucket
                     histograms); ``?format=json`` returns the raw
                     snapshot (what the fleet aggregate scrapes).

Distributed tracing (docs/OBSERVABILITY.md "Serving observability"): a
``/predict`` request carries its trace context in the ``X-LGBTPU-Trace``
header — accepted from the front (which minted the id and the
head-sampling decision) or minted here for direct clients.  Sampled
requests emit spans through admission -> batcher queue wait -> device
dispatch; errored and SLO-violating requests are tail-captured into a
bounded ring regardless of sampling; every request can be access-logged
as JSONL (``serve_access_log``).

Request resilience (docs/SERVING.md "Fleet architecture"): a ``/predict``
body may carry ``deadline_ms`` — the client's remaining budget.  The
budget propagates through queue admission and the batcher's pre-dispatch
check, so expired requests are shed as structured 503s instead of being
scored for nobody.  Every shed 503 carries a ``Retry-After`` header.

Shutdown: ``shutdown(drain=True)`` (wired to SIGTERM/SIGINT by
``run_server``) stops accepting connections, lets the batcher drain
everything already queued, then returns — a rolling restart loses zero
admitted requests.
"""
from __future__ import annotations

import json
import math
import signal
import socket
import threading
import time
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from ..robustness import chaos
from ..utils.log import LightGBMError, log_debug, log_info
from .batcher import DeadlineError, MicroBatcher, OverloadError
from .registry import ModelRegistry

_REQUEST_TIMEOUT_S = 30.0


def _jsonable(values: np.ndarray):
    v = np.asarray(values)
    return v.tolist()


def reuseport_available() -> bool:
    """Can several sockets share one listen port on this platform?
    (SO_REUSEPORT kernel load-balancing — Linux >= 3.9 and the BSDs;
    absent on some platforms, where the fleet uses the fanout front.)"""
    if not hasattr(socket, "SO_REUSEPORT"):
        return False
    try:
        with socket.socket() as a, socket.socket() as b:
            for s in (a, b):
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            a.bind(("127.0.0.1", 0))
            b.bind(("127.0.0.1", a.getsockname()[1]))
        return True
    except OSError:
        return False


class _ReusePortHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that joins an SO_REUSEPORT group before bind,
    so N replica processes share one listen port and the kernel balances
    accepted connections across them."""

    def server_bind(self):
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        ThreadingHTTPServer.server_bind(self)


class ServingApp:
    """Registry + batcher + HTTP server, wired together."""

    def __init__(self, model_path: str, *, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 256,
                 max_delay_ms: float = 2.0, queue_size: int = 512,
                 buckets_spec: str = "", warmup: bool = True,
                 heartbeat_path: str = "", deadline_ms: float = 0.0,
                 reuse_port: bool = False, trace_sample: float = 0.01,
                 trace_tail: int = 256, access_log: str = "",
                 slo_availability: float = 0.999, slo_p99_ms: float = 0.0,
                 slo_window_s: float = 60.0, slo_burn: float = 14.4,
                 binary_port: int = -1, binary_accept_threads: int = 2,
                 quality_sample: float = 0.01,
                 quality_audit_sample: float = 0.01,
                 drift_threshold: float = 0.2, drift_window_s: float = 60.0,
                 quality_min_rows: int = 200, quality_topk: int = 5,
                 models=None, hbm_budget_mb: float = 0.0,
                 default_model_id: str = "",
                 explain_max_batch: int = 16,
                 explain_queue_size: int = 64,
                 explain_max_delay_ms: float = 2.0):
        from ..telemetry import AccessLog, TailRing
        from ..telemetry.quality import QualityMonitor
        from .slo import SLOMonitor

        # multi-tenant: serve_models roster -> HBM-resident LRU cache of
        # tenant registries (docs/SERVING.md "Multi-tenant serving");
        # single-model keeps the flat registry surface unchanged
        self.multi = bool(models)
        if self.multi:
            from .multimodel import MultiModelRegistry
            self.registry = MultiModelRegistry(
                models, max_batch=max_batch, buckets_spec=buckets_spec,
                warmup=warmup, hbm_budget_mb=hbm_budget_mb,
                default_id=default_model_id or None)
        else:
            self.registry = ModelRegistry(model_path, max_batch=max_batch,
                                          buckets_spec=buckets_spec,
                                          warmup=warmup)
        self.batcher = MicroBatcher(self.registry, max_batch=max_batch,
                                    max_delay_ms=max_delay_ms,
                                    queue_size=queue_size,
                                    heartbeat_path=heartbeat_path)
        # the explain lane: its own bounded queue + worker + bucket
        # ladder, so deadline-bounded SHAP traffic coalesces on device
        # without starving /predict
        self.explain_batcher = MicroBatcher(
            self.registry, max_batch=explain_max_batch,
            max_delay_ms=explain_max_delay_ms,
            queue_size=explain_queue_size, mode="explain")
        server_cls = _ReusePortHTTPServer if reuse_port \
            else ThreadingHTTPServer
        self._httpd = server_cls((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.app = self          # handler back-pointer
        # binary row wire next to HTTP (serve_binary_port >= 0; 0 picks
        # an ephemeral port) — same registry + batcher, frames instead of
        # JSON (docs/SERVING.md "Binary wire protocol")
        self.binary = None
        if int(binary_port) >= 0:
            from .wire import BinaryServer
            self.binary = BinaryServer(self, host=host,
                                       port=int(binary_port),
                                       accept_threads=binary_accept_threads,
                                       reuse_port=reuse_port)
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        # default per-request budget (ms) when the body carries no
        # deadline_ms; 0 = unbounded (legacy 30 s future-wait only)
        self.deadline_ms = float(deadline_ms or 0.0)
        # fleet-runtime state (set by serving.fleet's replica loop;
        # standalone servers keep the defaults)
        self.replica_rank: Optional[int] = None
        self.generation: Optional[int] = None
        self.seen_generation: Optional[int] = None
        self.degraded: Optional[str] = None
        # fleet replicas route /reload through the shared promotion
        # pointer so ANY replica's reload is fleet-wide; standalone
        # servers keep the registry-local swap
        self.promote_fn = None
        # request observability (docs/OBSERVABILITY.md "Serving
        # observability"): head-sampled trace spans, tail capture of
        # errored/SLO-violating requests, JSONL access log, and the
        # error-budget burn monitor feeding /ready + /metrics
        self.trace_sample = max(float(trace_sample), 0.0)
        self.tail = TailRing(trace_tail)
        self.access_log = AccessLog(access_log) if access_log else None
        self.slo = SLOMonitor(availability_target=slo_availability,
                              p99_target_ms=slo_p99_ms,
                              window_s=slo_window_s,
                              burn_threshold=slo_burn)
        # per-tenant SLO isolation (multi only): one burn monitor per
        # model_id so one tenant's chaos fires ITS alert while siblings
        # stay green; the flat self.slo keeps judging the whole replica
        self.slo_by_model: Dict[str, Any] = {}
        if self.multi:
            self.slo_by_model = {
                mid: SLOMonitor(availability_target=slo_availability,
                                p99_target_ms=slo_p99_ms,
                                window_s=slo_window_s,
                                burn_threshold=slo_burn)
                for mid in self.registry.model_ids()}
        # data/model quality: drift monitor + shadow audit riding the
        # batcher dispatch path; the sidecar profile follows the registry
        # model (docs/OBSERVABILITY.md "Data & model quality").  Multi-
        # tenant apps run one monitor per model_id — each tenant's drift
        # window accumulates only its own traffic — and self.quality
        # aliases the default tenant's monitor so the flat /drift surface
        # keeps working
        self.quality_by_model: Dict[str, Any] = {}
        if self.multi:
            for mid in self.registry.model_ids():
                self.quality_by_model[mid] = QualityMonitor(
                    threshold=drift_threshold, window_s=drift_window_s,
                    sample=quality_sample,
                    audit_sample=quality_audit_sample,
                    min_rows=quality_min_rows, topk=quality_topk)
            self.quality = self.quality_by_model[self.registry.default_id]
        else:
            self.quality = QualityMonitor(threshold=drift_threshold,
                                          window_s=drift_window_s,
                                          sample=quality_sample,
                                          audit_sample=quality_audit_sample,
                                          min_rows=quality_min_rows,
                                          topk=quality_topk)
        if self.quality.enabled:
            if self.multi:
                self.batcher.quality_lookup = self._quality_for
            else:
                self.batcher.quality = self.quality
        # per-replica drift snapshot export for the fleet report CLI
        # (set by serving.fleet's replica loop)
        self.drift_export_path: str = ""
        # the SLO ticker runs on its own loop (not per-request) so an
        # alert also CLEARS while the replica is idle — e.g. when the
        # front stopped routing here because of the very burn that fired
        self._slo_stop = threading.Event()
        self._slo_thread: Optional[threading.Thread] = None
        self.t0 = time.time()

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def binary_port(self) -> Optional[int]:
        return self.binary.port if self.binary is not None else None

    @property
    def draining(self) -> bool:
        return self._draining

    def _quality_for(self, model_id: str):
        """Batcher hook: route quality accumulation to the tenant's own
        monitor (falls back to the default tenant's for legacy "")."""
        q = self.quality_by_model.get(model_id) if model_id \
            else self.quality
        return q if (q is not None and q.enabled) else None

    def _slo_loop(self) -> None:
        while not self._slo_stop.wait(1.0):
            # per-model monitors tick FIRST so the aggregate's gauges win
            # the shared slo/* gauge names
            for mon in self.slo_by_model.values():
                mon.tick()
            self.slo.tick()
            if self.quality.enabled:
                try:
                    if self.multi:
                        for mid, q in self.quality_by_model.items():
                            # peek, never current(): a 1 Hz tick must not
                            # readmit evicted tenants or touch the LRU
                            model = self.registry.peek(mid)
                            if model is not None:
                                q.tick(model=model)
                            q.audit_once()
                    else:
                        self.quality.tick(model=self.registry.current())
                        self.quality.audit_once()
                    if self.drift_export_path:
                        from ..telemetry.quality import write_snapshot
                        write_snapshot(self.drift_export_path,
                                       self.quality.snapshot())
                except Exception as e:   # noqa: BLE001 — ticker survives
                    log_debug(f"quality tick failed: {e}")

    def start(self) -> "ServingApp":
        """Non-blocking start (tests, embedding); ``run_server`` blocks."""
        self.batcher.start()
        self.explain_batcher.start()
        self._slo_thread = threading.Thread(target=self._slo_loop,
                                            name="lgbtpu-serve-slo",
                                            daemon=True)
        self._slo_thread.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="lgbtpu-serve-http",
                                        daemon=True)
        self._thread.start()
        if self.binary is not None:
            self.binary.start()
        log_info(f"serving on http://{self.host}:{self.port} "
                 + (f"+ binary :{self.binary.port} "
                    if self.binary is not None else "")
                 + f"(model v{self.registry.version})")
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Stop accepting, drain the queue (unless ``drain=False``), stop
        the worker.  Idempotent."""
        self._draining = True
        self._slo_stop.set()
        if self.binary is not None:
            self.binary.stop_accepting()
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.stop(drain=drain)
        self.explain_batcher.stop(drain=drain)
        if self.binary is not None:
            self.binary.stop()      # after the drain: futures resolved
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(5.0)
        if self._slo_thread is not None and self._slo_thread.is_alive():
            self._slo_thread.join(2.0)
        if self.access_log is not None:
            self.access_log.close()

    def note_request(self, ctx, status: int, latency_ms: float,
                     deadline_ms: float, obj: Dict[str, Any]) -> None:
        """Per-request bookkeeping after the response is decided: SLO
        outcome, access-log line, tail capture of the interesting ones.
        Must never raise — it runs on the answer path."""
        from ..telemetry.context import note_outcome

        extra: Dict[str, Any] = {"rows": obj.get("batched_rows")}
        if self.replica_rank is not None:
            extra["replica"] = self.replica_rank
        # drift snapshot rides the access log only while the alert is
        # active — healthy traffic logs stay lean
        drift = self.quality.brief()
        if drift is not None:
            extra["drift"] = drift
        # per-tenant SLO isolation: the request's model_id (stamped into
        # the response, error paths included) burns ONLY that model's
        # window — chaos against tenant A never pages tenant B
        mid = obj.get("model_id")
        mon = self.slo_by_model.get(mid) if mid else None
        if mon is not None:
            mon.record(status, latency_ms)
        # replicas see single attempts (retries=0); the front stamps
        # real retry counts in ITS log
        note_outcome(ctx=ctx, status=status, latency_ms=latency_ms,
                     deadline_ms=deadline_ms, obj=obj, slo=self.slo,
                     tail=self.tail, access_log=self.access_log,
                     extra=extra)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):   # route access logs off stderr
        log_debug("serve http: " + fmt % args)

    @property
    def app(self) -> ServingApp:
        return self.server.app

    def _send(self, code: int, obj: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str, content_type: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _drop_connection(self) -> None:
        """Chaos ``drop_conn``: reset the client socket mid-request —
        the transport failure the fanout front must absorb as a retry."""
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.close_connection = True

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            obj = json.loads(raw.decode("utf-8") or "{}")
        except ValueError as e:
            raise LightGBMError(f"request body is not valid JSON: {e}")
        if not isinstance(obj, dict):
            raise LightGBMError("request body must be a JSON object")
        return obj

    # -- routes ------------------------------------------------------------
    def do_GET(self):   # noqa: N802 — http.server API
        from .. import telemetry

        path = self.path.split("?")[0]
        try:
            chaos.request_hook()
        except chaos.DropConnection:
            self._drop_connection()
            return
        if path == "/health":
            self._send(*self._health())
        elif path == "/ready":
            self._send(*self._ready())
        elif path == "/stats":
            with telemetry.span("serve/stats"):
                self._send(200, self._stats())
        elif path == "/drift":
            # data/model quality surface: alert state, top-k drifted
            # features with PSI/JS, shadow-audit totals; available:false
            # (never zeros) when the model has no quality sidecar
            self._send(200, self.app.quality.snapshot())
        elif path == "/metrics":
            # Prometheus text exposition of the process registry;
            # ?format=json returns the raw snapshot (what the fleet
            # aggregator scrapes to relabel under replica="<r>")
            from ..telemetry.prometheus import CONTENT_TYPE, registry_text
            query = self.path.partition("?")[2]
            if "format=json" in query:
                self._send(200, telemetry.global_registry.snapshot())
            else:
                labels = {}
                if self.app.replica_rank is not None:
                    labels["replica"] = str(self.app.replica_rank)
                self._send_text(200, registry_text(labels=labels),
                                CONTENT_TYPE)
        else:
            self._send(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):   # noqa: N802
        from .. import telemetry

        path = self.path.split("?")[0]
        headers: Dict[str, str] = {}
        ctx = None
        t_req = time.perf_counter()
        deadline_ms = 0.0
        req_model_id = ""
        try:
            # the body must be consumed on EVERY branch — HTTP/1.1
            # keep-alive leaves unread bytes in rfile and the next request
            # on the connection would parse mid-body
            body = self._read_json()
            chaos.request_hook()
            if path in ("/predict", "/explain"):
                # trace context: accept the front's (or client's) header,
                # mint locally otherwise — the head-sampling decision is
                # taken exactly once per request, at the outermost tier
                ctx = telemetry.TraceContext.from_header(
                    self.headers.get(telemetry.TRACE_HEADER))
                if ctx is None:
                    ctx = telemetry.TraceContext.mint(self.app.trace_sample)
                try:
                    deadline_ms = float(body.get("deadline_ms",
                                                 self.app.deadline_ms)
                                        or 0.0)
                except (TypeError, ValueError):
                    deadline_ms = 0.0
                req_model_id = str(body.get("model_id") or "")
                with telemetry.request_span(
                        ctx, "serve" + path,
                        replica=self.app.replica_rank):
                    if path == "/predict":
                        code, obj = self._predict(body, ctx)
                    else:
                        code, obj = self._explain(body, ctx)
            elif path == "/reload":
                with telemetry.span("serve/reload"):
                    code, obj = self._reload(body)
            else:
                code, obj = 404, {"error": f"unknown path {self.path!r}"}
        except chaos.DropConnection:
            self._drop_connection()
            return
        except OverloadError as e:
            code, obj = 503, e.payload()
            # RFC 7231 Retry-After is integer seconds; the structured
            # body carries the float for backoff-aware clients
            headers["Retry-After"] = str(
                max(int(math.ceil(e.retry_after_s)), 0))
        except LightGBMError as e:
            code, obj = 400, {"error": str(e)}
        except CancelledError:
            # shutdown(drain=False) cancelled the future mid-wait; on
            # CPython >= 3.8 CancelledError is a BaseException, so the
            # generic net below would miss it and reset the connection
            code, obj = 503, {"error": "shutting down"}
        except Exception as e:  # noqa: BLE001 — serving must answer
            code, obj = 500, {"error": f"{type(e).__name__}: {e}"}
        if req_model_id:
            # error replies carry the routing key too, so per-model SLO
            # attribution (note_request) sees failures, not just 200s
            obj.setdefault("model_id", req_model_id)
        if ctx is not None:
            obj.setdefault("trace_id", ctx.trace_id)
            headers[telemetry.TRACE_HEADER] = ctx.header_value()
            try:
                self.app.note_request(
                    ctx, code, (time.perf_counter() - t_req) * 1e3,
                    deadline_ms, obj)
            except Exception as e:  # noqa: BLE001 — never fail the answer
                log_debug(f"serve note_request failed: {e}")
        self._send(code, obj, headers or None)

    def _predict(self, body, ctx=None):
        return self._scored(body, ctx, self.app.batcher, "predictions")

    def _explain(self, body, ctx=None):
        """Device-batched SHAP on the explain lane — the values are the
        reference's ``pred_contrib`` contract verbatim."""
        return self._scored(body, ctx, self.app.explain_batcher,
                            "contributions")

    def _scored(self, body, ctx, batcher, values_key: str):
        app = self.app
        if app.draining:
            raise OverloadError(batcher.queue_depth(),
                                batcher.queue_size, reason="draining",
                                retry_after_s=1.0)
        rows = body.get("rows", body.get("row"))
        if rows is None:
            kind = "predict" if values_key == "predictions" else "explain"
            return 400, {"error": f'{kind} body needs "rows" (matrix) '
                                  'or "row" (vector)'}
        t0 = time.perf_counter()
        # client budget: body deadline_ms overrides the server default;
        # <= 0 means "no deadline" either way
        try:
            budget_ms = float(body.get("deadline_ms", app.deadline_ms) or 0.0)
        except (TypeError, ValueError):
            return 400, {"error": "deadline_ms must be a number"}
        deadline = t0 + budget_ms / 1e3 if budget_ms > 0 else None
        fut = batcher.submit(rows,
                             raw_score=bool(body.get("raw_score", False)),
                             fast=bool(body.get("fast", False)),
                             deadline=deadline, trace=ctx,
                             model_id=str(body.get("model_id") or "")
                             or None)
        wait = _REQUEST_TIMEOUT_S if deadline is None else \
            max(deadline - time.perf_counter(), 0.0)
        try:
            res = fut.result(timeout=wait)
        except FutureTimeoutError:
            # the wait itself ran out the budget: report it as the same
            # structured deadline shed the batcher would have raised
            fut.cancel()
            raise DeadlineError(batcher.queue_depth(),
                                batcher.queue_size)
        sha = res.sha256 or app.registry.sha_for_version(res.model_version)
        out = {
            values_key: _jsonable(res.values),
            "model_version": res.model_version,
            "model_sha256": sha,
            "batched_rows": res.batched_rows,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        if res.model_id:
            out["model_id"] = res.model_id
        if app.replica_rank is not None:
            out["replica"] = app.replica_rank
        return 200, out

    def _reload(self, body):
        app = self.app
        mid = str(body.get("model_id") or "")
        if mid and not app.multi:
            return 400, {"error": "model_id routing needs serve_models "
                                  "(multi-tenant serving)"}
        path = str(body.get("path")
                   or app.registry.current(mid or None).path)
        if app.promote_fn is not None:
            # fleet replica: validate + advance the shared pointer; every
            # replica (this one included) applies it via its watcher
            try:
                return 200, (app.promote_fn(path, mid) if mid
                             else app.promote_fn(path))
            except LightGBMError as e:
                return 409, {"error": str(e),
                             "model_version": app.registry.version}
        try:
            model = (app.registry.load(path, mid) if mid
                     else app.registry.load(path))
        except LightGBMError as e:
            # the candidate was rejected; the old version keeps serving
            return 409, {"error": str(e),
                         "model_version": app.registry.version}
        out = {"model_version": model.version,
               "num_trees": model.num_trees,
               "sha256": model.sha256}
        if mid:
            out["model_id"] = mid
        return 200, out

    def _health(self):
        """LIVENESS: is this process worth keeping alive?  Deliberately
        ignores model/queue state — a draining or degraded replica is
        still alive; restarting it would lose work for nothing."""
        from ..robustness.heartbeat import heartbeat_age

        app = self.app
        alive = app.batcher.worker_alive
        out: Dict[str, Any] = {
            "status": ("draining" if app.draining
                       else "ok" if alive else "dead"),
            "model_version": app.registry.version,
            "uptime_s": round(time.time() - app.t0, 3),
            "queue_depth": app.batcher.queue_depth(),
            "worker_alive": alive,
        }
        if app.batcher.heartbeat_path:
            age = heartbeat_age(app.batcher.heartbeat_path)
            if age is not None:
                out["heartbeat_age_s"] = round(age, 3)
        return (200 if alive else 503), out

    def _ready(self):
        """READINESS: should traffic be routed here right now?  The
        fanout front and the fleet supervisor key off THIS (not
        liveness): a replica that is draining, model-less, or whose
        worker died gets no traffic but is reaped/restarted only on
        liveness signals.  A degraded replica (rejected promotion
        candidate) stays ready — it serves its old version — and
        surfaces the reason here."""
        from ..robustness.heartbeat import heartbeat_age

        app = self.app
        b = app.batcher
        ready = (b.worker_alive and not app.draining
                 and app.registry.version > 0)
        out: Dict[str, Any] = {
            "ready": ready,
            "queue_depth": b.queue_depth(),
            "queue_size": b.queue_size,
            "model_version": app.registry.version,
            "draining": app.draining,
        }
        cur = None
        try:
            cur = app.registry.current()
        except LightGBMError:
            pass
        if cur is not None:
            out["model_sha256"] = cur.sha256
        if app.replica_rank is not None:
            out["replica"] = app.replica_rank
        if app.generation is not None:
            out["generation"] = app.generation
        if app.seen_generation is not None:
            out["seen_generation"] = app.seen_generation
        # degraded reasons compose: a rejected promotion and a burning
        # error budget are both "degraded but still serving" states —
        # neither flips readiness (unrouting a replica because it is slow
        # would finish the outage), both must be visible to the fleet
        reasons = []
        if app.degraded:
            reasons.append(app.degraded)
        slo_state = app.slo.state()
        if slo_state["alerting"]:
            out["slo_alert"] = slo_state["alert"]
            reasons.append(f"slo burn: {slo_state['alert']} error budget "
                           f"burning >= {app.slo.burn_threshold:.1f}x")
        if not app.multi and app.quality.alerting:
            # drift is a quality degradation, not an outage: the replica
            # keeps serving (stale != broken), the reason surfaces here
            # and the refit pipeline keys off the drift/* gauges
            out["drift_alert"] = True
            reasons.append(f"data drift: PSI >= "
                           f"{app.quality.threshold:g} vs training "
                           "reference (see /drift)")
        if app.multi:
            # per-tenant readiness: each model's version/sha/residency
            # and ITS OWN alert state — one tenant's burn or drift names
            # only that tenant in the degraded reason, siblings stay
            # green (the isolation contract)
            models_out: Dict[str, Any] = {}
            for mid in app.registry.model_ids():
                reg = app.registry.tenant(mid)
                resident = reg.peek()
                m: Dict[str, Any] = {
                    "version": reg.version,
                    "resident": resident is not None,
                }
                if resident is not None:
                    m["sha256"] = resident.sha256
                if reg.generation is not None:
                    m["generation"] = reg.generation
                if reg.seen_generation is not None:
                    m["seen_generation"] = reg.seen_generation
                mon = app.slo_by_model.get(mid)
                if mon is not None:
                    mstate = mon.state()
                    if mstate["alerting"]:
                        m["slo_alert"] = mstate["alert"]
                        reasons.append(
                            f"model {mid}: slo burn {mstate['alert']}")
                q = app.quality_by_model.get(mid)
                if q is not None and q.alerting:
                    m["drift_alert"] = True
                    reasons.append(f"model {mid}: data drift (PSI >= "
                                   f"{q.threshold:g})")
                models_out[mid] = m
            out["models"] = models_out
        if reasons:
            out["degraded"] = "; ".join(reasons)
        if b.heartbeat_path:
            age = heartbeat_age(b.heartbeat_path)
            if age is not None:
                out["heartbeat_age_s"] = round(age, 3)
        return (200 if ready else 503), out

    def _stats(self) -> Dict[str, Any]:
        from .. import telemetry

        app = self.app
        out = {
            "uptime_s": round(time.time() - app.t0, 3),
            "registry": app.registry.stats(),
            "queue_depth": app.batcher.queue_depth(),
            "served": app.batcher.served,
            "batches": app.batcher.batches,
            "rejected": app.batcher.rejected,
            "deadline_expired": app.batcher.expired,
            "explain": {
                "served": app.explain_batcher.served,
                "batches": app.explain_batcher.batches,
                "rejected": app.explain_batcher.rejected,
                "deadline_expired": app.explain_batcher.expired,
                "queue_depth": app.explain_batcher.queue_depth(),
                "dispatch": telemetry.quantiles(
                    "serve/explain/dispatch_s"),
            },
            "degraded": app.degraded,
            "generation": app.generation,
            "latency": telemetry.quantiles("serve/latency_s"),
            "dispatch": telemetry.quantiles("serve/dispatch_s"),
            "batch_rows": telemetry.quantiles("serve/batch_rows"),
            "queue_depth_dist": telemetry.quantiles("serve/queue_depth"),
            "recompiles": {k: v for k, v in
                           telemetry.recompile_counts().items()
                           if k.startswith("serve")},
            # XLA cost records for the serving entry points (flops/bytes/
            # peak HBM + roofline verdict per compiled bucket program);
            # the full rollup incl. roofline peaks rides telemetry_summary
            "cost": telemetry.cost_summary(),
            "slo": app.slo.state(),
            "quality": {"available": app.quality.snapshot().get(
                            "available", False),
                        "alerting": app.quality.alerting,
                        "sample": app.quality.sample,
                        "audit_sample": app.quality.audit_sample},
            "trace_tail": app.tail.snapshot(last=20),
            "trace_sample": app.trace_sample,
            "binary": (app.binary.stats() if app.binary is not None
                       else None),
        }
        if app.multi:
            out["slo_models"] = {
                mid: {"alerting": mon.state()["alerting"],
                      "alert": mon.state()["alert"]}
                for mid, mon in app.slo_by_model.items()}
            out["quality_models"] = {
                mid: {"alerting": q.alerting}
                for mid, q in app.quality_by_model.items()}
        return out


def serve_from_params(params: Dict[str, Any]) -> ServingApp:
    """Build (not start) a ServingApp from resolved CLI/conf params."""
    from ..config import Config

    cfg = Config.from_params(params)
    model_path = str(params.get("input_model", "") or "")
    if not model_path and not cfg.serve_models:
        raise LightGBMError("task=serve requires input_model=<model file> "
                            "or serve_models=<id=path,...>")
    return ServingApp(
        model_path,
        models=cfg.serve_models or None,
        hbm_budget_mb=cfg.serve_hbm_budget_mb,
        default_model_id=cfg.serve_default_model,
        explain_max_batch=cfg.serve_explain_max_batch,
        explain_queue_size=cfg.serve_explain_queue_size,
        explain_max_delay_ms=cfg.serve_explain_max_delay_ms,
        host=cfg.serve_host, port=cfg.serve_port,
        max_batch=cfg.serve_max_batch,
        max_delay_ms=cfg.serve_max_delay_ms,
        queue_size=cfg.serve_queue_size,
        buckets_spec=cfg.serve_buckets,
        warmup=cfg.serve_warmup,
        heartbeat_path=cfg.serve_heartbeat,
        deadline_ms=cfg.serve_deadline_ms,
        trace_sample=cfg.serve_trace_sample,
        trace_tail=cfg.serve_trace_tail,
        access_log=cfg.serve_access_log,
        slo_availability=cfg.serve_slo_availability,
        slo_p99_ms=cfg.serve_slo_p99_ms,
        slo_window_s=cfg.serve_slo_window_s,
        slo_burn=cfg.serve_slo_burn,
        binary_port=cfg.serve_binary_port,
        binary_accept_threads=cfg.serve_binary_accept_threads,
        quality_sample=cfg.quality_sample,
        quality_audit_sample=cfg.quality_audit_sample,
        drift_threshold=cfg.drift_threshold,
        drift_window_s=cfg.drift_window_s,
        quality_min_rows=cfg.quality_min_rows,
        quality_topk=cfg.quality_topk)


def run_server(params: Dict[str, Any]) -> int:
    """Blocking CLI entry: serve until SIGTERM/SIGINT, then drain.
    ``serve_replicas > 1`` dispatches to the fleet supervisor
    (docs/SERVING.md "Fleet architecture") instead of one in-process
    server."""
    from .. import telemetry
    from ..config import Config

    if Config.from_params(params).serve_replicas > 1:
        from .fleet import run_fleet
        return run_fleet(params)
    if not telemetry.enabled():
        # serving without its latency histograms is flying blind; the
        # CLI turns the registry on (spans stay off unless trace_out set)
        telemetry.configure(enabled=True,
                            metrics_out=str(params.get("telemetry_out", ""))
                            or None)
    app = serve_from_params(params).start()
    stop = threading.Event()

    def _graceful(signum, frame):
        log_info(f"signal {signum}: draining serving queue")
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        stop.wait()
    finally:
        app.shutdown(drain=True)
        log_info(f"serving stopped after {app.batcher.served} requests "
                 f"({app.batcher.rejected} shed)")
    return 0
