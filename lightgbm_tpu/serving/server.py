"""JSON-over-HTTP serving front end (stdlib ``http.server`` only).

Endpoints:

  ``POST /predict``  body {"rows": [[...], ...]} or {"row": [...]},
                     optional "raw_score" (bool) and "fast" (bool — run a
                     single row synchronously on the native walk, no
                     queueing); replies {"predictions", "model_version",
                     "batched_rows", "latency_ms"}.  A full queue replies
                     503 with the structured overload payload; shape
                     errors reply 400.
  ``GET  /health``   liveness: worker thread state, heartbeat age, queue
                     depth, model version (503 when the worker died).
  ``POST /reload``   {"path": optional} — validated atomic hot-swap; a
                     rejected candidate replies 409 and the old version
                     keeps serving.
  ``GET  /stats``    latency/queue-depth percentiles from the telemetry
                     registry, request counters, recompile watchdog
                     counts, model + registry info.

Shutdown: ``shutdown(drain=True)`` (wired to SIGTERM/SIGINT by
``run_server``) stops accepting connections, lets the batcher drain
everything already queued, then returns — a rolling restart loses zero
admitted requests.
"""
from __future__ import annotations

import json
import signal
import threading
import time
from concurrent.futures import CancelledError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from ..utils.log import LightGBMError, log_debug, log_info
from .batcher import MicroBatcher, OverloadError
from .registry import ModelRegistry

_REQUEST_TIMEOUT_S = 30.0


def _jsonable(values: np.ndarray):
    v = np.asarray(values)
    return v.tolist()


class ServingApp:
    """Registry + batcher + HTTP server, wired together."""

    def __init__(self, model_path: str, *, host: str = "127.0.0.1",
                 port: int = 0, max_batch: int = 256,
                 max_delay_ms: float = 2.0, queue_size: int = 512,
                 buckets_spec: str = "", warmup: bool = True,
                 heartbeat_path: str = ""):
        self.registry = ModelRegistry(model_path, max_batch=max_batch,
                                      buckets_spec=buckets_spec,
                                      warmup=warmup)
        self.batcher = MicroBatcher(self.registry, max_batch=max_batch,
                                    max_delay_ms=max_delay_ms,
                                    queue_size=queue_size,
                                    heartbeat_path=heartbeat_path)
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.app = self          # handler back-pointer
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self.t0 = time.time()

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "ServingApp":
        """Non-blocking start (tests, embedding); ``run_server`` blocks."""
        self.batcher.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="lgbtpu-serve-http",
                                        daemon=True)
        self._thread.start()
        log_info(f"serving on http://{self.host}:{self.port} "
                 f"(model v{self.registry.version})")
        return self

    def shutdown(self, drain: bool = True) -> None:
        """Stop accepting, drain the queue (unless ``drain=False``), stop
        the worker.  Idempotent."""
        self._draining = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self.batcher.stop(drain=drain)
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(5.0)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # -- plumbing ----------------------------------------------------------
    def log_message(self, fmt, *args):   # route access logs off stderr
        log_debug("serve http: " + fmt % args)

    @property
    def app(self) -> ServingApp:
        return self.server.app

    def _send(self, code: int, obj: Dict[str, Any]) -> None:
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(length) if length else b"{}"
        try:
            obj = json.loads(raw.decode("utf-8") or "{}")
        except ValueError as e:
            raise LightGBMError(f"request body is not valid JSON: {e}")
        if not isinstance(obj, dict):
            raise LightGBMError("request body must be a JSON object")
        return obj

    # -- routes ------------------------------------------------------------
    def do_GET(self):   # noqa: N802 — http.server API
        from .. import telemetry

        if self.path.split("?")[0] == "/health":
            self._send(*self._health())
        elif self.path.split("?")[0] == "/stats":
            with telemetry.span("serve/stats"):
                self._send(200, self._stats())
        else:
            self._send(404, {"error": f"unknown path {self.path!r}"})

    def do_POST(self):   # noqa: N802
        from .. import telemetry

        path = self.path.split("?")[0]
        try:
            # the body must be consumed on EVERY branch — HTTP/1.1
            # keep-alive leaves unread bytes in rfile and the next request
            # on the connection would parse mid-body
            body = self._read_json()
            if path == "/predict":
                with telemetry.span("serve/predict"):
                    code, obj = self._predict(body)
            elif path == "/reload":
                with telemetry.span("serve/reload"):
                    code, obj = self._reload(body)
            else:
                code, obj = 404, {"error": f"unknown path {self.path!r}"}
        except OverloadError as e:
            code, obj = 503, e.payload()
        except LightGBMError as e:
            code, obj = 400, {"error": str(e)}
        except CancelledError:
            # shutdown(drain=False) cancelled the future mid-wait; on
            # CPython >= 3.8 CancelledError is a BaseException, so the
            # generic net below would miss it and reset the connection
            code, obj = 503, {"error": "shutting down"}
        except Exception as e:  # noqa: BLE001 — serving must answer
            code, obj = 500, {"error": f"{type(e).__name__}: {e}"}
        self._send(code, obj)

    def _predict(self, body):
        app = self.app
        if app.draining:
            return 503, {"error": "draining"}
        rows = body.get("rows", body.get("row"))
        if rows is None:
            return 400, {"error": 'predict body needs "rows" (matrix) '
                                  'or "row" (vector)'}
        t0 = time.perf_counter()
        fut = app.batcher.submit(rows,
                                 raw_score=bool(body.get("raw_score", False)),
                                 fast=bool(body.get("fast", False)))
        res = fut.result(timeout=_REQUEST_TIMEOUT_S)
        return 200, {
            "predictions": _jsonable(res.values),
            "model_version": res.model_version,
            "batched_rows": res.batched_rows,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }

    def _reload(self, body):
        app = self.app
        path = str(body.get("path") or app.registry.current().path)
        try:
            model = app.registry.load(path)
        except LightGBMError as e:
            # the candidate was rejected; the old version keeps serving
            return 409, {"error": str(e),
                         "model_version": app.registry.version}
        return 200, {"model_version": model.version,
                     "num_trees": model.num_trees,
                     "sha256": model.sha256}

    def _health(self):
        from ..robustness.heartbeat import heartbeat_age

        app = self.app
        alive = app.batcher.worker_alive
        out: Dict[str, Any] = {
            "status": ("draining" if app.draining
                       else "ok" if alive else "dead"),
            "model_version": app.registry.version,
            "uptime_s": round(time.time() - app.t0, 3),
            "queue_depth": app.batcher.queue_depth(),
            "worker_alive": alive,
        }
        if app.batcher.heartbeat_path:
            age = heartbeat_age(app.batcher.heartbeat_path)
            if age is not None:
                out["heartbeat_age_s"] = round(age, 3)
        return (200 if alive else 503), out

    def _stats(self) -> Dict[str, Any]:
        from .. import telemetry

        app = self.app
        return {
            "uptime_s": round(time.time() - app.t0, 3),
            "registry": app.registry.stats(),
            "queue_depth": app.batcher.queue_depth(),
            "served": app.batcher.served,
            "batches": app.batcher.batches,
            "rejected": app.batcher.rejected,
            "latency": telemetry.quantiles("serve/latency_s"),
            "dispatch": telemetry.quantiles("serve/dispatch_s"),
            "batch_rows": telemetry.quantiles("serve/batch_rows"),
            "queue_depth_dist": telemetry.quantiles("serve/queue_depth"),
            "recompiles": {k: v for k, v in
                           telemetry.recompile_counts().items()
                           if k.startswith("serve")},
        }


def serve_from_params(params: Dict[str, Any]) -> ServingApp:
    """Build (not start) a ServingApp from resolved CLI/conf params."""
    from ..config import Config

    cfg = Config.from_params(params)
    model_path = str(params.get("input_model", "") or "")
    if not model_path:
        raise LightGBMError("task=serve requires input_model=<model file>")
    return ServingApp(
        model_path,
        host=cfg.serve_host, port=cfg.serve_port,
        max_batch=cfg.serve_max_batch,
        max_delay_ms=cfg.serve_max_delay_ms,
        queue_size=cfg.serve_queue_size,
        buckets_spec=cfg.serve_buckets,
        warmup=cfg.serve_warmup,
        heartbeat_path=cfg.serve_heartbeat)


def run_server(params: Dict[str, Any]) -> int:
    """Blocking CLI entry: serve until SIGTERM/SIGINT, then drain."""
    from .. import telemetry

    if not telemetry.enabled():
        # serving without its latency histograms is flying blind; the
        # CLI turns the registry on (spans stay off unless trace_out set)
        telemetry.configure(enabled=True,
                            metrics_out=str(params.get("telemetry_out", ""))
                            or None)
    app = serve_from_params(params).start()
    stop = threading.Event()

    def _graceful(signum, frame):
        log_info(f"signal {signum}: draining serving queue")
        stop.set()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        stop.wait()
    finally:
        app.shutdown(drain=True)
        log_info(f"serving stopped after {app.batcher.served} requests "
                 f"({app.batcher.rejected} shed)")
    return 0
