"""SLO error-budget burn-rate monitoring for the serving tier.

Two service-level objectives, each with its own error budget:

  * **availability** — fraction of requests that do NOT fail with a
    non-503 error (``serve_slo_availability``, default 99.9%).  A 503
    shed is deliberate load management, not an outage, matching the
    fleet chaos gate's "zero non-503 errors" contract;
  * **latency** — fraction of 200 responses under the p99 target
    (``serve_slo_p99_ms``; 0 disables the dimension).  The objective is
    fixed at 99% — "p99 under X ms" IS the 99%-of-requests statement.

Alerting follows the multi-window burn-rate recipe (Google SRE workbook
ch. 5): the instantaneous **burn rate** is ``bad_fraction /
error_budget`` — 1.0 means the budget is being consumed exactly at the
rate that exhausts it at the window's end; 14.4 means 14.4x faster.  An
alert FIRES only when BOTH the fast window (``serve_slo_window_s``) and
the slow window (12x longer) exceed ``serve_slo_burn`` — the slow window
keeps a single bad second from paging, the fast window makes the alert
CLEAR quickly once the burn stops (recovery is judged on the fast window
alone).  State transitions land in a bounded timeline (the chaos bench
gates on fire-during-chaos + clear-after-recovery), in warning/info
logs, and in four gauges the ``/metrics`` surface exports:
``slo/availability_burn_fast``, ``slo/availability_burn_slow``,
``slo/latency_burn_fast``, ``slo/latency_burn_slow`` plus
``slo/alert``.

The clock is injectable, so tests drive burn -> alert -> recovery
deterministically; counts live in per-second buckets, so a record is
O(1) and a window sum is O(window seconds).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.log import log_info, log_warning

_SLOW_FACTOR = 12          # slow window = fast window x this
_MIN_EVENTS = 10           # below this many requests in the fast
#                            window, burn is not evidence (idle noise)
_MAX_TIMELINE = 256        # bounded alert-transition history


class _SecondBucket:
    __slots__ = ("sec", "total", "avail_bad", "lat_total", "lat_bad")

    def __init__(self, sec: int):
        self.sec = sec
        self.total = 0
        self.avail_bad = 0
        self.lat_total = 0
        self.lat_bad = 0


class SLOMonitor:
    """Multi-window burn-rate monitor over per-second outcome buckets."""

    def __init__(self, *, availability_target: float = 0.999,
                 p99_target_ms: float = 0.0, window_s: float = 60.0,
                 burn_threshold: float = 14.4, clock=time.monotonic,
                 min_events: int = _MIN_EVENTS,
                 slow_factor: float = _SLOW_FACTOR):
        if not 0.0 < availability_target < 1.0:
            raise ValueError("availability_target must be in (0, 1), got "
                             f"{availability_target}")
        self.availability_target = float(availability_target)
        self.p99_target_ms = max(float(p99_target_ms), 0.0)
        self.window_s = max(float(window_s), 1.0)
        self.burn_threshold = max(float(burn_threshold), 0.1)
        self.min_events = max(int(min_events), 1)
        # compressed-timescale harnesses (the chaos bench) shrink the
        # slow window; production keeps the 12x SRE-workbook pairing
        self.slow_factor = max(float(slow_factor), 1.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: "deque[_SecondBucket]" = deque()
        self._alert: Optional[str] = None     # alerting dimension(s)
        self._timeline: List[Dict[str, Any]] = []
        self.fired = 0
        self.cleared = 0

    # -- recording ---------------------------------------------------------
    def record(self, status: int, latency_ms: float) -> None:
        """One finished request: its HTTP status and client-side latency.
        Transport-level failures should be recorded as status 599."""
        sec = int(self._clock())
        avail_bad = status >= 500 and status != 503
        is_200 = status == 200
        lat_bad = (is_200 and self.p99_target_ms > 0
                   and latency_ms > self.p99_target_ms)
        with self._lock:
            b = self._buckets[-1] if self._buckets else None
            if b is None or b.sec != sec:
                b = _SecondBucket(sec)
                self._buckets.append(b)
                self._trim_locked(sec)
            b.total += 1
            b.avail_bad += int(avail_bad)
            b.lat_total += int(is_200)
            b.lat_bad += int(lat_bad)

    def _trim_locked(self, now_sec: int) -> None:
        horizon = now_sec - int(self.window_s * self.slow_factor) - 1
        while self._buckets and self._buckets[0].sec < horizon:
            self._buckets.popleft()

    # -- evaluation --------------------------------------------------------
    def _window_burn(self, now: float, span_s: float
                     ) -> Dict[str, float]:
        lo = int(now) - int(span_s)
        total = avail_bad = lat_total = lat_bad = 0
        with self._lock:
            for b in self._buckets:
                if b.sec > lo:
                    total += b.total
                    avail_bad += b.avail_bad
                    lat_total += b.lat_total
                    lat_bad += b.lat_bad
        avail_budget = 1.0 - self.availability_target
        out = {"total": float(total)}
        out["availability"] = (
            (avail_bad / total) / avail_budget if total else 0.0)
        out["latency"] = (
            (lat_bad / lat_total) / 0.01
            if (lat_total and self.p99_target_ms > 0) else 0.0)
        return out

    def burn(self) -> Dict[str, Dict[str, float]]:
        """Current burn rates: {dimension: {fast, slow}}."""
        now = self._clock()
        fast = self._window_burn(now, self.window_s)
        slow = self._window_burn(now, self.window_s * self.slow_factor)
        return {
            "availability": {"fast": round(fast["availability"], 3),
                             "slow": round(slow["availability"], 3)},
            "latency": {"fast": round(fast["latency"], 3),
                        "slow": round(slow["latency"], 3)},
            "fast_window_events": int(fast["total"]),
        }

    def tick(self) -> Dict[str, Any]:
        """Evaluate the state machine; call per record batch or on a
        poll loop so alerts also CLEAR while traffic is idle."""
        from .. import telemetry

        b = self.burn()
        thr = self.burn_threshold
        enough = b["fast_window_events"] >= self.min_events
        burning = sorted(
            dim for dim in ("availability", "latency")
            if enough and b[dim]["fast"] >= thr and b[dim]["slow"] >= thr)
        # recovery is judged on the fast window alone: once the recent
        # window is healthy the page stops, even while the slow window
        # still remembers the incident
        still = sorted(dim for dim in ("availability", "latency")
                       if b[dim]["fast"] >= thr)
        with self._lock:
            alert = self._alert
            if alert is None and burning:
                self._alert = alert = "+".join(burning)
                self.fired += 1
                event = {"t": round(self._clock(), 3), "kind": "fire",
                         "dimensions": alert, "burn": b}
                self._timeline.append(event)
                del self._timeline[:-_MAX_TIMELINE]
                fired = True
                cleared = False
            elif alert is not None and not still:
                event = {"t": round(self._clock(), 3), "kind": "clear",
                         "dimensions": alert, "burn": b}
                self._timeline.append(event)
                del self._timeline[:-_MAX_TIMELINE]
                self._alert = None
                self.cleared += 1
                fired = False
                cleared = True
                alert = None
            else:
                fired = cleared = False
        telemetry.gauge("slo/availability_burn_fast",
                        b["availability"]["fast"])
        telemetry.gauge("slo/availability_burn_slow",
                        b["availability"]["slow"])
        telemetry.gauge("slo/latency_burn_fast", b["latency"]["fast"])
        telemetry.gauge("slo/latency_burn_slow", b["latency"]["slow"])
        telemetry.gauge("slo/alert", 1.0 if alert else 0.0)
        if fired:
            log_warning(
                f"SLO burn alert: {event['dimensions']} error budget "
                f"burning at >= {thr:.1f}x (fast/slow windows "
                f"{self.window_s:.0f}s/{self.window_s * self.slow_factor:.0f}s"
                f"; burn {b})")
        elif cleared:
            log_info(f"SLO burn alert cleared ({event['dimensions']}); "
                     f"burn {b}")
        return {"alert": alert, "burn": b}

    # -- introspection -----------------------------------------------------
    def state(self) -> Dict[str, Any]:
        with self._lock:
            alert = self._alert
            fired, cleared = self.fired, self.cleared
        return {"alert": alert, "alerting": alert is not None,
                "fired": fired, "cleared": cleared,
                "availability_target": self.availability_target,
                "p99_target_ms": self.p99_target_ms,
                "window_s": self.window_s,
                "burn_threshold": self.burn_threshold,
                "burn": self.burn()}

    def timeline(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._timeline)
