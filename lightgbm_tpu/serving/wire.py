"""Persistent-connection binary row protocol (the serving fast wire).

The JSON/HTTP path pays parse + dict + float repr per request — fine at
hundreds of QPS, dominant at thousands.  This wire replaces it with
length-prefixed binary frames over long-lived TCP connections, so the
per-request server cost drops to one buffered ``recv`` + ``memcpy`` into
the micro-batcher (reference analog: the pre-bound
``PredictForMatSingleRowFast`` contract, c_api.h:1399-1428 — all setup
hoisted out of the per-row path).  Requests pipeline: a client may have
any number of frames in flight and responses match on ``request_id``
(they can return out of order across batcher dispatches).

Frame layout (all little-endian; docs/SERVING.md "Binary wire protocol"):

  handshake  client->server then server->client, 8 bytes each:
             ``b"LGBW"`` + u8 version + 3 reserved zero bytes.  The
             client sends the highest version it speaks; the server
             echoes the NEGOTIATED version ``min(client, server)`` and
             the rest of the connection runs at it.  A hello the server
             cannot negotiate down (version 0) draws a structured rid-0
             bad_request refusal frame, then a close; a wrong-magic
             hello is not our protocol at all and closes silently.  A
             v1-only server silently closes a v2 hello — clients
             downgrade-retry on a fresh connection with a v1 hello.

  request    u32 length            bytes AFTER this field
             u32 request_id        echoed verbatim in the response
             u8  op                1 = predict | 2 = explain (v2)
             u8  flags             1 raw_score | 2 fast | 4 trace attached
             u16 n_cols
             u32 n_rows
             f32 deadline_ms       0 = server default (serve_deadline_ms)
             [u8 model_len + ascii model_id]   v2 only; len 0 = default
             f32 x n_rows*n_cols   row-major feature values
             [u8 trace_len + trace bytes]   iff flags & 4 — the same
             ``<trace_id>[;s=0|1]`` context the X-LGBTPU-Trace header
             carries (docs/OBSERVABILITY.md)

  response   u32 length
             u32 request_id
             u8  status            0 ok | 2 overload | 3 deadline_expired
                                   | 4 bad_request | 5 server_error
                                   | 6 draining
             u8  sha_len           model sha256 hex length (ok), else 0
             u16 k                 values per row (ok), else 0
             u32 n_rows            (ok), else 0
             u32 model_version
             f32 retry_after_s     backoff hint on sheds, else 0
             [u8 model_len + ascii model_id]   v2 only (every status)
             [sha_len sha hex bytes][f64 x n_rows*k predictions]   (ok)
             [u16 msg_len + utf8 message]                          (error)

Predictions travel as float64, so the wire is exactly as bitwise-auditable
against ``Booster.predict`` as the JSON path (BENCH_FLEET keys its
zero-mis-versioned gate off the sha + f64 payload).

Malformed input never wedges a worker (the LGB008 discipline applied to
the accept loop): a truncated length prefix or mid-frame disconnect is a
clean close, an oversize length or bad header draws a structured error
frame and then a close, a wrong row width draws an error frame and the
connection keeps serving.  Responses are written by a per-connection
writer thread behind a bounded queue — a client that stops reading gets
disconnected instead of blocking the batcher worker.

The server runs a MULTI-ACCEPT front: ``accept_threads`` acceptors share
the listening socket so connection setup never serializes behind one
thread.
"""
from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from concurrent.futures import CancelledError
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..robustness import chaos
from ..utils.log import LightGBMError, log_debug, log_info
from .batcher import DeadlineError, OverloadError

MAGIC = b"LGBW"
VERSION = 2                      # current: model-id routing + explain op
VERSION_MIN = 1                  # still negotiated for pre-v2 clients
HANDSHAKE = MAGIC + bytes([VERSION, 0, 0, 0])
HANDSHAKE_V1 = MAGIC + bytes([1, 0, 0, 0])


def handshake(version: int = VERSION) -> bytes:
    return MAGIC + bytes([version, 0, 0, 0])
MAX_FRAME = 8 * 2 ** 20          # request bytes after the length prefix
# responses can legally outgrow requests (f32 rows in, f64 x num_class
# predictions out), so the client-side bound is wider: 2x for the dtype
# plus headroom for num_class > n_cols models and the sha/header tail
MAX_RESP_FRAME = 8 * MAX_FRAME
OP_PREDICT = 1
OP_EXPLAIN = 2                   # v2: device-batched SHAP contributions

FLAG_RAW = 1
FLAG_FAST = 2
FLAG_TRACE = 4

ST_OK = 0
ST_OVERLOAD = 2
ST_DEADLINE = 3
ST_BAD_REQUEST = 4
ST_ERROR = 5
ST_DRAINING = 6

_LEN = struct.Struct("<I")
_REQ_HEAD = struct.Struct("<IBBHIf")     # id, op, flags, ncols, nrows, ddl
# id, status, sha_len (u8 — hex sha is 64 bytes), k (u16 — num_class up
# to 65535; a u8 here would break >255-class models), nrows, version, ra
_RESP_HEAD = struct.Struct("<IBBHIIf")


class WireError(LightGBMError):
    """Malformed frame (protocol violation, not a transport failure)."""


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def _model_field(model_id: str) -> bytes:
    mb = str(model_id or "").encode("ascii", errors="replace")[:255]
    return bytes([len(mb)]) + mb


def encode_request(request_id: int, rows: np.ndarray, *,
                   raw_score: bool = False, fast: bool = False,
                   deadline_ms: float = 0.0,
                   trace: Optional[str] = None,
                   model_id: str = "", op: int = OP_PREDICT,
                   version: int = VERSION) -> bytes:
    """One request frame (length prefix included)."""
    if version < 2 and (model_id or op != OP_PREDICT):
        raise WireError(
            "model_id / explain need wire v2; connection negotiated v1")
    rows = np.ascontiguousarray(rows, dtype="<f4")
    if rows.ndim == 1:
        rows = rows.reshape(1, -1)
    n, c = rows.shape
    flags = (FLAG_RAW if raw_score else 0) | (FLAG_FAST if fast else 0)
    tail = b""
    if trace:
        tb = str(trace).encode("utf-8")[:255]
        tail = bytes([len(tb)]) + tb
        flags |= FLAG_TRACE
    mid = _model_field(model_id) if version >= 2 else b""
    body = (_REQ_HEAD.pack(request_id & 0xFFFFFFFF, op, flags,
                           c, n, float(deadline_ms))
            + mid + rows.tobytes() + tail)
    return _LEN.pack(len(body)) + body


def parse_request(payload: bytes, version: int = VERSION) -> Dict[str, Any]:
    """Decode a request frame body (everything after the length prefix)
    at the connection's negotiated ``version``.  Raises
    :class:`WireError` on any malformation."""
    if len(payload) < _REQ_HEAD.size:
        raise WireError(f"request frame too short ({len(payload)} < "
                        f"{_REQ_HEAD.size} header bytes)")
    req_id, op, flags, ncols, nrows, deadline_ms = _REQ_HEAD.unpack_from(
        payload)
    if op not in (OP_PREDICT, OP_EXPLAIN):
        raise WireError(f"unknown wire op {op}")
    if op == OP_EXPLAIN and version < 2:
        raise WireError("explain op needs wire v2")
    off = _REQ_HEAD.size
    model_id = ""
    if version >= 2:
        if len(payload) < off + 1:
            raise WireError("v2 frame missing the model-id field")
        ml = payload[off]
        if len(payload) < off + 1 + ml:
            raise WireError("model-id bytes truncated")
        model_id = payload[off + 1:off + 1 + ml].decode("ascii",
                                                        errors="replace")
        off += 1 + ml
    want = nrows * ncols * 4
    if len(payload) < off + want:
        raise WireError(
            f"request frame payload short: {nrows}x{ncols} f32 rows need "
            f"{want} bytes, frame carries {len(payload) - off}")
    rows = np.frombuffer(payload, dtype="<f4", count=nrows * ncols,
                         offset=off).reshape(nrows, ncols)
    off += want
    trace = None
    if flags & FLAG_TRACE:
        if len(payload) < off + 1:
            raise WireError("trace flag set but no trace bytes")
        tl = payload[off]
        if len(payload) < off + 1 + tl:
            raise WireError("trace bytes truncated")
        trace = payload[off + 1:off + 1 + tl].decode("utf-8",
                                                     errors="replace")
    return {"request_id": req_id, "rows": rows, "op": op,
            "raw_score": bool(flags & FLAG_RAW),
            "fast": bool(flags & FLAG_FAST),
            "deadline_ms": float(deadline_ms), "trace": trace,
            "model_id": model_id}


def encode_response_ok(request_id: int, values: np.ndarray,
                       model_version: int, sha256: str,
                       model_id: str = "",
                       version: int = VERSION) -> bytes:
    v = np.ascontiguousarray(values, dtype="<f8")
    if v.ndim == 1:
        n, k = v.shape[0], 1
    else:
        n, k = v.shape
    if k > 0xFFFF:
        raise WireError(f"num_class {k} exceeds the wire's u16 field")
    sha_b = (sha256 or "").encode("ascii")[:255]
    mid = _model_field(model_id) if version >= 2 else b""
    body = (_RESP_HEAD.pack(request_id & 0xFFFFFFFF, ST_OK, len(sha_b), k,
                            n, int(model_version), 0.0)
            + mid + sha_b + v.tobytes())
    return _LEN.pack(len(body)) + body


def encode_response_error(request_id: int, status: int, message: str,
                          retry_after_s: float = 0.0,
                          model_id: str = "",
                          version: int = VERSION) -> bytes:
    mb = str(message).encode("utf-8")[:2048]
    mid = _model_field(model_id) if version >= 2 else b""
    body = (_RESP_HEAD.pack(request_id & 0xFFFFFFFF, status, 0, 0, 0, 0,
                            float(retry_after_s))
            + mid + struct.pack("<H", len(mb)) + mb)
    return _LEN.pack(len(body)) + body


def parse_response(payload: bytes, version: int = VERSION) -> Dict[str, Any]:
    if len(payload) < _RESP_HEAD.size:
        raise WireError(f"response frame too short ({len(payload)})")
    (req_id, status, sha_len, k, nrows, version_m,
     retry_after) = _RESP_HEAD.unpack_from(payload)
    off = _RESP_HEAD.size
    out: Dict[str, Any] = {"request_id": req_id, "status": status,
                           "model_version": version_m,
                           "retry_after_s": retry_after, "model_id": ""}
    if version >= 2:
        if len(payload) < off + 1:
            raise WireError("v2 response missing the model-id field")
        ml = payload[off]
        if len(payload) < off + 1 + ml:
            raise WireError("response model-id bytes truncated")
        out["model_id"] = payload[off + 1:off + 1 + ml].decode(
            "ascii", errors="replace")
        off += 1 + ml
    if status == ST_OK:
        if len(payload) < off + sha_len + nrows * k * 8:
            raise WireError("ok response frame truncated")
        out["model_sha256"] = payload[off:off + sha_len].decode("ascii")
        off += sha_len
        v = np.frombuffer(payload, dtype="<f8", count=nrows * k, offset=off)
        out["predictions"] = v if k == 1 else v.reshape(nrows, k)
    else:
        if len(payload) >= off + 2:
            (ml,) = struct.unpack_from("<H", payload, off)
            out["error"] = payload[off + 2:off + 2 + ml].decode(
                "utf-8", errors="replace")
        else:
            out["error"] = ""
    return out


def _read_exact(f, n: int) -> Optional[bytes]:
    """Read exactly n bytes from a buffered file-like; None on EOF before
    the first byte, :class:`WireError` on EOF mid-read."""
    data = f.read(n)
    if not data:
        return None
    if len(data) < n:
        raise WireError(f"connection closed mid-frame ({len(data)}/{n} "
                        "bytes)")
    return data


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _Conn:
    """One client connection: socket + bounded outbound queue + writer
    thread, so a response producer (the batcher worker resolving a
    future) never blocks on a slow client's send buffer."""

    def __init__(self, sock: socket.socket, out_depth: int = 1024):
        self.sock = sock
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(out_depth)
        self._closed = threading.Event()
        self._writer = threading.Thread(target=self._write_loop,
                                        name="lgbtpu-binwire-writer",
                                        daemon=True)
        self._writer.start()

    def send(self, frame: bytes) -> None:
        try:
            self._q.put_nowait(frame)
        except queue.Full:
            # the client stopped reading: disconnecting it is the bounded
            # behavior — blocking here would wedge the batcher worker
            log_debug("binary wire: outbound queue full; dropping client")
            self.close()

    def _write_loop(self) -> None:
        while True:
            frame = self._q.get()
            if frame is None:
                return
            try:
                self.sock.sendall(frame)
            except OSError:
                self.close()
                return

    def close(self, flush: bool = False) -> None:
        """``flush=True`` drains queued frames (bounded wait) before the
        socket closes — a structured refusal must reach the client."""
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._q.put(None, timeout=0.5 if flush else 0.0)
        except queue.Full:
            pass
        if flush and self._writer.is_alive() \
                and threading.current_thread() is not self._writer:
            self._writer.join(2.0)
        try:
            self.sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class BinaryServer:
    """Multi-accept binary front riding the same registry + micro-batcher
    as the HTTP endpoints (``serve_binary_port``)."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 0,
                 accept_threads: int = 2, reuse_port: bool = False,
                 max_frame: int = MAX_FRAME):
        self.app = app
        self.accept_threads = max(int(accept_threads), 1)
        self.max_frame = int(max_frame)
        self._lock = threading.Lock()
        self._conns: List[_Conn] = []
        self._threads: List[threading.Thread] = []
        self._stopping = threading.Event()
        self.requests = 0
        self.bad_frames = 0
        self.connections = 0
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port and hasattr(socket, "SO_REUSEPORT"):
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(128)
        self.host, self.port = self._sock.getsockname()[:2]

    def start(self) -> "BinaryServer":
        for i in range(self.accept_threads):
            t = threading.Thread(target=self._accept_loop,
                                 name=f"lgbtpu-binwire-accept{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        log_info(f"binary wire on {self.host}:{self.port} "
                 f"({self.accept_threads} acceptors)")
        return self

    def stop_accepting(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        """Close the listener and every live connection.  Called after
        the batcher drain so in-flight futures already resolved — the
        flush makes sure their queued response frames reach the client
        before the FIN (the drain contract: admitted work is answered)."""
        self.stop_accepting()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close(flush=True)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"requests": self.requests,
                    "bad_frames": self.bad_frames,
                    "connections": self.connections,
                    "open_connections": sum(1 for c in self._conns
                                            if not c.closed)}

    # -- accept + per-connection serve loops ------------------------------
    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                sock, _addr = self._sock.accept()
            except OSError:
                return     # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(sock,),
                                 name="lgbtpu-binwire-conn", daemon=True)
            t.start()

    def _serve_conn(self, sock: socket.socket) -> None:
        from .. import telemetry

        conn = _Conn(sock)
        with self._lock:
            self._conns.append(conn)
            self.connections += 1
        telemetry.inc("serve/bin_connections")
        f = sock.makefile("rb", buffering=256 * 1024)
        ver = VERSION
        try:
            hello = _read_exact(f, len(HANDSHAKE))
            if hello is None or hello[:4] != MAGIC:
                return     # not our protocol at all: silent close
            if hello[4] < VERSION_MIN:
                # correct magic, a version we cannot negotiate down to:
                # a STRUCTURED refusal (satellite contract — old/broken
                # peers learn why), then close
                with self._lock:
                    self.bad_frames += 1
                telemetry.inc("serve/bin_bad_frames")
                conn.send(encode_response_error(
                    0, ST_BAD_REQUEST,
                    f"unsupported wire version {hello[4]} "
                    f"(supported {VERSION_MIN}..{VERSION})",
                    version=VERSION_MIN))
                return
            # negotiate: run the connection at min(client, server) and
            # echo that version so the client knows what it got
            ver = min(int(hello[4]), VERSION)
            sock.sendall(handshake(ver))
            while not conn.closed:
                head = f.read(_LEN.size)
                if not head:
                    return                     # clean close between frames
                if len(head) < _LEN.size:
                    raise WireError("truncated length prefix")
                (length,) = _LEN.unpack(head)
                if length < _REQ_HEAD.size or length > self.max_frame:
                    # structured refusal, then close: an oversize length
                    # cannot be resynchronized past
                    with self._lock:
                        self.bad_frames += 1
                    telemetry.inc("serve/bin_bad_frames")
                    conn.send(encode_response_error(
                        0, ST_BAD_REQUEST,
                        f"frame length {length} outside "
                        f"[{_REQ_HEAD.size}, {self.max_frame}]",
                        version=ver))
                    return
                payload = _read_exact(f, length)
                if payload is None:
                    raise WireError("connection closed after length prefix")
                self._handle_frame(conn, payload, ver)
        except WireError as e:
            with self._lock:
                self.bad_frames += 1
            telemetry.inc("serve/bin_bad_frames")
            log_debug(f"binary wire: {e}; closing connection")
        except chaos.DropConnection:
            pass
        except OSError as e:
            log_debug(f"binary wire connection error: {e}")
        finally:
            try:
                f.close()
            except OSError:
                pass
            conn.close(flush=True)
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass

    def _handle_frame(self, conn: _Conn, payload: bytes,
                      ver: int = VERSION) -> None:
        from .. import telemetry

        try:
            req = parse_request(payload, version=ver)
        except WireError as e:
            with self._lock:
                self.bad_frames += 1
            telemetry.inc("serve/bin_bad_frames")
            conn.send(encode_response_error(0, ST_BAD_REQUEST, str(e),
                                            version=ver))
            return
        rid = req["request_id"]
        mid = req["model_id"]
        with self._lock:
            self.requests += 1
        chaos.request_hook()     # may raise DropConnection (handled above)
        app = self.app
        if app.draining:
            conn.send(encode_response_error(rid, ST_DRAINING,
                                            "shutting down", 1.0,
                                            model_id=mid, version=ver))
            return
        batcher = app.batcher
        if req["op"] == OP_EXPLAIN:
            batcher = getattr(app, "explain_batcher", None)
            if batcher is None:
                conn.send(encode_response_error(
                    rid, ST_BAD_REQUEST,
                    "explain is not enabled on this server",
                    model_id=mid, version=ver))
                return
        ctx = None
        if req["trace"]:
            ctx = telemetry.TraceContext.from_header(req["trace"])
        budget_ms = req["deadline_ms"] or app.deadline_ms
        deadline = (time.perf_counter() + budget_ms / 1e3
                    if budget_ms and budget_ms > 0 else None)
        rows = np.asarray(req["rows"], np.float64)
        try:
            fut = batcher.submit(
                rows, raw_score=req["raw_score"],
                fast=req["fast"] and rows.shape[0] == 1,
                deadline=deadline, trace=ctx,
                model_id=mid or None)
        except DeadlineError as e:
            conn.send(encode_response_error(rid, ST_DEADLINE, str(e),
                                            e.retry_after_s,
                                            model_id=mid, version=ver))
            return
        except OverloadError as e:
            conn.send(encode_response_error(rid, ST_OVERLOAD, str(e),
                                            e.retry_after_s,
                                            model_id=mid, version=ver))
            return
        except LightGBMError as e:
            conn.send(encode_response_error(rid, ST_BAD_REQUEST, str(e),
                                            model_id=mid, version=ver))
            return
        fut.add_done_callback(
            lambda fu, c=conn, r=rid, m=mid, v=ver:
            self._reply(c, r, fu, m, v))

    def _reply(self, conn: _Conn, rid: int, fut, mid: str = "",
               ver: int = VERSION) -> None:
        """Resolve one future into a response frame (runs on whichever
        thread resolved the future — encode is microseconds, the send is
        a bounded-queue handoff)."""
        from .. import telemetry

        try:
            res = fut.result(timeout=0)
            sha = (res.sha256
                   or self.app.registry.sha_for_version(res.model_version)
                   or "")
            frame = encode_response_ok(rid, res.values, res.model_version,
                                       sha, model_id=res.model_id or mid,
                                       version=ver)
        except DeadlineError as e:
            frame = encode_response_error(rid, ST_DEADLINE, str(e),
                                          e.retry_after_s,
                                          model_id=mid, version=ver)
        except OverloadError as e:
            frame = encode_response_error(rid, ST_OVERLOAD, str(e),
                                          e.retry_after_s,
                                          model_id=mid, version=ver)
        except LightGBMError as e:
            frame = encode_response_error(rid, ST_BAD_REQUEST, str(e),
                                          model_id=mid, version=ver)
        except CancelledError:
            frame = encode_response_error(rid, ST_DRAINING,
                                          "shutting down", 1.0,
                                          model_id=mid, version=ver)
        except Exception as e:  # noqa: BLE001 — the wire must answer
            frame = encode_response_error(rid, ST_ERROR,
                                          f"{type(e).__name__}: {e}",
                                          model_id=mid, version=ver)
            telemetry.inc("serve/bin_errors")
        conn.send(frame)


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------

class BinaryClient:
    """Blocking single-connection client (tests, bench, simple callers).

    ``request`` is one synchronous round trip; ``pipeline`` sends a burst
    of requests before reading any response — the shape that saturates
    the micro-batcher (responses are matched back by request_id)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 version: int = VERSION):
        self._host, self._port, self._timeout = host, int(port), timeout
        self.version = int(version)
        try:
            self._connect(self.version)
        except (OSError, WireError):
            if self.version <= VERSION_MIN:
                raise
            # downgrade retry: a v1-only server silently closes an
            # unknown-version hello — reconnect speaking v1
            self.version = VERSION_MIN
            self._connect(self.version)
        self._next_id = 0

    def _connect(self, version: int) -> None:
        self.sock = socket.create_connection((self._host, self._port),
                                             timeout=self._timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(handshake(version))
        self._f = self.sock.makefile("rb", buffering=256 * 1024)
        hello = None
        try:
            hello = _read_exact(self._f, len(HANDSHAKE))
        except WireError:
            pass
        if hello is None or len(hello) < len(HANDSHAKE):
            self.close()
            raise WireError("server closed the wire handshake "
                            f"(no v{version} support?)")
        if hello[:4] != MAGIC:
            # maybe a structured rid-0 refusal frame: its first 4 bytes
            # are a length prefix — try to surface the server's reason
            msg = "server did not answer the wire handshake"
            try:
                (length,) = _LEN.unpack(hello[:4])
                if _RESP_HEAD.size <= length <= MAX_RESP_FRAME:
                    rest = _read_exact(self._f, length - 4)
                    resp = parse_response(hello[4:] + (rest or b""),
                                          version=VERSION_MIN)
                    if resp.get("error"):
                        msg = f"server refused handshake: {resp['error']}"
            except (WireError, struct.error):
                pass
            self.close()
            raise WireError(msg)
        # the server echoes the NEGOTIATED version; run the codec at it
        self.version = min(int(hello[4]) or VERSION_MIN, version)

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "BinaryClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def send_request(self, rows, *, raw_score: bool = False,
                     fast: bool = False, deadline_ms: float = 0.0,
                     trace: Optional[str] = None, model_id: str = "",
                     op: int = OP_PREDICT) -> int:
        self._next_id = (self._next_id + 1) & 0xFFFFFFFF
        rid = self._next_id
        self.sock.sendall(encode_request(rid, np.asarray(rows),
                                         raw_score=raw_score, fast=fast,
                                         deadline_ms=deadline_ms,
                                         trace=trace, model_id=model_id,
                                         op=op, version=self.version))
        return rid

    def read_response(self) -> Dict[str, Any]:
        head = _read_exact(self._f, _LEN.size)
        if head is None:
            raise WireError("connection closed by server")
        (length,) = _LEN.unpack(head)
        if length > MAX_RESP_FRAME:
            raise WireError(f"oversize response frame ({length})")
        payload = _read_exact(self._f, length)
        if payload is None:
            raise WireError("response frame truncated")
        return parse_response(payload, version=self.version)

    def request(self, rows, *, raw_score: bool = False, fast: bool = False,
                deadline_ms: float = 0.0,
                trace: Optional[str] = None, model_id: str = "",
                op: int = OP_PREDICT) -> Dict[str, Any]:
        rid = self.send_request(rows, raw_score=raw_score, fast=fast,
                                deadline_ms=deadline_ms, trace=trace,
                                model_id=model_id, op=op)
        while True:
            resp = self.read_response()
            if resp["request_id"] == rid or resp["request_id"] == 0:
                return resp

    def explain(self, rows, *, deadline_ms: float = 0.0,
                model_id: str = "") -> Dict[str, Any]:
        """SHAP contributions over the wire (v2 ``op=explain``) — the
        ``pred_contrib`` contract, k*(n_features+1) values per row."""
        return self.request(rows, deadline_ms=deadline_ms,
                            model_id=model_id, op=OP_EXPLAIN)

    def pipeline(self, bodies: List[np.ndarray], *,
                 raw_score: bool = False,
                 deadline_ms: float = 0.0,
                 model_id: str = "") -> List[Dict[str, Any]]:
        """Send every body back to back, then collect every response
        (responses may arrive out of order; returned in request order)."""
        ids = []
        frames = []
        for rows in bodies:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            ids.append(self._next_id)
            frames.append(encode_request(self._next_id, np.asarray(rows),
                                         raw_score=raw_score,
                                         deadline_ms=deadline_ms,
                                         model_id=model_id,
                                         version=self.version))
        self.sock.sendall(b"".join(frames))
        got: Dict[int, Dict[str, Any]] = {}
        want = set(ids)
        while want:
            resp = self.read_response()
            rid = resp["request_id"]
            if rid in want:
                want.discard(rid)
                got[rid] = resp
            elif rid == 0:
                # connection-level refusal (bad frame): attribute to all
                for w in want:
                    got[w] = resp
                break
        return [got[i] for i in ids]


class FleetBinaryClient:
    """Replica-aware binary client: per-replica persistent connections,
    deadline-split retry on a DIFFERENT replica after a transport
    failure, and a short cooldown for failed replicas — the client-side
    analog of the fanout front's route-around behavior (the binary wire
    has no proxy tier; smart clients route)."""

    def __init__(self, endpoints_fn: Callable[[], Dict[int, Tuple[str, int]]],
                 attempts: int = 3, cooldown_s: float = 1.0,
                 connect_timeout: float = 2.0,
                 endpoints_ttl_s: float = 0.5):
        self._endpoints_fn = endpoints_fn
        self.attempts = max(int(attempts), 1)
        self.cooldown_s = float(cooldown_s)
        self.connect_timeout = float(connect_timeout)
        # discovery can be file reads / an HTTP scrape — cache it and
        # refresh only on TTL expiry or after a transport failure (the
        # moment a stale port could matter), never per steady request
        self.endpoints_ttl_s = float(endpoints_ttl_s)
        self._eps: Dict[int, Tuple[str, int]] = {}
        self._eps_at = float("-inf")
        self._conns: Dict[int, BinaryClient] = {}
        self._addr: Dict[int, Tuple[str, int]] = {}
        self._bad_until: Dict[int, float] = {}
        # round-robin base so concurrent clients / successive requests
        # spread across replicas instead of all camping on the lowest rank
        self._rr = 0
        self.retries = 0

    def _endpoints(self, force: bool = False) -> Dict[int, Tuple[str, int]]:
        now = time.perf_counter()
        if force or now - self._eps_at > self.endpoints_ttl_s:
            try:
                self._eps = dict(self._endpoints_fn())
            except OSError:
                self._eps = {}
            self._eps_at = now
        return self._eps

    def close(self) -> None:
        for c in self._conns.values():
            c.close()
        self._conns.clear()

    def _drop(self, rank: int) -> None:
        c = self._conns.pop(rank, None)
        if c is not None:
            c.close()
        self._bad_until[rank] = time.perf_counter() + self.cooldown_s

    def _conn(self, rank: int, addr: Tuple[str, int],
              timeout: float) -> BinaryClient:
        c = self._conns.get(rank)
        if c is not None and self._addr.get(rank) == addr:
            c.sock.settimeout(timeout)
            return c
        if c is not None:
            c.close()
            del self._conns[rank]
        c = BinaryClient(addr[0], addr[1],
                         timeout=max(self.connect_timeout, timeout))
        c.sock.settimeout(timeout)
        self._conns[rank] = c
        self._addr[rank] = addr
        return c

    def request(self, rows, *, raw_score: bool = False,
                deadline_ms: float = 2000.0,
                model_id: str = "") -> Dict[str, Any]:
        """Returns the wire response dict; transport failures surface as
        ``{"status": ST_OVERLOAD, "error": "retries_exhausted"}`` after
        the bounded route-around (the HTTP front's structured-503
        analog).  ``model_id`` routes to a tenant on v2 replicas; a v1
        replica that negotiated down refuses it with a WireError, which
        the route-around treats as a transport failure and diverts."""
        t_end = time.perf_counter() + deadline_ms / 1e3
        tried: set = set()
        last: Optional[Dict[str, Any]] = None
        self._rr += 1
        for attempt in range(self.attempts):
            remaining = t_end - time.perf_counter()
            if remaining <= 0:
                break
            # retries force a discovery refresh — a restarted replica
            # publishes a NEW port; steady state rides the cached map
            eps = self._endpoints(force=attempt > 0)
            if not eps:
                time.sleep(min(0.05, max(remaining, 0)))
                continue
            now = time.perf_counter()
            fresh = sorted(r for r in eps if r not in tried
                           and self._bad_until.get(r, 0) <= now)
            pool = (fresh or sorted(r for r in eps if r not in tried)
                    or sorted(eps))
            rank = pool[(self._rr + attempt) % len(pool)]
            per_timeout = max(remaining / (self.attempts - attempt), 0.05)
            try:
                c = self._conn(rank, eps[rank], per_timeout)
                resp = c.request(rows, raw_score=raw_score,
                                 deadline_ms=remaining * 1e3,
                                 model_id=model_id)
            except (OSError, WireError):
                # killed/hung/reset replica: drop the conn (a late reply
                # would desync it), cool the replica down, go elsewhere
                self._drop(rank)
                tried.add(rank)
                self.retries += 1
                continue
            if resp["status"] in (ST_OK, ST_BAD_REQUEST):
                return resp
            # overload / deadline / draining: divert, keep the connection
            last = resp
            tried.add(rank)
            self.retries += 1
        if last is not None:
            return last
        return {"request_id": 0, "status": ST_OVERLOAD,
                "model_version": 0, "retry_after_s": 0.05,
                "error": "retries_exhausted"}
