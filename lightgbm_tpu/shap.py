"""SHAP feature contributions (pred_contrib).

Reference: src/io/tree.cpp TreeSHAP (Lundberg's exact algorithm) used by
GBDT::PredictContrib (gbdt.cpp:655). Exact per-row TreeSHAP over host trees; output
layout matches the reference: (N, F+1) per class with the expected value in the last
column. Round-1 implementation is host-side Python — correct but not optimised for very
large prediction batches.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import Tree


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElem], zero_fraction, one_fraction, feature_index):
    path.append(_PathElem(feature_index, zero_fraction, one_fraction,
                          1.0 if len(path) == 0 else 0.0))
    d = len(path) - 1
    for i in range(d - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (d + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (d - i) / (d + 1)


def _unwind_path(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (d - i) / (d + 1)
        else:
            path[i].pweight = path[i].pweight * (d + 1) / (zero_fraction * (d - i))
    for i in range(path_index, d):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    total = 0.0
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((d - i) / (d + 1))
        elif zero_fraction != 0:
            total += (path[i].pweight / zero_fraction) / ((d - i) / (d + 1))
    return total


def _decision(tree: Tree, node: int, x: np.ndarray) -> bool:
    f = int(tree.split_feature[node])
    v = x[f]
    dt = int(tree.decision_type[node])
    if dt & 1:  # categorical
        if np.isnan(v) or v < 0:
            return False
        c = int(v)
        kcat = int(tree.threshold_bin[node])
        s, e = tree.cat_boundaries[kcat], tree.cat_boundaries[kcat + 1]
        if c // 32 >= e - s:
            return False
        return bool((int(tree.cat_threshold[s + c // 32]) >> (c % 32)) & 1)
    missing_type = (dt >> 2) & 3
    is_missing = np.isnan(v) or (missing_type == 1 and abs(v) < 1e-35)
    if is_missing and missing_type != 0:
        return bool(dt & 2)  # default left
    if np.isnan(v):
        v = 0.0
    return v <= tree.threshold[node]


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               path: List[_PathElem], parent_zero_fraction: float,
               parent_one_fraction: float, parent_feature_index: int) -> None:
    path = [
        _PathElem(p.feature_index, p.zero_fraction, p.one_fraction, p.pweight)
        for p in path
    ]
    _extend_path(path, parent_zero_fraction, parent_one_fraction,
                 parent_feature_index)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_path_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * \
                tree.leaf_value[leaf]
        return
    hot = _decision(tree, node, x)
    hot_child = int(tree.left_child[node] if hot else tree.right_child[node])
    cold_child = int(tree.right_child[node] if hot else tree.left_child[node])
    w_node = _node_weight(tree, node)
    w_hot = _child_weight(tree, hot_child)
    w_cold = _child_weight(tree, cold_child)
    hot_zero_fraction = w_hot / w_node if w_node > 0 else 0.0
    cold_zero_fraction = w_cold / w_node if w_node > 0 else 0.0
    incoming_zero = 1.0
    incoming_one = 1.0
    f = int(tree.split_feature[node])
    # undo previous split on the same feature along the path
    path_index = next((i for i in range(len(path))
                       if path[i].feature_index == f), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, path_index)
    _tree_shap(tree, x, phi, hot_child, path,
               hot_zero_fraction * incoming_zero, incoming_one, f)
    _tree_shap(tree, x, phi, cold_child, path,
               cold_zero_fraction * incoming_zero, 0.0, f)


def _node_weight(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


_child_weight = _node_weight


def _all_decisions(tree: Tree, X: np.ndarray) -> np.ndarray:
    """(N, n_internal) bool — each row's decision at EVERY internal node
    (vectorised _decision); TreeSHAP consults off-path nodes too."""
    n = X.shape[0]
    ni = max(tree.num_leaves - 1, 0)
    dec = np.zeros((n, ni), bool)
    for node in range(ni):
        f = int(tree.split_feature[node])
        v = X[:, f]
        dt = int(tree.decision_type[node])
        if dt & 1:  # categorical
            iv = np.where(np.isnan(v) | (v < 0), -1, v).astype(np.int64)
            kcat = int(tree.threshold_bin[node])
            s, e = tree.cat_boundaries[kcat], tree.cat_boundaries[kcat + 1]
            words = np.asarray(tree.cat_threshold[s:e], np.uint32)
            word_idx = iv // 32
            ok = (iv >= 0) & (word_idx < (e - s))
            w = words[np.clip(word_idx, 0, max(e - s - 1, 0))]
            dec[:, node] = ok & (((w >> (iv % 32).astype(np.uint32)) & 1) > 0)
            continue
        missing_type = (dt >> 2) & 3
        nanv = np.isnan(v)
        is_missing = nanv | ((missing_type == 1) & (np.abs(v) < 1e-35))
        go = np.where(nanv, 0.0, v) <= tree.threshold[node]
        if missing_type != 0:
            go = np.where(is_missing, bool(dt & 2), go)
        dec[:, node] = go
    return dec


def _tree_shap_batch(tree: Tree, dec: np.ndarray, phi: np.ndarray) -> None:
    """Row-vectorised exact TreeSHAP: the recursion order over nodes is
    row-independent; only the hot/cold assignment and the path fractions vary
    per row, carried as (N,) vectors (same math as the scalar reference
    implementation above / src/io/tree.cpp TreeSHAP)."""
    n = dec.shape[0]
    leaf_value = np.asarray(tree.leaf_value, np.float64)

    def node_weight(node):
        return (float(tree.leaf_count[~node]) if node < 0
                else float(tree.internal_count[node]))

    def recurse(node, feat_idx, zf, of, pw, pz, po, pf):
        # copy-extend the path (reference copies the path per call)
        d = len(feat_idx)
        feat_idx = feat_idx + [pf]
        zf = np.vstack([zf, pz[None, :]])
        of = np.vstack([of, po[None, :]])
        pw = np.vstack([pw, np.full((1, n), 1.0 if d == 0 else 0.0)])
        for i in range(d - 1, -1, -1):
            pw[i + 1] += po * pw[i] * (i + 1) / (d + 1)
            pw[i] = pz * pw[i] * (d - i) / (d + 1)

        if node < 0:  # leaf: unwound path sums -> phi
            dd = len(feat_idx) - 1
            for i in range(1, len(feat_idx)):
                ofi, zfi = of[i], zf[i]
                next_one = pw[dd].copy()
                total = np.zeros(n)
                for j in range(dd - 1, -1, -1):
                    tmp = np.where(
                        ofi != 0,
                        next_one * (dd + 1) / ((j + 1) * np.where(ofi != 0,
                                                                  ofi, 1.0)),
                        0.0)
                    safe_z = np.where(zfi != 0, zfi, 1.0)
                    alt = np.where(zfi != 0,
                                   (pw[j] / safe_z) / ((dd - j) / (dd + 1)),
                                   0.0)
                    total += np.where(ofi != 0, tmp, alt)
                    next_one = pw[j] - tmp * zfi * ((dd - j) / (dd + 1))
                phi[:, feat_idx[i]] += total * (ofi - zfi) * leaf_value[~node]
            return

        lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
        hot_is_left = dec[:, node]
        w_node = node_weight(node)
        w_l, w_r = node_weight(lc), node_weight(rc)
        zl = w_l / w_node if w_node > 0 else 0.0
        zr = w_r / w_node if w_node > 0 else 0.0
        f = int(tree.split_feature[node])
        inc_zero = np.ones(n)
        inc_one = np.ones(n)
        if f in feat_idx:
            pi = feat_idx.index(f)
            inc_zero = zf[pi].copy()
            inc_one = of[pi].copy()
            # unwind the previous occurrence of this feature
            dd = len(feat_idx) - 1
            ofi, zfi = of[pi], zf[pi]
            next_one = pw[dd].copy()
            for j in range(dd - 1, -1, -1):
                tmp = pw[j].copy()
                upd = np.where(ofi != 0,
                               next_one * (dd + 1) / ((j + 1) * np.where(
                                   ofi != 0, ofi, 1.0)),
                               pw[j] * (dd + 1) / (np.where(zfi != 0, zfi,
                                                            1.0) * (dd - j)))
                pw[j] = upd
                next_one = tmp - upd * zfi * (dd - j) / (dd + 1)
            feat_idx = feat_idx[:pi] + feat_idx[pi + 1:]
            zf = np.delete(zf, pi, axis=0)
            of = np.delete(of, pi, axis=0)
            pw = pw[:-1]

        # zero fractions are child_weight/node_weight regardless of hot/cold;
        # only the one fraction depends on the row's decision
        z_left = zl * inc_zero
        o_left = np.where(hot_is_left, inc_one, 0.0)
        z_right = zr * inc_zero
        o_right = np.where(hot_is_left, 0.0, inc_one)
        recurse(lc, list(feat_idx), zf.copy(), of.copy(), pw.copy(),
                z_left, o_left, f)
        recurse(rc, list(feat_idx), zf.copy(), of.copy(), pw.copy(),
                z_right, o_right, f)

    recurse(0, [], np.zeros((0, n)), np.zeros((0, n)), np.zeros((0, n)),
            np.ones(n), np.ones(n), -1)


def predict_contrib(trees: List[Tree], X: np.ndarray, num_class: int) -> np.ndarray:
    n, nf = X.shape
    k = max(num_class, 1)
    out = np.zeros((n, k, nf + 1), np.float64)
    for ti, tree in enumerate(trees):
        kk = ti % k
        if tree.num_leaves <= 1:
            out[:, kk, nf] += tree.leaf_value[0] if len(tree.leaf_value) else 0.0
            continue
        out[:, kk, nf] += tree.expected_value()
        # chunk rows: the batched recursion keeps O(depth^2 * chunk) copies
        # of the path arrays alive along the DFS
        for s in range(0, n, 16384):
            e = min(s + 16384, n)
            dec = _all_decisions(tree, X[s:e])
            phi = np.zeros((e - s, nf + 1), np.float64)
            _tree_shap_batch(tree, dec, phi)
            out[s:e, kk, :nf] += phi[:, :nf]
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (nf + 1))
