"""SHAP feature contributions (pred_contrib).

Reference: src/io/tree.cpp TreeSHAP (Lundberg's exact algorithm) used by
GBDT::PredictContrib (gbdt.cpp:655). Exact per-row TreeSHAP over host trees; output
layout matches the reference: (N, F+1) per class with the expected value in the last
column. Two paths: an exact host walk (f64) and a device kernel — one jitted
lax.scan over padded (L, D, N) tree-path tensors with N on the VPU lane axis
(engaged on TPU for large batches; ~70x the host walk at 100k rows x 500
trees).
"""
from __future__ import annotations

import functools
from typing import List

import numpy as np

from .tree import Tree


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElem], zero_fraction, one_fraction, feature_index):
    path.append(_PathElem(feature_index, zero_fraction, one_fraction,
                          1.0 if len(path) == 0 else 0.0))
    d = len(path) - 1
    for i in range(d - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (d + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (d - i) / (d + 1)


def _unwind_path(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (d - i) / (d + 1)
        else:
            path[i].pweight = path[i].pweight * (d + 1) / (zero_fraction * (d - i))
    for i in range(path_index, d):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    total = 0.0
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((d - i) / (d + 1))
        elif zero_fraction != 0:
            total += (path[i].pweight / zero_fraction) / ((d - i) / (d + 1))
    return total


def _decision(tree: Tree, node: int, x: np.ndarray) -> bool:
    f = int(tree.split_feature[node])
    v = x[f]
    dt = int(tree.decision_type[node])
    if dt & 1:  # categorical
        if np.isnan(v) or v < 0:
            return False
        c = int(v)
        kcat = int(tree.threshold_bin[node])
        s, e = tree.cat_boundaries[kcat], tree.cat_boundaries[kcat + 1]
        if c // 32 >= e - s:
            return False
        return bool((int(tree.cat_threshold[s + c // 32]) >> (c % 32)) & 1)
    missing_type = (dt >> 2) & 3
    is_missing = np.isnan(v) or (missing_type == 1 and abs(v) < 1e-35)
    if is_missing and missing_type != 0:
        return bool(dt & 2)  # default left
    if np.isnan(v):
        v = 0.0
    return v <= tree.threshold[node]


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               path: List[_PathElem], parent_zero_fraction: float,
               parent_one_fraction: float, parent_feature_index: int) -> None:
    path = [
        _PathElem(p.feature_index, p.zero_fraction, p.one_fraction, p.pweight)
        for p in path
    ]
    _extend_path(path, parent_zero_fraction, parent_one_fraction,
                 parent_feature_index)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_path_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * \
                tree.leaf_value[leaf]
        return
    hot = _decision(tree, node, x)
    hot_child = int(tree.left_child[node] if hot else tree.right_child[node])
    cold_child = int(tree.right_child[node] if hot else tree.left_child[node])
    w_node = _node_weight(tree, node)
    w_hot = _child_weight(tree, hot_child)
    w_cold = _child_weight(tree, cold_child)
    hot_zero_fraction = w_hot / w_node if w_node > 0 else 0.0
    cold_zero_fraction = w_cold / w_node if w_node > 0 else 0.0
    incoming_zero = 1.0
    incoming_one = 1.0
    f = int(tree.split_feature[node])
    # undo previous split on the same feature along the path
    path_index = next((i for i in range(len(path))
                       if path[i].feature_index == f), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, path_index)
    _tree_shap(tree, x, phi, hot_child, path,
               hot_zero_fraction * incoming_zero, incoming_one, f)
    _tree_shap(tree, x, phi, cold_child, path,
               cold_zero_fraction * incoming_zero, 0.0, f)


def _node_weight(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


_child_weight = _node_weight


def _all_decisions(tree: Tree, X: np.ndarray) -> np.ndarray:
    """(N, n_internal) bool — each row's decision at EVERY internal node
    (vectorised _decision); TreeSHAP consults off-path nodes too."""
    n = X.shape[0]
    ni = max(tree.num_leaves - 1, 0)
    dec = np.zeros((n, ni), bool)
    for node in range(ni):
        f = int(tree.split_feature[node])
        v = X[:, f]
        dt = int(tree.decision_type[node])
        if dt & 1:  # categorical
            iv = np.where(np.isnan(v) | (v < 0), -1, v).astype(np.int64)
            kcat = int(tree.threshold_bin[node])
            s, e = tree.cat_boundaries[kcat], tree.cat_boundaries[kcat + 1]
            words = np.asarray(tree.cat_threshold[s:e], np.uint32)
            word_idx = iv // 32
            ok = (iv >= 0) & (word_idx < (e - s))
            w = words[np.clip(word_idx, 0, max(e - s - 1, 0))]
            dec[:, node] = ok & (((w >> (iv % 32).astype(np.uint32)) & 1) > 0)
            continue
        missing_type = (dt >> 2) & 3
        nanv = np.isnan(v)
        is_missing = nanv | ((missing_type == 1) & (np.abs(v) < 1e-35))
        go = np.where(nanv, 0.0, v) <= tree.threshold[node]
        if missing_type != 0:
            go = np.where(is_missing, bool(dt & 2), go)
        dec[:, node] = go
    return dec


def _tree_shap_batch(tree: Tree, dec: np.ndarray, phi: np.ndarray) -> None:
    """Row-vectorised exact TreeSHAP: the recursion order over nodes is
    row-independent; only the hot/cold assignment and the path fractions vary
    per row, carried as (N,) vectors (same math as the scalar reference
    implementation above / src/io/tree.cpp TreeSHAP)."""
    n = dec.shape[0]
    leaf_value = np.asarray(tree.leaf_value, np.float64)

    def node_weight(node):
        return (float(tree.leaf_count[~node]) if node < 0
                else float(tree.internal_count[node]))

    def recurse(node, feat_idx, zf, of, pw, pz, po, pf):
        # copy-extend the path (reference copies the path per call)
        d = len(feat_idx)
        feat_idx = feat_idx + [pf]
        zf = np.vstack([zf, pz[None, :]])
        of = np.vstack([of, po[None, :]])
        pw = np.vstack([pw, np.full((1, n), 1.0 if d == 0 else 0.0)])
        for i in range(d - 1, -1, -1):
            pw[i + 1] += po * pw[i] * (i + 1) / (d + 1)
            pw[i] = pz * pw[i] * (d - i) / (d + 1)

        if node < 0:  # leaf: unwound path sums -> phi
            dd = len(feat_idx) - 1
            for i in range(1, len(feat_idx)):
                ofi, zfi = of[i], zf[i]
                next_one = pw[dd].copy()
                total = np.zeros(n)
                for j in range(dd - 1, -1, -1):
                    tmp = np.where(
                        ofi != 0,
                        next_one * (dd + 1) / ((j + 1) * np.where(ofi != 0,
                                                                  ofi, 1.0)),
                        0.0)
                    safe_z = np.where(zfi != 0, zfi, 1.0)
                    alt = np.where(zfi != 0,
                                   (pw[j] / safe_z) / ((dd - j) / (dd + 1)),
                                   0.0)
                    total += np.where(ofi != 0, tmp, alt)
                    next_one = pw[j] - tmp * zfi * ((dd - j) / (dd + 1))
                phi[:, feat_idx[i]] += total * (ofi - zfi) * leaf_value[~node]
            return

        lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
        hot_is_left = dec[:, node]
        w_node = node_weight(node)
        w_l, w_r = node_weight(lc), node_weight(rc)
        zl = w_l / w_node if w_node > 0 else 0.0
        zr = w_r / w_node if w_node > 0 else 0.0
        f = int(tree.split_feature[node])
        inc_zero = np.ones(n)
        inc_one = np.ones(n)
        if f in feat_idx:
            pi = feat_idx.index(f)
            inc_zero = zf[pi].copy()
            inc_one = of[pi].copy()
            # unwind the previous occurrence of this feature
            dd = len(feat_idx) - 1
            ofi, zfi = of[pi], zf[pi]
            next_one = pw[dd].copy()
            for j in range(dd - 1, -1, -1):
                tmp = pw[j].copy()
                upd = np.where(ofi != 0,
                               next_one * (dd + 1) / ((j + 1) * np.where(
                                   ofi != 0, ofi, 1.0)),
                               pw[j] * (dd + 1) / (np.where(zfi != 0, zfi,
                                                            1.0) * (dd - j)))
                pw[j] = upd
                next_one = tmp - upd * zfi * (dd - j) / (dd + 1)
            feat_idx = feat_idx[:pi] + feat_idx[pi + 1:]
            zf = np.delete(zf, pi, axis=0)
            of = np.delete(of, pi, axis=0)
            pw = pw[:-1]

        # zero fractions are child_weight/node_weight regardless of hot/cold;
        # only the one fraction depends on the row's decision
        z_left = zl * inc_zero
        o_left = np.where(hot_is_left, inc_one, 0.0)
        z_right = zr * inc_zero
        o_right = np.where(hot_is_left, 0.0, inc_one)
        recurse(lc, list(feat_idx), zf.copy(), of.copy(), pw.copy(),
                z_left, o_left, f)
        recurse(rc, list(feat_idx), zf.copy(), of.copy(), pw.copy(),
                z_right, o_right, f)

    recurse(0, [], np.zeros((0, n)), np.zeros((0, n)), np.zeros((0, n)),
            np.ones(n), np.ones(n), -1)


def _leaf_paths(tree: Tree, max_depth: int):
    """Per-leaf padded path arrays for the device TreeSHAP kernel.

    For each leaf: the root-to-leaf path compressed to UNIQUE features
    (duplicate occurrences merge exactly as TreeSHAP's unwind does: zero
    fractions multiply, hot requires every occurrence hot). Returns
      feat      (L, D) int32   unique feature per slot (-1 pad)
      zfrac     (L, D) f64     merged zero fraction per slot
      occ_node  (L, R) int32   raw path node ids (-1 pad)
      occ_left  (L, R) bool    path goes LEFT at that node
      occ_slot  (L, R) int32   unique-feature slot of the occurrence
      plen      (L,)   int32   unique path length
    """
    L = tree.num_leaves
    ni = L - 1
    parent = {}
    for i in range(ni):
        lc, rc = int(tree.left_child[i]), int(tree.right_child[i])
        parent[lc] = (i, True)
        parent[rc] = (i, False)
    D = max_depth
    feat = np.full((L, D), -1, np.int64)
    zfrac = np.ones((L, D), np.float64)
    occ_node = np.full((L, D), -1, np.int64)
    occ_left = np.zeros((L, D), bool)
    occ_slot = np.zeros((L, D), np.int64)
    plen = np.zeros(L, np.int64)
    for leaf in range(L):
        # walk up: list of (node, went_left)
        raw = []
        cur = ~leaf
        while cur in parent:
            node, went_left = parent[cur]
            raw.append((node, went_left))
            cur = node
        raw.reverse()
        slots: List[int] = []
        for r, (node, went_left) in enumerate(raw):
            f = int(tree.split_feature[node])
            w_node = _node_weight(tree, node)
            child = int(tree.left_child[node] if went_left
                        else tree.right_child[node])
            zf = _node_weight(tree, child) / w_node if w_node > 0 else 0.0
            if f in slots:
                si = slots.index(f)
            else:
                si = len(slots)
                slots.append(f)
                feat[leaf, si] = f
            zfrac[leaf, si] *= zf
            occ_node[leaf, r] = node
            occ_left[leaf, r] = went_left
            occ_slot[leaf, r] = si
        plen[leaf] = len(slots)
    return feat, zfrac, occ_node, occ_left, occ_slot, plen


def _raw_tree_depth(tree: Tree) -> int:
    L = tree.num_leaves
    depth = {0: 0}
    best = 0
    for i in range(L - 1):
        for c in (int(tree.left_child[i]), int(tree.right_child[i])):
            if c >= 0:
                depth[c] = depth[i] + 1
            else:
                best = max(best, depth[i] + 1)
    return best


def _shap_device(trees: List[Tree], X: np.ndarray, num_class: int,
                 max_depth: int) -> np.ndarray:
    """Exact TreeSHAP as ONE jitted lax.scan over padded tree arrays —
    per (row, leaf) path-polynomial extend + per-feature unwound sums
    (the same arithmetic as the scalar recursion above, expressed over
    (N, L, D) tensors). Numeric trees only; f32 on device.

    Reference analog: the OpenMP-parallel PredictContrib
    (gbdt.cpp:655) — here parallelism is (rows x leaves) on the VPU."""
    import jax
    import jax.numpy as jnp

    n, nf = X.shape
    k = max(num_class, 1)
    T = len(trees)
    L = max(t.num_leaves for t in trees)
    ni = max(L - 1, 1)
    D = max_depth

    sf = np.zeros((T, ni), np.int64)
    thr = np.full((T, ni), np.inf)
    dt = np.zeros((T, ni), np.int64)
    lv = np.zeros((T, L))
    feat = np.full((T, L, D), -1, np.int64)
    zfrac = np.ones((T, L, D))
    occ_node = np.full((T, L, D), -1, np.int64)
    occ_left = np.zeros((T, L, D), bool)
    occ_slot = np.zeros((T, L, D), np.int64)
    plen = np.zeros((T, L), np.int64)
    base = np.zeros(k)
    for ti, t in enumerate(trees):
        nt = max(t.num_leaves - 1, 0)
        sf[ti, :nt] = t.split_feature[:nt]
        thr[ti, :nt] = t.threshold[:nt]
        dt[ti, :nt] = t.decision_type[:nt]
        lv[ti, :t.num_leaves] = t.leaf_value[:t.num_leaves]
        base[ti % k] += (t.expected_value() if t.num_leaves > 1
                         else (t.leaf_value[0] if len(t.leaf_value) else 0.0))
        if t.num_leaves > 1:
            f_, z_, on_, ol_, os_, pl_ = _leaf_paths(t, D)
            feat[ti, :t.num_leaves] = f_
            zfrac[ti, :t.num_leaves] = z_
            occ_node[ti, :t.num_leaves] = on_
            occ_left[ti, :t.num_leaves] = ol_
            occ_slot[ti, :t.num_leaves] = os_
            plen[ti, :t.num_leaves] = pl_

    # occurrence -> slot one-hot (static per tree, tiny)
    occ_map = (occ_slot[..., None] == np.arange(D)) \
        & (occ_node[..., None] >= 0)                       # (T, L, D_occ, D_slot)
    cls = np.arange(T) % k

    f32 = jnp.float32
    Xd = jnp.asarray(X.T, f32)                                  # (nf, N)
    Xnan = jnp.isnan(Xd)

    from .telemetry.watchdog import watched_jit

    @functools.partial(watched_jit, name="shap_batch", warn_after=0)
    def run(Xd, Xnan, arrays):
        # N rides the LAST (lane) axis throughout: the per-row tensors are
        # (L, D, N)-shaped so the 128-lane VPU is fully utilised (an
        # (N, L, D) layout leaves the tiny L/D dims on the lanes and runs
        # ~50x slower)
        def body(phi, a):
            (sf_t, thr_t, dt_t, lv_t, feat_t, z_t, occ_node_t, occ_left_t,
             occ_map_t, plen_t, cls_t) = a
            # decisions at every node (ni, N)
            v = Xd[sf_t, :]
            isnan = Xnan[sf_t, :]
            mt = (dt_t >> 2) & 3
            dfl = (dt_t & 2) != 0
            miss = isnan | ((mt == 1)[:, None] & (jnp.abs(v) < 1e-35))
            go = jnp.where(isnan, 0.0, v) <= thr_t[:, None].astype(f32)
            dec = jnp.where(miss & (mt != 0)[:, None], dfl[:, None], go)
            # hot per (L, slot, N): every occurrence agrees with the path
            occ_ok = jnp.where(occ_node_t[..., None] >= 0,
                               dec[jnp.clip(occ_node_t, 0, None), :]
                               == occ_left_t[..., None], True)  # (L, Docc, N)
            o = jnp.all(jnp.where(occ_map_t[..., None],
                                  occ_ok[:, :, None, :], True),
                        axis=1)                                 # (L, Dslot, N)
            of = jnp.where(o, 1.0, 0.0).astype(f32)
            z = jnp.asarray(z_t, f32)[..., None]                # (L, D, 1)

            # ---- extend the path polynomial (dummy root element first) ----
            N = Xd.shape[1]
            pw = jnp.zeros((L, D + 1, N), f32).at[:, 0, :].set(1.0)
            jj = jnp.arange(D + 1, dtype=f32)
            for kk in range(1, D + 1):
                act = (kk <= plen_t)[:, None, None]             # (L, 1, 1)
                zk = z[:, kk - 1:kk, :]
                ok = of[:, kk - 1:kk, :]
                pw_prev = jnp.pad(pw[:, :-1, :], ((0, 0), (1, 0), (0, 0)))
                neww = (zk * pw * ((kk - jj) / (kk + 1))[None, :, None]
                        + ok * pw_prev * (jj / (kk + 1))[None, :, None])
                neww = jnp.where((jj <= kk)[None, :, None], neww, pw)
                pw = jnp.where(act, neww, pw)

            # ---- per-feature unwound sums ----
            d_leaf = plen_t[:, None].astype(f32)                # (L, 1)
            pw_at_d = jnp.take_along_axis(
                pw, plen_t[:, None, None].repeat(N, 2), axis=1)[:, 0, :]
            contribs = []
            for i in range(1, D + 1):
                i_act = (i <= plen_t)[:, None]                  # (L, 1)
                oi = of[:, i - 1, :]
                zi = z[:, i - 1, :]
                next_one = pw_at_d
                total = jnp.zeros_like(pw_at_d)
                for j in range(D - 1, -1, -1):
                    j_act = (j <= plen_t - 1)[:, None]
                    dp1 = d_leaf + 1.0
                    tmp_hot = next_one * dp1 / ((j + 1) * jnp.maximum(oi, 0.5))
                    t_cold = jnp.where(
                        zi != 0.0,
                        (pw[:, j, :] / jnp.where(zi != 0.0, zi, 1.0))
                        / jnp.maximum((d_leaf - j) / dp1, 1e-30), 0.0)
                    add = jnp.where(oi > 0.5, tmp_hot, t_cold)
                    nxt = jnp.where(
                        oi > 0.5,
                        pw[:, j, :] - tmp_hot * zi * (d_leaf - j) / dp1,
                        next_one)
                    total = jnp.where(j_act, total + add, total)
                    next_one = jnp.where(j_act, nxt, next_one)
                w_i = total * (oi - zi) * lv_t[:, None].astype(f32)
                contribs.append(jnp.where(i_act, w_i, 0.0))
            contrib = jnp.stack(contribs, axis=1)               # (L, D, N)

            # scatter per-slot contributions to features:
            # (nf+1, L*D) @ (L*D, N)
            oh = jax.nn.one_hot(jnp.where(feat_t >= 0, feat_t, nf),
                                nf + 1, dtype=f32).reshape(L * D, nf + 1)
            phi_t = oh.T @ contrib.reshape(L * D, N)            # (nf+1, N)
            phi = phi.at[cls_t].add(phi_t[:nf, :])
            return phi, None

        phi0 = jnp.zeros((k, nf, Xd.shape[1]), f32)
        phi, _ = jax.lax.scan(body, phi0, arrays)
        return phi

    arrays = (jnp.asarray(sf), jnp.asarray(thr), jnp.asarray(dt),
              jnp.asarray(lv, f32), jnp.asarray(feat), jnp.asarray(zfrac),
              jnp.asarray(occ_node), jnp.asarray(occ_left),
              jnp.asarray(occ_map), jnp.asarray(plen), jnp.asarray(cls))
    out = np.zeros((n, k, nf + 1))
    # row chunks bound device memory ((L, D, N) intermediates)
    chunk = max(1024, min(n, 65536))
    for s_ in range(0, n, chunk):
        e_ = min(s_ + chunk, n)
        out[s_:e_, :, :nf] = np.asarray(
            run(Xd[:, s_:e_], Xnan[:, s_:e_], arrays),
            np.float64).transpose(2, 0, 1)
    out[:, :, nf] += base[None, :]
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (nf + 1))


def predict_contrib(trees: List[Tree], X: np.ndarray, num_class: int) -> np.ndarray:
    n, nf = X.shape
    k = max(num_class, 1)
    # device path: one jitted scan over padded tree arrays — numeric splits
    # only (categorical trees keep the exact host walk), bounded depth.
    # f32 threshold compares can flip rows sitting exactly on a bin edge
    # (shifting attribution between correlated features by ~1e-3), so the
    # device path engages only on the TPU for large batches where the
    # host walk would take minutes; LGBTPU_SHAP_DEVICE=1/0 forces it
    import os as _os
    import jax as _jax
    has_cat = any((np.asarray(t.decision_type[:max(t.num_leaves - 1, 0)])
                   & 1).any() for t in trees)
    max_d = max((_raw_tree_depth(t) for t in trees if t.num_leaves > 1),
                default=0)
    force = _os.environ.get("LGBTPU_SHAP_DEVICE", "")
    want = (force == "1"
            or (force != "0"
                and _jax.default_backend() in ("tpu", "axon")
                and n * len(trees) >= 1_000_000))
    if trees and want and not has_cat and 0 < max_d <= 24:
        try:
            return _shap_device(trees, X, num_class, max_d)
        except Exception as ex:  # pragma: no cover — host walk always works
            from .utils.log import log_warning
            log_warning(f"device TreeSHAP failed ({ex}); using host path")
    out = np.zeros((n, k, nf + 1), np.float64)
    for ti, tree in enumerate(trees):
        kk = ti % k
        if tree.num_leaves <= 1:
            out[:, kk, nf] += tree.leaf_value[0] if len(tree.leaf_value) else 0.0
            continue
        out[:, kk, nf] += tree.expected_value()
        # chunk rows: the batched recursion keeps O(depth^2 * chunk) copies
        # of the path arrays alive along the DFS
        for s in range(0, n, 16384):
            e = min(s + 16384, n)
            dec = _all_decisions(tree, X[s:e])
            phi = np.zeros((e - s, nf + 1), np.float64)
            _tree_shap_batch(tree, dec, phi)
            out[s:e, kk, :nf] += phi[:, :nf]
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (nf + 1))
