"""SHAP feature contributions (pred_contrib).

Reference: src/io/tree.cpp TreeSHAP (Lundberg's exact algorithm) used by
GBDT::PredictContrib (gbdt.cpp:655). Exact per-row TreeSHAP over host trees; output
layout matches the reference: (N, F+1) per class with the expected value in the last
column. Round-1 implementation is host-side Python — correct but not optimised for very
large prediction batches.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import Tree


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index, zero_fraction, one_fraction, pweight):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight


def _extend_path(path: List[_PathElem], zero_fraction, one_fraction, feature_index):
    path.append(_PathElem(feature_index, zero_fraction, one_fraction,
                          1.0 if len(path) == 0 else 0.0))
    d = len(path) - 1
    for i in range(d - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (d + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (d - i) / (d + 1)


def _unwind_path(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (d - i) / (d + 1)
        else:
            path[i].pweight = path[i].pweight * (d + 1) / (zero_fraction * (d - i))
    for i in range(path_index, d):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction
    path.pop()


def _unwound_path_sum(path: List[_PathElem], path_index):
    d = len(path) - 1
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[d].pweight
    total = 0.0
    for i in range(d - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (d + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * ((d - i) / (d + 1))
        elif zero_fraction != 0:
            total += (path[i].pweight / zero_fraction) / ((d - i) / (d + 1))
    return total


def _decision(tree: Tree, node: int, x: np.ndarray) -> bool:
    f = int(tree.split_feature[node])
    v = x[f]
    dt = int(tree.decision_type[node])
    if dt & 1:  # categorical
        if np.isnan(v) or v < 0:
            return False
        c = int(v)
        kcat = int(tree.threshold_bin[node])
        s, e = tree.cat_boundaries[kcat], tree.cat_boundaries[kcat + 1]
        if c // 32 >= e - s:
            return False
        return bool((int(tree.cat_threshold[s + c // 32]) >> (c % 32)) & 1)
    missing_type = (dt >> 2) & 3
    is_missing = np.isnan(v) or (missing_type == 1 and abs(v) < 1e-35)
    if is_missing and missing_type != 0:
        return bool(dt & 2)  # default left
    if np.isnan(v):
        v = 0.0
    return v <= tree.threshold[node]


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray, node: int,
               path: List[_PathElem], parent_zero_fraction: float,
               parent_one_fraction: float, parent_feature_index: int) -> None:
    path = [
        _PathElem(p.feature_index, p.zero_fraction, p.one_fraction, p.pweight)
        for p in path
    ]
    _extend_path(path, parent_zero_fraction, parent_one_fraction,
                 parent_feature_index)
    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, len(path)):
            w = _unwound_path_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * \
                tree.leaf_value[leaf]
        return
    hot = _decision(tree, node, x)
    hot_child = int(tree.left_child[node] if hot else tree.right_child[node])
    cold_child = int(tree.right_child[node] if hot else tree.left_child[node])
    w_node = _node_weight(tree, node)
    w_hot = _child_weight(tree, hot_child)
    w_cold = _child_weight(tree, cold_child)
    hot_zero_fraction = w_hot / w_node if w_node > 0 else 0.0
    cold_zero_fraction = w_cold / w_node if w_node > 0 else 0.0
    incoming_zero = 1.0
    incoming_one = 1.0
    f = int(tree.split_feature[node])
    # undo previous split on the same feature along the path
    path_index = next((i for i in range(len(path))
                       if path[i].feature_index == f), -1)
    if path_index >= 0:
        incoming_zero = path[path_index].zero_fraction
        incoming_one = path[path_index].one_fraction
        _unwind_path(path, path_index)
    _tree_shap(tree, x, phi, hot_child, path,
               hot_zero_fraction * incoming_zero, incoming_one, f)
    _tree_shap(tree, x, phi, cold_child, path,
               cold_zero_fraction * incoming_zero, 0.0, f)


def _node_weight(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


_child_weight = _node_weight


def predict_contrib(trees: List[Tree], X: np.ndarray, num_class: int) -> np.ndarray:
    n, nf = X.shape
    k = max(num_class, 1)
    out = np.zeros((n, k, nf + 1), np.float64)
    for ti, tree in enumerate(trees):
        kk = ti % k
        if tree.num_leaves <= 1:
            out[:, kk, nf] += tree.leaf_value[0] if len(tree.leaf_value) else 0.0
            continue
        expected = tree.expected_value()
        out[:, kk, nf] += expected
        for r in range(n):
            phi = np.zeros(nf + 1, np.float64)
            _tree_shap(tree, X[r], phi, 0, [], 1.0, 1.0, -1)
            out[r, kk, :nf] += phi[:nf]
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (nf + 1))
