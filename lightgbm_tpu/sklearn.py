"""scikit-learn estimator API.

Reference: python-package/lightgbm/sklearn.py — LGBMModel (:535), LGBMRegressor (:1409),
LGBMClassifier (:1524), LGBMRanker (:1832), custom objective/metric wrappers (:157,:244).
Class names match the reference for drop-in porting.
"""
from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

from .basic import Booster, Dataset
from .callback import early_stopping as _early_stopping_cb
from .callback import log_evaluation as _log_evaluation_cb
from .config import resolve_aliases
from .engine import train as _train
from .utils.log import LightGBMError, log_warning

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]


def _objective_fn_wrapper(func):
    """Wrap sklearn-style fobj(y_true, y_pred) into engine fobj(preds, dataset)."""
    def inner(preds, dataset):
        labels = dataset.get_label()
        res = func(labels, preds)
        if len(res) == 2:
            grad, hess = res
        else:
            raise ValueError("custom objective must return (grad, hess)")
        return np.asarray(grad), np.asarray(hess)
    return inner


def _eval_fn_wrapper(func):
    def inner(preds, dataset):
        labels = dataset.get_label()
        res = func(labels, preds)
        return res
    return inner


class LGBMModel:
    """Base estimator (reference: sklearn.py:535)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None, n_jobs: int = -1,
                 importance_type: str = "split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration = -1
        self._n_features = -1
        self._classes = None
        self._n_classes = -1
        self._objective = objective

    # -- sklearn plumbing ----------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin, "objective": self.objective,
            "class_weight": self.class_weight, "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples, "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree, "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda, "random_state": self.random_state,
            "n_jobs": self.n_jobs, "importance_type": self.importance_type,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _default_objective(self) -> str:
        return "regression"

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        obj = params.pop("objective", None)
        if callable(obj):
            params["objective"] = "none"
        elif obj is not None:
            params["objective"] = obj
        else:
            params["objective"] = self._default_objective()
        if self.random_state is not None:
            params["seed"] = (self.random_state
                              if isinstance(self.random_state, int) else 0)
        params.pop("random_state", None)
        params.pop("n_jobs", None)
        # alias-style names pass straight through the config resolver
        return params

    def _sample_weight_from_class_weight(self, y, sample_weight):
        if self.class_weight is None:
            return sample_weight
        classes, counts = np.unique(y, return_counts=True)
        if self.class_weight == "balanced":
            wmap = {c: len(y) / (len(classes) * cnt)
                    for c, cnt in zip(classes, counts)}
        elif isinstance(self.class_weight, dict):
            wmap = self.class_weight
        else:
            raise ValueError("class_weight must be 'balanced' or a dict")
        cw = np.asarray([wmap.get(v, 1.0) for v in y], np.float64)
        if sample_weight is None:
            return cw
        return cw * np.asarray(sample_weight, np.float64)

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, feature_name="auto", categorical_feature="auto",
            callbacks=None, init_model=None) -> "LGBMModel":
        params = self._process_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        fobj = _objective_fn_wrapper(self.objective) if callable(self.objective) else None
        feval = _eval_fn_wrapper(eval_metric) if callable(eval_metric) else None

        y_arr = np.asarray(y).reshape(-1)
        sample_weight = self._sample_weight_from_class_weight(y_arr, sample_weight)
        train_set = Dataset(X, label=y_arr, weight=sample_weight, group=group,
                            init_score=init_score, feature_name=feature_name,
                            categorical_feature=categorical_feature, params=params)
        valid_sets = []
        valid_names = eval_names
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                    continue
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vis = eval_init_score[i] if eval_init_score else None
                valid_sets.append(train_set.create_valid(
                    vx, label=np.asarray(vy).reshape(-1), weight=vw, group=vg,
                    init_score=vis))

        self._evals_result = {}
        callbacks = list(callbacks or [])
        from .callback import record_evaluation
        if valid_sets:
            callbacks.append(record_evaluation(self._evals_result))

        if fobj is not None:
            booster = Booster(params=params, train_set=train_set)
            for vi, vs in enumerate(valid_sets):
                name = (valid_names[vi] if valid_names
                        else f"valid_{vi}")
                booster.add_valid(vs, name)
            for _ in range(self.n_estimators):
                booster.update(fobj=fobj)
            self._Booster = booster
        else:
            self._Booster = _train(params, train_set,
                                   num_boost_round=self.n_estimators,
                                   valid_sets=valid_sets or None,
                                   valid_names=valid_names, feval=feval,
                                   init_model=(init_model.booster_
                                               if isinstance(init_model, LGBMModel)
                                               else init_model),
                                   callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._n_features = train_set.num_feature()
        return self

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted")
        return self._Booster.predict(X, raw_score=raw_score,
                                     start_iteration=start_iteration,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf, pred_contrib=pred_contrib)

    # -- fitted attributes ---------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit beforehand.")
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def best_score_(self) -> Dict:
        return self._best_score

    @property
    def n_features_(self) -> int:
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        return self._n_features

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        return self.booster_.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        return np.asarray(self.booster_.feature_name())

    @property
    def n_estimators_(self) -> int:
        return self.booster_.current_iteration()

    @property
    def n_iter_(self) -> int:
        return self.booster_.current_iteration()

    @property
    def objective_(self):
        return self.objective or self._default_objective()


class LGBMRegressor(LGBMModel):
    """reference: sklearn.py:1409."""

    def _default_objective(self) -> str:
        return "regression"

    def score(self, X, y, sample_weight=None) -> float:
        pred = self.predict(X)
        y = np.asarray(y, np.float64).reshape(-1)
        w = np.ones_like(y) if sample_weight is None else np.asarray(sample_weight)
        ybar = np.average(y, weights=w)
        ss_res = np.sum(w * (y - pred) ** 2)
        ss_tot = np.sum(w * (y - ybar) ** 2)
        return float(1.0 - ss_res / max(ss_tot, 1e-300))


class LGBMClassifier(LGBMModel):
    """reference: sklearn.py:1524."""

    def _default_objective(self) -> str:
        return "binary" if (self._n_classes is None or self._n_classes <= 2) \
            else "multiclass"

    def fit(self, X, y, **kwargs):
        y_arr = np.asarray(y).reshape(-1)
        self._classes, y_enc = np.unique(y_arr, return_inverse=True)
        self._n_classes = len(self._classes)
        params_obj = self.objective
        if not callable(params_obj) and params_obj is None:
            if self._n_classes > 2:
                self._other_params["num_class"] = self._n_classes
                self.objective = "multiclass"
            else:
                self.objective = "binary"
        elif isinstance(params_obj, str) and params_obj.startswith("multiclass"):
            self._other_params["num_class"] = self._n_classes
        try:
            return super().fit(X, y_enc.astype(np.float64), **kwargs)
        finally:
            self.objective = params_obj

    def predict_proba(self, X, raw_score: bool = False, start_iteration: int = 0,
                      num_iteration: Optional[int] = None, **kwargs) -> np.ndarray:
        res = super().predict(X, raw_score=raw_score,
                              start_iteration=start_iteration,
                              num_iteration=num_iteration)
        if raw_score:
            return res
        if res.ndim == 1:
            return np.column_stack([1.0 - res, res])
        return res

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, **kwargs):
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(X, raw_score=raw_score,
                                   start_iteration=start_iteration,
                                   num_iteration=num_iteration,
                                   pred_leaf=pred_leaf, pred_contrib=pred_contrib)
        proba = self.predict_proba(X, start_iteration=start_iteration,
                                   num_iteration=num_iteration)
        return self._classes[np.argmax(proba, axis=1)]

    def score(self, X, y, sample_weight=None) -> float:
        pred = self.predict(X)
        return float(np.average(pred == np.asarray(y).reshape(-1),
                                weights=sample_weight))

    @property
    def classes_(self) -> np.ndarray:
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes


class LGBMRanker(LGBMModel):
    """reference: sklearn.py:1832."""

    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, eval_set=None, eval_group=None, eval_at=(1, 2, 3, 4, 5),
            **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        self._other_params["eval_at"] = list(eval_at)
        return super().fit(X, y, group=group, eval_set=eval_set,
                           eval_group=eval_group, **kwargs)
