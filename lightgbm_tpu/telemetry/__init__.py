"""Unified telemetry: trace spans, training metrics, recompile watchdog.

One master switch drives the whole subsystem (param ``telemetry=True``,
or :func:`configure` directly):

  * **span tracer** (:mod:`.tracer`) — nested host-side spans exported as
    Chrome/Perfetto trace-event JSON via :func:`export_trace`;
  * **metrics registry** (:mod:`.metrics`) — counters/gauges/time
    histograms plus the per-iteration training records the GBDT loop
    emits, streamed to a JSONL sink (param ``telemetry_out``);
  * **recompile watchdog** (:mod:`.watchdog`) — always-on compile
    counting per jitted entry point with threshold warnings (param
    ``telemetry_recompile_threshold``);
  * **multi-host straggler detection** lives in
    :mod:`lightgbm_tpu.parallel.straggler` (it needs the process mesh,
    which is the parallel layer's concern) and reports through the
    registry here.

Everything is a no-op behind a single boolean check when disabled, so
instrumentation can stay in hot paths unconditionally.
"""
from __future__ import annotations

import atexit
from typing import Any, Dict, Optional

from . import costmodel
from .context import (TRACE_HEADER, AccessLog, TailRing, TraceContext,
                      new_trace_id, request_complete, request_instant,
                      request_span)
from .costmodel import cost_summary, machine_balance
from .metrics import (MetricsRegistry, device_memory_gb, global_registry,
                      host_rss_gb, memory_snapshot)
from .prometheus import registry_text, render_parts, render_prometheus
from .quality import (QualityMonitor, QualityProfile, js_divergence,
                      psi, quality_sidecar_path)
from .tracer import SpanTracer, global_tracer
from .watchdog import (WatchEntry, get_recompile_threshold, host_sync_count,
                       launch_count, note_host_sync, note_launch,
                       recompile_counts, reset_counters,
                       reset_watchdog, set_recompile_threshold,
                       watchdog_summary, watched_jit)

__all__ = [
    "SpanTracer", "MetricsRegistry", "WatchEntry",
    "global_tracer", "global_registry",
    "configure", "enabled", "enabled_source", "enable", "disable", "reset",
    "span", "instant", "counter_sample", "inc", "gauge", "observe",
    "quantiles", "record", "export_trace", "flush", "summary",
    "watched_jit", "recompile_counts", "watchdog_summary",
    "set_recompile_threshold", "get_recompile_threshold", "reset_watchdog",
    "launch_count", "host_sync_count", "note_host_sync", "note_launch",
    "reset_counters", "costmodel", "cost_summary", "machine_balance",
    "memory_snapshot", "device_memory_gb", "host_rss_gb",
    "TraceContext", "TailRing", "AccessLog", "TRACE_HEADER",
    "new_trace_id", "request_span", "request_complete", "request_instant",
    "render_prometheus", "render_parts", "registry_text",
    "QualityMonitor", "QualityProfile", "psi", "js_divergence",
    "quality_sidecar_path",
]

_trace_out: Optional[str] = None
# who enabled telemetry: "api" (user called configure/enable directly) or
# "params" (a Booster's construction params). Param-driven enablement is
# per-model: constructing a later Booster WITHOUT telemetry params turns it
# off again, so model B never inherits model A's sinks or per-iteration
# sync overhead; an explicit API enable is never clobbered by a Booster.
_enabled_source: Optional[str] = None


def configure(enabled: bool = True, metrics_out: Optional[str] = None,
              trace_out: Optional[str] = None,
              recompile_threshold: Optional[int] = None,
              cost_capture: Optional[str] = None,
              _source: str = "api") -> None:
    """Turn telemetry on/off and point its sinks.

    ``metrics_out`` — JSONL path for streamed records; ``trace_out`` —
    Chrome trace JSON written by :func:`flush` (training calls it at the
    end of ``train()``); ``recompile_threshold`` — watchdog warn level;
    ``cost_capture`` — XLA cost-model mode (``auto``/``off``/``lowered``/
    ``full``, see :mod:`.costmodel`; env ``LGBTPU_COST`` overrides)."""
    global _trace_out, _enabled_source
    if enabled:
        global_tracer.enable()
        global_registry.enable()
        _enabled_source = _source
    else:
        global_tracer.disable()
        global_registry.disable()
        _enabled_source = None
    costmodel.configure(enabled=enabled, mode=cost_capture)
    if metrics_out is not None:
        global_registry.set_sink(metrics_out or None)
    if trace_out is not None:
        _trace_out = trace_out or None
    if recompile_threshold is not None:
        set_recompile_threshold(recompile_threshold)


def enabled_source() -> Optional[str]:
    return _enabled_source


def enabled() -> bool:
    return global_tracer.enabled or global_registry.enabled


def enable() -> None:
    configure(enabled=True)


def disable() -> None:
    configure(enabled=False)


def reset() -> None:
    """Clear collected spans/metrics/cost records (keeps enabled state
    and sinks)."""
    global_tracer.reset()
    global_registry.reset()
    costmodel.reset()


# -- thin instrument aliases (the hot-path entry points) --------------------
span = global_tracer.span
instant = global_tracer.instant
counter_sample = global_tracer.counter
inc = global_registry.inc
gauge = global_registry.gauge
observe = global_registry.observe
quantiles = global_registry.quantiles
record = global_registry.record


def export_trace(path: str) -> str:
    """Write the span buffer as Chrome/Perfetto trace-event JSON."""
    return global_tracer.export_trace(path)


def trace_out_path() -> Optional[str]:
    return _trace_out


def flush() -> None:
    """Write the configured trace file (if any). Safe to call repeatedly."""
    if _trace_out:
        try:
            export_trace(_trace_out)
        except OSError:
            pass


@atexit.register
def _flush_at_exit() -> None:   # best-effort for CLI / script runs
    flush()


def summary() -> Dict[str, Any]:
    """One dict with everything: metrics snapshot, span phase totals,
    recompile rollup, memory, and sink locations."""
    phases = global_tracer.phase_snapshot()
    counts = global_tracer.phase_counts()
    out: Dict[str, Any] = {
        "enabled": enabled(),
        **global_registry.snapshot(),
        "phases": {k: {"total_s": round(v, 6), "calls": counts.get(k, 0),
                       "mean_s": round(v / max(counts.get(k, 1), 1), 6)}
                   for k, v in sorted(phases.items(),
                                      key=lambda kv: -kv[1])},
        "recompiles": watchdog_summary(),
        # XLA flops/HBM accounting + roofline verdicts per watched entry
        # (docs/OBSERVABILITY.md "Cost model & profiling")
        "cost": cost_summary(),
        "memory": memory_snapshot(),
        # events the bounded span buffer had to drop (the tracer warns
        # once when this first goes nonzero)
        "trace_dropped_events": global_tracer.dropped,
    }
    if global_registry.sink_path:
        out["telemetry_out"] = global_registry.sink_path
    if _trace_out:
        out["trace_out"] = _trace_out
    return out
