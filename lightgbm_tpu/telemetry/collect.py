"""Cross-process trace collector: merge per-process shards onto one timeline.

Every process (fleet front/supervisor, each serving replica, a training
run) exports its own Chrome-trace shard with event timestamps relative to
its OWN ``perf_counter`` epoch — meaningless across processes.  Each
shard also carries the wall-clock anchor (``clock_sync``: the
``time.time()`` captured at the same instant as that epoch), which is the
one piece of shared truth.  This module shifts every shard onto the
earliest shard's clock and emits ONE Perfetto-loadable file, so a single
request's spans — front routing, replica admission, batcher queue wait,
device dispatch — line up on one timeline.

CLI::

    python -m lightgbm_tpu.telemetry.collect FLEET_DIR -o merged.json
    python -m lightgbm_tpu.telemetry.collect trace_front.json \
        trace_replica_*.json -o merged.json --trace-id 4f2a...

A directory argument collects every ``trace*.json`` inside it.
``--trace-id`` keeps only the named request's events (plus process
metadata) — the single-request drill-down view.
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _read_blob(path: str) -> Dict[str, Any]:
    """One shard file -> parsed blob; ``.gz`` shards (jax.profiler's
    ``*.trace.json.gz``) are transparently decompressed."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        return json.load(fh)


def _find_anchor(blob: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    anchor = (blob.get("otherData") or {}).get("clock_sync")
    if isinstance(anchor, dict) and "unix_time_s" in anchor:
        return anchor
    for ev in blob.get("traceEvents", []):
        if ev.get("name") == "clock_sync":
            args = ev.get("args") or {}
            if "unix_time_s" in args:
                return args
    return None


def _event_matches(ev: Dict[str, Any], trace_id: str) -> bool:
    args = ev.get("args") or {}
    if args.get("trace_id") == trace_id:
        return True
    ids = args.get("trace_ids")
    return isinstance(ids, (list, tuple)) and trace_id in ids


def merge_traces(paths: Sequence[str], trace_id: Optional[str] = None
                 ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Merge shard files; returns ``(merged_blob, summary)``.

    Shards without a clock anchor (pre-anchor exports) are kept at
    offset 0 and reported in the summary — their events render but are
    NOT aligned."""
    shards: List[Dict[str, Any]] = []
    for path in paths:
        try:
            blob = _read_blob(path)
        except (OSError, ValueError) as e:
            raise RuntimeError(f"cannot read trace shard {path!r}: {e}")
        shards.append({"path": path, "blob": blob,
                       "anchor": _find_anchor(blob)})
    if not shards:
        raise RuntimeError("no trace shards to merge")
    anchored = [s for s in shards if s["anchor"] is not None]
    base_unix = min(s["anchor"]["unix_time_s"] for s in anchored) \
        if anchored else 0.0

    meta: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    shard_summaries = []
    for i, shard in enumerate(shards):
        anchor = shard["anchor"]
        offset_us = ((anchor["unix_time_s"] - base_unix) * 1e6
                     if anchor else 0.0)
        # two shards claiming one pid (pid reuse after a replica restart)
        # would interleave into one Perfetto track; remap the later shard
        pid_map: Dict[int, int] = {}
        n_events = 0
        for ev in shard["blob"].get("traceEvents", []):
            ev = dict(ev)
            pid = ev.get("pid")
            if isinstance(pid, int):
                if pid not in pid_map:
                    owner = seen_pids.get(pid)
                    if owner is not None and owner != shard["path"]:
                        mapped = pid + 1_000_000 * (i + 1)
                    else:
                        seen_pids[pid] = shard["path"]
                        mapped = pid
                    pid_map[pid] = mapped
                ev["pid"] = pid_map[pid]
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + offset_us
            if ev.get("ph") == "M":
                meta.append(ev)
                continue
            if trace_id is not None and not _event_matches(ev, trace_id):
                continue
            events.append(ev)
            n_events += 1
        shard_summaries.append({
            "path": shard["path"],
            "aligned": anchor is not None,
            "offset_ms": round(offset_us / 1e3, 3),
            "replica_rank": (anchor or {}).get("replica_rank"),
            "events": n_events,
        })
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    stamped = [float(ev["ts"]) for ev in events if "ts" in ev]
    blob = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "lightgbm_tpu.telemetry.collect",
            "base_unix_s": base_unix,
            "trace_id_filter": trace_id,
            "shards": shard_summaries,
        },
    }
    summary = {
        "shards": len(shards),
        "unaligned_shards": [s["path"] for s in shards
                             if s["anchor"] is None],
        "events": len(events),
        # device shards may carry flow/metadata events without a ts
        "span_ms": round((max(stamped) - min(stamped)) / 1e3, 3)
        if len(stamped) > 1 else 0.0,
        "processes": sorted({ev["pid"] for ev in events
                             if isinstance(ev.get("pid"), int)}),
    }
    return blob, summary


def _expand(inputs: Sequence[str]) -> List[str]:
    out: List[str] = []
    for item in inputs:
        if os.path.isdir(item):
            out.extend(sorted(glob.glob(os.path.join(item, "trace*.json"))
                              + glob.glob(os.path.join(item,
                                                       "trace*.json.gz"))))
        else:
            out.append(item)
    return out


def write_merged(blob: Dict[str, Any], path: str) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(blob, fh, default=str)
    os.replace(tmp, path)
    return path


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.telemetry.collect",
        description="Merge per-process trace shards onto one wall-clock-"
                    "aligned Perfetto timeline.")
    ap.add_argument("inputs", nargs="+",
                    help="shard files, or directories holding trace*.json")
    ap.add_argument("-o", "--output", default="merged_trace.json",
                    help="merged trace path (default merged_trace.json)")
    ap.add_argument("--trace-id", default=None,
                    help="keep only this request's events")
    args = ap.parse_args(argv)
    paths = _expand(args.inputs)
    if not paths:
        print("collect: no trace shards found", file=sys.stderr)
        return 1
    try:
        blob, summary = merge_traces(paths, trace_id=args.trace_id)
    except RuntimeError as e:
        print(f"collect: {e}", file=sys.stderr)
        return 1
    write_merged(blob, args.output)
    print(json.dumps({"output": args.output, **summary}))
    for warn in summary["unaligned_shards"]:
        print(f"collect: WARNING shard {warn} has no clock_sync anchor — "
              "kept at offset 0 (re-export with a current tracer)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
