"""Distributed request-trace context for the serving fleet.

One request entering the fleet gets ONE trace id — minted at the front
(or accepted from the client) and propagated via the ``X-LGBTPU-Trace``
header through front routing/retry/breaker events, replica admission,
batcher queue wait, batch assembly, and the device dispatch.  Every
process stamps its spans with the trace id, and the cross-process
collector (:mod:`.collect`) merges the per-process shards onto one
wall-clock-aligned timeline.

Three sampling/capture surfaces live here:

  * **head sampling** — the routing tier decides ONCE per request
    (probability ``serve_trace_sample``) whether its spans are recorded;
    the decision rides in the header (``s=0|1``) so every downstream
    process agrees without coordination.  The disabled path is one
    boolean check, so default-rate tracing does not tax the hot path;
  * **tail capture** — errored and SLO-violating requests are captured
    into a bounded ring (:class:`TailRing`) REGARDLESS of the head
    decision: the interesting 0.1% is exactly what a 1% head sample
    would usually miss.  The ring holds compact outcome records (not
    full span trees — those cannot be reconstructed after the fact);
  * **access log** — an append-only JSONL stream (:class:`AccessLog`),
    one line per request with the audit fields (trace_id, outcome,
    latency, deadline, retries, model_sha256).
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .tracer import _NULL_SPAN, global_tracer

TRACE_HEADER = "X-LGBTPU-Trace"

# sampling RNG: an owned, seeded instance (never the np.random global
# stream — lgbtlint LGB004); the fixed seed makes a replica's sampling
# pattern reproducible, which is a feature for debugging, and the pid
# fold keeps fleet replicas from sampling the same request positions
_rng = random.Random(0x7EACE ^ os.getpid())


def new_trace_id() -> str:
    """16 hex chars of process-independent randomness."""
    return os.urandom(8).hex()


@dataclass
class TraceContext:
    """One request's identity: trace id + the head-sampling decision."""

    trace_id: str
    sampled: bool = False

    def header_value(self) -> str:
        return f"{self.trace_id};s={int(self.sampled)}"

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        """Parse ``<trace_id>[;s=0|1]``; None on absent/garbage (the
        request then gets a locally minted context)."""
        if not value:
            return None
        tid, _, opts = value.partition(";")
        tid = tid.strip()
        if not tid or len(tid) > 64 or not all(
                c in "0123456789abcdefABCDEF-_" for c in tid):
            return None
        sampled = False
        for tok in opts.split(";"):
            key, _, val = tok.strip().partition("=")
            if key == "s":
                sampled = val.strip() == "1"
        return cls(trace_id=tid, sampled=sampled)

    @classmethod
    def mint(cls, sample_rate: float) -> "TraceContext":
        """New context with the head-sampling decision taken here."""
        rate = max(float(sample_rate), 0.0)
        return cls(trace_id=new_trace_id(),
                   sampled=rate > 0 and _rng.random() < rate)


def request_span(ctx: Optional[TraceContext], name: str, **args: Any):
    """Span stamped with the request's trace id — records ONLY for
    head-sampled requests (one boolean check otherwise), so per-request
    span emission follows ``serve_trace_sample``, not the global tracer
    switch alone."""
    if ctx is None or not ctx.sampled or not global_tracer.enabled:
        return _NULL_SPAN
    return global_tracer.span(name, trace_id=ctx.trace_id, **args)


def request_complete(ctx: Optional[TraceContext], name: str, start: float,
                     duration: float, **args: Any) -> None:
    """Cross-thread "X" event for a sampled request (queue wait)."""
    if ctx is None or not ctx.sampled or not global_tracer.enabled:
        return
    global_tracer.complete(name, start, duration,
                           trace_id=ctx.trace_id, **args)


def request_instant(ctx: Optional[TraceContext], name: str,
                    **args: Any) -> None:
    """Point event for a sampled request (retry, breaker trip)."""
    if ctx is None or not ctx.sampled or not global_tracer.enabled:
        return
    global_tracer.instant(name, trace_id=ctx.trace_id, **args)


def note_outcome(*, ctx, status: int, latency_ms: float,
                 deadline_ms: float, obj: Dict[str, Any], slo=None,
                 tail=None, access_log=None, retries: int = 0,
                 extra: Optional[Dict[str, Any]] = None,
                 slo_status: Optional[int] = None) -> None:
    """Shared per-request outcome bookkeeping (front AND replica run the
    same flow, so the record schema cannot drift between tiers): SLO
    sample, access-log line, tail capture of errored/SLO-slow requests.

    ``slo_status`` lets the caller record a DIFFERENT status against the
    SLO than the client saw (the front maps transport-exhausted sheds to
    599 so a total outage burns the availability budget, while the
    client still gets its honest 503 + Retry-After)."""
    if slo is not None:
        slo.record(status if slo_status is None else slo_status,
                   latency_ms)
    record: Dict[str, Any] = {
        "trace_id": ctx.trace_id if ctx is not None else None,
        "outcome": int(status),
        "latency_ms": round(latency_ms, 3),
        "deadline_ms": round(float(deadline_ms or 0.0), 3),
        "retries": int(retries),
        "model_sha256": obj.get("model_sha256"),
        "reason": obj.get("reason") or (obj.get("error")
                                        if status != 200 else None),
    }
    if extra:
        record.update(extra)
    if access_log is not None:
        access_log.write(dict(record))
    slow = (status == 200 and slo is not None and slo.p99_target_ms > 0
            and latency_ms > slo.p99_target_ms)
    if tail is not None and (status != 200 or slow):
        record["captured"] = "error" if status != 200 else "slo_slow"
        tail.add(record)


class TailRing:
    """Bounded ring of the requests worth keeping: errored or
    SLO-violating.  Overwrites oldest-first; thread-safe; surfaced via
    ``/stats``."""

    def __init__(self, capacity: int = 256):
        self._ring: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self._captured = 0

    def add(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(record)
            self._captured += 1

    def snapshot(self, last: Optional[int] = None) -> Dict[str, Any]:
        with self._lock:
            rows = list(self._ring)
            captured = self._captured
        if last is not None:
            rows = rows[-int(last):]
        return {"captured": captured, "capacity": self._ring.maxlen,
                "recent": rows}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class AccessLog:
    """Append-only JSONL request log (one line per finished request).

    Append streams are crash-consistent by construction (a torn final
    line is detectable, everything before it survives), mirroring the
    metrics registry's JSONL sink.  Write failures disable the log
    rather than failing requests."""

    SCHEMA = ("ts", "trace_id", "outcome", "latency_ms", "deadline_ms",
              "retries", "model_sha256")

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._fh = None
        self._dead = False

    def write(self, record: Dict[str, Any]) -> None:
        if self._dead:
            return
        record.setdefault("ts", round(time.time(), 6))
        with self._lock:
            if self._fh is None:
                try:
                    self._fh = open(self.path, "a")
                except OSError:
                    self._dead = True
                    return
            try:
                self._fh.write(json.dumps(record) + "\n")
                self._fh.flush()
            except (OSError, TypeError, ValueError):
                self._dead = True

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
