"""Compiled-program cost model: XLA flops/HBM accounting + roofline verdicts.

The watchdog (:mod:`.watchdog`) can say *that* an entry point compiled and
*how often* it dispatches; this module says *what each dispatch costs*.
At every compilation-cache miss of a ``watched_jit`` entry it captures the
XLA executable's own accounting:

  * ``Lowered.cost_analysis()`` — flops, transcendentals, bytes accessed
    (cheap: the jaxpr trace is cached, lowering is ~1 ms, no XLA compile);
  * ``Compiled.cost_analysis()`` + ``Compiled.memory_analysis()`` —
    optimized-HLO cost plus argument/output/temp buffer sizes whose sum is
    the program's peak HBM footprint (``full`` mode only: the AOT
    ``.compile()`` is a SECOND XLA compile of the entry).

From flops and bytes it derives the arithmetic intensity (flops/byte) and
a roofline verdict against the device's machine balance — ``compute-bound``
when the intensity clears the ridge point (peak_flops / peak_HBM_bandwidth),
``hbm-bound`` below it — so an s/tree regression is attributable: did the
program get more flops, more bytes, or neither (dispatch/comms)?

Dispatch-weighted totals feed the per-iteration training record
(``flops`` / ``hbm_bytes`` fields, docs/OBSERVABILITY.md) and the
``cost/<name>/*`` gauge family on ``/metrics``; ``cost_summary()`` is the
rollup in ``telemetry_summary()["cost"]`` and ``/stats``.

Degradation contract: on backends where cost/memory analysis raises or
returns nothing (older jaxlib, exotic plugins) the entry is recorded as
``available: false`` with ``verdict: "unavailable"`` — never a zero that a
budget gate (scripts/perf_sentinel.py) could mistake for a 100%
improvement.

Modes (param ``telemetry_cost``, env ``LGBTPU_COST`` overrides):
``auto``/``lowered`` capture from the lowered module whenever telemetry is
on; ``full`` additionally AOT-compiles for the memory analysis; ``off``
disables capture.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

_VALID_MODES = ("auto", "off", "lowered", "full")

_lock = threading.Lock()
_enabled = False            # master switch (follows telemetry.configure)
_mode = "auto"              # configured mode (param); env wins at resolve
_resolved = "off"           # effective mode after the env override
_records: Dict[str, Dict[str, Any]] = {}     # entry name -> latest record
_flops_total = 0.0          # dispatch-weighted running totals
_bytes_total = 0.0
_balance: Optional[Dict[str, Any]] = None    # cached machine balance

# Published peak dense-f32-equivalent flops and HBM bandwidth per device
# kind (roofline ridge = peak_flops / peak_bw).  Matched by prefix on
# jax's ``device_kind``; LGBTPU_PEAK_FLOPS / LGBTPU_PEAK_BW override for
# unlisted parts.  TPU numbers are the public per-chip specs.
_DEVICE_PEAKS = {
    "TPU v2": (45e12, 700e9),
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6": (918e12, 1640e9),
}
# conservative single-socket CPU estimate (AVX fma) — the exact numbers
# matter less than a stable ridge so CPU verdicts are deterministic
_CPU_DEFAULT = (5e11, 5e10)
_GENERIC_DEFAULT = (1e13, 1e12)


# -- control ----------------------------------------------------------------
def configure(enabled: Optional[bool] = None,
              mode: Optional[str] = None) -> None:
    """Set the capture switch and/or mode; re-resolves the env override."""
    global _enabled, _mode, _resolved
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if mode is not None:
            m = str(mode).strip().lower()
            if m not in _VALID_MODES:
                raise ValueError(
                    f"telemetry_cost={mode!r} is not one of "
                    f"{', '.join(_VALID_MODES)}")
            _mode = m
        env = os.environ.get("LGBTPU_COST", "").strip().lower()
        eff = env if env in _VALID_MODES else _mode
        if eff == "auto":
            eff = "lowered"
        _resolved = eff if _enabled and eff != "off" else "off"


def set_enabled(on: bool) -> None:
    configure(enabled=on)


def active() -> bool:
    """Fast hot-path check: is capture on right now?"""
    return _resolved != "off"


def mode() -> str:
    """Effective capture mode ("off" | "lowered" | "full")."""
    return _resolved


def reset() -> None:
    """Drop captured records and dispatch-weighted totals (keeps the
    enabled state and mode — a new Booster's telemetry reset)."""
    global _flops_total, _bytes_total
    with _lock:
        _records.clear()
        _flops_total = 0.0
        _bytes_total = 0.0


# -- roofline ---------------------------------------------------------------
def machine_balance() -> Dict[str, Any]:
    """Peak flops, HBM bandwidth, and the roofline ridge intensity for
    device 0 (cached; env LGBTPU_PEAK_FLOPS/LGBTPU_PEAK_BW override)."""
    global _balance
    if _balance is not None:
        return dict(_balance)
    kind = platform = "unknown"
    try:
        import jax
        dev = jax.local_devices()[0]
        kind = str(getattr(dev, "device_kind", "") or "unknown")
        platform = str(getattr(dev, "platform", "") or "unknown")
    except Exception:
        pass
    peaks = None
    for prefix, pair in _DEVICE_PEAKS.items():
        if kind.lower().startswith(prefix.lower()):
            peaks = pair
            break
    if peaks is None:
        peaks = _CPU_DEFAULT if platform == "cpu" else _GENERIC_DEFAULT
    peak_flops, peak_bw = peaks
    try:
        peak_flops = float(os.environ.get("LGBTPU_PEAK_FLOPS", peak_flops))
        peak_bw = float(os.environ.get("LGBTPU_PEAK_BW", peak_bw))
    except ValueError:
        pass
    _balance = {
        "device_kind": kind,
        "platform": platform,
        "peak_flops_per_s": peak_flops,
        "peak_hbm_bytes_per_s": peak_bw,
        "ridge_intensity": round(peak_flops / max(peak_bw, 1.0), 3),
    }
    return dict(_balance)


def roofline_verdict(flops: float, bytes_accessed: float) -> Dict[str, Any]:
    """Classify one program against the device roofline."""
    if bytes_accessed <= 0.0:
        return {"intensity": None, "verdict": "unavailable"}
    bal = machine_balance()
    intensity = flops / bytes_accessed
    verdict = ("compute-bound" if intensity >= bal["ridge_intensity"]
               else "hbm-bound")
    return {"intensity": round(intensity, 4), "verdict": verdict}


# -- capture ----------------------------------------------------------------
def _normalize_cost(ca: Any) -> Optional[Dict[str, float]]:
    """``cost_analysis()`` returns a dict (Lowered) or a list of dicts
    (Compiled, one per partition) depending on backend/version."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    if "flops" not in ca and "bytes accessed" not in ca:
        return None
    return ca


def _build_record(name: str, ca: Any, source: str,
                  mem: Any = None) -> Dict[str, Any]:
    norm = _normalize_cost(ca)
    if norm is None:
        return _unavailable_record(
            name, f"{source} cost_analysis returned no flops/bytes")
    flops = float(norm.get("flops", 0.0))
    bytes_accessed = float(norm.get("bytes accessed", 0.0))
    if flops < 0.0 or bytes_accessed < 0.0:
        # XLA reports -1 for "unknown" on some backends — that is an
        # unavailable measurement, not a negative cost
        return _unavailable_record(
            name, f"{source} cost_analysis reported unknown (-1) cost")
    rec: Dict[str, Any] = {
        "name": name,
        "available": True,
        "source": source,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "transcendentals": float(norm.get("transcendentals", 0.0)),
        **roofline_verdict(flops, bytes_accessed),
    }
    if mem is not None:
        arg = float(getattr(mem, "argument_size_in_bytes", 0))
        out = float(getattr(mem, "output_size_in_bytes", 0))
        tmp = float(getattr(mem, "temp_size_in_bytes", 0))
        alias = float(getattr(mem, "alias_size_in_bytes", 0))
        rec.update({
            "argument_bytes": arg, "output_bytes": out, "temp_bytes": tmp,
            # aliased (donated) buffers are counted once on the argument
            # side; peak = everything resident while the program runs
            "peak_hbm_bytes": arg + out + tmp - alias,
        })
    return rec


def _unavailable_record(name: str, error: str) -> Dict[str, Any]:
    return {"name": name, "available": False, "verdict": "unavailable",
            "error": error[:200]}


def _store(rec: Dict[str, Any]) -> None:
    from .metrics import global_registry
    name = rec["name"]
    with _lock:
        prev = _records.get(name)
        rec["captures"] = (prev.get("captures", 0) if prev else 0) + 1
        # a compiled/aot capture carries the memory analysis a later
        # lowered-only capture lacks — keep the richer fields current
        if prev and prev.get("available"):
            if not rec.get("available"):
                rec = {**prev, "captures": rec["captures"]}
            else:
                # a lowered re-capture (fresh trace in auto mode) must
                # not DROP the memory fields a previous full/aot capture
                # measured: carry them forward (stamped as such) so the
                # record, the gauges, and the sentinel's peak-HBM check
                # stay populated
                for k in ("argument_bytes", "output_bytes", "temp_bytes",
                          "peak_hbm_bytes"):
                    if k not in rec and k in prev:
                        rec[k] = prev[k]
                        rec["memory_source"] = prev.get(
                            "memory_source", prev.get("source"))
        _records[name] = rec
    if rec.get("available"):
        global_registry.gauge(f"cost/{name}/flops", rec["flops"])
        global_registry.gauge(f"cost/{name}/bytes", rec["bytes_accessed"])
        if rec.get("intensity") is not None:
            global_registry.gauge(f"cost/{name}/intensity",
                                  rec["intensity"])
        if "peak_hbm_bytes" in rec:
            global_registry.gauge(f"cost/{name}/peak_hbm_bytes",
                                  rec["peak_hbm_bytes"])


def _capture(entry, jitted, args: tuple, kwargs: dict) -> None:
    """Capture cost for one freshly traced entry from its concrete args.

    ``jitted.lower`` hits the cached jaxpr trace (the compile that just
    happened populated it), so ``lowered`` mode costs ~1 ms; ``full``
    mode pays one extra XLA compile for ``memory_analysis``."""
    import jax
    try:
        from jax.core import Tracer
    except ImportError:   # moved in newer jax
        from jax._src.core import Tracer
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    if any(isinstance(x, Tracer) for x in leaves):
        # dispatched inside an OUTER trace: abstract args cannot be
        # lowered here — leave cost_seen behind so a later concrete
        # dispatch captures
        return
    name = entry.name
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception as e:
        _store(_unavailable_record(
            name, f"lower failed: {type(e).__name__}: {e}"))
        entry.cost_seen = entry.count
        return
    rec = None
    if _resolved == "full":
        try:
            compiled = lowered.compile()
            rec = _build_record(name, compiled.cost_analysis(), "compiled",
                                mem=compiled.memory_analysis())
        except Exception:
            rec = None   # fall back to the lowered-module analysis
    if rec is None:
        try:
            rec = _build_record(name, lowered.cost_analysis(), "lowered")
        except Exception as e:
            rec = _unavailable_record(
                name, f"cost_analysis failed: {type(e).__name__}: {e}")
    _store(rec)
    entry.cost_seen = entry.count


def note_compiled(entry, compiled) -> None:
    """Capture from an already-compiled AOT executable (the forwarded
    ``.lower(...).compile()`` surface) — the full analysis for free."""
    if not active():
        return
    try:
        rec = _build_record(entry.name, compiled.cost_analysis(), "aot",
                            mem=compiled.memory_analysis())
    except Exception as e:
        rec = _unavailable_record(
            entry.name, f"aot analysis failed: {type(e).__name__}: {e}")
    try:
        _store(rec)
        entry.cost_seen = entry.count
    except Exception:
        pass


def note_dispatch(entry) -> None:
    """Add one dispatch of ``entry`` to the flops/bytes running totals.

    Runs on the dispatch hot path — no lock: like the watchdog's
    ``_launches += 1``, the GIL makes the float adds effectively atomic
    and a once-in-a-blue-moon lost increment costs an epsilon of
    attribution, not correctness."""
    global _flops_total, _bytes_total
    rec = _records.get(entry.name)
    if rec is None or not rec.get("available"):
        return
    _flops_total += rec["flops"]
    _bytes_total += rec["bytes_accessed"]


def after_dispatch(entry, jitted, args: tuple, kwargs: dict) -> None:
    """Post-dispatch hook from watched_jit: capture on a fresh trace,
    then account the dispatch.  Must never break the dispatch path."""
    try:
        if entry.count > entry.cost_seen:
            _capture(entry, jitted, args, kwargs)
        note_dispatch(entry)
    except Exception:    # noqa: BLE001 — observability never raises
        pass


# -- introspection ----------------------------------------------------------
def dispatch_totals() -> Tuple[float, float]:
    """(flops, bytes) executed so far across all captured entries,
    dispatch-weighted — the per-iteration record diffs this."""
    with _lock:
        return _flops_total, _bytes_total


def cost_records() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _records.items()}


def cost_summary() -> Dict[str, Any]:
    """Everything the cost model knows: per-entry records, dispatch-
    weighted totals, and the device roofline they were judged against."""
    with _lock:
        entries = {k: dict(v) for k, v in sorted(_records.items())}
        totals = {"flops": _flops_total, "hbm_bytes": _bytes_total}
    out: Dict[str, Any] = {
        "enabled": active(),
        "mode": _resolved,
        "entries": entries,
        "totals": totals,
    }
    if entries or active():
        out["roofline"] = machine_balance()
    return out
