"""Metrics registry: counters, gauges, time histograms, JSONL sink.

Holds the per-run training metrics the span tracer cannot express —
monotonic counters (iterations, recompiles), point-in-time gauges (peak
HBM), and log-bucketed time histograms — plus the stream of per-iteration
training records the GBDT loop emits. Records append to an optional JSONL
sink as they arrive, so a crashed run still leaves its telemetry behind.

The device/host memory probes mirror the ones bench.py has always
reported (peak_bytes_in_use from ``device.memory_stats()``, live-array
residency as the tunnel fallback, ru_maxrss for host RSS).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

# time-histogram bucket upper bounds, seconds (last bucket is +inf)
_HIST_BOUNDS = (0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3,
                1.0, 3.0, 10.0, 30.0, 100.0, 300.0)
_MAX_RECORDS = int(os.environ.get("LIGHTGBM_TPU_METRICS_MAX_RECORDS",
                                  1_000_000))


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms + record stream."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Dict[str, Any]] = {}
        self._records: List[Dict[str, Any]] = []
        self._sink_path: Optional[str] = None
        self._sink_fh = None

    # -- control -----------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._counters = {}
            self._gauges = {}
            self._hists = {}
            self._records = []

    def set_sink(self, path: Optional[str]) -> None:
        """Point the JSONL record sink at ``path`` (None closes it)."""
        with self._lock:
            if self._sink_fh is not None:
                try:
                    self._sink_fh.close()
                except OSError:
                    pass
                self._sink_fh = None
            self._sink_path = path

    @property
    def sink_path(self) -> Optional[str]:
        return self._sink_path

    # -- instruments -------------------------------------------------------
    def inc(self, name: str, value: float = 1.0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, seconds: float,
                bounds: Optional[tuple] = None) -> None:
        """Add one sample to the named histogram.  ``bounds`` overrides
        the log-time bucket upper bounds for value-shaped distributions
        (queue depths, batch sizes); only the FIRST observation's bounds
        stick for a given name."""
        if not self.enabled:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                bb = tuple(bounds) if bounds is not None else _HIST_BOUNDS
                h = self._hists[name] = {
                    "count": 0, "sum": 0.0, "min": float("inf"),
                    "max": 0.0, "bounds": bb,
                    "buckets": [0] * (len(bb) + 1)}
            h["count"] += 1
            h["sum"] += seconds
            h["min"] = min(h["min"], seconds)
            h["max"] = max(h["max"], seconds)
            for i, bound in enumerate(h["bounds"]):
                if seconds <= bound:
                    h["buckets"][i] += 1
                    break
            else:
                h["buckets"][-1] += 1

    def quantiles(self, name: str, qs=(0.5, 0.95, 0.99)
                  ) -> Dict[str, float]:
        """Approximate quantiles from the named histogram's buckets
        (linear interpolation inside the hit bucket, clamped to the
        observed min/max) — {"p50": ..., "p95": ..., "p99": ...}."""
        with self._lock:
            h = self._hists.get(name)
            if h is None or not h["count"]:
                return {}
            buckets = list(h["buckets"])
            bounds = list(h["bounds"])
            total, vmin, vmax = h["count"], h["min"], h["max"]
        out: Dict[str, float] = {}
        for q in qs:
            # nudge the rank target down by an epsilon: q*total lands
            # EXACTLY on a cumulative-bucket boundary whenever the
            # quantile value sits on a bucket bound (0.95*20 is
            # 19.000000000000004 in binary), and without the nudge the
            # walk would step past the bucket actually holding the value
            # and report from the NEXT one
            target = q * total - 1e-9
            cum = 0.0
            val = vmax
            for i, c in enumerate(buckets):
                if c and cum + c >= target:
                    lo = bounds[i - 1] if i > 0 else 0.0
                    hi = bounds[i] if i < len(bounds) else vmax
                    val = lo + (target - cum) / c * (hi - lo)
                    break
                cum += c
            out[f"p{int(q * 100)}"] = round(min(max(val, vmin), vmax), 6)
        return out

    def record(self, obj: Dict[str, Any]) -> None:
        """Append one structured record and stream it to the JSONL sink."""
        if not self.enabled:
            return
        with self._lock:
            if len(self._records) < _MAX_RECORDS:
                self._records.append(obj)
            if self._sink_path is not None:
                if self._sink_fh is None:
                    try:
                        self._sink_fh = open(self._sink_path, "a")
                    except OSError:
                        self._sink_path = None
                        return
                try:
                    self._sink_fh.write(json.dumps(obj) + "\n")
                    self._sink_fh.flush()
                except (OSError, TypeError, ValueError):
                    pass

    # -- introspection -----------------------------------------------------
    @property
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def tail(self, n: int, event: Optional[str] = None
             ) -> List[Dict[str, Any]]:
        """Last ``n`` records (optionally of one event type) without
        copying the whole buffer — per-iteration callbacks poll this."""
        with self._lock:
            if event is None:
                return list(self._records[-n:])
            out: List[Dict[str, Any]] = []
            for r in reversed(self._records):
                if r.get("event") == event:
                    out.append(r)
                    if len(out) == n:
                        break
            return out[::-1]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            hists = {}
            for k, h in self._hists.items():
                mean = h["sum"] / h["count"] if h["count"] else 0.0
                # bounds + per-bucket counts ride along: any cumulative-
                # bucket exporter (the Prometheus text endpoint) needs
                # them, and the summary stats alone cannot rebuild them
                hists[k] = {"count": h["count"],
                            "sum_s": round(h["sum"], 6),
                            "mean_s": round(mean, 6),
                            "min_s": round(h["min"], 6),
                            "max_s": round(h["max"], 6),
                            "bounds": list(h["bounds"]),
                            "buckets": list(h["buckets"])}
            return {"counters": dict(self._counters),
                    "gauges": dict(self._gauges),
                    "histograms": hists,
                    "num_records": len(self._records)}


def host_rss_gb() -> float:
    """Host resident-set peak in GB (0.0 where /usr/bin getrusage missing)."""
    try:
        import resource
        return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                     / 2 ** 20, 4)
    except Exception:
        return 0.0


def device_memory_gb() -> Dict[str, float]:
    """Peak device HBM (or live-array residency on tunnel devices that
    report no allocator stats) — the probe bench.py has always used."""
    out: Dict[str, float] = {}
    try:
        import jax
        import numpy as np
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
        if peak:
            out["peak_hbm_gb"] = round(peak / 2 ** 30, 4)
        else:
            live = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in jax.live_arrays())
            out["device_hbm_gb"] = round(live / 2 ** 30, 4)
    except Exception:
        pass
    return out


def memory_snapshot() -> Dict[str, float]:
    """Combined device + host memory fields for iteration records."""
    out = device_memory_gb()
    rss = host_rss_gb()
    if rss:
        out["host_rss_gb"] = rss
    return out


global_registry = MetricsRegistry()
