"""Device-trace profiling session: XLA timeline + host spans, merged.

The span tracer (:mod:`.tracer`) sees the HOST side of a run — phases,
queue waits, dispatch points.  ``jax.profiler.trace()`` sees the DEVICE
side — every XLA op's start/stop on the accelerator timeline.  Each alone
answers half of "where did the time go"; this module runs a workload under
both and merges them onto ONE wall-clock-aligned Perfetto timeline using
the tracer's clock anchor (:meth:`SpanTracer.clock_sync` — the
``time.time()`` paired with the ``perf_counter`` epoch) and the session's
own anchor captured at ``start_trace``.

API::

    from lightgbm_tpu.telemetry.profile import ProfileSession
    session = ProfileSession("prof_out").start()
    ...   # train / serve / anything that dispatches XLA programs
    info = session.stop()         # info["merged_trace"] -> Perfetto file

or wrap training declaratively with the ``profile_out`` param — ``train()``
runs the whole boosting loop inside a session and logs the merged path.

CLI::

    python -m lightgbm_tpu.telemetry.profile -o prof_out            # tiny
                                                      # synthetic training
    python -m lightgbm_tpu.telemetry.profile -o prof_out --task serve
    python -m lightgbm_tpu.telemetry.profile -o prof_out -- \
        task=train data=train.csv num_iterations=50   # full CLI workload

Outputs in the session directory: ``device/`` (the raw jax.profiler dump,
TensorBoard-loadable), ``trace_host.json`` (host span shard),
``trace_device.json`` (device shard re-anchored to wall clock), and
``merged_trace.json`` (the combined Perfetto timeline).  If the backend
cannot produce a device trace the session degrades to the host shard and
says so in the returned summary — never an exception on the workload
path.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from .collect import merge_traces, write_merged
from .tracer import global_tracer


class ProfileSession:
    """One profiling window: host tracer + ``jax.profiler`` together."""

    def __init__(self, out_dir: str, keep_python_frames: bool = False
                 ) -> None:
        self.out_dir = str(out_dir)
        self.device_dir = os.path.join(self.out_dir, "device")
        # the profiler's own python-stack sampler emits "$file:line fn"
        # frames — hundreds of MB that duplicate the host span tracer's
        # job; dropped from the merged shard unless explicitly kept (the
        # raw dump under device/ always has them)
        self.keep_python_frames = keep_python_frames
        self._t_unix: Optional[float] = None
        self._device_started = False
        self._device_error: Optional[str] = None
        self._was_enabled = False

    def start(self) -> "ProfileSession":
        import jax
        os.makedirs(self.device_dir, exist_ok=True)
        from . import enabled as _tel_enabled
        self._was_enabled = _tel_enabled()
        if not self._was_enabled:
            # spans are the host half of the merge; turn the tracer on for
            # the session (left on afterwards — disabling would also kill
            # a caller's own telemetry mid-run)
            from . import configure
            configure(enabled=True)
        # the device shard's wall-clock anchor: jax.profiler timestamps
        # are relative to start_trace, so the unix time AT start_trace is
        # what aligns them with the host shard's clock_sync
        self._t_unix = time.time()
        try:
            jax.profiler.start_trace(self.device_dir)
            self._device_started = True
        except Exception as e:   # noqa: BLE001 — degrade, don't break work
            self._device_error = f"{type(e).__name__}: {e}"
        return self

    def __enter__(self) -> "ProfileSession":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def _device_shard(self) -> Optional[str]:
        """Re-anchor THIS session's jax.profiler Chrome trace onto the
        wall clock and write it as a mergeable shard.  A reused out_dir
        may hold earlier sessions' dumps — only traces written since
        this session's start are candidates, so a failed profiler start
        can never silently re-anchor a stale timeline."""
        pattern = os.path.join(self.device_dir, "plugins", "profile",
                               "*", "*.trace.json.gz")
        candidates = sorted(
            (p for p in glob.glob(pattern)
             if os.path.getmtime(p) >= (self._t_unix or 0.0) - 1.0),
            key=os.path.getmtime)
        if not candidates:
            self._device_error = (self._device_error
                                  or "profiler produced no trace.json.gz")
            return None
        try:
            with gzip.open(candidates[-1], "rt") as fh:
                blob = json.load(fh)
        except (OSError, ValueError) as e:
            self._device_error = f"unreadable device trace: {e}"
            return None
        if not self.keep_python_frames:
            blob["traceEvents"] = [
                ev for ev in blob.get("traceEvents", [])
                if not str(ev.get("name", "")).startswith("$")]
        blob.setdefault("otherData", {})["clock_sync"] = {
            "unix_time_s": self._t_unix,
            "pid": os.getpid(),
            "producer": "jax.profiler",
        }
        path = os.path.join(self.out_dir, "trace_device.json")
        return write_merged(blob, path)

    def stop(self) -> Dict[str, Any]:
        """End the session; returns paths + merge summary."""
        import jax
        device_ran = False
        if self._device_started:
            try:
                jax.profiler.stop_trace()
                device_ran = True
            except Exception as e:   # noqa: BLE001
                self._device_error = f"stop_trace failed: {e}"
            self._device_started = False
        host_path = os.path.join(self.out_dir, "trace_host.json")
        global_tracer.export_trace(host_path)
        shards: List[str] = [host_path]
        # no successful device session -> no device shard: a stale dump
        # from a previous run in the same out_dir must not be re-anchored
        device_path = self._device_shard() if device_ran else None
        if device_path is not None:
            shards.append(device_path)
        merged_path = os.path.join(self.out_dir, "merged_trace.json")
        blob, msum = merge_traces(shards)
        write_merged(blob, merged_path)
        out: Dict[str, Any] = {
            "out_dir": self.out_dir,
            "host_trace": host_path,
            "device_trace": device_path,
            "merged_trace": merged_path,
            "merged_events": msum["events"],
            "shards": msum["shards"],
            "span_ms": msum["span_ms"],
        }
        if self._device_error:
            out["device_trace_error"] = self._device_error
        return out


# -- CLI workloads ----------------------------------------------------------
def _synthetic_data(rows: int, features: int = 16, seed: int = 7):
    """The shared seeded workload generator — the profile CLI and the
    perf sentinel's budget measurement both use THIS, so the two
    surfaces can never drift onto different data."""
    import numpy as np
    rs = np.random.RandomState(seed)
    X = rs.randn(rows, features).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.3 * rs.randn(rows) > 0).astype(np.float64)
    return X, y


def _run_train(rows: int, iters: int) -> Dict[str, Any]:
    import lightgbm_tpu as lgb
    X, y = _synthetic_data(rows)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "telemetry": True},
                    lgb.Dataset(X, label=y), num_boost_round=iters)
    return {"workload": "train", "rows": rows, "iterations": iters,
            "trees": bst.num_trees()}


def _run_serve(rows: int, iters: int) -> Dict[str, Any]:
    import tempfile

    import lightgbm_tpu as lgb
    from ..serving.registry import ModelRegistry
    X, y = _synthetic_data(rows)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=iters)
    with tempfile.TemporaryDirectory(prefix="lgb_profile_") as td:
        path = os.path.join(td, "model.txt")
        bst.save_model(path)
        reg = ModelRegistry(path, max_batch=64)
        model = reg.current()
        served = 0
        for m in (1, 8, 64):
            model.predict(X[:m], raw_score=True)
            served += m
    return {"workload": "serve", "rows_scored": served,
            "trees": bst.num_trees()}


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.telemetry.profile",
        description="Run a workload under jax.profiler + the host span "
                    "tracer and merge both onto one Perfetto timeline.")
    ap.add_argument("-o", "--out", default="profile_out",
                    help="session directory (default profile_out)")
    ap.add_argument("--task", choices=("train", "serve"), default="train",
                    help="built-in synthetic workload (default train)")
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("cli", nargs="*", metavar="key=value",
                    help="after '--': full lightgbm_tpu CLI params to run "
                         "under the session instead of the synthetic task")
    args = ap.parse_args(argv)
    session = ProfileSession(args.out).start()
    try:
        if args.cli:
            from ..cli import main as cli_main
            rc = cli_main(list(args.cli))
            work: Dict[str, Any] = {"workload": "cli", "rc": rc}
        elif args.task == "serve":
            work = _run_serve(args.rows, args.iters)
        else:
            work = _run_train(args.rows, args.iters)
    finally:
        info = session.stop()
    print(json.dumps({**work, **info}))
    if info.get("device_trace_error"):
        print(f"profile: WARNING device trace unavailable "
              f"({info['device_trace_error']}) — merged timeline holds "
              "host spans only", file=sys.stderr)
    return int(work.get("rc", 0) or 0)


if __name__ == "__main__":
    import sys
    sys.exit(main())
