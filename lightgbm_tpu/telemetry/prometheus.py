"""Prometheus text-exposition rendering of the metrics registry.

Turns :meth:`MetricsRegistry.snapshot` dicts into the Prometheus text
format (version 0.0.4): ``# TYPE``-declared families, ``_total``-suffixed
monotone counters, gauges, and histograms with CUMULATIVE ``le`` buckets
plus ``_sum``/``_count`` — the standard scrape surface every collector
(Prometheus, VictoriaMetrics, Grafana agent) understands.

Naming: registry names are slash-paths (``serve/latency_s``); they map to
``<prefix>_serve_latency_s`` with every non-``[a-zA-Z0-9_:]`` character
folded to ``_``.  The one sanctioned dynamic-name family,
``fleet/replica/<r>/<metric>``, is re-shaped into a LABELED series
(``<prefix>_fleet_replica_<metric>{replica="<r>"}``) so per-replica
cardinality lives in a label value, never in the metric-name space.

``render_parts`` renders SEVERAL snapshots (the fleet aggregate: the
supervisor/front's own registry plus every replica's scrape) under
distinct label sets in ONE pass, so each family gets exactly one
``# TYPE`` line — concatenating independent renders would be invalid
exposition text.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

_PREFIX = "lgbtpu"
_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_REPLICA = re.compile(r"^fleet/replica/([0-9]+)/(.+)$")


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _metric_name(name: str, prefix: str) -> str:
    base = _NAME_BAD.sub("_", name.strip("/"))
    if not base:
        base = "unnamed"
    if base[0].isdigit():
        base = "_" + base
    return f"{prefix}_{base}"


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k]).replace("\\", r"\\").replace(
            '"', r"\"").replace("\n", r"\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _split_replica(name: str) -> Tuple[str, Dict[str, str]]:
    """``fleet/replica/3/up`` -> (``fleet/replica_up``, {replica: "3"})."""
    m = _REPLICA.match(name)
    if m is None:
        return name, {}
    return f"fleet/replica_{m.group(2)}", {"replica": m.group(1)}


class _Family:
    __slots__ = ("mtype", "samples")

    def __init__(self, mtype: str):
        self.mtype = mtype
        # (suffix, labels, value) triples in insertion order
        self.samples: List[Tuple[str, Dict[str, str], float]] = []


def render_parts(parts: Iterable[Tuple[Dict[str, str], Dict[str, Any]]],
                 prefix: str = _PREFIX) -> str:
    """Render ``(labels, snapshot)`` parts as one exposition document."""
    fams: Dict[str, _Family] = {}

    def family(name: str, mtype: str) -> _Family:
        fam = fams.get(name)
        if fam is None:
            fam = fams[name] = _Family(mtype)
        elif fam.mtype != mtype:
            # one name, two types across parts would be invalid text;
            # keep the first registration and coerce to it as a gauge
            fam.mtype = "gauge"
        return fam

    for labels, snap in parts:
        labels = dict(labels or {})
        for name, value in sorted((snap.get("counters") or {}).items()):
            base, extra = _split_replica(name)
            mname = _metric_name(base, prefix) + "_total"
            family(mname, "counter").samples.append(
                ("", {**labels, **extra}, float(value)))
        for name, value in sorted((snap.get("gauges") or {}).items()):
            base, extra = _split_replica(name)
            mname = _metric_name(base, prefix)
            family(mname, "gauge").samples.append(
                ("", {**labels, **extra}, float(value)))
        for name, h in sorted((snap.get("histograms") or {}).items()):
            bounds = h.get("bounds")
            buckets = h.get("buckets")
            if bounds is None or buckets is None:
                continue     # pre-anchor snapshot without bucket export
            base, extra = _split_replica(name)
            mname = _metric_name(base, prefix)
            fam = family(mname, "histogram")
            lb = {**labels, **extra}
            cum = 0
            for bound, count in zip(bounds, buckets):
                cum += int(count)
                fam.samples.append(
                    ("_bucket", {**lb, "le": _fmt(bound)}, cum))
            fam.samples.append(
                ("_bucket", {**lb, "le": "+Inf"}, int(h["count"])))
            fam.samples.append(("_sum", lb, float(h["sum_s"])))
            fam.samples.append(("_count", lb, int(h["count"])))

    lines: List[str] = []
    for mname in sorted(fams):
        fam = fams[mname]
        lines.append(f"# TYPE {mname} {fam.mtype}")
        for suffix, lb, value in fam.samples:
            lines.append(f"{mname}{suffix}{_label_str(lb)} {_fmt(value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_prometheus(snapshot: Dict[str, Any],
                      labels: Optional[Dict[str, str]] = None,
                      prefix: str = _PREFIX) -> str:
    """One snapshot -> exposition text (optionally labeled)."""
    return render_parts([(labels or {}, snapshot)], prefix=prefix)


def registry_text(labels: Optional[Dict[str, str]] = None,
                  prefix: str = _PREFIX) -> str:
    """The global registry's current scrape document."""
    from .metrics import global_registry
    return render_prometheus(global_registry.snapshot(), labels=labels,
                             prefix=prefix)


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
