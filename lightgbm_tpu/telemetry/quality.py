"""Data & model quality observability: drift monitoring + shadow audit.

Three legs (docs/OBSERVABILITY.md "Data & model quality"):

  * **Reference profile** — at train time, :class:`QualityProfile` captures
    per-feature bin-count histograms straight from the binned matrix (the
    boundaries are the ``BinMapper``'s, so train and serve share bin
    semantics by construction), missing-bin rates, label and raw-score
    histograms, and the final holdout metric.  It is serialized as a
    ``<model>.quality.json`` sidecar written atomically next to the model,
    sha256-linked to the model text the same way the robustness
    ``.manifest.json`` is, and loaded by ``ModelRegistry`` on (re)load.
    Because the binned matrix is chunk/rank-invariant (stream and
    in-memory ingest produce bit-identical bins, test-gated), the profile
    is too.

  * **Drift monitor** — :class:`QualityMonitor` accumulates sampled
    serving traffic into per-feature bin histograms (rows re-binned with
    the profile's own mappers) and a score histogram, computes PSI and
    Jensen–Shannon divergence per feature plus score drift and
    missing-rate deltas against the reference, and runs an ``slo.py``-style
    multi-window state machine: the alert FIRES when the fast AND slow
    windows both exceed ``drift_threshold`` (with at least
    ``quality_min_rows`` sampled rows in the fast window) and CLEARS when
    the fast window alone recovers.  Missing or corrupt sidecars degrade
    to ``available: false`` — never a zero a gate could misread.

  * **Shadow audit** — a sampled ring of served rows is re-scored through
    the genuine ``Booster.predict`` host path and compared **bitwise**
    against the f64 values the wire returned (the serving exactness
    contract, continuously verified in production).  Mismatches are
    logged with trace id + model sha256.

``python -m lightgbm_tpu.telemetry.quality report <fleet_dir>`` merges
the per-replica drift snapshots a fleet exports into one report.
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.log import log_info, log_warning
from . import global_registry as telemetry

QUALITY_SUFFIX = ".quality.json"
PROFILE_VERSION = 1
# fixed equal-width resolution for the label / raw-score histograms
_SCORE_BINS = 32
_SLOW_FACTOR = 12        # slow window spans 12x the fast window (SLO-style)
_FAST_SUBWINDOWS = 4     # fast window = 4 sub-windows (25% granularity)
_MAX_TIMELINE = 256
_AUDIT_CAPACITY = 4096   # pending shadow-audit entries (bounded ring)
_AUDIT_DRAIN = 64        # entries re-scored per audit_once() call
_PSI_BUCKETS = 16        # coarse buckets for PSI/JS (noise control)


# ---------------------------------------------------------------------------
# drift math
# ---------------------------------------------------------------------------

def psi(ref_counts, obs_counts, eps: float = 1e-4) -> float:
    """Population Stability Index between two count histograms over the
    same bins: ``sum((q - p) * ln(q / p))`` with fractions floored at
    ``eps`` (the classic guard against empty bins).  0 = identical;
    >= 0.2 is the textbook "significant shift" threshold."""
    r = np.asarray(ref_counts, dtype=np.float64)
    o = np.asarray(obs_counts, dtype=np.float64)
    rs, os_ = float(r.sum()), float(o.sum())
    if rs <= 0.0 or os_ <= 0.0:
        return 0.0
    p = np.maximum(r / rs, eps)
    q = np.maximum(o / os_, eps)
    return float(np.sum((q - p) * np.log(q / p)))


def _coarsen(ref: np.ndarray, obs: np.ndarray,
             max_buckets: int = _PSI_BUCKETS):
    """Sum contiguous bins so drift math sees at most ``max_buckets``
    buckets.  Fine feature histograms (up to 255 bins) make PSI explode
    from sampling noise alone — every empty observed bin contributes
    ``~p*ln(p/eps)`` — which is why textbook PSI uses ~10 coarse buckets.
    Ref and obs are coarsened with the SAME edges, so identical
    distributions still score 0."""
    r = np.asarray(ref, dtype=np.float64)
    o = np.asarray(obs, dtype=np.float64)
    n = r.shape[0]
    if n <= max_buckets:
        return r, o
    edges = np.linspace(0, n, max_buckets + 1).astype(np.int64)[:-1]
    return np.add.reduceat(r, edges), np.add.reduceat(o, edges)


def js_divergence(ref_counts, obs_counts) -> float:
    """Jensen–Shannon divergence (base 2, so bounded in [0, 1]) between
    two count histograms over the same bins.  Symmetric and finite even
    for disjoint support — the stable companion to PSI."""
    r = np.asarray(ref_counts, dtype=np.float64)
    o = np.asarray(obs_counts, dtype=np.float64)
    rs, os_ = float(r.sum()), float(o.sum())
    if rs <= 0.0 or os_ <= 0.0:
        return 0.0
    p, q = r / rs, o / os_
    m = 0.5 * (p + q)

    def _kl(a: np.ndarray) -> float:
        mask = a > 0.0
        return float(np.sum(a[mask] * np.log2(a[mask] / m[mask])))

    return 0.5 * _kl(p) + 0.5 * _kl(q)


# ---------------------------------------------------------------------------
# reference profile
# ---------------------------------------------------------------------------

def quality_sidecar_path(model_path: str) -> str:
    """The quality sidecar path for a model file."""
    return str(model_path) + QUALITY_SUFFIX


def _binned_feature_counts(binned) -> List[np.ndarray]:
    """Per-feature original-bin count histograms reconstructed from the
    packed (EFB-bundled) group matrix.  Bundled groups reserve local 0
    for the shared default bin; each feature's non-default bins occupy a
    contiguous local segment with the default bin squeezed out
    (``local = b - 1 if b > default_bin else b``), so the default-bin
    count is recovered as ``num_data - sum(segment)``."""
    n = int(binned.num_data)
    mappers = binned.bin_mappers
    per_feature: Dict[int, np.ndarray] = {}
    for gi, feats in enumerate(binned.group_features):
        col = np.asarray(binned.bins[:n, gi])
        gc = np.bincount(col, minlength=int(binned.group_bin_counts[gi]))
        if len(feats) == 1:
            f = feats[0]
            nb = int(mappers[f].num_bins)
            c = np.zeros(nb, dtype=np.int64)
            upto = min(nb, gc.shape[0])
            c[:upto] = gc[:upto]
            per_feature[f] = c
        else:
            in_group = 1
            for f in feats:
                m = mappers[f]
                nb = int(m.num_bins)
                c = np.zeros(nb, dtype=np.int64)
                seg = gc[in_group:in_group + nb - 1].astype(np.int64)
                local = np.arange(seg.shape[0])
                orig = np.where(local < m.default_bin, local, local + 1)
                c[orig] = seg
                c[int(m.default_bin)] = n - int(seg.sum())
                per_feature[f] = c
                in_group += nb - 1
    out: List[np.ndarray] = []
    for f in range(int(binned.num_features)):
        if f in per_feature:
            out.append(per_feature[f])
        else:
            # trivial feature (single bin): every row in the default bin
            m = mappers[f]
            nb = max(int(m.num_bins), 1)
            c = np.zeros(nb, dtype=np.int64)
            c[min(int(m.default_bin), nb - 1)] = n
            out.append(c)
    return out


def _value_histogram(values: np.ndarray, bins: int = _SCORE_BINS
                     ) -> Dict[str, list]:
    """Fixed equal-width histogram with edges stored alongside the counts
    so serve-time values bin identically (out-of-range values clamp into
    the end bins)."""
    v = np.asarray(values, dtype=np.float64).ravel()
    v = v[np.isfinite(v)]
    if v.size == 0:
        lo, hi = 0.0, 1.0
    else:
        lo, hi = float(v.min()), float(v.max())
        if hi <= lo:
            hi = lo + 1.0
    edges = np.linspace(lo, hi, bins + 1)
    idx = np.clip(np.searchsorted(edges[1:-1], v), 0, bins - 1)
    counts = np.bincount(idx, minlength=bins)
    return {"edges": [float(e) for e in edges],
            "counts": [int(c) for c in counts]}


class QualityProfile:
    """The training-time reference distribution a server drifts against."""

    def __init__(self, data: Dict[str, Any]) -> None:
        self.data = data
        self._mappers: Optional[list] = None

    # -- accessors ---------------------------------------------------------
    @property
    def model_sha256(self) -> str:
        return self.data.get("model_sha256", "")

    @property
    def num_features(self) -> int:
        return int(self.data.get("num_features", 0))

    @property
    def num_data(self) -> int:
        return int(self.data.get("num_data", 0))

    def feature_counts(self, f: int) -> np.ndarray:
        return np.asarray(self.data["features"][f]["counts"],
                          dtype=np.float64)

    def missing_rate(self, f: int) -> float:
        return float(self.data["features"][f]["missing_rate"])

    def missing_bin(self, f: int) -> int:
        return int(self.data["features"][f]["missing_bin"])

    @property
    def score_hist(self) -> Dict[str, list]:
        return self.data["score_hist"]

    def mappers(self) -> list:
        """Reconstruct the per-feature :class:`BinMapper` objects — the
        exact transform training used, so serve rows bin identically."""
        if self._mappers is None:
            from ..binning import BinMapper
            ms = []
            for fd in self.data["features"]:
                ms.append(BinMapper(
                    upper_bounds=np.asarray(fd["upper_bounds"],
                                            dtype=np.float64),
                    bin_type=int(fd["bin_type"]),
                    missing_type=int(fd["missing_type"]),
                    categories=np.asarray(fd["categories"],
                                          dtype=np.int64),
                    num_bins=int(fd["num_bins"]),
                    default_bin=int(fd["default_bin"]),
                    most_freq_bin=int(fd["most_freq_bin"]),
                    min_val=float(fd["min_val"]),
                    max_val=float(fd["max_val"])))
            self._mappers = ms
        return self._mappers

    # -- construction ------------------------------------------------------
    @classmethod
    def from_booster(cls, booster, model_text: str) -> "QualityProfile":
        """Build the reference profile from a trained booster's binned
        training matrix + engine scores.  ``model_text`` is the exact
        string being written to disk — its sha256 links sidecar to model
        (manifest-style poisoning detection)."""
        from ..binning import MISSING_NAN, MISSING_ZERO
        binned = booster.train_set.binned
        n = int(binned.num_data)
        counts = _binned_feature_counts(binned)
        features = []
        for f, m in enumerate(binned.bin_mappers):
            nb = int(m.num_bins)
            if m.missing_type == MISSING_NAN:
                miss_bin = nb - 1
            elif m.missing_type == MISSING_ZERO:
                miss_bin = int(m.default_bin)
            else:
                miss_bin = -1
            c = counts[f]
            miss_rate = (float(c[miss_bin]) / n
                         if miss_bin >= 0 and n else 0.0)
            features.append({
                "counts": [int(x) for x in c],
                "missing_bin": miss_bin,
                "missing_rate": miss_rate,
                "upper_bounds": [float(x) for x in m.upper_bounds],
                "bin_type": int(m.bin_type),
                "missing_type": int(m.missing_type),
                "categories": [int(x) for x in m.categories],
                "num_bins": nb,
                "default_bin": int(m.default_bin),
                "most_freq_bin": int(m.most_freq_bin),
                "min_val": float(m.min_val),
                "max_val": float(m.max_val),
            })
        raw = np.asarray(booster._engine._unpad_score(),
                         dtype=np.float64).ravel()
        label = booster.train_set.get_label()
        metric: Dict[str, float] = {}
        for ds_name, ms in (booster.best_score or {}).items():
            for mname, val in ms.items():
                metric[f"{ds_name}:{mname}"] = float(val)
        data = {
            "version": PROFILE_VERSION,
            "model_sha256": hashlib.sha256(
                model_text.encode("utf-8")).hexdigest(),
            "created_unix": time.time(),
            "num_data": n,
            "num_features": int(binned.num_features),
            "features": features,
            "score_hist": _value_histogram(raw),
            "label_hist": (_value_histogram(np.asarray(label,
                                                       dtype=np.float64))
                           if label is not None else None),
            "holdout_metric": metric,
        }
        return cls(data)

    # -- persistence -------------------------------------------------------
    def save(self, model_path: str) -> str:
        """Atomically write the sidecar next to ``model_path``."""
        from ..robustness.checkpoint import atomic_write_text
        path = quality_sidecar_path(model_path)
        atomic_write_text(path, json.dumps(self.data) + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "QualityProfile":
        with open(path) as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or "features" not in data \
                or "score_hist" not in data:
            raise ValueError(f"malformed quality sidecar: {path}")
        return cls(data)

    @classmethod
    def load_for_model(cls, model_path: str,
                       sha256: str) -> Optional["QualityProfile"]:
        """Best-effort sidecar load for a served model: ``None`` (with a
        warning) on a missing, corrupt, or sha-mismatched sidecar —
        serving must never depend on the sidecar being healthy."""
        path = quality_sidecar_path(model_path)
        if not os.path.exists(path):
            return None
        try:
            prof = cls.load(path)
        except (OSError, ValueError, KeyError, TypeError) as exc:
            log_warning(f"quality: ignoring corrupt sidecar {path}: {exc}")
            return None
        if prof.model_sha256 != sha256:
            log_warning(
                f"quality: sidecar {path} is linked to model sha256 "
                f"{prof.model_sha256[:12]}.. but the model file hashes to "
                f"{sha256[:12]}.. — ignoring (poisoned or stale sidecar)")
            return None
        return prof


# ---------------------------------------------------------------------------
# serving-time monitor
# ---------------------------------------------------------------------------

class _Window:
    """One sub-window of sampled-traffic accumulation."""
    __slots__ = ("idx", "rows", "counts", "score")

    def __init__(self, idx: int, num_features: int, max_bins: int,
                 score_bins: int) -> None:
        self.idx = idx
        self.rows = 0
        self.counts = np.zeros((num_features, max_bins), dtype=np.int64)
        self.score = np.zeros(score_bins, dtype=np.int64)


class QualityMonitor:
    """Multi-window drift monitor + shadow-audit ring for one server.

    ``observe_batch`` / ``offer_audit`` sit on the micro-batcher dispatch
    path behind per-batch (resp. per-request) sampling draws, so the
    un-sampled hot path pays one RNG call.  ``tick`` (the server's 1 Hz
    maintenance loop) runs the drift state machine and publishes gauges;
    ``audit_once`` drains the audit ring through ``Booster.predict``."""

    def __init__(self, *, threshold: float = 0.2, window_s: float = 60.0,
                 sample: float = 0.01, audit_sample: float = 0.01,
                 min_rows: int = 200, topk: int = 5,
                 clock=time.monotonic, slow_factor: int = _SLOW_FACTOR,
                 audit_capacity: int = _AUDIT_CAPACITY) -> None:
        self.threshold = float(threshold)
        self.window_s = max(float(window_s), 1e-3)
        self.sample = float(sample)
        self.audit_sample = float(audit_sample)
        self.min_rows = int(min_rows)
        self.topk = int(topk)
        self._clock = clock
        self._slow_factor = max(int(slow_factor), 1)
        self._span = self.window_s / _FAST_SUBWINDOWS
        self._slow_n = _FAST_SUBWINDOWS * self._slow_factor
        self._lock = threading.Lock()
        self._rng = random.Random(0x7EACE ^ os.getpid())
        # reference state (swapped on model change)
        self._sha: Optional[str] = None
        self._profile: Optional[QualityProfile] = None
        self._mappers: list = []
        self._num_bins: List[int] = []
        self._max_bins = 0
        self._score_inner: Optional[np.ndarray] = None
        self._score_bins = _SCORE_BINS
        # accumulators
        self._windows: List[_Window] = []
        self._sampled_rows = 0
        # audit ring (list guarded by _lock; bounded)
        self._audit: List[tuple] = []
        self._audit_capacity = int(audit_capacity)
        self._audit_rows = 0
        self._audit_mismatches = 0
        self._audit_dropped = 0
        # alert state machine
        self.alerting = False
        self.fired = 0
        self.cleared = 0
        self._timeline: List[Dict[str, Any]] = []
        self._last: Dict[str, Any] = {}

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0 or self.audit_sample > 0.0

    # -- model tracking ----------------------------------------------------
    def sync_model(self, model) -> bool:
        """Track the serving model: on a sha change, adopt its sidecar
        profile (possibly ``None``) and reset the accumulators + alert.
        Returns True when a reference profile is available."""
        sha = getattr(model, "sha256", None)
        if sha == self._sha:
            return self._profile is not None
        profile = getattr(model, "quality", None)
        with self._lock:
            if sha == self._sha:            # lost the race; state is set
                return self._profile is not None
            self._sha = sha
            self._profile = profile
            self._windows = []
            self._sampled_rows = 0
            if self.alerting:
                self.alerting = False
                self.cleared += 1
            self._last = {}
            self._timeline.append({"t": self._clock(), "kind": "model",
                                   "sha256": sha,
                                   "profile": profile is not None})
            del self._timeline[:-_MAX_TIMELINE]
            if profile is not None:
                self._mappers = profile.mappers()
                self._num_bins = [int(m.num_bins) for m in self._mappers]
                self._max_bins = max(self._num_bins + [1])
                edges = np.asarray(profile.score_hist["edges"],
                                   dtype=np.float64)
                self._score_inner = edges[1:-1]
                self._score_bins = len(profile.score_hist["counts"])
            else:
                self._mappers = []
                self._num_bins = []
                self._max_bins = 0
                self._score_inner = None
        return profile is not None

    # -- accumulation (batcher worker thread) ------------------------------
    def observe_batch(self, model, X, raw) -> None:
        """Accumulate one dispatched batch into the drift histograms.
        One sampling draw per BATCH keeps the hot-path cost negligible."""
        if self.sample <= 0.0 or not self.sync_model(model):
            return
        if self._rng.random() >= self.sample:
            return
        Xa = np.asarray(X, dtype=np.float64)
        if Xa.ndim != 2 or Xa.shape[1] != len(self._mappers):
            return
        n = Xa.shape[0]
        scores = np.asarray(raw, dtype=np.float64).ravel()
        idx = int(self._clock() // self._span)
        with self._lock:
            w = self._window_locked(idx)
            for f, m in enumerate(self._mappers):
                nb = self._num_bins[f]
                b = np.asarray(m.transform(Xa[:, f]), dtype=np.int64)
                w.counts[f, :nb] += np.bincount(
                    np.clip(b, 0, nb - 1), minlength=nb)
            if self._score_inner is not None and scores.size:
                si = np.clip(np.searchsorted(self._score_inner, scores),
                             0, self._score_bins - 1)
                w.score += np.bincount(si, minlength=self._score_bins)
            w.rows += n
            self._sampled_rows += n
        telemetry.inc("quality/sampled_rows", n)

    def _window_locked(self, idx: int) -> _Window:
        if self._windows and self._windows[-1].idx == idx:
            return self._windows[-1]
        w = _Window(idx, len(self._mappers), self._max_bins,
                    self._score_bins)
        self._windows.append(w)
        del self._windows[:-(self._slow_n + 1)]
        return w

    # -- shadow audit ------------------------------------------------------
    def offer_audit(self, model, rows, raw_slice, raw_score: bool,
                    trace_id: Optional[str]) -> None:
        """Maybe enqueue one served request for background re-scoring.
        ``raw_slice`` is the request's slice of the dispatched raw-score
        batch; the served values are recovered with the model's own
        ``finish`` (bit-identical — same function, same input).  Runs
        even without a reference profile: the exactness contract does
        not depend on the sidecar."""
        if self.audit_sample <= 0.0 \
                or self._rng.random() >= self.audit_sample:
            return
        values = np.asarray(model.finish(raw_slice, raw_score),
                            dtype=np.float64)
        entry = (np.array(rows, dtype=np.float64, copy=True),
                 np.array(values, dtype=np.float64, copy=True),
                 bool(raw_score), trace_id, model)
        with self._lock:
            if len(self._audit) >= self._audit_capacity:
                self._audit_dropped += 1
                return
            self._audit.append(entry)

    def audit_once(self, max_entries: int = _AUDIT_DRAIN) -> int:
        """Drain up to ``max_entries`` pending audits through the host
        ``Booster.predict`` path and compare bitwise (f64) against what
        the wire returned.  Returns the number of rows audited.

        Entries are grouped per (model, raw_score) and re-scored in ONE
        concatenated predict call: the host tree walk is per-row, so
        batch composition cannot change any row's f64 sum, and one call
        instead of ~64 keeps the 1 Hz drain off the serving threads'
        GIL budget."""
        with self._lock:
            drained = self._audit[:max_entries]
            del self._audit[:max_entries]
        if not drained:
            return 0
        groups: Dict[tuple, List[tuple]] = {}
        for entry in drained:
            groups.setdefault((id(entry[4]), entry[2]), []).append(entry)
        rows_done = 0
        for entries in groups.values():
            model, raw_score = entries[0][4], entries[0][2]
            rows_cat = (entries[0][0] if len(entries) == 1 else
                        np.concatenate([e[0] for e in entries], axis=0))
            try:
                expect = np.asarray(
                    model._booster.predict(rows_cat, raw_score=raw_score),
                    dtype=np.float64)
            except Exception as exc:        # audit must never kill serving
                log_warning(f"quality: shadow audit re-score failed: {exc}")
                continue
            off = 0
            for rows, values, _, trace_id, _ in entries:
                m = rows.shape[0]
                sl = expect[off:off + m]
                off += m
                rows_done += m
                if sl.ravel().tobytes() != values.ravel().tobytes():
                    with self._lock:
                        self._audit_mismatches += 1
                    log_warning(
                        "quality: shadow audit BITWISE MISMATCH "
                        f"trace={trace_id} "
                        f"model_sha256={model.sha256[:12]}.. "
                        f"rows={m} raw_score={raw_score} — served "
                        "values diverge from Booster.predict")
        if rows_done:
            with self._lock:
                self._audit_rows += rows_done
        return rows_done

    # -- drift computation + state machine ---------------------------------
    def _aggregate_locked(self, now_idx: int, n_windows: int):
        ws = [w for w in self._windows if w.idx > now_idx - n_windows]
        if not ws:
            return 0, None, None
        rows = sum(w.rows for w in ws)
        counts = ws[0].counts.copy()
        score = ws[0].score.copy()
        for w in ws[1:]:
            counts += w.counts
            score += w.score
        return rows, counts, score

    def compute(self) -> Dict[str, Any]:
        """Current drift statistics vs the reference (both windows)."""
        with self._lock:
            profile = self._profile
            if profile is None:
                return {"available": False}
            now_idx = int(self._clock() // self._span)
            del self._windows[: max(
                0, len(self._windows) - (self._slow_n + 1))]
            f_rows, f_counts, f_score = self._aggregate_locked(
                now_idx, _FAST_SUBWINDOWS)
            s_rows, s_counts, s_score = self._aggregate_locked(
                now_idx, self._slow_n)
        nf = profile.num_features
        feats = []
        max_fast = max_slow = nan_delta_max = 0.0
        for f in range(nf):
            ref = profile.feature_counts(f)
            pf = ps = jd = 0.0
            if f_counts is not None:
                rc, oc = _coarsen(ref, f_counts[f, :len(ref)])
                pf = psi(rc, oc)
            if s_counts is not None:
                rc, oc = _coarsen(ref, s_counts[f, :len(ref)])
                ps = psi(rc, oc)
                jd = js_divergence(rc, oc)
            nd = 0.0
            mb = profile.missing_bin(f)
            if mb >= 0 and s_counts is not None and s_rows:
                nd = abs(float(s_counts[f, mb]) / s_rows
                         - profile.missing_rate(f))
            nan_delta_max = max(nan_delta_max, nd)
            max_fast, max_slow = max(max_fast, pf), max(max_slow, ps)
            feats.append({"feature": f, "psi_fast": round(pf, 6),
                          "psi_slow": round(ps, 6), "js": round(jd, 6),
                          "nan_delta": round(nd, 6)})
        ref_score = np.asarray(profile.score_hist["counts"],
                               dtype=np.float64)
        sc_fast = sc_slow = 0.0
        if f_score is not None:
            sc_fast = psi(*_coarsen(ref_score, f_score))
        if s_score is not None:
            sc_slow = psi(*_coarsen(ref_score, s_score))
        feats.sort(key=lambda d: -d["psi_fast"])
        return {
            "available": True,
            "fast_rows": f_rows, "slow_rows": s_rows,
            "max_psi_fast": round(max_fast, 6),
            "max_psi_slow": round(max_slow, 6),
            "score_psi_fast": round(sc_fast, 6),
            "score_psi_slow": round(sc_slow, 6),
            "nan_delta_max": round(nan_delta_max, 6),
            "drift_fast": round(max(max_fast, sc_fast), 6),
            "drift_slow": round(max(max_slow, sc_slow), 6),
            "top_features": feats[:self.topk],
        }

    def tick(self, model=None) -> Dict[str, Any]:
        """Run one maintenance step: recompute drift, advance the alert
        state machine, publish gauges.  Mirrors ``SLOMonitor.tick`` —
        fire on fast AND slow, clear on fast alone."""
        if model is not None:
            self.sync_model(model)
        d = self.compute()
        telemetry.gauge("drift/available", 1.0 if d["available"] else 0.0)
        if not d["available"]:
            with self._lock:
                self._last = d
                alerting = self.alerting
            # deliberately do NOT publish drift/* values: a 0.0 here
            # would read as "no drift" when the truth is "cannot tell"
            telemetry.gauge("drift/alert", 1.0 if alerting else 0.0)
            return d
        enough = d["fast_rows"] >= self.min_rows
        over_fast = d["drift_fast"] >= self.threshold
        over_slow = d["drift_slow"] >= self.threshold
        fired = cleared = False
        with self._lock:
            self._last = d
            if not self.alerting and enough and over_fast and over_slow:
                self.alerting = fired = True
                self.fired += 1
                self._timeline.append({
                    "t": self._clock(), "kind": "fire",
                    "drift_fast": d["drift_fast"],
                    "drift_slow": d["drift_slow"],
                    "top": [f["feature"] for f in d["top_features"]]})
                del self._timeline[:-_MAX_TIMELINE]
            elif self.alerting and not over_fast:
                self.alerting = False
                cleared = True
                self.cleared += 1
                self._timeline.append({
                    "t": self._clock(), "kind": "clear",
                    "drift_fast": d["drift_fast"]})
                del self._timeline[:-_MAX_TIMELINE]
        if fired:
            top = ", ".join(
                f"f{f['feature']}(psi={f['psi_fast']:.3f})"
                for f in d["top_features"][:3])
            log_warning(
                f"quality: DRIFT alert FIRED — fast={d['drift_fast']:.3f} "
                f"slow={d['drift_slow']:.3f} >= {self.threshold} over "
                f"{d['fast_rows']} sampled rows; top features: {top}")
        elif cleared:
            log_info(f"quality: drift alert cleared "
                     f"(fast={d['drift_fast']:.3f} < {self.threshold})")
        telemetry.gauge("drift/max_psi_fast", d["max_psi_fast"])
        telemetry.gauge("drift/max_psi_slow", d["max_psi_slow"])
        telemetry.gauge("drift/score_psi_fast", d["score_psi_fast"])
        telemetry.gauge("drift/score_psi_slow", d["score_psi_slow"])
        telemetry.gauge("drift/nan_delta_max", d["nan_delta_max"])
        telemetry.gauge("drift/alert", 1.0 if self.alerting else 0.0)
        for fd in d["top_features"]:
            f = fd["feature"]
            # bounded by quality_topk (config), never by traffic
            telemetry.gauge(f"drift/feature/{f}/psi", fd["psi_fast"])
            telemetry.gauge(f"drift/feature/{f}/js", fd["js"])
        with self._lock:
            audit = {"rows": self._audit_rows,
                     "mismatches": self._audit_mismatches,
                     "pending": len(self._audit),
                     "dropped": self._audit_dropped}
        for k, v in audit.items():
            telemetry.gauge(f"quality/audit/{k}", float(v))
        return d

    # -- introspection -----------------------------------------------------
    def brief(self) -> Optional[Dict[str, Any]]:
        """Compact drift snapshot for the structured access log — only
        non-None while the alert is active, so healthy traffic logs stay
        lean."""
        if not self.alerting:
            return None
        d = self._last or {}
        return {"alert": True,
                "drift_fast": d.get("drift_fast"),
                "drift_slow": d.get("drift_slow")}

    def snapshot(self) -> Dict[str, Any]:
        """The full ``/drift`` payload (and the per-replica export)."""
        d = self._last or self.compute()
        with self._lock:
            profile = self._profile
            out: Dict[str, Any] = {
                "available": bool(d.get("available")),
                "model_sha256": self._sha,
                "alerting": self.alerting,
                "fired": self.fired,
                "cleared": self.cleared,
                "threshold": self.threshold,
                "window_s": self.window_s,
                "slow_factor": self._slow_factor,
                "sample": self.sample,
                "audit_sample": self.audit_sample,
                "min_rows": self.min_rows,
                "sampled_rows": self._sampled_rows,
                "audit": {"rows": self._audit_rows,
                          "mismatches": self._audit_mismatches,
                          "pending": len(self._audit),
                          "dropped": self._audit_dropped},
                "timeline": list(self._timeline[-32:]),
            }
        if out["available"]:
            out["drift"] = {k: d[k] for k in (
                "fast_rows", "slow_rows", "max_psi_fast", "max_psi_slow",
                "score_psi_fast", "score_psi_slow", "nan_delta_max",
                "drift_fast", "drift_slow")}
            out["top_features"] = d.get("top_features", [])
            if profile is not None:
                out["profile"] = {
                    "created_unix": profile.data.get("created_unix"),
                    "num_data": profile.num_data,
                    "num_features": profile.num_features,
                    "holdout_metric": profile.data.get("holdout_metric",
                                                       {}),
                }
        else:
            out["reason"] = ("no quality sidecar for model "
                             f"{(self._sha or 'unknown')[:12]}")
        return out


# ---------------------------------------------------------------------------
# fleet report CLI
# ---------------------------------------------------------------------------

def write_snapshot(path: str, snap: Dict[str, Any]) -> None:
    """Atomically export one replica's drift snapshot for the report CLI."""
    from ..robustness.checkpoint import atomic_write_text
    atomic_write_text(path, json.dumps(snap) + "\n")


def merge_reports(fleet_dir: str) -> Dict[str, Any]:
    """Merge ``drift_replica_<r>.json`` exports under ``fleet_dir`` into
    one fleet-level drift report."""
    replicas: Dict[str, Any] = {}
    feature_psi: Dict[int, float] = {}
    audit_rows = audit_mismatches = 0
    any_alerting = False
    available = False
    for path in sorted(glob.glob(
            os.path.join(fleet_dir, "drift_replica_*.json"))):
        rank = os.path.basename(path)[len("drift_replica_"):-len(".json")]
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except (OSError, ValueError) as exc:
            replicas[rank] = {"error": str(exc)}
            continue
        replicas[rank] = {
            "available": snap.get("available", False),
            "alerting": snap.get("alerting", False),
            "fired": snap.get("fired", 0),
            "cleared": snap.get("cleared", 0),
            "sampled_rows": snap.get("sampled_rows", 0),
            "drift": snap.get("drift"),
            "audit": snap.get("audit", {}),
            "model_sha256": snap.get("model_sha256"),
        }
        available = available or bool(snap.get("available"))
        any_alerting = any_alerting or bool(snap.get("alerting"))
        audit_rows += int(snap.get("audit", {}).get("rows", 0))
        audit_mismatches += int(snap.get("audit", {}).get("mismatches", 0))
        for fd in snap.get("top_features", []) or []:
            f = int(fd["feature"])
            feature_psi[f] = max(feature_psi.get(f, 0.0),
                                 float(fd.get("psi_fast", 0.0)))
    top = sorted(feature_psi.items(), key=lambda kv: -kv[1])[:10]
    return {
        "fleet_dir": fleet_dir,
        "replicas": replicas,
        "num_replicas": len(replicas),
        "available": available,
        "any_alerting": any_alerting,
        "audit": {"rows": audit_rows, "mismatches": audit_mismatches},
        "top_features": [{"feature": f, "max_psi": round(v, 6)}
                         for f, v in top],
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.telemetry.quality",
        description="Data/model quality drift tooling")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report",
                         help="merge per-replica drift snapshots from a "
                              "fleet dir into one drift report")
    rep.add_argument("fleet_dir")
    ns = ap.parse_args(argv)
    if ns.cmd == "report":
        out = merge_reports(ns.fleet_dir)
        print(json.dumps(out, indent=2, sort_keys=True))
        if not out["replicas"]:
            print(f"NOTICE: no drift_replica_*.json under {ns.fleet_dir}",
                  file=__import__("sys").stderr)
            return 1
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
