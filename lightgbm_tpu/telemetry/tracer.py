"""Nested span tracer with Chrome/Perfetto trace-event export.

The host-side analog of the reference's ``Common::Timer``/``FunctionTimer``
RAII scopes (include/LightGBM/utils/common.h:980) — but structured: spans
nest, carry attributes, and export to the Chrome trace-event JSON format
(the ``chrome://tracing`` / https://ui.perfetto.dev schema), so a training
run can be inspected on the same timeline tooling used for device profiles.

Design constraints:
  * zero overhead when disabled — ``span()`` returns one shared no-op
    context manager behind a single boolean check, allocating nothing;
  * thread-safe — events append under a lock, nesting is tracked per
    thread (trace-event "B"/"E" pairs nest per ``tid`` by construction);
  * bounded — the event buffer is capped; overflow increments a drop
    counter instead of growing without limit.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

# Chrome trace-event phases used here: B/E = nested begin/end duration
# events, C = counter track, i = instant event, M = metadata.
_MAX_EVENTS = int(os.environ.get("LIGHTGBM_TPU_TRACE_MAX_EVENTS", 2_000_000))


class _NullSpan:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records a "B" event on enter and an "E" on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tracer._emit("B", self._name, self._t0, self._args)
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._emit("E", self._name, t1, None)
        self._tracer._account(self._name, t1 - self._t0)
        return False


class SpanTracer:
    """Nested, thread-safe span recorder (low-overhead when disabled)."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0
        self._drop_warned = False
        # wall-clock anchor: the unix time captured at the SAME instant as
        # the perf_counter epoch — perf_counter is monotonic but has an
        # arbitrary per-process zero, so two processes' traces can only be
        # merged onto one timeline through this pairing (the collector,
        # telemetry/collect.py, aligns shards on it)
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()
        self._phase_totals: Dict[str, float] = {}
        self._phase_counts: Dict[str, int] = {}
        self._local = threading.local()

    # -- control -----------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events = []
            self._dropped = 0
            self._drop_warned = False
            # re-anchor: both halves of the clock pairing move together
            self._epoch = time.perf_counter()
            self._epoch_unix = time.time()
            self._phase_totals = {}
            self._phase_counts = {}

    # -- recording ---------------------------------------------------------
    def span(self, name: str, **args: Any):
        """Context manager for a traced region; no-op when disabled.

        The disabled path is a single boolean check returning a shared
        object — safe to leave in hot loops."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args: Any) -> None:
        """Point-in-time marker (watchdog warnings, stop events, ...)."""
        if not self.enabled:
            return
        self._emit("i", name, time.perf_counter(), args or None,
                   extra={"s": "t"})

    def counter(self, name: str, **values: float) -> None:
        """Counter-track sample: renders as a stacked area in Perfetto."""
        if not self.enabled:
            return
        self._emit("C", name, time.perf_counter(),
                   {k: float(v) for k, v in values.items()})

    def complete(self, name: str, start: float, duration: float,
                 **args: Any) -> None:
        """One finished span as a single "X" (complete) event.

        ``start`` is an absolute ``time.perf_counter`` point and
        ``duration`` is in seconds.  Unlike :meth:`span`, the begin and
        end may have happened on DIFFERENT threads (a request enqueued by
        an HTTP handler and dispatched by the batcher worker) — the event
        is attributed to the emitting thread's track."""
        if not self.enabled:
            return
        self._emit("X", name, start, args or None,
                   extra={"dur": max(duration, 0.0) * 1e6})
        self._account(name, duration)

    def _emit(self, ph: str, name: str, t: float, args: Optional[dict],
              extra: Optional[dict] = None) -> None:
        ev: Dict[str, Any] = {
            "name": name, "ph": ph, "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "ts": (t - self._epoch) * 1e6,
        }
        if args:
            ev["args"] = args
        if extra:
            ev.update(extra)
        with self._lock:
            if len(self._events) < _MAX_EVENTS:
                self._events.append(ev)
                return
            self._dropped += 1
            warn = not self._drop_warned
            self._drop_warned = True
        if warn:
            from ..utils.log import log_warning
            log_warning(
                f"telemetry: trace event buffer full ({_MAX_EVENTS} "
                "events) — further spans are DROPPED, not recorded "
                "(raise LIGHTGBM_TPU_TRACE_MAX_EVENTS or lower "
                "serve_trace_sample); the drop count is in "
                "telemetry_summary()['trace_dropped_events']")

    def _account(self, name: str, dt: float) -> None:
        with self._lock:
            self._phase_totals[name] = self._phase_totals.get(name, 0.0) + dt
            self._phase_counts[name] = self._phase_counts.get(name, 0) + 1

    # -- introspection -----------------------------------------------------
    @property
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        """Events dropped after the bounded buffer filled."""
        with self._lock:
            return self._dropped

    def clock_sync(self) -> Dict[str, Any]:
        """The wall-clock anchor record: ``unix_time_s`` is the
        ``time.time()`` captured at the same instant the ``perf_counter``
        epoch (event ``ts`` zero-point) was taken, plus the process
        identity a multi-process merge needs."""
        with self._lock:
            anchor = {"unix_time_s": self._epoch_unix,
                      "perf_epoch_s": self._epoch}
        anchor["pid"] = os.getpid()
        rank = os.environ.get("LGBTPU_REPLICA_RANK")
        if rank is not None:
            try:
                anchor["replica_rank"] = int(rank)
            except ValueError:
                pass
        return anchor

    def phase_snapshot(self) -> Dict[str, float]:
        """Copy of cumulative per-span-name wall totals (seconds)."""
        with self._lock:
            return dict(self._phase_totals)

    def phase_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._phase_counts)

    # -- export ------------------------------------------------------------
    def export_trace(self, path: str) -> str:
        """Write the collected events as Chrome trace-event JSON.

        The output object is the standard ``{"traceEvents": [...]}``
        envelope (plus process/thread metadata), loadable directly in
        Perfetto or chrome://tracing."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        anchor = self.clock_sync()
        rank = anchor.get("replica_rank")
        proc_name = ("lightgbm_tpu host" if rank is None
                     else f"lightgbm_tpu replica {rank}")
        meta: List[Dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": os.getpid(),
             "tid": 0, "args": {"name": proc_name}},
            # wall-clock anchor as a metadata event at ts 0: every ts in
            # this file is relative to anchor.unix_time_s, which is what
            # lets the collector align shards from different processes
            {"name": "clock_sync", "ph": "M", "pid": os.getpid(),
             "tid": 0, "ts": 0.0, "args": anchor},
        ]
        for tid in sorted({e["tid"] for e in events}):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": os.getpid(), "tid": tid,
                         "args": {"name": f"host-thread-{tid}"}})
        blob = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "lightgbm_tpu.telemetry",
                          "dropped_events": dropped,
                          "clock_sync": anchor},
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            # default=str: span attributes are user-supplied (numpy scalars,
            # paths, ...) and must never make the end-of-run export raise
            json.dump(blob, fh, default=str)
        os.replace(tmp, path)
        return path


global_tracer = SpanTracer()
