"""Nested span tracer with Chrome/Perfetto trace-event export.

The host-side analog of the reference's ``Common::Timer``/``FunctionTimer``
RAII scopes (include/LightGBM/utils/common.h:980) — but structured: spans
nest, carry attributes, and export to the Chrome trace-event JSON format
(the ``chrome://tracing`` / https://ui.perfetto.dev schema), so a training
run can be inspected on the same timeline tooling used for device profiles.

Design constraints:
  * zero overhead when disabled — ``span()`` returns one shared no-op
    context manager behind a single boolean check, allocating nothing;
  * thread-safe — events append under a lock, nesting is tracked per
    thread (trace-event "B"/"E" pairs nest per ``tid`` by construction);
  * bounded — the event buffer is capped; overflow increments a drop
    counter instead of growing without limit.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

# Chrome trace-event phases used here: B/E = nested begin/end duration
# events, C = counter track, i = instant event, M = metadata.
_MAX_EVENTS = int(os.environ.get("LIGHTGBM_TPU_TRACE_MAX_EVENTS", 2_000_000))


class _NullSpan:
    """Shared no-op context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records a "B" event on enter and an "E" on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        self._tracer._emit("B", self._name, self._t0, self._args)
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self._tracer._emit("E", self._name, t1, None)
        self._tracer._account(self._name, t1 - self._t0)
        return False


class SpanTracer:
    """Nested, thread-safe span recorder (low-overhead when disabled)."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0
        self._epoch = time.perf_counter()
        self._phase_totals: Dict[str, float] = {}
        self._phase_counts: Dict[str, int] = {}
        self._local = threading.local()

    # -- control -----------------------------------------------------------
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._events = []
            self._dropped = 0
            self._epoch = time.perf_counter()
            self._phase_totals = {}
            self._phase_counts = {}

    # -- recording ---------------------------------------------------------
    def span(self, name: str, **args: Any):
        """Context manager for a traced region; no-op when disabled.

        The disabled path is a single boolean check returning a shared
        object — safe to leave in hot loops."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args: Any) -> None:
        """Point-in-time marker (watchdog warnings, stop events, ...)."""
        if not self.enabled:
            return
        self._emit("i", name, time.perf_counter(), args or None,
                   extra={"s": "t"})

    def counter(self, name: str, **values: float) -> None:
        """Counter-track sample: renders as a stacked area in Perfetto."""
        if not self.enabled:
            return
        self._emit("C", name, time.perf_counter(),
                   {k: float(v) for k, v in values.items()})

    def _emit(self, ph: str, name: str, t: float, args: Optional[dict],
              extra: Optional[dict] = None) -> None:
        ev: Dict[str, Any] = {
            "name": name, "ph": ph, "pid": os.getpid(),
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "ts": (t - self._epoch) * 1e6,
        }
        if args:
            ev["args"] = args
        if extra:
            ev.update(extra)
        with self._lock:
            if len(self._events) < _MAX_EVENTS:
                self._events.append(ev)
            else:
                self._dropped += 1

    def _account(self, name: str, dt: float) -> None:
        with self._lock:
            self._phase_totals[name] = self._phase_totals.get(name, 0.0) + dt
            self._phase_counts[name] = self._phase_counts.get(name, 0) + 1

    # -- introspection -----------------------------------------------------
    @property
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def phase_snapshot(self) -> Dict[str, float]:
        """Copy of cumulative per-span-name wall totals (seconds)."""
        with self._lock:
            return dict(self._phase_totals)

    def phase_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._phase_counts)

    # -- export ------------------------------------------------------------
    def export_trace(self, path: str) -> str:
        """Write the collected events as Chrome trace-event JSON.

        The output object is the standard ``{"traceEvents": [...]}``
        envelope (plus process/thread metadata), loadable directly in
        Perfetto or chrome://tracing."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": os.getpid(), "tid": 0,
            "args": {"name": "lightgbm_tpu host"},
        }]
        for tid in sorted({e["tid"] for e in events}):
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": os.getpid(), "tid": tid,
                         "args": {"name": f"host-thread-{tid}"}})
        blob = {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "lightgbm_tpu.telemetry",
                          "dropped_events": dropped},
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            # default=str: span attributes are user-supplied (numpy scalars,
            # paths, ...) and must never make the end-of-run export raise
            json.dump(blob, fh, default=str)
        os.replace(tmp, path)
        return path


global_tracer = SpanTracer()
