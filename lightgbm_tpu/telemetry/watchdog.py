"""Recompile watchdog: count XLA traces per jitted entry point.

Retraces are the #1 silent TPU perf killer — a shape or dtype drift turns
a cached dispatch into a multi-second XLA compile in the middle of
training, with nothing in the logs. ``watched_jit`` wraps ``jax.jit`` so
every trace of the underlying function increments a per-entry counter
(tracing happens exactly once per compilation-cache miss; steady-state
dispatches go through jit's C++ fast path and never touch the wrapper),
and an entry that retraces beyond a configurable threshold logs a warning
carrying the offending argument shapes/dtypes.

Entries are identified by (name, owner): engine-owned jits pass their
engine instance as ``owner`` so a rebuild of the same logical entry point
(e.g. ``Booster.reset_parameter`` re-jitting the grower mid-training)
keeps counting against the same entry, while a fresh model's first
compile does not inherit another model's count. Module-level kernel jits
that legitimately re-specialize per shape (pallas kernels, ranking
buckets) pass ``warn_after=0`` to count without ever warning.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import Any, Dict, List, Optional

import jax

from ..utils.log import log_warning
from . import costmodel as _costmodel

_lock = threading.Lock()
# weak enumeration for summaries: an entry stays alive exactly as long as
# something can still trace it (the jitted closure and, for owned entries,
# the owner's `_telemetry_watches` dict hold the strong references), so a
# dead model's counters neither leak nor get inherited by an unrelated new
# model that happens to reuse its memory address
_entries: "weakref.WeakSet[WatchEntry]" = weakref.WeakSet()
_default_threshold = 2

# ---- dispatch / host-sync accounting (docs/OBSERVABILITY.md) ----
# `launches` counts every dispatch of a watched_jit entry point (one XLA
# program execution request); `host_syncs` counts device->host transfers
# noted by the engine (device_get, blocking flag reads).  Both are plain
# int increments on the dispatch path — the GIL makes the += effectively
# atomic and the cost (~100 ns) vanishes against any real launch.  The
# straggler report derives launches/iter and host_syncs/iter from window
# diffs, which is what lets `bottleneck:` tell a dispatch-bound loop from
# a link-bound one.
_launches = 0
_host_syncs = 0


def launch_count() -> int:
    """Cumulative watched_jit dispatches in this process."""
    return _launches


def host_sync_count() -> int:
    """Cumulative engine-noted device->host transfers."""
    return _host_syncs


def note_host_sync(n: int = 1) -> None:
    """Record ``n`` device->host transfers (called at the engine's
    sanctioned readback sites — the batched flag fetch, score pulls)."""
    global _host_syncs
    _host_syncs += n


def note_launch(n: int = 1) -> None:
    """Record ``n`` dispatches issued OUTSIDE watched_jit — the engine
    notes its known eager op groups (each eager jnp op on device arrays
    is one XLA execution) with conservative lower-bound counts, so the
    launches/iter figure stays comparable between the fused one-launch
    path and the eager pipeline it replaces."""
    global _launches
    _launches += n


def reset_counters() -> None:
    """Zero the module-global ``launches``/``host_syncs`` dispatch
    counters.  Per-entry compile counters have :func:`reset_watchdog`;
    this is the A/B counterpart for the globals — bench arms call it at
    the start of each timed arm so launches/iter and host_syncs/iter are
    attributable to THAT arm, not contaminated by the previous one."""
    global _launches, _host_syncs
    _launches = 0
    _host_syncs = 0


class WatchEntry:
    """Compile counter for one watched entry point."""

    def __init__(self, name: str, warn_after: Optional[int]) -> None:
        self.name = name
        self.warn_after = warn_after   # None = use the global threshold
        self.count = 0
        self.signatures: List[str] = []   # last few trace signatures
        self.warned = 0
        # trace count already cost-captured (telemetry/costmodel.py);
        # count > cost_seen means a fresh compile awaits capture
        self.cost_seen = 0

    def effective_threshold(self) -> int:
        return _default_threshold if self.warn_after is None else self.warn_after

    def note_trace(self, args: tuple, kwargs: dict) -> None:
        sig = _signature(args, kwargs)
        with _lock:
            self.count += 1
            self.signatures.append(sig)
            if len(self.signatures) > 4:
                del self.signatures[0]
            count = self.count
            prev = self.signatures[-2] if len(self.signatures) >= 2 else None
        thr = self.effective_threshold()
        if thr > 0 and count > thr:
            with _lock:
                self.warned += 1
            msg = (f"telemetry: {self.name!r} recompiled (trace #{count}, "
                   f"threshold {thr}) — mid-training retraces stall the "
                   f"device for the full XLA compile; new signature {sig}")
            if prev is not None and prev != sig:
                msg += f"; previous signature {prev}"
            log_warning(msg)
            from .tracer import global_tracer
            global_tracer.instant(f"recompile:{self.name}", count=count,
                                  signature=sig)
        from .metrics import global_registry
        global_registry.inc(f"recompile/{self.name}")


def _abbrev(x: Any) -> str:
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        return f"{getattr(aval.dtype, 'name', aval.dtype)}{list(aval.shape)}"
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return f"{getattr(x.dtype, 'name', x.dtype)}{list(x.shape)}"
    r = repr(x)
    return r if len(r) <= 24 else r[:21] + "..."


def _signature(args: tuple, kwargs: dict) -> str:
    try:
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        return "(" + ", ".join(_abbrev(v) for v in leaves[:24]) + \
            (", ..." if len(leaves) > 24 else "") + ")"
    except Exception:
        return "(?)"


def set_recompile_threshold(n: int) -> None:
    """Global warn threshold: entries warn on trace count > n (0 = never)."""
    global _default_threshold
    _default_threshold = int(n)


def get_recompile_threshold() -> int:
    return _default_threshold


def watched_jit(fun=None, *, name: Optional[str] = None, owner: Any = None,
                warn_after: Optional[int] = None, **jit_kwargs):
    """``jax.jit`` with per-entry-point compile counting.

    Usable directly (``watched_jit(f, name=...)``) or as a decorator
    factory (``@watched_jit(name=..., static_argnames=...)``). ``owner``
    scopes the counter: passing the same (name, owner) pair again — e.g.
    when an engine re-jits one of its entry points — reuses the counter,
    which is exactly what turns a parameter-reset retrace into a warning.
    """
    def wrap(f):
        wname = name or getattr(f, "__name__", "jit_fn")
        entry = None
        if owner is not None:
            watches = owner.__dict__.setdefault("_telemetry_watches", {})
            entry = watches.get(wname)
        if entry is None:
            entry = WatchEntry(wname, warn_after)
            if owner is not None:
                watches[wname] = entry
        with _lock:
            _entries.add(entry)

        @functools.wraps(f)
        def traced(*args, **kwargs):
            # runs ONLY while jax traces (i.e. on a compilation-cache miss)
            entry.note_trace(args, kwargs)
            return f(*args, **kwargs)

        jitted = jax.jit(traced, **jit_kwargs)

        @functools.wraps(f)
        def dispatched(*args, **kwargs):
            # one extra Python frame per dispatch buys the launches counter
            # (straggler `bottleneck: dispatch` classification); the jit's
            # C++ fast path still runs inside
            global _launches
            _launches += 1
            out = jitted(*args, **kwargs)
            if _costmodel.active():
                _costmodel.after_dispatch(entry, jitted, args, kwargs)
            return out

        dispatched._telemetry_watch = entry
        dispatched._jitted = jitted
        # forward the jit AOT/introspection surface the wrapper would
        # otherwise hide — with the compile/execute path WATCHED: a
        # `.lower(...).compile()` entry compile counts against the same
        # entry (and feeds the cost model), and calls on the compiled
        # executable count as launches, so the AOT surface cannot bypass
        # the recompile/dispatch accounting
        def lower(*args, **kwargs):
            c0 = entry.count
            lowered = jitted.lower(*args, **kwargs)
            # a jaxpr-cache miss runs `traced` during lower and already
            # counted; the wrapper must then NOT count the .compile() too
            return _WatchedLowered(lowered, entry, args, kwargs,
                                   counted=entry.count > c0)

        dispatched.lower = lower
        for attr in ("trace", "eval_shape", "clear_cache"):
            bound = getattr(jitted, attr, None)
            if bound is not None:
                setattr(dispatched, attr, bound)
        return dispatched

    return wrap if fun is None else wrap(fun)


class _WatchedLowered:
    """Forwarded ``.lower(...)`` result whose ``.compile()`` stays on the
    books: the AOT entry compile increments the entry's trace counter
    (``recompile/<name>`` included) and hands the compiled executable to
    the cost model — the full analysis for free, since the caller paid
    for the compile anyway."""

    __slots__ = ("_lowered", "_entry", "_args", "_kwargs", "_counted")

    def __init__(self, lowered, entry: WatchEntry, args: tuple,
                 kwargs: dict, counted: bool = False) -> None:
        self._lowered = lowered
        self._entry = entry
        self._args = args
        self._kwargs = kwargs
        self._counted = counted

    def compile(self, *args, **kwargs):
        compiled = self._lowered.compile(*args, **kwargs)
        if not self._counted:
            # lower() hit the jaxpr cache, so nothing counted this entry
            # compile yet — an AOT compile of an already-traced signature
            # is still a real XLA compile
            self._entry.note_trace(self._args, self._kwargs)
        self._counted = False   # a second .compile() of this Lowered counts
        _costmodel.note_compiled(self._entry, compiled)
        return _WatchedCompiled(compiled, self._entry)

    def __getattr__(self, name):
        return getattr(self._lowered, name)


class _WatchedCompiled:
    """AOT executable wrapper: every call is one XLA program execution,
    so it lands in the ``launches`` counter like a jit dispatch."""

    __slots__ = ("_compiled", "_entry")

    def __init__(self, compiled, entry: WatchEntry) -> None:
        self._compiled = compiled
        self._entry = entry

    def __call__(self, *args, **kwargs):
        global _launches
        _launches += 1
        out = self._compiled(*args, **kwargs)
        if _costmodel.active():
            _costmodel.note_dispatch(self._entry)
        return out

    def __getattr__(self, name):
        return getattr(self._compiled, name)


def recompile_counts() -> Dict[str, int]:
    """Aggregate trace counts by entry-point name (live entries; the
    metrics registry's ``recompile/<name>`` counters are cumulative)."""
    out: Dict[str, int] = {}
    with _lock:
        for entry in _entries:
            out[entry.name] = out.get(entry.name, 0) + entry.count
    return out


def watchdog_summary() -> Dict[str, Any]:
    """Per-name {entries, compiles, max_per_entry, warned} rollup."""
    out: Dict[str, Dict[str, int]] = {}
    with _lock:
        for entry in _entries:
            s = out.setdefault(entry.name, {"entries": 0, "compiles": 0,
                                            "max_per_entry": 0, "warned": 0})
            s["entries"] += 1
            s["compiles"] += entry.count
            s["max_per_entry"] = max(s["max_per_entry"], entry.count)
            s["warned"] += entry.warned
    return out


def reset_watchdog() -> None:
    """Zero every live entry's counters. Entries stay registered — the
    module-level kernel jits were wrapped once at import and can never
    re-register, so clearing the set would blind the watchdog to them."""
    with _lock:
        for entry in _entries:
            entry.count = 0
            entry.signatures = []
            entry.warned = 0
            entry.cost_seen = 0
