"""Tree model structure.

Reference: include/LightGBM/tree.h:27 (flat-array binary tree: split feature, bin + real
thresholds, child pointers with ~leaf encoding, leaf values/counts, categorical bitsets)
and src/io/tree.cpp (serialization). Here the device-side tree is a NamedTuple of fixed-size
JAX arrays (shapes static under jit); the host-side `Tree` adds real-valued thresholds and
category bitsets for model IO and raw-feature prediction.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, NamedTuple, Optional

import numpy as np

# dir_flags bits shared with ops.split
from .ops.split import (DIR_CAT_ONEHOT, DIR_CAT_REVERSED, DIR_CATEGORICAL,
                        DIR_DEFAULT_LEFT)


class TreeArrays(NamedTuple):
    """Device-side tree produced by the grower; sizes padded to the num_leaves budget L.

    Child pointer convention matches the reference (tree.h): value >= 0 is an internal
    node index, value < 0 encodes leaf ~leaf_idx."""
    split_feature: "np.ndarray"     # (L-1,) i32
    threshold_bin: "np.ndarray"     # (L-1,) i32 feature-local bin / cat prefix len
    dir_flags: "np.ndarray"         # (L-1,) i32
    left_child: "np.ndarray"        # (L-1,) i32
    right_child: "np.ndarray"       # (L-1,) i32
    split_gain: "np.ndarray"        # (L-1,) f32
    internal_value: "np.ndarray"    # (L-1,) f32 (node output if it were a leaf)
    internal_weight: "np.ndarray"   # (L-1,) f32 (sum_hessian)
    internal_count: "np.ndarray"    # (L-1,) f32
    cat_bitset: "np.ndarray"        # (L-1, Bmax) bool — left-side bin membership
    leaf_value: "np.ndarray"        # (L,) f32
    leaf_weight: "np.ndarray"       # (L,) f32
    leaf_count: "np.ndarray"        # (L,) f32
    leaf_parent: "np.ndarray"       # (L,) i32 node index (-1 for root)
    num_leaves: "np.ndarray"        # () i32 — actual leaf count
    leaf_depth: "np.ndarray"        # (L,) i32


@dataclass
class Tree:
    """Host-side tree with real-valued thresholds (model IO + raw prediction).

    ``shrinkage`` records the cumulative learning-rate factor applied to leaf values
    (reference: Tree::Shrinkage, tree.h)."""

    num_leaves: int
    split_feature: np.ndarray        # (num_leaves-1,) int32
    threshold_bin: np.ndarray        # (num_leaves-1,) int32
    threshold: np.ndarray            # (num_leaves-1,) float64 — real split value
    decision_type: np.ndarray        # (num_leaves-1,) uint8 — LightGBM-compatible bits
    left_child: np.ndarray
    right_child: np.ndarray
    split_gain: np.ndarray
    internal_value: np.ndarray
    internal_weight: np.ndarray
    internal_count: np.ndarray
    leaf_value: np.ndarray           # (num_leaves,) float64
    leaf_weight: np.ndarray
    leaf_count: np.ndarray
    cat_boundaries: np.ndarray = field(default_factory=lambda: np.zeros(1, np.int32))
    cat_threshold: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint32))
    shrinkage: float = 1.0
    is_linear: bool = False
    # linear-tree fields (reference: tree.h leaf_const_/leaf_coeff_/leaf_features_)
    leaf_const: Optional[np.ndarray] = None        # (num_leaves,) float64
    leaf_features: Optional[List[List[int]]] = None
    leaf_coeff: Optional[List[List[float]]] = None

    # LightGBM decision_type bit layout (reference: tree.h kCategoricalMask etc.)
    _CAT_MASK = 1
    _DEFAULT_LEFT_MASK = 2
    # missing type in bits 2-3: 0 none, 1 zero, 2 nan
    @staticmethod
    def make_decision_type(is_cat: bool, default_left: bool, missing_type: int) -> int:
        d = 0
        if is_cat:
            d |= Tree._CAT_MASK
        if default_left:
            d |= Tree._DEFAULT_LEFT_MASK
        d |= (missing_type & 3) << 2
        return d

    @property
    def num_cat(self) -> int:
        return int(len(self.cat_boundaries) - 1) if len(self.cat_threshold) else 0

    def shrink(self, rate: float) -> None:
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs] for cs in self.leaf_coeff]

    def add_bias(self, bias: float) -> None:
        """Fold a constant into the tree (reference: Tree::AddBias, used by
        boost_from_average so saved models are self-contained, gbdt.cpp:425)."""
        self.leaf_value = self.leaf_value + bias
        self.internal_value = self.internal_value + bias
        if self.is_linear and self.leaf_const is not None:
            self.leaf_const = self.leaf_const + bias

    # ------------------------------------------------------------------
    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Vectorised raw-feature prediction (reference: Tree::Predict / tree.h:135
        NumericalDecision: missing handling + `value <= threshold` goes left)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.full(n, self.leaf_value[0] if len(self.leaf_value) else 0.0)
        node = np.zeros(n, dtype=np.int64)
        out_leaf = np.full(n, -1, dtype=np.int64)
        active = node >= 0
        # max path length bounded by number of internal nodes
        for _ in range(self.num_leaves - 1):
            if not active.any():
                break
            idx = node[active]
            f = self.split_feature[idx]
            v = X[active, f]
            dt = self.decision_type[idx]
            is_cat = (dt & self._CAT_MASK) != 0
            default_left = (dt & self._DEFAULT_LEFT_MASK) != 0
            missing_type = (dt >> 2) & 3
            nan_mask = np.isnan(v)
            zero_missing = missing_type == 1
            miss = np.where(zero_missing, nan_mask | (np.abs(v) < 1e-35), nan_mask)
            go_left = v <= self.threshold[idx]
            # categorical: membership in bitset
            if is_cat.any():
                ci = idx[is_cat]
                vi = v[is_cat]
                iv = np.where(np.isnan(vi), -1, vi).astype(np.int64)
                gl = np.zeros(len(ci), dtype=bool)
                for j, (node_i, cat_v) in enumerate(zip(ci, iv)):
                    k = self._cat_index_of_node(node_i)
                    if k >= 0 and cat_v >= 0:
                        s, e = self.cat_boundaries[k], self.cat_boundaries[k + 1]
                        word = cat_v // 32
                        if word < e - s:
                            gl[j] = bool((self.cat_threshold[s + word] >> (cat_v % 32)) & 1)
                go_left[is_cat] = gl
                miss = miss & ~is_cat
            go_left = np.where(miss, default_left, go_left)
            nxt = np.where(go_left, self.left_child[idx], self.right_child[idx])
            leaf_hit = nxt < 0
            sel = np.where(active)[0]
            out_leaf[sel[leaf_hit]] = ~nxt[leaf_hit]
            node[sel] = nxt
            active = node >= 0
        out_leaf = np.where(out_leaf < 0, 0, out_leaf)
        if self.is_linear and self.leaf_const is not None:
            return self._linear_output(X, out_leaf)
        return self.leaf_value[out_leaf]

    def _linear_output(self, X: np.ndarray, leaf: np.ndarray) -> np.ndarray:
        """Linear-leaf prediction: const + coeff . x; rows with NaN in any
        used feature fall back to the regular constant leaf output
        (reference: Tree::Predict linear branch, tree.h)."""
        out = self.leaf_const[leaf].astype(np.float64).copy()
        for ln in range(self.num_leaves):
            feats = self.leaf_features[ln] if self.leaf_features else []
            rows = np.where(leaf == ln)[0]
            if len(rows) == 0 or not feats:
                continue
            sub = X[np.ix_(rows, feats)]
            nan_rows = np.isnan(sub).any(axis=1)
            lin = sub @ np.asarray(self.leaf_coeff[ln], np.float64)
            out[rows] = np.where(nan_rows, self.leaf_value[ln],
                                 out[rows] + lin)
        return out

    def predict_leaf_raw(self, X: np.ndarray) -> np.ndarray:
        """Leaf index per row (pred_leaf path)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        saved = self.leaf_value
        try:
            self.leaf_value = np.arange(self.num_leaves, dtype=np.float64)
            return self.predict_raw(X).astype(np.int32)
        finally:
            self.leaf_value = saved

    def _cat_index_of_node(self, node_i: int) -> int:
        """Index into cat_boundaries for a categorical node: the threshold_bin field of a
        categorical node stores its categorical-split ordinal."""
        return int(self.threshold_bin[node_i])

    # -- SHAP-style expected-value helpers ------------------------------
    def expected_value(self) -> float:
        if self.num_leaves <= 1:
            return float(self.leaf_value[0]) if len(self.leaf_value) else 0.0
        total = self.internal_count[0] if len(self.internal_count) else 0
        if total <= 0:
            return 0.0
        return float(np.sum(self.leaf_value[:self.num_leaves] *
                            self.leaf_count[:self.num_leaves]) / max(total, 1.0))


def finalize_tree(arrays, bin_mappers, feat_group, learning_rate: float = 1.0,
                  missing_types=None) -> Tree:
    """Convert device TreeArrays to a host Tree: bin thresholds -> real thresholds,
    bin bitsets -> category-value bitsets, trim padding."""
    import jax
    import numpy as _np

    arrays = jax.device_get(arrays)  # one transfer for the whole pytree
    nl = int(arrays.num_leaves)
    ni = max(nl - 1, 0)
    split_feature = _np.asarray(arrays.split_feature[:ni], dtype=np.int32)
    thr_bin = _np.asarray(arrays.threshold_bin[:ni], dtype=np.int32)
    dirf = _np.asarray(arrays.dir_flags[:ni], dtype=np.int32)
    cat_bits = _np.asarray(arrays.cat_bitset[:ni]) if ni else _np.zeros((0, 1), bool)

    threshold = _np.zeros(ni, dtype=np.float64)
    decision_type = _np.zeros(ni, dtype=np.uint8)
    cat_boundaries = [0]
    cat_words: List[np.ndarray] = []
    thr_out = thr_bin.copy()
    n_cat = 0
    for i in range(ni):
        f = int(split_feature[i])
        m = bin_mappers[f]
        is_cat = bool(dirf[i] & DIR_CATEGORICAL)
        default_left = bool(dirf[i] & DIR_DEFAULT_LEFT)
        if is_cat:
            # bins in the left set -> category values
            left_bins = _np.where(cat_bits[i])[0]
            left_bins = left_bins[left_bins < len(m.categories)]
            cats = m.categories[left_bins]
            max_cat = int(cats.max()) if len(cats) else 0
            words = _np.zeros(max_cat // 32 + 1, dtype=np.uint32)
            for c in cats:
                words[int(c) // 32] |= np.uint32(1 << (int(c) % 32))
            cat_words.append(words)
            cat_boundaries.append(cat_boundaries[-1] + len(words))
            thr_out[i] = n_cat            # categorical ordinal
            threshold[i] = float(n_cat)
            n_cat += 1
            decision_type[i] = Tree.make_decision_type(True, False, 0)
        else:
            threshold[i] = m.bin_to_threshold(int(thr_bin[i]))
            decision_type[i] = Tree.make_decision_type(
                False, default_left, int(m.missing_type))

    tree = Tree(
        num_leaves=max(nl, 1),
        split_feature=split_feature,
        threshold_bin=thr_out,
        threshold=threshold,
        decision_type=decision_type,
        left_child=_np.asarray(arrays.left_child[:ni], dtype=np.int32),
        right_child=_np.asarray(arrays.right_child[:ni], dtype=np.int32),
        split_gain=_np.asarray(arrays.split_gain[:ni], dtype=np.float64),
        internal_value=_np.asarray(arrays.internal_value[:ni], dtype=np.float64),
        internal_weight=_np.asarray(arrays.internal_weight[:ni], dtype=np.float64),
        internal_count=_np.asarray(arrays.internal_count[:ni], dtype=np.float64),
        leaf_value=_np.asarray(arrays.leaf_value[:max(nl, 1)], dtype=np.float64),
        leaf_weight=_np.asarray(arrays.leaf_weight[:max(nl, 1)], dtype=np.float64),
        leaf_count=_np.asarray(arrays.leaf_count[:max(nl, 1)], dtype=np.float64),
        cat_boundaries=_np.asarray(cat_boundaries, dtype=np.int32),
        cat_threshold=(_np.concatenate(cat_words) if cat_words
                       else _np.zeros(0, dtype=np.uint32)),
    )
    if learning_rate != 1.0:
        tree.shrink(learning_rate)
    return tree
