from .log import (LightGBMError, log_debug, log_fatal, log_info, log_warning,
                  register_logger, set_verbosity)
from .timer import Timer, named_scope

__all__ = [
    "LightGBMError", "log_debug", "log_fatal", "log_info", "log_warning",
    "register_logger", "set_verbosity", "Timer", "named_scope",
]
