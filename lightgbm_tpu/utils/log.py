"""Leveled logging with a redirectable callback.

Reference: include/LightGBM/utils/log.h:79-181 (Log class with Fatal/Warning/Info/Debug and a
resettable callback) and python-package/lightgbm/basic.py:215 (register_logger).
"""
from __future__ import annotations

import logging
from typing import Any, Callable, Optional

_logger: Any = logging.getLogger("lightgbm_tpu")
# guard against duplicate handlers on re-import/reload, and respect a logger
# the user configured before importing this package: only attach our default
# StreamHandler when none exists, and only set a level when none was chosen
if not _logger.handlers:
    _logger.addHandler(logging.StreamHandler())
if _logger.level == logging.NOTSET:
    _logger.setLevel(logging.INFO)

_info_method_name = "info"
_warning_method_name = "warning"

# verbosity: <0 fatal only, 0 warning+, 1 info+, >=2 debug+
_verbosity = 1


def register_logger(logger: Any, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Redirect framework logging to a custom logger (parity: lightgbm.register_logger)."""
    global _logger, _info_method_name, _warning_method_name
    if not (hasattr(logger, info_method_name) and hasattr(logger, warning_method_name)):
        raise TypeError("logger must provide the given info/warning methods")
    _logger = logger
    _info_method_name = info_method_name
    _warning_method_name = warning_method_name


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = int(v)


def get_verbosity() -> int:
    return _verbosity


def log_debug(msg: str) -> None:
    if _verbosity >= 2:
        getattr(_logger, _info_method_name)(f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= 1:
        getattr(_logger, _info_method_name)(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= 0:
        getattr(_logger, _warning_method_name)(f"[LightGBM-TPU] [Warning] {msg}")


class LightGBMError(Exception):
    """Error raised by the framework (parity: lightgbm.basic.LightGBMError)."""


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)
