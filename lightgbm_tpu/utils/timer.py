"""Named accumulating timers + profiler scopes.

Reference: include/LightGBM/utils/common.h:980 (Common::Timer / global_timer, RAII
FunctionTimer, printed at exit under USE_TIMETAG). TPU equivalent additionally wraps
jax.named_scope so regions show up in xprof traces.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator

import jax


class Timer:
    """Accumulating named wall-clock timer (host-side)."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.enabled = os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

    @contextlib.contextmanager
    def scope(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = [f"{name}: {total:.3f}s ({self.counts[name]} calls)"
                 for name, total in sorted(self.totals.items())]
        return "\n".join(lines)


global_timer = Timer()


@atexit.register
def _print_timers() -> None:
    if global_timer.enabled and global_timer.totals:
        print("[LightGBM-TPU] timers:\n" + global_timer.report())


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """Combined host timer + device trace annotation (shows in JAX profiler)."""
    with jax.named_scope(name):
        with global_timer.scope(name):
            yield
