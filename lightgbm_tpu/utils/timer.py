"""Named accumulating timers + profiler scopes.

Reference: include/LightGBM/utils/common.h:980 (Common::Timer / global_timer, RAII
FunctionTimer, printed at exit under USE_TIMETAG). TPU equivalent additionally wraps
jax.named_scope so regions show up in xprof traces, and feeds the telemetry span
tracer (lightgbm_tpu.telemetry) so the same regions land in exported Chrome traces.
"""
from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator, Optional

import jax

from ..telemetry.tracer import global_tracer


class Timer:
    """Accumulating named wall-clock timer (host-side).

    ``enabled`` re-reads ``LIGHTGBM_TPU_TIMETAG`` lazily on every check, so
    setting the env var after import works; :meth:`enable`/:meth:`disable`
    (or assigning ``enabled``) override the env var for this process."""

    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._enabled_override: Optional[bool] = None

    @property
    def enabled(self) -> bool:
        if self._enabled_override is not None:
            return self._enabled_override
        return os.environ.get("LIGHTGBM_TPU_TIMETAG", "") not in ("", "0")

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self._enabled_override = bool(value)

    def enable(self) -> None:
        self._enabled_override = True

    def disable(self) -> None:
        self._enabled_override = False

    def reset_enabled(self) -> None:
        """Drop any override; follow the env var again."""
        self._enabled_override = None

    @contextlib.contextmanager
    def scope(self, name: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        """Hot spots first: sorted by total time descending, with per-call
        mean (the alphabetical order of the original hid the hot paths)."""
        lines = []
        for name, total in sorted(self.totals.items(),
                                  key=lambda kv: -kv[1]):
            n = self.counts[name]
            mean_ms = total / n * 1e3 if n else 0.0
            lines.append(f"{name}: {total:.3f}s ({n} calls, "
                         f"{mean_ms:.3f} ms/call)")
        return "\n".join(lines)


global_timer = Timer()


@atexit.register
def _print_timers() -> None:
    if global_timer.enabled and global_timer.totals:
        print("[LightGBM-TPU] timers:\n" + global_timer.report())


@contextlib.contextmanager
def named_scope(name: str) -> Iterator[None]:
    """Combined device trace annotation (JAX profiler) + host timer +
    telemetry span (Chrome trace export) for one region."""
    with jax.named_scope(name):
        with global_timer.scope(name):
            with global_tracer.span(name):
                yield
