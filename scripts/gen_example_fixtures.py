"""Generate stock-LightGBM oracle fixtures from the reference's bundled
example datasets (reference: examples/*/train.conf, consumed the same way
by tests/python_package_test/test_consistency.py and cpp testutils.cpp).

Runs the stock CLI on each example's own train.conf and records the final
validation metrics into tests/fixtures/examples_stock.json. The real-data
consistency tier (tests/test_consistency_examples.py) trains our CLI on
the same confs and asserts metric parity within tolerance.

Usage: LGBM_CLI=/tmp/refsrc2/lightgbm python scripts/gen_example_fixtures.py
(see the stock-CLI build recipe in that test's docstring if /tmp was wiped)
"""
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = Path("/root/reference/examples")

# example dir -> files to copy; train.conf is implied
CASES = {
    "binary_classification": ["binary.train", "binary.test",
                              "binary.train.weight", "binary.test.weight",
                              "forced_splits.json"],
    "lambdarank": ["rank.train", "rank.test", "rank.train.query",
                   "rank.test.query"],
    "multiclass_classification": ["multiclass.train", "multiclass.test"],
    "regression": ["regression.train", "regression.test",
                   "regression.train.init", "regression.test.init"],
}

METRIC_RE = re.compile(
    r"Iteration:(\d+), (valid_1|training) ([a-zA-Z_@0-9.]+) : ([-0-9.eE+]+)")


def run_case(cli, name, files):
    src = EXAMPLES / name
    with tempfile.TemporaryDirectory() as td:
        for f in files + ["train.conf"]:
            if (src / f).exists():
                shutil.copy(src / f, td)
        out = subprocess.run([cli, "config=train.conf"], cwd=td,
                             capture_output=True, text=True, timeout=600)
        text = out.stdout + out.stderr
        if "Finished training" not in text:
            raise RuntimeError(f"{name}: stock CLI failed:\n{text[-2000:]}")
    finals = {}
    for it, split, metric, val in METRIC_RE.findall(text):
        finals[f"{split}:{metric}"] = float(val)   # last occurrence wins
    return finals


def main():
    cli = os.environ.get("LGBM_CLI", "/tmp/refsrc2/lightgbm")
    if not Path(cli).exists():
        sys.exit(f"stock CLI not found at {cli}; set LGBM_CLI")
    fixtures = {}
    for name, files in CASES.items():
        fixtures[name] = run_case(cli, name, files)
        print(name, {k: v for k, v in fixtures[name].items()
                     if k.startswith("valid_1")})
    dest = REPO / "tests" / "fixtures" / "examples_stock.json"
    dest.write_text(json.dumps(fixtures, indent=1, sort_keys=True) + "\n")
    print("wrote", dest)


if __name__ == "__main__":
    main()
