"""Generate golden interop fixtures against a stock LightGBM CLI binary.

Usage: LGBM_CLI=/path/to/lightgbm python scripts/gen_golden_fixtures.py

Produces, under tests/fixtures/:
  - stock_{binary,regression_cat,multiclass}.model  — models trained by STOCK
    LightGBM on the deterministic data below
  - golden_X.csv / golden_y_{task}.csv              — the data
  - stock_pred_{task}.txt                            — stock's predictions
  - ours_{binary}.model + stock_pred_on_ours.txt     — a model trained by
    lightgbm_tpu, verified to LOAD in stock LightGBM, with stock's
    predictions on it (proves the reference grammar accepts our files;
    reference: src/boosting/gbdt_model_text.cpp:315, src/io/tree.cpp)

The fixtures are checked in; tests/test_golden.py never needs the binary.
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np

ROOT = Path(__file__).resolve().parent.parent
FIX = ROOT / "tests" / "fixtures"
CLI = os.environ.get("LGBM_CLI", "/tmp/refsrc/lightgbm")


def make_data(seed=42, n=600, f=6):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f).round(4)
    X[:, 4] = rs.randint(0, 5, n)            # categorical-able column
    X[rs.rand(n) < 0.08, 0] = np.nan         # missing values
    logit = X[:, 1] - 0.8 * np.nan_to_num(X[:, 0]) + (X[:, 4] == 2) * 1.5
    y_bin = (rs.rand(n) < 1 / (1 + np.exp(-logit))).astype(int)
    y_reg = (X[:, 1] * 2 + np.nan_to_num(X[:, 0]) + (X[:, 4] == 3) * 2
             + 0.05 * rs.randn(n)).round(5)
    y_mc = (np.clip((X[:, 1] > 0).astype(int) + (X[:, 2] > 0.3), 0, 2))
    return X, y_bin, y_reg, y_mc


def write_csv(path, y, X):
    data = np.column_stack([y, np.nan_to_num(X, nan=np.nan)])
    with open(path, "w") as fh:
        for row in data:
            fh.write(",".join("" if np.isnan(v) else f"{v:.6g}" for v in row)
                     + "\n")


def run_cli(conf: dict, cwd):
    args = [CLI] + [f"{k}={v}" for k, v in conf.items()]
    r = subprocess.run(args, cwd=cwd, capture_output=True, text=True)
    if r.returncode != 0:
        sys.exit(f"CLI failed: {args}\n{r.stdout}\n{r.stderr}")
    return r.stdout


def main():
    FIX.mkdir(parents=True, exist_ok=True)
    X, y_bin, y_reg, y_mc = make_data()
    train_csv = FIX / "golden_train_binary.csv"
    write_csv(train_csv, y_bin, X)
    write_csv(FIX / "golden_train_reg.csv", y_reg, X)
    write_csv(FIX / "golden_train_mc.csv", y_mc, X)
    # prediction input: the training matrix without labels
    with open(FIX / "golden_X.csv", "w") as fh:
        for row in X:
            fh.write(",".join("" if np.isnan(v) else f"{v:.6g}" for v in row)
                     + "\n")

    common = {"num_leaves": 15, "min_data_in_leaf": 5, "max_bin": 63,
              "num_iterations": 10, "learning_rate": 0.1, "verbosity": -1,
              "header": "false", "label_column": "0"}
    tasks = [
        ("binary", {"objective": "binary",
                    "data": str(train_csv)}),
        ("regression_cat", {"objective": "regression",
                            "data": str(FIX / 'golden_train_reg.csv'),
                            "categorical_feature": "4"}),
        ("multiclass", {"objective": "multiclass", "num_class": "3",
                        "data": str(FIX / 'golden_train_mc.csv')}),
    ]
    for name, extra in tasks:
        model = FIX / f"stock_{name}.model"
        run_cli({**common, **extra, "task": "train",
                 "output_model": str(model)}, FIX)
        run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
                 "input_model": str(model), "header": "false",
                 "output_result": str(FIX / f"stock_pred_{name}.txt"),
                 "predict_raw_score": "true", "verbosity": -1}, FIX)
        print(f"generated stock_{name}.model")

    # ---- predict modes on the binary model (gbdt_prediction.cpp:
    # PredictLeafIndex; TreeSHAP PredictContrib, tree.cpp:1103) ----
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(FIX / 'stock_binary.model'), "header": "false",
             "output_result": str(FIX / "stock_pred_binary_leaf.txt"),
             "predict_leaf_index": "true", "verbosity": -1}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(FIX / 'stock_binary.model'), "header": "false",
             "output_result": str(FIX / "stock_pred_binary_contrib.txt"),
             "predict_contrib": "true", "verbosity": -1}, FIX)
    print("generated leaf/contrib predictions")

    # ---- weighted training (reference: metadata.cpp LoadWeights) ----
    rs = np.random.RandomState(7)
    w = (0.5 + rs.rand(len(X))).round(4)
    np.savetxt(str(train_csv) + ".weight", w, fmt="%.4f")
    model = FIX / "stock_binary_weighted.model"
    run_cli({**common, "objective": "binary", "data": str(train_csv),
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_binary_weighted.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    np.savetxt(FIX / "golden_weights.csv", w, fmt="%.4f")
    os.remove(str(train_csv) + ".weight")
    print("generated stock_binary_weighted.model")

    # ---- monotone constraint methods (monotone_constraints.hpp) ----
    for method in ("basic", "intermediate", "advanced"):
        model = FIX / f"stock_monotone_{method}.model"
        run_cli({**common, "objective": "regression",
                 "data": str(FIX / 'golden_train_reg.csv'),
                 "monotone_constraints": "1,-1,0,0,0,0",
                 "monotone_constraints_method": method,
                 "task": "train", "output_model": str(model)}, FIX)
        run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
                 "input_model": str(model), "header": "false",
                 "output_result": str(FIX / f"stock_pred_monotone_{method}.txt"),
                 "predict_raw_score": "true", "verbosity": -1}, FIX)
        print(f"generated stock_monotone_{method}.model")

    # ---- interaction constraints (col_sampler.hpp) ----
    model = FIX / "stock_interaction.model"
    run_cli({**common, "objective": "regression",
             "data": str(FIX / 'golden_train_reg.csv'),
             "interaction_constraints": "[0,1],[2,3,4,5]",
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_interaction.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_interaction.model")

    # ---- forced bin bounds (bin.cpp FindBinWithPredefinedBin) ----
    import json as _json
    fb = [{"feature": 1, "bin_upper_bound": [-0.5, 0.1, 0.75]},
          {"feature": 3, "bin_upper_bound": [0.0, 0.42]}]
    (FIX / "golden_forcedbins.json").write_text(_json.dumps(fb))
    model = FIX / "stock_forcedbins.model"
    run_cli({**common, "objective": "regression",
             "data": str(FIX / 'golden_train_reg.csv'),
             "forcedbins_filename": str(FIX / "golden_forcedbins.json"),
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_forcedbins.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_forcedbins.model")

    # ---- deterministic objective families (regression_objective.hpp:
    # percentile boost/renewal for l1/quantile/mape, log-link for
    # poisson/gamma/tweedie, fair's L2-inherited mean boost) ----
    ypos = (np.abs(y_reg) + 0.1).round(5)
    pos_csv = FIX / "golden_train_pos.csv"
    write_csv(pos_csv, ypos, X)
    obj_cases = [
        ("huber", train_csv.parent / "golden_train_reg.csv", {}),
        ("fair", train_csv.parent / "golden_train_reg.csv", {}),
        ("regression_l1", train_csv.parent / "golden_train_reg.csv", {}),
        ("quantile", train_csv.parent / "golden_train_reg.csv",
         {"alpha": "0.7"}),
        ("poisson", pos_csv, {}),
        ("gamma", pos_csv, {}),
        ("tweedie", pos_csv, {}),
        ("mape", pos_csv, {}),
    ]
    for obj, data, extra in obj_cases:
        model = FIX / f"stock_obj_{obj}.model"
        run_cli({**common, "objective": obj, "data": str(data), **extra,
                 "task": "train", "output_model": str(model)}, FIX)
        run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
                 "input_model": str(model), "header": "false",
                 "output_result": str(FIX / f"stock_pred_obj_{obj}.txt"),
                 "predict_raw_score": "true", "verbosity": -1}, FIX)
        print(f"generated stock_obj_{obj}.model")

    # ---- regularized scan params (GetLeafGain/CalculateSplittedLeafOutput
    # variants: path smoothing, L1/L2, depth cap, min-gain gate) ----
    model = FIX / "stock_regularized.model"
    run_cli({**common, "objective": "regression",
             "data": str(FIX / 'golden_train_reg.csv'),
             "path_smooth": "0.5", "lambda_l1": "0.5", "lambda_l2": "0.2",
             "max_depth": "5", "min_gain_to_split": "0.01",
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_regularized.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_regularized.model")

    # ---- max_delta_step (USE_MAX_OUTPUT: gains at clamped outputs) ----
    model = FIX / "stock_maxdelta.model"
    run_cli({**common, "objective": "regression",
             "data": str(FIX / 'golden_train_reg.csv'),
             "max_delta_step": "0.3",
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_maxdelta.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_maxdelta.model")

    # ---- zero_as_missing (MissingType::Zero) ----
    rs3 = np.random.RandomState(21)
    nz = 600
    Xz = rs3.randn(nz, 4).round(4)
    Xz[rs3.rand(nz, 4) < 0.35] = 0.0
    yz = (Xz[:, 0] + 0.5 * Xz[:, 1] - Xz[:, 2] + 0.1 * rs3.randn(nz)).round(5)
    zam_csv = FIX / "golden_train_zam.csv"
    write_csv(zam_csv, yz, Xz)
    with open(FIX / "golden_X_zam.csv", "w") as fh:
        for row in Xz:
            fh.write(",".join(f"{v:.6g}" for v in row) + "\n")
    model = FIX / "stock_zam.model"
    run_cli({**common, "objective": "regression", "data": str(zam_csv),
             "zero_as_missing": "true",
             "task": "train", "output_model": str(model)}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X_zam.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_zam.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_zam.model")

    # ---- refit on perturbed labels (Application task=refit) ----
    rs2 = np.random.RandomState(13)
    flip = rs2.rand(len(y_bin)) < 0.15
    y_refit = np.where(flip, 1 - y_bin, y_bin)
    refit_csv = FIX / "golden_train_refit.csv"
    write_csv(refit_csv, y_refit, X)
    model = FIX / "stock_binary_refit.model"
    # objective must be passed explicitly: CLI task=refit builds its objective
    # from the config (default "regression"), NOT the model's objective line
    # (application.cpp:262 CreateObjectiveFunction(config_.objective)); the
    # Python-API refit the test exercises uses the booster's objective
    run_cli({"task": "refit", "data": str(refit_csv),
             "input_model": str(FIX / 'stock_binary.model'),
             "output_model": str(model), "header": "false",
             "label_column": "0", "refit_decay_rate": "0.9",
             "objective": "binary",
             "verbosity": -1}, FIX)
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(model), "header": "false",
             "output_result": str(FIX / "stock_pred_binary_refit.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    print("generated stock_binary_refit.model")

    # ---- reverse direction: OUR model must load in stock LightGBM ----
    sys.path.insert(0, str(ROOT))
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y_bin.astype(float), categorical_feature=[4],
                     params={"max_bin": 63, "verbosity": -1})
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                     "min_data_in_leaf": 5, "max_bin": 63}, ds,
                    num_boost_round=10)
    ours = FIX / "ours_binary.model"
    bst.save_model(str(ours))
    run_cli({"task": "predict", "data": str(FIX / 'golden_X.csv'),
             "input_model": str(ours), "header": "false",
             "output_result": str(FIX / "stock_pred_on_ours.txt"),
             "predict_raw_score": "true", "verbosity": -1}, FIX)
    stock_on_ours = np.loadtxt(FIX / "stock_pred_on_ours.txt")
    ours_pred = bst.predict(X, raw_score=True)
    err = np.abs(stock_on_ours - ours_pred).max()
    print(f"stock-on-ours max |diff| vs our predict: {err:.3e}")
    if err > 1e-6:
        sys.exit("our saved model predicts differently under stock LightGBM")
    print("all fixtures generated")


if __name__ == "__main__":
    main()
