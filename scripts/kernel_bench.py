"""Microbenchmark of the fused route+hist stream kernel (dev tool).

Times route_and_hist directly at HIGGS bench shapes (10.5M rows, G=28,
B=64, S=64, L=255) under each LGBTPU_KABLATE probe, isolating kernel-phase
costs from engine overhead (the full-bench ablation route corrupts training
and shifts time into trivial-tree host syncs, so it cannot attribute time).

Usage: python scripts/kernel_bench.py [rows] — runs ONE configuration per
process; the sweep driver loops over LGBTPU_KABLATE values externally
(the probe is read at stream_kernel import time).

KB_TRACE_OUT=<path> records each pass as a telemetry span and writes a
Chrome/Perfetto trace (lightgbm_tpu.telemetry.export_trace) on exit.
"""
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 10_500_000
    int_path = os.environ.get("KB_INT", "1") == "1"
    two_pass = os.environ.get("KB_TWOPASS", "0") == "1"
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.pallas.stream_kernel import (build_route_tables,
                                                   pack_bins_T,
                                                   route_and_hist,
                                                   stream_block_rows)
    from lightgbm_tpu.ops.grow import RoutingLayout

    G, Bmax, S, L = 28, 63, 64, 255
    T = stream_block_rows(Bmax, G)
    rs = np.random.RandomState(0)
    bins = rs.randint(0, Bmax, size=(rows, G)).astype(np.uint8)
    layout = pack_bins_T(jnp.asarray(bins), T, max_bins=Bmax)
    n_pad = layout.n_pad
    F = G
    routing = RoutingLayout(
        feat_group=jnp.arange(F, dtype=jnp.int32),
        span_start=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        bundled=jnp.zeros(F, bool),
        nan_bin=jnp.full(F, -1, jnp.int32),
        num_bins=jnp.full(F, Bmax, jnp.int32))

    leaf_id = jnp.zeros((1, n_pad), jnp.int32)
    if int_path:
        g = rs.randint(-32, 32, size=n_pad).astype(np.float32)
        h = rs.randint(0, 32, size=n_pad).astype(np.float32)
    else:
        g = rs.randn(n_pad).astype(np.float32)
        h = rs.rand(n_pad).astype(np.float32)
    w_T = jnp.zeros((8, n_pad), jnp.float32)
    w_T = w_T.at[0].set(jnp.asarray(g)).at[1].set(jnp.asarray(h)) \
             .at[2].set(1.0)

    # S/2 random leaf splits (plausible mid-tree round)
    zL = jnp.zeros(L, jnp.int32)
    chosen = jnp.zeros(L, jnp.int32).at[:S].set(1)
    feats = jnp.asarray(rs.randint(0, F, L), jnp.int32)
    thrs = jnp.asarray(rs.randint(1, Bmax - 1, L), jnp.int32)
    newid = jnp.asarray(np.arange(L) + 1, jnp.int32) % L
    s1 = jnp.zeros(L, jnp.int32).at[:S].set(jnp.arange(1, S + 1, dtype=jnp.int32))
    tabs = build_route_tables(chosen, feats, thrs, zL, newid, s1, zL, zL,
                              routing, L)
    bits = jnp.zeros((-(-Bmax // 8) * 8, L), jnp.bfloat16)

    def run(lid):
        nl, hist, cnt = route_and_hist(
            layout.bins_T, lid, w_T, tabs, bits, S, Bmax, G, L,
            block_rows=T, has_cat=False, two_pass=two_pass,
            int_weights=int_path)
        return nl, hist, cnt

    from lightgbm_tpu import telemetry as tel
    trace_out = os.environ.get("KB_TRACE_OUT", "")
    if trace_out:
        tel.configure(enabled=True, trace_out=trace_out)

    with tel.span("kernel_bench::warmup", rows=rows):
        nl, hist, cnt = run(leaf_id)
        jax.block_until_ready((nl, hist, cnt))
    reps = 10
    # chain each rep on the previous output so every dispatch is real
    # sequential device work (identical repeated dispatches measured
    # impossibly fast through the tunnel)
    lid = nl % L
    t0 = time.time()
    for rep in range(reps):
        with tel.span("kernel_bench::route_and_hist", rep=rep):
            out = run(lid)
            lid = out[0] % L
    jax.block_until_ready(out)
    dt = (time.time() - t0) / reps
    if trace_out:
        tel.flush()
        print(f"KB trace written to {trace_out}")
    gbps = (layout.bins_T.size * 4 + n_pad * (4 + 12)) / dt / 1e9
    print(f"KB ablate={os.environ.get('LGBTPU_KABLATE','')!r} "
          f"int={int_path} two_pass={two_pass} rows={rows} T={T} "
          f"-> {dt*1e3:.2f} ms/pass  ({rows/dt/1e9:.2f} Grows/s, "
          f"~{gbps:.0f} GB/s effective)")


if __name__ == "__main__":
    main()
