"""Perf-regression sentinel: cost budgets + bench-history compare.

Wall-clock on a shared test box is noisy; XLA flops, peak-HBM bytes, and
launches-per-iteration are not — they are properties of the compiled
programs.  The sentinel therefore gates on two complementary surfaces
(docs/OBSERVABILITY.md "Perf-regression sentinel"):

**Budget mode** (``--budgets PERF_BUDGETS.json --measure``): trains the
manifest's fixed small workload with full cost capture
(telemetry/costmodel.py), exercises the serving predictor, and compares
each watched entry's measured flops / peak-HBM / launches-per-iter
against its budget ceiling.  Deterministic on any box — silent compute
bloat (an accidental f32 upcast, a lost fusion, a new per-round gather)
fails here even when wall-clock noise would hide it.  Entries whose
backend reports no cost analysis are ``unavailable`` and are SKIPPED
with a notice — never treated as zero (a zero would read as a 100%
improvement and grandfather real regressions under a later budget
refresh).

**History mode** (``--history BENCH_HISTORY.jsonl``): compares the
newest bench value per (metric, host) against the median of its
predecessors on the SAME host, directional per metric (qps up is good,
s/tree down is good), with a noise tolerance.  Hosts with fewer than
``--min-runs`` entries are skipped with a notice, so the gate is safe to
run everywhere and only bites where history exists.

Exit status: 0 = all checks passed/skipped, 1 = regression, 2 = usage /
manifest error.  ``--current FILE`` substitutes a saved measurement for
``--measure`` (fixture injection for tests; also useful to re-judge one
measurement against edited budgets without retraining).
"""
import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# the budget workload: small, fixed, seeded — flops/HBM are then pure
# functions of the compiled programs, comparable across boxes
DEFAULT_WORKLOAD = {
    "rows": 20_000, "features": 16, "num_leaves": 31, "max_bin": 63,
    "iters": 4, "seed": 7,
}
# fields a budget entry may bound (ceilings; measured must stay under
# budget * (1 + tolerance))
BUDGET_FIELDS = ("flops", "bytes_accessed", "peak_hbm_bytes")


def measure(workload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Train the fixed workload + exercise serving with full cost capture;
    returns {entries, launches_per_iter, workload, platform}."""
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.telemetry import costmodel
    from lightgbm_tpu.telemetry.profile import _synthetic_data

    w = {**DEFAULT_WORKLOAD, **(workload or {})}
    X, y = _synthetic_data(int(w["rows"]), int(w["features"]),
                           int(w["seed"]))
    params = {
        "objective": "binary", "num_leaves": int(w["num_leaves"]),
        "max_bin": int(w["max_bin"]), "learning_rate": 0.1,
        "verbosity": -1, "telemetry": True, "telemetry_cost": "full",
    }
    # an exported LGBTPU_COST (e.g. "off" on a dev box) overrides the
    # param and would let the gate pass vacuously with zero checks —
    # the sentinel's measurement MUST run at full capture
    cost_env = os.environ.pop("LGBTPU_COST", None)
    try:
        telemetry.reset_watchdog()
        telemetry.reset_counters()
        bst = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=int(w["iters"]))
        if costmodel.mode() != "full":
            raise RuntimeError(
                f"cost capture resolved to {costmodel.mode()!r}, not "
                "'full' — the budget measurement would be vacuous")
    finally:
        if cost_env is not None:
            os.environ["LGBTPU_COST"] = cost_env
    # ingest entry: the streamed chunked bin-and-ship program
    # (ingest_ship, device_data.ship_binned_chunks) — forced on via the
    # env override so the CPU sentinel box compiles it too
    ship_env = os.environ.get("LGBTPU_INGEST_SHIP")
    os.environ["LGBTPU_INGEST_SHIP"] = "1"
    try:
        ship_ds = lgb.Dataset(X, label=y, params={
            "verbosity": -1, "ingest_mode": "stream",
            "ingest_chunk_rows": max(4096, int(w["rows"]) // 4),
            "max_bin": int(w["max_bin"])})
        ship_ds.device_data()
    finally:
        if ship_env is None:
            os.environ.pop("LGBTPU_INGEST_SHIP", None)
        else:
            os.environ["LGBTPU_INGEST_SHIP"] = ship_env
    # serving entries: the bucketed compiled predictor (serve_predict)
    # and the stacked multi-tenant dispatch (serve_predict_multi) — two
    # same-shape tenants through ONE grouped window, so the stacked
    # program's cost is attributable on the same fixed workload
    with tempfile.TemporaryDirectory(prefix="lgb_sentinel_") as td:
        path = os.path.join(td, "model.txt")
        bst.save_model(path)
        from lightgbm_tpu.serving.registry import ModelRegistry
        reg = ModelRegistry(path, max_batch=64)
        reg.current().predict(X[:8], raw_score=True)
        import shutil
        from lightgbm_tpu.serving.multimodel import MultiModelRegistry
        path_b = os.path.join(td, "model_b.txt")
        shutil.copy(path, path_b)
        sidecar = path + ".quality.json"
        if os.path.exists(sidecar):
            shutil.copy(sidecar, path_b + ".quality.json")
        mreg = MultiModelRegistry({"a": path, "b": path_b},
                                  max_batch=64, warmup=False)
        mreg.raw_scores_grouped([(mreg.current("a"), X[:8]),
                                 (mreg.current("b"), X[:8])])
    from lightgbm_tpu.telemetry import global_registry
    recs = [r for r in global_registry.records
            if r.get("event") == "iteration" and "launches" in r]
    # steady state: the first iteration carries the compile-time eager
    # setup dispatches — budgets bound the repeated per-iteration cost
    steady = [float(r["launches"]) for r in recs[1:]] or \
        [float(r["launches"]) for r in recs]
    launches_per_iter = max(steady) if steady else 0.0
    entries: Dict[str, Any] = {}
    unavailable: List[str] = []
    for name, rec in costmodel.cost_records().items():
        if rec.get("available"):
            entries[name] = {k: rec[k] for k in
                             (*BUDGET_FIELDS, "intensity", "verdict")
                             if k in rec}
        else:
            unavailable.append(name)
            entries[name] = {"available": False,
                             "error": rec.get("error", "")}
    # feature-parallel grow program (tree_learner=feature): measured in a
    # SUBPROCESS on a forced 4-device CPU platform (this process's device
    # count is fixed at jax init) with the fused path off, so grow_tree is
    # its own watched jit and its XLA cost is attributable
    entries["grow_tree_feature"] = _measure_feature_grow(w)
    if entries["grow_tree_feature"].get("available") is False:
        unavailable.append("grow_tree_feature")
    # histogram-floor backends (PR "break the histogram floor"): the
    # scatter-add grow program (single device) and the packed-int16-wire
    # quantized grow program (4-device CPU mesh) — each in a subprocess
    # for the same jax-init reasons as the feature entry
    entries["grow_tree_scatter"] = _measure_backend_grow(
        w, {"hist_backend": "scatter", "hist_precision": "single"}, 0)
    if entries["grow_tree_scatter"].get("available") is False:
        unavailable.append("grow_tree_scatter")
    entries["grow_tree_packed16"] = _measure_backend_grow(
        w, {"hist_backend": "stream", "tree_learner": "data",
            "use_quantized_grad": True, "hist_packed_width": 16}, 4)
    if entries["grow_tree_packed16"].get("available") is False:
        unavailable.append("grow_tree_packed16")
    # 2D rows x feature-groups grow program (docs/DISTRIBUTED.md "2D
    # mesh"): data:2,feature:2 on the same 4-device CPU mesh — segsum
    # pinned because the 2D path forbids stream and the sentinel must
    # watch ONE deterministic backend
    entries["grow_tree_mesh2d"] = _measure_backend_grow(
        w, {"tree_learner": "data", "mesh_shape": "data:2,feature:2",
            "hist_backend": "segsum"}, 4)
    if entries["grow_tree_mesh2d"].get("available") is False:
        unavailable.append("grow_tree_mesh2d")
    import jax
    return {
        "workload": w,
        "platform": jax.default_backend(),
        "entries": entries,
        "launches_per_iter": round(launches_per_iter, 3),
        "unavailable": sorted(unavailable),
    }


_FEATURE_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["LGBTPU_FUSE_ITER"] = "0"
os.environ.pop("LGBTPU_COST", None)
sys.path.insert(0, sys.argv[1])
w = json.loads(sys.argv[2])
# a sitecustomize hook (TPU containers) may have imported jax and
# registered an accelerator backend at interpreter startup — env vars
# alone are too late there (the tests/conftest.py pattern)
import jax
jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
    clear_backends()
except Exception:
    pass
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import costmodel
from lightgbm_tpu.telemetry.profile import _synthetic_data
X, y = _synthetic_data(int(w["rows"]), int(w["features"]), int(w["seed"]))
params = {"objective": "binary", "num_leaves": int(w["num_leaves"]),
          "max_bin": int(w["max_bin"]), "learning_rate": 0.1,
          "verbosity": -1, "telemetry": True, "telemetry_cost": "full",
          "tree_learner": "feature"}
bst = lgb.train(params, lgb.Dataset(X, label=y),
                num_boost_round=int(w["iters"]))
assert bst.engine._feature_mode
rec = costmodel.cost_records().get("grow_tree",
                                   {"available": False,
                                    "error": "no grow_tree cost record"})
print("FEATURE_COST " + json.dumps(rec))
"""


_BACKEND_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
n_dev = int(sys.argv[3])
if n_dev > 0:
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % n_dev)
os.environ["LGBTPU_FUSE_ITER"] = "0"
os.environ.pop("LGBTPU_COST", None)
for k in ("LGBTPU_HIST_BACKEND", "LGBTPU_HIST_PACKED_WIDTH",
          "LGBTPU_ROUTE_FUSION", "LGBTPU_HIST_COMMS"):
    os.environ.pop(k, None)
sys.path.insert(0, sys.argv[1])
w = json.loads(sys.argv[2])
extra = json.loads(sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
    clear_backends()
except Exception:
    pass
import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry import costmodel
from lightgbm_tpu.telemetry.profile import _synthetic_data
X, y = _synthetic_data(int(w["rows"]), int(w["features"]), int(w["seed"]))
params = {"objective": "binary", "num_leaves": int(w["num_leaves"]),
          "max_bin": int(w["max_bin"]), "learning_rate": 0.1,
          "verbosity": -1, "telemetry": True, "telemetry_cost": "full"}
params.update(extra)
bst = lgb.train(params, lgb.Dataset(X, label=y),
                num_boost_round=int(w["iters"]))
assert bst.engine._grow_params.hist_backend == extra["hist_backend"]
rec = costmodel.cost_records().get("grow_tree",
                                   {"available": False,
                                    "error": "no grow_tree cost record"})
print("BACKEND_COST " + json.dumps(rec))
"""


def _measure_backend_grow(w, extra, n_dev):
    """Cost record of a hist-backend grow program variant on the fixed
    workload (subprocess; n_dev > 0 forces a CPU virtual mesh).  Failure
    -> unavailable, never zero."""
    import subprocess
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LGBTPU_FUSE_ITER")}
    try:
        r = subprocess.run(
            [sys.executable, "-c", _BACKEND_CHILD, ROOT, json.dumps(w),
             str(n_dev), json.dumps(extra)],
            capture_output=True, text=True, timeout=600, env=env)
    except subprocess.TimeoutExpired:
        return {"available": False, "error": "backend-grow child timed out"}
    for line in r.stdout.splitlines():
        if line.startswith("BACKEND_COST "):
            rec = json.loads(line[len("BACKEND_COST "):])
            if rec.get("available"):
                return {k: rec[k] for k in
                        ("flops", "bytes_accessed", "peak_hbm_bytes",
                         "intensity", "verdict") if k in rec}
            return {"available": False, "error": rec.get("error", "?")}
    tail = (r.stdout + r.stderr)[-500:].replace("\n", " | ")
    return {"available": False,
            "error": f"backend-grow child failed (rc={r.returncode}): "
                     f"{tail}"}


def _measure_feature_grow(w):
    """Cost record of the feature-parallel grow program on the fixed
    workload (4-device CPU mesh, subprocess).  Failure -> unavailable,
    never zero."""
    import subprocess
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LGBTPU_FUSE_ITER")}
    try:
        r = subprocess.run(
            [sys.executable, "-c", _FEATURE_CHILD, ROOT, json.dumps(w)],
            capture_output=True, text=True, timeout=600, env=env)
    except subprocess.TimeoutExpired:
        return {"available": False, "error": "feature-grow child timed out"}
    for line in r.stdout.splitlines():
        if line.startswith("FEATURE_COST "):
            rec = json.loads(line[len("FEATURE_COST "):])
            if rec.get("available"):
                return {k: rec[k] for k in
                        ("flops", "bytes_accessed", "peak_hbm_bytes",
                         "intensity", "verdict") if k in rec}
            return {"available": False, "error": rec.get("error", "?")}
    tail = (r.stdout + r.stderr)[-500:].replace("\n", " | ")
    return {"available": False,
            "error": f"feature-grow child failed (rc={r.returncode}): "
                     f"{tail}"}


def compare_budgets(measured: Dict[str, Any], budgets: Dict[str, Any]
                    ) -> Tuple[List[str], List[str], int]:
    """(violations, skipped_notices, checks_run) for one measurement."""
    tol = float(budgets.get("tolerance", 0.10))
    violations: List[str] = []
    skipped: List[str] = []
    checks = 0
    m_entries = measured.get("entries", {})
    for name, limits in sorted(budgets.get("entries", {}).items()):
        got = m_entries.get(name)
        if got is None:
            skipped.append(f"{name}: not exercised by the sentinel "
                           "workload (no cost record)")
            continue
        if got.get("available") is False:
            skipped.append(f"{name}: cost analysis unavailable on this "
                           f"backend ({got.get('error', '?')}) — budget "
                           "NOT judged (unavailable is never zero)")
            continue
        for field in BUDGET_FIELDS:
            if field not in limits:
                continue
            limit = float(limits[field])
            val = got.get(field)
            if val is None:
                skipped.append(f"{name}.{field}: not captured "
                               "(lowered-only record?) — skipped")
                continue
            checks += 1
            if float(val) > limit * (1.0 + tol):
                violations.append(
                    f"{name}.{field}: measured {float(val):.6g} exceeds "
                    f"budget {limit:.6g} (+{tol:.0%} tolerance) — "
                    f"{float(val) / limit:.2f}x")
    lpi_max = budgets.get("launches_per_iter_max")
    if lpi_max is not None:
        checks += 1
        lpi = float(measured.get("launches_per_iter", 0.0))
        if lpi > float(lpi_max):
            violations.append(
                f"launches_per_iter: measured {lpi} exceeds budget "
                f"{lpi_max} — dispatch-count bloat")
    return violations, skipped, checks


def _metric_direction(metric: str) -> int:
    """+1 = higher is better (throughput), -1 = lower is better."""
    m = metric.lower()
    return +1 if ("qps" in m or "throughput" in m
                  or "rows_per_s" in m) else -1


def check_history(path: str, tolerance: float = 0.25, min_runs: int = 3
                  ) -> Tuple[List[str], List[str], int]:
    """Latest value per (metric, host) vs the median of its same-host
    predecessors; returns (violations, notices, checks_run)."""
    if not os.path.exists(path):
        return [], [f"no history file at {path} — nothing to compare"], 0
    rows: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and row.get("metric") is not None \
                    and isinstance(row.get("value"), (int, float)):
                rows.append(row)
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for row in rows:
        key = (str(row["metric"]), str(row.get("host", "unknown")))
        groups.setdefault(key, []).append(row)
    violations: List[str] = []
    notices: List[str] = []
    checks = 0
    for (metric, host), grp in sorted(groups.items()):
        if len(grp) < min_runs:
            notices.append(f"{metric}@{host}: {len(grp)} run(s) < "
                           f"{min_runs} — wall-clock compare skipped")
            continue
        grp = sorted(grp, key=lambda r: str(r.get("date", "")))
        latest = float(grp[-1]["value"])
        # baseline = median of the most recent prior runs: a years-old
        # 100x-slower entry must not dilute the bar the latest run clears
        prior = grp[max(0, len(grp) - 6):-1]
        base = statistics.median(float(r["value"]) for r in prior)
        if base <= 0.0:
            notices.append(f"{metric}@{host}: non-positive baseline "
                           f"{base} — skipped")
            continue
        checks += 1
        direction = _metric_direction(metric)
        if direction < 0 and latest > base * (1.0 + tolerance):
            violations.append(
                f"{metric}@{host}: latest {latest:.6g} is "
                f"{latest / base:.2f}x the median of the last "
                f"{len(prior)} prior runs ({base:.6g}; +{tolerance:.0%} "
                "tolerance, lower is better)")
        elif direction > 0 and latest < base * (1.0 - tolerance):
            violations.append(
                f"{metric}@{host}: latest {latest:.6g} is "
                f"{latest / base:.2f}x the median of the last "
                f"{len(prior)} prior runs ({base:.6g}; -{tolerance:.0%} "
                "tolerance, higher is better)")
    return violations, notices, checks


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python scripts/perf_sentinel.py",
        description="Gate compiled-program cost budgets and bench "
                    "wall-clock history against regressions.")
    ap.add_argument("--budgets", default=None,
                    help="PERF_BUDGETS.json manifest path")
    ap.add_argument("--measure", action="store_true",
                    help="measure the budget workload in-process")
    ap.add_argument("--current", default=None,
                    help="saved measurement JSON instead of --measure")
    ap.add_argument("--history", default=None,
                    help="BENCH_HISTORY.jsonl path for wall-clock compare")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="history noise tolerance (default 0.25)")
    ap.add_argument("--min-runs", type=int, default=3,
                    help="history entries per (metric, host) needed "
                         "before comparing (default 3)")
    ap.add_argument("--save-measurement", default=None,
                    help="write the --measure result JSON here (budget "
                         "recalibration workflow)")
    args = ap.parse_args(argv)
    if not args.budgets and not args.history:
        ap.error("nothing to do: pass --budgets and/or --history")

    all_violations: List[str] = []
    if args.budgets:
        try:
            with open(args.budgets) as fh:
                budgets = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"perf_sentinel: cannot read budgets {args.budgets!r}: "
                  f"{e}", file=sys.stderr)
            return 2
        if args.current:
            try:
                with open(args.current) as fh:
                    measured = json.load(fh)
            except (OSError, ValueError) as e:
                print(f"perf_sentinel: cannot read measurement "
                      f"{args.current!r}: {e}", file=sys.stderr)
                return 2
        elif args.measure:
            measured = measure(budgets.get("workload"))
        else:
            ap.error("--budgets needs --measure or --current FILE")
        if args.save_measurement:
            tmp = f"{args.save_measurement}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(measured, fh, indent=2)
            os.replace(tmp, args.save_measurement)
        violations, skipped, checks = compare_budgets(measured, budgets)
        for s in skipped:
            print(f"perf_sentinel: NOTICE {s}")
        print(f"perf_sentinel: budgets — {checks} check(s), "
              f"{len(violations)} violation(s), {len(skipped)} skipped "
              f"[platform {measured.get('platform', '?')}]")
        all_violations += violations

    if args.history:
        violations, notices, checks = check_history(
            args.history, tolerance=args.tolerance, min_runs=args.min_runs)
        for s in notices:
            print(f"perf_sentinel: NOTICE {s}")
        print(f"perf_sentinel: history — {checks} comparison(s), "
              f"{len(violations)} regression(s)")
        all_violations += violations

    for v in all_violations:
        print(f"perf_sentinel: REGRESSION {v}", file=sys.stderr)
    if all_violations:
        print("perf_sentinel: FAIL — see regressions above (recalibrate "
              "PERF_BUDGETS.json only for UNDERSTOOD cost changes, with "
              "the measurement attached)", file=sys.stderr)
        return 1
    print("perf_sentinel: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
