"""Profile one training iteration on device and aggregate op durations
from the chrome trace (dev tool).

Usage: python scripts/profile_grow.py [rows]
       PROFILE_TASK=ranking python scripts/profile_grow.py [docs]
(BENCH_EXTRA_PARAMS merges into the training params for either task.)

PROFILE_TRACE_OUT=<path> additionally records the profiled iterations
through the telemetry span tracer and writes the host-side Chrome trace
there (load it in the same Perfetto tab as the device trace to line up
host phases against device ops).
"""
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main():
    import jax
    import lightgbm_tpu as lgb

    ranking = os.environ.get("PROFILE_TASK", "") == "ranking"
    default_rows = 2_270_000 if ranking else 10_500_000
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else default_rows
    params = {"objective": "binary", "num_leaves": 255, "learning_rate": 0.1,
              "max_bin": 63, "verbosity": -1,
              "use_quantized_grad": True, "num_grad_quant_bins": 64}
    extra = os.environ.get("BENCH_EXTRA_PARAMS", "")
    if extra:
        params.update(json.loads(extra))
    if ranking:
        import bench as B
        X, y, sizes = B.make_mslr_like(rows, 136)
        params["objective"] = "lambdarank"
        ds = lgb.Dataset(X, label=y, group=sizes)
    else:
        rs = np.random.RandomState(7)
        X = rs.randn(rows, 28).astype(np.float32)
        y = (rs.rand(rows) < 0.5).astype(np.float64)
        ds = lgb.Dataset(X, label=y)
    host_trace = os.environ.get("PROFILE_TRACE_OUT", "")
    from lightgbm_tpu import telemetry as tel
    if host_trace:
        tel.configure(enabled=True, trace_out=host_trace)
    bst = lgb.Booster(params, ds)
    for _ in range(3):      # warmup: compile everything
        bst.update()
    bst.engine.score.block_until_ready()

    tdir = "/tmp/lgb_trace"
    os.system(f"rm -rf {tdir}")
    with jax.profiler.trace(tdir):
        t0 = time.time()
        for _ in range(3):
            bst.update()
        bst.engine.score.block_until_ready()
        wall = time.time() - t0
    print(f"3 iters wall: {wall*1e3:.1f} ms ({wall/3*1e3:.1f} ms/iter)")
    if host_trace:
        tel.flush()
        s = bst.telemetry_summary()
        print(f"host trace written to {host_trace}; phases:",
              {k: v["total_s"] for k, v in s.get("phases", {}).items()})

    files = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)
    if not files:
        print("no trace files found under", tdir)
        return
    agg = defaultdict(float)
    cnt = defaultdict(int)
    total = 0.0
    for fpath in files:
        with gzip.open(fpath, "rt") as fh:
            tr = json.load(fh)
        # device lanes only: pick pids whose process name mentions TPU/device
        dev_pids = set()
        for ev in tr.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                nm = ev.get("args", {}).get("name", "")
                if "TPU" in nm or "Device" in nm or "/device" in nm:
                    dev_pids.add(ev.get("pid"))
        for ev in tr.get("traceEvents", []):
            if ev.get("ph") != "X" or ev.get("pid") not in dev_pids:
                continue
            name = ev.get("name", "?")
            dur = float(ev.get("dur", 0.0))
            agg[name] += dur
            cnt[name] += 1
            total += dur
    top = sorted(agg.items(), key=lambda kv: -kv[1])[:35]
    print(f"total device op time {total/1e3:.1f} ms across {len(files)} files")
    for name, dur in top:
        print(f"{dur/1e3:9.2f} ms  x{cnt[name]:<5d} {name[:110]}")


if __name__ == "__main__":
    main()
