#!/bin/sh
# Both test tiers, fast first (fail fast on cheap breakage), then the slow
# nightly consistency suites. ~17 min total on the 8-device CPU mesh.
set -e
cd "$(dirname "$0")/.."
# telemetry first: cheapest suite, and a broken observability layer makes
# every later perf triage lie
python -m pytest tests/test_telemetry.py -x -q
python -m pytest tests/ -x -q
python -m pytest tests/ -x -q -m slow
