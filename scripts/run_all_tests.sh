#!/bin/sh
# Both test tiers, fast first (fail fast on cheap breakage), then the slow
# nightly consistency suites. ~17 min total on the 8-device CPU mesh.
set -e
cd "$(dirname "$0")/.."
# telemetry first: cheapest suite, and a broken observability layer makes
# every later perf triage lie
python -m pytest tests/test_telemetry.py -x -q
# robustness fast tier next: checkpoint/resume bit-identity and the chaos
# guard paths protect every longer suite below from wasted reruns (the
# multi-process kill/retry/hang cases are in the slow tier)
python -m pytest tests/test_robustness.py -x -q -m 'not slow'
# serving fast tier: the online path (bucketed compiled predictor,
# micro-batcher, hot reload) is bit-identity-gated against predict, so a
# regression here flags scoring breakage before the long suites run
python -m pytest tests/test_serving.py -x -q -m 'not slow'
python -m pytest tests/ -x -q
python -m pytest tests/ -x -q -m slow
