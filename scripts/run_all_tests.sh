#!/bin/sh
# Both test tiers, fast first (fail fast on cheap breakage), then the slow
# nightly consistency suites. ~17 min total on the 8-device CPU mesh.
set -e
cd "$(dirname "$0")/.."
# lgbtlint first: the static-analysis gate (docs/ANALYSIS.md) is the
# cheapest stage (< 10 s, no test models trained) and a jit-discipline /
# atomic-IO / lock regression fails here with file:line before any suite
# spends minutes training models
echo "=== stage: lgbtlint static-analysis gate ==="
python -m lightgbm_tpu.analysis
# telemetry next: cheapest suite, and a broken observability layer makes
# every later perf triage lie
echo "=== stage: telemetry fast tier ==="
python -m pytest tests/test_telemetry.py -x -q
# fleet observability next: trace-context propagation, the Prometheus
# /metrics surface, the cross-process trace collector, and the SLO
# burn-rate state machine — the layer the serving and perf gates below
# report through (docs/OBSERVABILITY.md "Serving observability")
echo "=== stage: observability fast tier ==="
python -m pytest tests/test_observability.py -x -q -m 'not slow'
# the analysis-engine suite rides with it (per-rule tripping fixtures +
# the repo-clean findings==baseline gate test; no models trained)
echo "=== stage: analysis-engine fast tier ==="
python -m pytest tests/test_analysis.py -x -q
# robustness fast tier next: checkpoint/resume bit-identity and the chaos
# guard paths protect every longer suite below from wasted reruns (the
# multi-process kill/retry/hang cases are in the slow tier)
echo "=== stage: robustness fast tier ==="
python -m pytest tests/test_robustness.py -x -q -m 'not slow'
# serving fast tier: the online path (bucketed compiled predictor,
# micro-batcher, hot reload) is bit-identity-gated against predict, so a
# regression here flags scoring breakage before the long suites run
echo "=== stage: serving fast tier ==="
python -m pytest tests/test_serving.py tests/test_wire.py -x -q -m 'not slow'
# fleet resilience fast tier: deadline propagation, bounded overload
# shedding, circuit breaker, replica restart-with-backoff, and the
# poisoned-candidate fleet-wide reload (docs/SERVING.md fleet section)
echo "=== stage: serving fleet fast tier ==="
python -m pytest tests/test_fleet.py -x -q -m 'not slow'
# multi-tenant serving fast tier: the HBM-resident multi-model cache
# (LRU evict / manifest-verified readmit, evict-path in-flight drain),
# per-tenant routing bitwise over HTTP + stacked dispatch with zero
# fresh traces, per-model SLO/drift isolation, /explain pred_contrib
# contract, and per-tenant promotion pointer keying
# (docs/SERVING.md "Multi-tenant serving")
echo "=== stage: multi-tenant serving fast tier ==="
python -m pytest tests/test_multimodel.py -x -q -m 'not slow'
# data/model quality fast tier: the train-time quality sidecar (binned
# feature profile + score histogram), the PSI/JS drift monitor's
# fire/clear state machine, the bitwise train-vs-serve shadow audit, and
# the /drift + fleet-report surfaces (docs/OBSERVABILITY.md "Data &
# model quality") — a lying drift monitor poisons every rollout decision
echo "=== stage: data/model quality fast tier ==="
python -m pytest tests/test_quality.py -x -q -m 'not slow'
# closed-loop freshness fast tier: TPU-native refit bitwise vs the host
# reference (weighted + decay), streamed-fresh-data byte identity,
# checkpoint/resume bit-identity through refit, generation-pointer
# monotonicity, and the pointer-only pipeline end-to-end with
# poison/torn chaos arms (docs/ROBUSTNESS.md "Closed-loop freshness")
echo "=== stage: closed-loop pipeline fast tier ==="
python -m pytest tests/test_pipeline.py -x -q -m 'not slow'
# drift bench smoke: reduced rows + short alternating QPS windows —
# gates the full behavior arm (alert FIRES under a +6-sigma covariate
# shift, CLEARS on recovery, shadow audit is 0-mismatch over >= 500
# rows) and sanity-checks the quality-on/off QPS ratio at a loosened
# 10% tolerance (the strict 3% gate needs the full-size windows and
# lives with the committed artifact); BENCH_DRIFT_SMOKE=1
# never clobbers the committed BENCH_DRIFT.json artifact (the
# BENCH_GOSS lesson)
echo "=== stage: drift bench smoke (BENCH_DRIFT=1) ==="
BENCH_DRIFT=1 \
BENCH_DRIFT_SMOKE=1 \
BENCH_HISTORY=0 \
    python bench.py
# distributed fast tier on a 4-device CPU mesh: the reduce-scatter comms
# path (psum vs reduce_scatter bit-identity, comms-bytes counters,
# straggler split) runs on every CPU verify at a second device count —
# conftest keeps a pre-set device-count flag, so this exercises D=4 while
# the full suites below run the default 8
# keep any caller-provided XLA flags, overriding only the device count
echo "=== stage: distributed fast tier (D=4) ==="
XLA_FLAGS="$(printf '%s' "${XLA_FLAGS:-}" \
    | sed 's/--xla_force_host_platform_device_count=[0-9]*//') \
--xla_force_host_platform_device_count=4" \
    python -m pytest tests/test_distributed_fast.py -x -q
# fused-sharded iteration tier on the same 4-device mesh: the default
# one-launch-per-iteration mesh path must match the unfused pipeline
# (round-1 byte + structural ulp identity), keep its state sharded
# across iterations, and resume bit-identically from a sharded snapshot
# (docs/DISTRIBUTED.md "fused iteration & sharded state")
echo "=== stage: fused-sharded iteration tier (D=4) ==="
XLA_FLAGS="$(printf '%s' "${XLA_FLAGS:-}" \
    | sed 's/--xla_force_host_platform_device_count=[0-9]*//') \
--xla_force_host_platform_device_count=4" \
    python -m pytest tests/test_fused_sharded.py -x -q
# wide-data learners on the same 4-device mesh: feature-parallel must be
# BYTE-identical to serial across the layout matrix with zero histogram
# wire traffic, voting (PV-Tree) must pass its layout/compaction/resume
# matrix — the second device count for both (the full suites run the
# default 8)
echo "=== stage: feature/voting learner tier (D=4) ==="
XLA_FLAGS="$(printf '%s' "${XLA_FLAGS:-}" \
    | sed 's/--xla_force_host_platform_device_count=[0-9]*//') \
--xla_force_host_platform_device_count=4" \
    python -m pytest tests/test_feature_parallel.py tests/test_voting.py \
    -x -q -m 'not slow'
# 2D rows x feature-groups mesh on 4 devices (the 2x2 identity matrix):
# plain/bagging/GOSS/multiclass-batched vs serial, fused single launch,
# state placement, the d_feat analytic comms model vs the telemetry
# gauge, and the mesh_shape 2D validation paths (docs/DISTRIBUTED.md
# "2D mesh") — run at exactly the device count the mesh needs
echo "=== stage: 2D-mesh tier (D=4, data:2,feature:2) ==="
XLA_FLAGS="$(printf '%s' "${XLA_FLAGS:-}" \
    | sed 's/--xla_force_host_platform_device_count=[0-9]*//') \
--xla_force_host_platform_device_count=4" \
    python -m pytest tests/test_mesh2d.py -x -q -m 'not slow'
# wide-data bench smoke: reduced rows/features, single device count —
# gates the structural payload claims (feature ships ZERO histogram
# bytes, voting <= 2k elected columns, both beat data-parallel by the
# predicted bytes/round ratios) plus AUC; BENCH_WIDE_SMOKE=1 never
# clobbers the committed BENCH_WIDE.json artifact (the BENCH_GOSS lesson)
echo "=== stage: wide-data bench smoke (BENCH_TASK=wide) ==="
BENCH_TASK=wide \
BENCH_WIDE_SMOKE=1 \
BENCH_WIDE_F="${BENCH_WIDE_F:-512}" \
BENCH_WIDE_ROWS="${BENCH_WIDE_ROWS:-6000}" \
BENCH_HISTORY=0 \
    python bench.py
# out-of-core ingest fast tier: sketch-vs-exact boundary equivalence,
# chunk/rank determinism, stream-vs-inmem tree bit-identity, and the
# binned-cache corruption matrix (docs/INGEST.md) — the loaders every
# suite below constructs its datasets through
echo "=== stage: out-of-core ingest fast tier ==="
python -m pytest tests/test_ingest.py -x -q -m 'not slow'
echo "=== stage: full fast tier ==="
python -m pytest tests/ -x -q
# GOSS sampling bench: the row-compaction speedup gate (docs/PERF.md
# "sample-strategy speedups") — sampled trees must run >= 2x faster than
# the unsampled arm at matched AUC, or the stage fails.  Reduced rows /
# iters keep the CPU stage to a few minutes; BENCH_ROWS/BENCH_GOSS_ITERS
# pre-set by the caller are respected (full-size on TPU runs).
echo "=== stage: GOSS sampling bench (BENCH_TASK=goss) ==="
BENCH_TASK=goss \
BENCH_ROWS="${BENCH_ROWS:-100000}" \
BENCH_GOSS_ITERS="${BENCH_GOSS_ITERS:-5}" \
    python bench.py
# histogram-formulation floor: the backend identity matrix (scatter
# bitwise vs segsum, packed-wire byte halving, route-fusion bit-identity
# + validation/env plumbing) then the reduced A/B matrix — every arm
# AUC-gated, packed16 bytes/round must measure exactly half the int32
# wire, fusion must drop hist/route_only_passes to 1/tree
# (docs/PERF.md "histogram-formulation floor").  BENCH_HISTFLOOR_SMOKE=1
# never clobbers the committed BENCH_HISTFLOOR.json artifact.
echo "=== stage: histogram backend fast tier ==="
python -m pytest tests/test_hist_backends.py -x -q -m 'not slow'
echo "=== stage: histogram floor bench smoke (BENCH_TASK=histfloor) ==="
BENCH_TASK=histfloor \
BENCH_HISTFLOOR_SMOKE=1 \
BENCH_HISTORY=0 \
    python bench.py
# perf sentinel: compiled-program cost budgets (per-entry XLA flops,
# peak-HBM bytes, launches/iter on a fixed small workload vs
# PERF_BUDGETS.json — deterministic, so the gate holds on any test box)
# plus the wall-clock history compare, which only bites where
# BENCH_HISTORY.jsonl already holds >= 3 same-host runs of a metric
# (docs/OBSERVABILITY.md "Perf-regression sentinel")
# out-of-core ingest bench (reduced-size smoke): trees must be bitwise
# identical across the in-memory loader, the streaming loader, and a
# binned-cache re-run, and the subprocess stream arm must hold its
# peak-RSS delta under the configured budget at the gated rows/s
# (docs/INGEST.md; full-size numbers live in BENCH_INGEST.json)
echo "=== stage: out-of-core ingest bench (BENCH_TASK=ingest) ==="
BENCH_TASK=ingest \
BENCH_INGEST_ID_ROWS="${BENCH_INGEST_ID_ROWS:-60000}" \
BENCH_INGEST_ROWS="${BENCH_INGEST_ROWS:-400000}" \
BENCH_INGEST_FEATURES="${BENCH_INGEST_FEATURES:-16}" \
BENCH_INGEST_SMOKE=1 \
BENCH_HISTORY=0 \
    python bench.py
echo "=== stage: perf sentinel (cost budgets + bench history) ==="
python scripts/perf_sentinel.py --budgets PERF_BUDGETS.json --measure \
    --history BENCH_HISTORY.jsonl
# serving throughput bench: the binary-wire hot path must sustain
# BENCH_SERVE_QPS_MIN (default 10k) loopback QPS with a bounded window
# p99, zero errors, zero serve_predict recompiles after warmup, and
# bitwise exactness vs Booster.predict on every bucket size for
# numeric(+NaN), categorical, and multiclass models — over the wire
# (docs/SERVING.md "Binary wire protocol"); appends serve_binary_qps
# to BENCH_HISTORY.jsonl for the sentinel's wall-clock compare
echo "=== stage: serving throughput bench (BENCH_SERVE=1) ==="
BENCH_SERVE=1 \
BENCH_SERVE_ROWS="${BENCH_SERVE_ROWS:-60000}" \
BENCH_SERVE_MODEL_ITERS="${BENCH_SERVE_MODEL_ITERS:-30}" \
BENCH_SERVE_SECS="${BENCH_SERVE_SECS:-4}" \
BENCH_SERVE_HTTP_SECS="${BENCH_SERVE_HTTP_SECS:-2}" \
    python bench.py
# fleet chaos bench: 3 replicas under sustained loopback load while
# chaos SIGKILLs one and wedges another mid-run, with a mid-chaos
# fleet-wide /reload — gates on zero non-503 errors, bitwise-exact
# responses per claimed model sha256, bounded p99, replica restarts,
# and promotion convergence; writes BENCH_FLEET.json
echo "=== stage: fleet chaos bench (BENCH_FLEET=1) ==="
BENCH_FLEET=1 \
BENCH_FLEET_ROWS="${BENCH_FLEET_ROWS:-20000}" \
BENCH_FLEET_MODEL_ITERS="${BENCH_FLEET_MODEL_ITERS:-10}" \
BENCH_FLEET_SECS="${BENCH_FLEET_SECS:-8}" \
    python bench.py
# closed-loop pipeline chaos bench (reduced-size smoke): one CLI
# invocation drives train -> TPU refit -> gate -> atomic promote ->
# observe against a live 2-replica fleet while chaos poisons the refit,
# truncates the candidate, SIGKILLs the pipeline pre-pointer-write,
# tears the pointer, and a covariate shift forces the automatic
# post-promotion rollback — all under bitwise-checked traffic;
# BENCH_PIPELINE_SMOKE=1 never clobbers the committed BENCH_PIPELINE.json
echo "=== stage: pipeline chaos bench smoke (BENCH_TASK=pipeline) ==="
BENCH_TASK=pipeline \
BENCH_PIPELINE_SMOKE=1 \
BENCH_HISTORY=0 \
    python bench.py
# multi-tenant serving bench (reduced-size smoke): N same-shape tenants
# take mixed wire-v2 + /explain traffic bitwise-checked per tenant with
# ZERO fresh traces after warmup, the cache budget squeeze churns LRU
# evict/readmit under load with zero non-503 errors, and ONE
# pipeline_model_id promotion (+ a refused poisoned candidate) leaves
# the sibling tenant bitwise; BENCH_MULTIMODEL_SMOKE=1 never clobbers
# the committed BENCH_MULTIMODEL.json artifact
echo "=== stage: multi-tenant bench smoke (BENCH_TASK=multimodel) ==="
BENCH_TASK=multimodel \
BENCH_MULTIMODEL_SMOKE=1 \
BENCH_HISTORY=0 \
    python bench.py
# native sanitizer tier: builds native/binner.cpp under ASan/UBSan and
# drives every extern-C entry point (incl. the categorical bitset
# walker's word-index edges) — the reference's sanitizer CI lanes.
# Runs as its own labeled stage so a toolchain-less box reports WHY the
# lane did not run instead of silently skipping inside the slow suite.
echo "=== stage: native sanitizer tier (ASan/UBSan) ==="
if command -v g++ >/dev/null 2>&1; then
    python -m pytest tests/test_native_sanitizers.py -x -q -m slow
else
    echo "NOTICE: no g++ toolchain on this machine — native ASan/UBSan"
    echo "lane SKIPPED (install g++ with libasan/libubsan to enable)"
fi
echo "=== stage: slow consistency tier ==="
# sanitizers already ran (or were skipped with notice) in their own
# stage above — don't rebuild and rerun the ASan/UBSan binary here
python -m pytest tests/ -x -q -m slow \
    --ignore=tests/test_native_sanitizers.py
