#!/bin/sh
# Both test tiers, fast first (fail fast on cheap breakage), then the slow
# nightly consistency suites. ~17 min total on the 8-device CPU mesh.
set -e
cd "$(dirname "$0")/.."
# telemetry first: cheapest suite, and a broken observability layer makes
# every later perf triage lie
python -m pytest tests/test_telemetry.py -x -q
# robustness fast tier next: checkpoint/resume bit-identity and the chaos
# guard paths protect every longer suite below from wasted reruns (the
# multi-process kill/retry/hang cases are in the slow tier)
python -m pytest tests/test_robustness.py -x -q -m 'not slow'
# serving fast tier: the online path (bucketed compiled predictor,
# micro-batcher, hot reload) is bit-identity-gated against predict, so a
# regression here flags scoring breakage before the long suites run
python -m pytest tests/test_serving.py -x -q -m 'not slow'
# distributed fast tier on a 4-device CPU mesh: the reduce-scatter comms
# path (psum vs reduce_scatter bit-identity, comms-bytes counters,
# straggler split) runs on every CPU verify at a second device count —
# conftest keeps a pre-set device-count flag, so this exercises D=4 while
# the full suites below run the default 8
# keep any caller-provided XLA flags, overriding only the device count
XLA_FLAGS="$(printf '%s' "${XLA_FLAGS:-}" \
    | sed 's/--xla_force_host_platform_device_count=[0-9]*//') \
--xla_force_host_platform_device_count=4" \
    python -m pytest tests/test_distributed_fast.py -x -q
python -m pytest tests/ -x -q
python -m pytest tests/ -x -q -m slow
