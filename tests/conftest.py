"""Test configuration: force an 8-device CPU platform so sharding/multi-chip paths are
testable without TPU hardware (mirrors the reference's strategy of testing distributed
mode with localhost multi-process, SURVEY.md §4 tier 2)."""
import os

# Force the CPU platform with 8 virtual devices. A site hook may have already
# imported jax and registered an accelerator backend at interpreter startup, so
# env-var settings alone are too late — update jax.config and clear any
# initialized backends. XLA_FLAGS is still read lazily at CPU client creation.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:  # noqa: BLE001 — best effort; fresh interpreters need no clearing
    pass

import numpy as np
import pytest

# persistent compilation cache: repeated test runs skip XLA compiles
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def make_synthetic_regression(n=2000, f=10, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


def make_synthetic_binary(n=2000, f=10, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    logit = X[:, 0] * 1.5 - X[:, 1] + X[:, 2] * X[:, 3] * 0.5
    p = 1.0 / (1.0 + np.exp(-logit))
    y = (rs.rand(n) < p).astype(np.float64)
    return X, y


def make_synthetic_multiclass(n=3000, f=10, k=4, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    centers = rs.randn(k, f) * 1.5
    logits = X @ centers.T
    y = np.argmax(logits + 0.5 * rs.randn(n, k), axis=1).astype(np.float64)
    return X, y


def make_synthetic_ranking(nq=100, docs_per_q=(5, 40), f=10, seed=0):
    rs = np.random.RandomState(seed)
    sizes = rs.randint(docs_per_q[0], docs_per_q[1], size=nq)
    n = int(sizes.sum())
    X = rs.randn(n, f)
    rel_score = X[:, 0] * 2.0 + X[:, 1] + 0.3 * rs.randn(n)
    # map to 0-4 relevance grades within query
    y = np.zeros(n)
    start = 0
    for s in sizes:
        seg = rel_score[start:start + s]
        ranks = np.argsort(np.argsort(seg))
        y[start:start + s] = np.minimum(4, (ranks * 5) // max(s, 1))
        start += s
    return X, y, sizes


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")


# ---------------------------------------------------------------------------
# two-process collective capability probe (slow tier)
# ---------------------------------------------------------------------------
#
# The localhost multi-process suites need the jax CPU backend to run
# cross-process collectives (the gloo implementation; the default CPU
# client refuses with "Multiprocess computations aren't implemented on
# the CPU backend", and very old jax lacks the gloo option entirely).
# Probe it ONCE per session with a minimal 2-process allgather and skip
# the dependent tests with the root cause in the reason — the slow tier
# must be green-or-skipped, never red, on hosts without the capability.

_PROBE_CHILD = r"""
import os, sys
os.environ.pop("XLA_FLAGS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
try:
    from jax.extend.backend import clear_backends; clear_backends()
except Exception:
    pass
jax.distributed.initialize(f"localhost:{sys.argv[1]}", num_processes=2,
                           process_id=int(sys.argv[2]))
import jax.numpy as jnp
from jax.experimental import multihost_utils
multihost_utils.process_allgather(jnp.ones((2,)))
"""

_two_process_probe_result = []   # memo: [error-string-or-None]


def two_process_collectives_error():
    """None when 2-process jax CPU collectives work here; otherwise the
    root-cause line from the failing probe."""
    if _two_process_probe_result:
        return _two_process_probe_result[0]
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [_sys.executable, "-c", _PROBE_CHILD, str(port), str(r)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs, err = [], None
    for p in procs:
        try:
            outs.append(p.communicate(timeout=180)[0].decode())
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append(p.communicate()[0].decode())
            err = "2-process collective probe timed out"
    if err is None and any(p.returncode != 0 for p in procs):
        tail = next(o for p, o in zip(procs, outs) if p.returncode != 0)
        lines = [ln.strip() for ln in tail.splitlines() if ln.strip()]
        root = [ln for ln in lines if "rror" in ln]
        err = (root or lines or ["probe failed"])[-1]
    _two_process_probe_result.append(err)
    return err


@pytest.fixture
def require_two_process_collectives():
    """Skip (root cause in the reason) when this host's jax CPU backend
    cannot run cross-process collectives."""
    err = two_process_collectives_error()
    if err is not None:
        pytest.skip("jax CPU backend refuses 2-process collectives on "
                    f"this host: {err}")
