"""Test configuration: force an 8-device CPU platform so sharding/multi-chip paths are
testable without TPU hardware (mirrors the reference's strategy of testing distributed
mode with localhost multi-process, SURVEY.md §4 tier 2)."""
import os

# Force the CPU platform with 8 virtual devices. A site hook may have already
# imported jax and registered an accelerator backend at interpreter startup, so
# env-var settings alone are too late — update jax.config and clear any
# initialized backends. XLA_FLAGS is still read lazily at CPU client creation.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:  # noqa: BLE001 — best effort; fresh interpreters need no clearing
    pass

import numpy as np
import pytest

# persistent compilation cache: repeated test runs skip XLA compiles
jax.config.update("jax_compilation_cache_dir", "/tmp/lgb_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def make_synthetic_regression(n=2000, f=10, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    y = (X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + X[:, 2] * X[:, 3]
         + 0.1 * rs.randn(n))
    return X, y


def make_synthetic_binary(n=2000, f=10, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    logit = X[:, 0] * 1.5 - X[:, 1] + X[:, 2] * X[:, 3] * 0.5
    p = 1.0 / (1.0 + np.exp(-logit))
    y = (rs.rand(n) < p).astype(np.float64)
    return X, y


def make_synthetic_multiclass(n=3000, f=10, k=4, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, f)
    centers = rs.randn(k, f) * 1.5
    logits = X @ centers.T
    y = np.argmax(logits + 0.5 * rs.randn(n, k), axis=1).astype(np.float64)
    return X, y


def make_synthetic_ranking(nq=100, docs_per_q=(5, 40), f=10, seed=0):
    rs = np.random.RandomState(seed)
    sizes = rs.randint(docs_per_q[0], docs_per_q[1], size=nq)
    n = int(sizes.sum())
    X = rs.randn(n, f)
    rel_score = X[:, 0] * 2.0 + X[:, 1] + 0.3 * rs.randn(n)
    # map to 0-4 relevance grades within query
    y = np.zeros(n)
    start = 0
    for s in sizes:
        seg = rel_score[start:start + s]
        ranks = np.argsort(np.argsort(seg))
        y[start:start + s] = np.minimum(4, (ranks * 5) // max(s, 1))
        start += s
    return X, y, sizes


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
