"""lgbtlint engine + rule-catalog tests (docs/ANALYSIS.md).

One tripping fixture per rule (asserting the rule id AND the line), the
suppression-baseline round-trip, and the repo-wide ``findings == baseline``
gate that keeps the analyzer clean on every fast-tier run.
"""
import json
from pathlib import Path

import pytest

from lightgbm_tpu.analysis import engine as eng
from lightgbm_tpu.analysis.rules import all_rules
from lightgbm_tpu.analysis.rules.atomic_io import AtomicIORule
from lightgbm_tpu.analysis.rules.collective_axis import CollectiveAxisRule
from lightgbm_tpu.analysis.rules.config_doc import ConfigDocRule
from lightgbm_tpu.analysis.rules.cost_attribution import CostAttributionRule
from lightgbm_tpu.analysis.rules.determinism import DeterminismRule
from lightgbm_tpu.analysis.rules.host_sync import HostSyncRule
from lightgbm_tpu.analysis.rules.jit_discipline import JitDisciplineRule
from lightgbm_tpu.analysis.rules.lock_discipline import LockDisciplineRule
from lightgbm_tpu.analysis.rules.metric_name import MetricNameRule
from lightgbm_tpu.analysis.rules.subprocess_discipline import (
    SubprocessDisciplineRule)

REPO = Path(__file__).resolve().parent.parent


def run_snippet(tmp_path, source, rule, name="mod.py"):
    p = tmp_path / name
    p.write_text(source)
    return eng.run_analysis(tmp_path, files=[p], rules=[rule])


# ---------------------------------------------------------------------------
# one tripping fixture per rule
# ---------------------------------------------------------------------------

def test_lgb001_bare_jit_trips(tmp_path):
    src = ("import jax\n"
           "import functools\n"
           "f = jax.jit(lambda x: x + 1)\n"                      # line 3
           "g = functools.partial(jax.jit, static_argnums=0)\n"  # line 4
           "@jax.jit\n"                                          # line 5
           "def h(x):\n"
           "    return x\n")
    found = run_snippet(tmp_path, src, JitDisciplineRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB001", 3), ("LGB001", 4), ("LGB001", 5)]
    assert "watchdog" in found[0].message


def test_lgb001_watched_and_wrapped_pallas_clean(tmp_path):
    src = ("import functools\n"
           "from lightgbm_tpu.telemetry.watchdog import watched_jit\n"
           "from jax.experimental import pallas as pl\n"
           "@functools.partial(watched_jit, name='k', warn_after=0)\n"
           "def kernel(x):\n"
           "    return pl.pallas_call(None, out_shape=x)(x)\n")
    assert run_snippet(tmp_path, src, JitDisciplineRule()) == []


def test_lgb001_bare_pallas_call_trips(tmp_path):
    src = ("from jax.experimental import pallas as pl\n"
           "def kernel(x):\n"
           "    return pl.pallas_call(None, out_shape=x)(x)\n")   # line 3
    found = run_snippet(tmp_path, src, JitDisciplineRule())
    assert [(f.rule, f.line) for f in found] == [("LGB001", 3)]


def test_lgb002_host_sync_trips(tmp_path):
    src = ("from lightgbm_tpu.telemetry.watchdog import watched_jit\n"
           "import numpy as np\n"
           "def build(engine):\n"
           "    def _fn(grad, hess):\n"
           "        total = grad + hess\n"
           "        bad = float(total)\n"                         # line 6
           "        arr = np.asarray(grad)\n"                     # line 7
           "        n = int(grad.shape[0])\n"                     # static: ok
           "        return bad + arr.sum() + n\n"
           "    return watched_jit(_fn, name='g', owner=engine)\n")
    found = run_snippet(tmp_path, src, HostSyncRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB002", 6), ("LGB002", 7)]
    assert "host sync" in found[0].message


def test_lgb002_jnp_asarray_clean(tmp_path):
    # jnp.asarray is device-side: must NOT be confused with numpy.asarray
    src = ("import jax\n"
           "import jax.numpy as jnp\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return jnp.asarray(x) + 1\n")
    assert run_snippet(tmp_path, src, HostSyncRule()) == []


def test_lgb002_iteration_loop_host_sync_trips(tmp_path):
    """The iteration-loop extension: jax.device_get, .block_until_ready()
    and np.asarray on sharded state inside the GBDT per-iteration
    functions stall the one-launch pipeline (docs/ANALYSIS.md)."""
    src = ("import jax\n"
           "import numpy as np\n"
           "class GBDT:\n"
           "    def _train_one_iter_impl(self):\n"
           "        fin = jax.device_get(self._finished_dev)\n"   # line 5
           "        self.score.block_until_ready()\n"             # line 6
           "        s = np.asarray(self.score)\n"                 # line 7
           "        n = np.asarray(self.score.shape)\n"           # static ok
           "        return fin, s, n\n")
    found = run_snippet(tmp_path, src, HostSyncRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB002", 5), ("LGB002", 6), ("LGB002", 7)]
    assert "iteration-loop" in found[0].message
    assert "_poll_device_flags" in found[0].hint


def test_lgb002_iteration_loop_clean(tmp_path):
    """Deferred device flags and metadata reads stay clean — and the same
    syncs OUTSIDE the iteration loop are not this extension's business."""
    src = ("import jax\n"
           "import numpy as np\n"
           "class GBDT:\n"
           "    def _train_one_iter_impl(self):\n"
           "        self._finished_dev = self.score.sum() <= 1\n"
           "        return self.score.shape[0]\n"
           "    def _flush_models(self):\n"
           "        return jax.device_get(self._lazy)\n")
    assert run_snippet(tmp_path, src, HostSyncRule()) == []


def test_lgb003_unbound_axis_trips(tmp_path):
    src = ("import jax\n"
           "from jax.sharding import PartitionSpec as P\n"
           "SPEC = P('data')\n"
           "def local(h):\n"
           "    good = jax.lax.psum(h, 'data')\n"
           "    return jax.lax.psum(good, 'dta')\n")              # line 6
    found = run_snippet(tmp_path, src, CollectiveAxisRule())
    assert [(f.rule, f.line) for f in found] == [("LGB003", 6)]
    assert "'dta'" in found[0].message and "data" in found[0].message


def test_lgb003_variable_axis_clean(tmp_path):
    src = ("import jax\n"
           "def local(h, axis):\n"
           "    return jax.lax.psum(h, axis)\n")
    assert run_snippet(tmp_path, src, CollectiveAxisRule()) == []


def test_lgb003_feature_axis_vocabulary(tmp_path):
    """Importing FEATURE_AXIS from parallel.mesh binds 'feature' into the
    module's axis vocabulary (the feature-parallel learner's collectives
    — best-record all_gather, owner bitset / route-bin psum — all ride
    this axis), while a typo'd spelling still trips."""
    src = ("import jax\n"
           "from lightgbm_tpu.parallel.mesh import FEATURE_AXIS\n"
           "def local(h):\n"
           "    good = jax.lax.all_gather(h, 'feature')\n"
           "    return jax.lax.psum(good, 'featur')\n")           # line 5
    found = run_snippet(tmp_path, src, CollectiveAxisRule())
    assert [(f.rule, f.line) for f in found] == [("LGB003", 5)]
    assert "feature" in found[0].message


def test_lgb004_determinism_trips(tmp_path):
    src = ("import time\n"
           "import numpy as np\n"
           "import jax\n"
           "mask = np.random.rand(16) < 0.5\n"                   # line 4
           "for g in {'a', 'b'}:\n"                              # line 5
           "    print(g)\n"
           "cols = [c for c in set(['x', 'y'])]\n"               # line 7
           "good = sorted(set(['x', 'y']))\n"                    # sorted: ok
           "rs = np.random.RandomState(7)\n"                     # seeded: ok
           "@jax.jit\n"
           "def f(x):\n"
           "    return x * time.time()\n")                       # line 12
    found = run_snippet(tmp_path, src, DeterminismRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB004", 4), ("LGB004", 5), ("LGB004", 7), ("LGB004", 12)]


def test_lgb005_atomic_io_trips(tmp_path):
    src = ("import os, json\n"
           "def bad(path, blob):\n"
           "    with open(path, 'w') as fh:\n"                   # line 3
           "        json.dump(blob, fh)\n"
           "def good(path, blob):\n"
           "    tmp = path + '.tmp'\n"
           "    with open(tmp, 'w') as fh:\n"                    # replaced: ok
           "        json.dump(blob, fh)\n"
           "    os.replace(tmp, path)\n"
           "def append(path, line):\n"
           "    with open(path, 'a') as fh:\n"                   # append: ok
           "        fh.write(line)\n")
    found = run_snippet(tmp_path, src, AtomicIORule())
    assert [(f.rule, f.line) for f in found] == [("LGB005", 3)]
    assert "os.replace" in found[0].message


def test_lgb006_lock_discipline_trips(tmp_path):
    src = ("import threading\n"
           "class Registry:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.loads = 0\n"
           "        self._current = None\n"
           "    def swap(self, model):\n"
           "        with self._lock:\n"
           "            self._current = model\n"
           "        self.loads += 1\n"                           # line 10
           "    def sneak(self, model):\n"
           "        self._current = model\n")                    # line 12
    found = run_snippet(tmp_path, src, LockDisciplineRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB006", 10), ("LGB006", 12)]
    assert "races" in found[0].message


def test_lgb006_lockless_class_clean(tmp_path):
    src = ("class Plain:\n"
           "    def __init__(self):\n"
           "        self.count = 0\n"
           "    def bump(self):\n"
           "        self.count += 1\n")
    assert run_snippet(tmp_path, src, LockDisciplineRule()) == []


def test_lgb007_doc_drift_trips(tmp_path):
    (tmp_path / "scripts").mkdir()
    (tmp_path / "docs").mkdir()
    (tmp_path / "scripts" / "gen_params_doc.py").write_text(
        "def render_doc():\n"
        "    return '| `alpha` |\\n| `beta` |\\n'\n")
    (tmp_path / "docs" / "Parameters.md").write_text("| `alpha` |\n")
    found = list(ConfigDocRule().check_repo(tmp_path, []))
    assert [f.rule for f in found] == ["LGB007"]
    assert "beta" in found[0].message
    # in-sync doc -> clean
    (tmp_path / "docs" / "Parameters.md").write_text(
        "| `alpha` |\n| `beta` |\n")
    assert list(ConfigDocRule().check_repo(tmp_path, [])) == []


def test_lgb007_respects_changed_only_trigger(tmp_path):
    # no trigger file changed -> the (expensive) check is skipped entirely
    assert list(ConfigDocRule().check_repo(
        tmp_path, [], changed=["lightgbm_tpu/ops/grow.py"])) == []


def run_scoped_snippet(tmp_path, source, rule,
                       name="lightgbm_tpu/serving/mod.py"):
    """Like run_snippet but at a nested repo-relative path (LGB008 only
    applies inside the supervisor directories)."""
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(source)
    return eng.run_analysis(tmp_path, files=[p], rules=[rule])


def test_lgb008_unsupervised_subprocess_trips(tmp_path):
    src = ("import subprocess\n"
           "def fire_and_forget(cmd):\n"
           "    subprocess.run(cmd, check=True)\n"               # line 3
           "    return subprocess.Popen(cmd)\n"                  # line 4
           "def bounded(cmd):\n"
           "    subprocess.run(cmd, timeout=30)\n"               # ok
           "def polled(cmd):\n"
           "    p = subprocess.Popen(cmd)\n"                     # ok: polled
           "    while p.poll() is None:\n"
           "        pass\n"
           "def waited(cmd):\n"
           "    p = subprocess.Popen(cmd)\n"                     # ok: deadline
           "    p.wait(timeout=10)\n")
    found = run_scoped_snippet(tmp_path, src, SubprocessDisciplineRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB008", 3), ("LGB008", 4)]
    assert "timeout" in found[0].message
    assert "unsupervised" in found[1].message


def test_lgb008_class_level_supervision_clean(tmp_path):
    # the fleet-supervisor shape: _spawn Popens, _supervise polls — the
    # poll loop lives in ANOTHER method of the same class
    src = ("import subprocess\n"
           "class Supervisor:\n"
           "    def spawn(self, cmd):\n"
           "        self.proc = subprocess.Popen(cmd)\n"
           "    def babysit(self):\n"
           "        while self.proc.poll() is None:\n"
           "            pass\n")
    assert run_scoped_snippet(tmp_path, src,
                              SubprocessDisciplineRule()) == []


def test_lgb008_unbounded_wait_not_supervision(tmp_path):
    # wait() WITHOUT a timeout is exactly the unbounded block the rule
    # exists to catch — it must not count as supervision
    src = ("import subprocess\n"
           "def forever(cmd):\n"
           "    p = subprocess.Popen(cmd)\n"                     # line 3
           "    p.wait()\n")
    found = run_scoped_snippet(tmp_path, src, SubprocessDisciplineRule())
    assert [(f.rule, f.line) for f in found] == [("LGB008", 3)]


def test_lgb008_out_of_scope_dirs_clean(tmp_path):
    # bench/scripts/tests run subprocesses unbounded by design: a hung
    # bench is an operator's Ctrl-C, not a production outage
    src = ("import subprocess\n"
           "def bench(cmd):\n"
           "    subprocess.run(cmd, check=True)\n")
    assert run_scoped_snippet(tmp_path, src, SubprocessDisciplineRule(),
                              name="bench.py") == []
    assert run_scoped_snippet(tmp_path, src, SubprocessDisciplineRule(),
                              name="lightgbm_tpu/ops/mod.py") == []


def test_lgb009_dynamic_metric_name_trips(tmp_path):
    src = ("from lightgbm_tpu import telemetry\n"
           "def serve(name, rank):\n"
           "    telemetry.inc(name)\n"                            # line 3
           "    telemetry.gauge('queue/' + name, 1.0)\n"          # line 4
           "    telemetry.observe(f'serve/{name}_s', 0.1)\n"      # line 5
           "    telemetry.inc('serve/%s' % name)\n"               # line 6
           "    telemetry.inc('serve/requests')\n"                # literal ok
           "    telemetry.gauge(f'fleet/replica/{rank}/up', 1)\n"  # allowed
           "    telemetry.inc(f'recompile/{name}')\n")            # allowed
    found = run_snippet(tmp_path, src, MetricNameRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB009", 3), ("LGB009", 4), ("LGB009", 5), ("LGB009", 6)]
    assert "cardinality" in found[0].message
    assert "serve/*_s" in found[2].message


def test_lgb009_registry_receiver_and_kwarg(tmp_path):
    src = ("from lightgbm_tpu.telemetry import global_registry\n"
           "def record(key):\n"
           "    global_registry.inc(name=key)\n"                  # line 3
           "    global_registry.inc(name='serve/requests')\n")    # ok
    found = run_snippet(tmp_path, src, MetricNameRule())
    assert [(f.rule, f.line) for f in found] == [("LGB009", 3)]


def test_lgb009_unrelated_receivers_clean(tmp_path):
    # .inc/.gauge/.observe on arbitrary objects are not metric calls
    src = ("def bump(counter, name):\n"
           "    counter.inc(name)\n"
           "    self_made = {}\n"
           "    return counter, self_made\n")
    assert run_snippet(tmp_path, src, MetricNameRule()) == []


def test_lgb009_cost_family_allowed(tmp_path):
    # cost/<entry>/<field> is bounded by the watched_jit entry set (the
    # same budget as recompile/<name>; LGB010 keeps names stable)
    src = ("from lightgbm_tpu import telemetry\n"
           "def capture(name, flops):\n"
           "    telemetry.gauge(f'cost/{name}/flops', flops)\n"      # ok
           "    telemetry.gauge(f'cost/{name}/peak_hbm_bytes', 1)\n"  # ok
           "    telemetry.gauge(f'cost/{name}', flops)\n")            # line 5
    found = run_snippet(tmp_path, src, MetricNameRule())
    assert [(f.rule, f.line) for f in found] == [("LGB009", 5)]


def test_lgb009_drift_and_quality_families_allowed(tmp_path):
    # drift/feature/<i>/<stat> is bounded by quality_topk (config) and
    # quality/audit/<stat> by a fixed stat set — sanctioned skeletons
    src = ("from lightgbm_tpu import telemetry\n"
           "def publish(f, stat, v):\n"
           "    telemetry.gauge(f'drift/feature/{f}/psi', v)\n"      # ok
           "    telemetry.gauge(f'drift/feature/{f}/js', v)\n"       # ok
           "    telemetry.gauge(f'quality/audit/{stat}', v)\n"       # ok
           "    telemetry.gauge('drift/max_psi_fast', v)\n"          # literal
           "    telemetry.gauge(f'drift/{f}/psi', v)\n"              # line 7
           "    telemetry.inc(f'quality/{stat}/rows', v)\n")         # line 8
    found = run_snippet(tmp_path, src, MetricNameRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB009", 7), ("LGB009", 8)]


def test_lgb010_watched_jit_without_name_trips(tmp_path):
    src = ("import functools\n"
           "from lightgbm_tpu.telemetry.watchdog import watched_jit\n"
           "def build(engine, fn, key):\n"
           "    a = watched_jit(fn, owner=engine)\n"                  # line 4
           "    b = functools.partial(watched_jit, warn_after=0)\n"   # line 5
           "    c = watched_jit(fn, name=key)\n"                      # line 6
           "    return a, b, c\n"
           "@watched_jit\n"                                           # line 8
           "def bare(x):\n"
           "    return x\n")
    found = run_snippet(tmp_path, src, CostAttributionRule())
    assert [(f.rule, f.line) for f in found] == [
        ("LGB010", 4), ("LGB010", 5), ("LGB010", 6), ("LGB010", 8)]
    assert "cost" in found[0].message
    assert "literal" in found[2].message


def test_lgb010_named_call_sites_clean(tmp_path):
    src = ("import functools\n"
           "from lightgbm_tpu.telemetry.watchdog import watched_jit\n"
           "@functools.partial(watched_jit, name='kernel', warn_after=0)\n"
           "def kernel(x):\n"
           "    return x\n"
           "def build(engine, fn):\n"
           "    return watched_jit(fn, name='grow_tree', owner=engine)\n")
    assert run_snippet(tmp_path, src, CostAttributionRule()) == []


# ---------------------------------------------------------------------------
# engine mechanics: baseline round-trip, stale entries, parse errors
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    src = "f = open('out.txt', 'w')\n"
    found = run_snippet(tmp_path, src, AtomicIORule())
    assert len(found) == 1
    entries = [eng.Suppression(f.rule, f.file, f.line, "fixture pin")
               for f in found]
    bpath = tmp_path / "baseline.toml"
    bpath.write_text(eng.render_baseline(entries))
    loaded = eng.load_baseline(bpath)
    assert loaded == entries
    active, suppressed, stale = eng.apply_baseline(found, loaded)
    assert active == [] and len(suppressed) == 1 and stale == []


def test_baseline_stale_entry_detected(tmp_path):
    sup = eng.Suppression("LGB005", "gone.py", 3, "was fixed")
    active, suppressed, stale = eng.apply_baseline([], [sup])
    assert active == [] and suppressed == [] and stale == [sup]


def test_baseline_requires_reason(tmp_path):
    bpath = tmp_path / "baseline.toml"
    bpath.write_text('[[suppress]]\nrule = "LGB001"\nfile = "x.py"\n'
                     'line = 1\nreason = ""\n')
    with pytest.raises(ValueError, match="justification"):
        eng.load_baseline(bpath)


def test_syntax_error_is_a_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def oops(:\n")
    found = eng.run_analysis(tmp_path, files=[p], rules=[])
    assert [f.rule for f in found] == ["LGB000"]
    assert "parse" in found[0].message


# ---------------------------------------------------------------------------
# the repo gate: today's tree is clean modulo the reviewed baseline
# ---------------------------------------------------------------------------

def test_repo_findings_match_baseline():
    """The CI gate's exact semantics: every finding on the current tree is
    pinned by a justified baseline entry, and no baseline entry is stale.
    A regression in jit discipline, atomic IO, lock usage, determinism, or
    config<->doc sync fails THIS test with file:line."""
    findings = eng.run_analysis(REPO)
    baseline = eng.load_baseline(eng.default_baseline_path(REPO))
    active, suppressed, stale = eng.apply_baseline(findings, baseline)
    assert active == [], "unsuppressed findings:\n" + "\n".join(
        f.render() for f in active)
    assert stale == [], "stale baseline entries: " + ", ".join(
        f"{s.file}:{s.line}" for s in stale)
    for s in baseline:
        assert s.reason.strip() and not s.reason.startswith("TODO")


def test_cli_json_output(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    rc = eng.main(["--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == [] and out["stale_baseline"] == []
    assert len(out["checked_rules"]) == 10


def test_cli_list_rules(capsys):
    assert eng.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("LGB001", "LGB002", "LGB003", "LGB004", "LGB005",
                "LGB006", "LGB007", "LGB008", "LGB009", "LGB010"):
        assert rid in out
