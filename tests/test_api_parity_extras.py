"""API-parity extras (reference: python-package/lightgbm/basic.py):
trees_to_dataframe, model_from_string, leaf output get/set, score bounds,
shuffle_models, Dataset get_data/set_categorical_feature/get_ref_chain."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def model():
    rs = np.random.RandomState(0)
    X = rs.randn(800, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=5)
    return bst, ds, X, y


def test_trees_to_dataframe(model):
    pd = pytest.importorskip("pandas")
    bst, _, _, _ = model
    df = bst.trees_to_dataframe()
    expect_cols = ["tree_index", "node_depth", "node_index", "left_child",
                   "right_child", "parent_index", "split_feature",
                   "split_gain", "threshold", "decision_type",
                   "missing_direction", "missing_type", "value", "weight",
                   "count"]
    assert list(df.columns) == expect_cols
    assert df["tree_index"].nunique() == 5
    # nodes = leaves + internals per tree
    t0 = df[df.tree_index == 0]
    leaves = t0[t0.left_child.isna()]
    assert len(leaves) == (len(t0) + 1) // 2
    # root has depth 1, no parent
    root = t0[t0.node_depth == 1]
    assert len(root) == 1 and root.parent_index.isna().all()
    # leaf count sums to the training rows
    assert int(leaves["count"].sum()) == 800


def test_model_from_string_inplace(model):
    bst, _, X, _ = model
    base = bst.predict(X[:10], raw_score=True)
    other = lgb.Booster(model_str=bst.model_to_string())
    fresh = lgb.train({"objective": "regression", "num_leaves": 4,
                       "verbosity": -1},
                      lgb.Dataset(X, label=X[:, 0]), num_boost_round=2)
    fresh.model_from_string(bst.model_to_string())
    np.testing.assert_allclose(fresh.predict(X[:10], raw_score=True), base,
                               rtol=1e-12)
    np.testing.assert_allclose(other.predict(X[:10], raw_score=True), base,
                               rtol=1e-12)


def test_leaf_output_get_set(model):
    bst, _, X, _ = model
    b = lgb.Booster(model_str=bst.model_to_string())
    v = b.get_leaf_output(0, 0)
    base = b.predict(X[:50], raw_score=True)
    b.set_leaf_output(0, 0, v + 1.0)
    assert b.get_leaf_output(0, 0) == pytest.approx(v + 1.0)
    shifted = b.predict(X[:50], raw_score=True)
    d = shifted - base
    # rows landing in that leaf move by exactly +1, others by 0
    assert set(np.round(d, 9)) <= {0.0, 1.0}
    assert (d == 1.0).any()


def test_bounds_and_shuffle(model):
    bst, _, X, _ = model
    b = lgb.Booster(model_str=bst.model_to_string())
    lo, hi = b.lower_bound(), b.upper_bound()
    p = b.predict(X, raw_score=True)
    assert lo <= p.min() and p.max() <= hi
    np.random.seed(0)
    b.shuffle_models()
    # tree order doesn't change summed predictions
    np.testing.assert_allclose(b.predict(X, raw_score=True), p, rtol=1e-12)


def test_dataset_extras(model):
    _, ds, X, _ = model
    assert ds.get_data() is not None
    assert ds.get_feature_name() == ds.feature_name()
    chain = ds.get_ref_chain()
    assert ds in chain and len(chain) == 1
    d2 = lgb.Dataset(X[:100], reference=ds)
    assert ds in d2.get_ref_chain() and len(d2.get_ref_chain()) == 2
    with pytest.raises(lgb.LightGBMError, match="constructed"):
        ds.set_categorical_feature([1])
    fresh = lgb.Dataset(X)
    fresh.set_categorical_feature([1])
    assert fresh._categorical_feature_arg == [1]


def test_set_reference(model):
    _, ds, X, _ = model
    d2 = lgb.Dataset(X[:200])
    d2.set_reference(ds)
    d2.construct()
    # reference mappers adopted: identical binning of shared rows
    np.testing.assert_array_equal(
        np.asarray(d2.binned.bins), np.asarray(ds.construct().binned.bins)[:200])
    with pytest.raises(lgb.LightGBMError, match="constructed"):
        d2.set_reference(lgb.Dataset(X[:50]))


def test_set_reference_realigns_dataframe_categories():
    """set_reference AFTER __init__ must rebuild the frame's categorical
    codes through the reference's category lists (they were baked locally
    at init), and adopt the reference's names/categorical spec."""
    pd = pytest.importorskip("pandas")
    rs = np.random.RandomState(1)
    n = 600
    colors = rs.choice(["a", "b", "c"], n)
    x = rs.randn(n)
    y = (colors == "a").astype(np.float64)
    train_df = pd.DataFrame({
        "c": pd.Categorical(colors, categories=["a", "b", "c"]), "x": x})
    ds = lgb.Dataset(train_df, label=y, categorical_feature=["c"])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    ds, num_boost_round=4)
    # validation frame with a DIFFERENT category order, reference set late
    val_df = pd.DataFrame({
        "c": pd.Categorical(colors[:200], categories=["c", "b", "a"]),
        "x": x[:200]})
    dv = lgb.Dataset(val_df, label=y[:200]).set_reference(ds)
    dv.construct()
    ref_bins = np.asarray(lgb.Dataset(val_df, label=y[:200], reference=ds)
                          .construct().binned.bins)
    np.testing.assert_array_equal(np.asarray(dv.binned.bins), ref_bins)
    assert dv.feature_name() == ds.feature_name()
    # arrow/Sequence sources fail loud instead of silently re-binning
    pa = pytest.importorskip("pyarrow")
    t = pa.table({"x": x})
    with pytest.raises(lgb.LightGBMError, match="arrow"):
        lgb.Dataset(t).set_reference(ds)
