"""Binary dataset save/load + EFB bundle correctness with NaN.

Reference: src/io/dataset.cpp SaveBinaryFile / dataset_loader.cpp
LoadFromBinFile; EFB: include/LightGBM/dataset.h feature groups."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def test_save_binary_roundtrip(tmp_path):
    rs = np.random.RandomState(3)
    X = rs.randn(1000, 6)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rs.randn(1000)
    w = rs.rand(1000) + 0.5
    ds = lgb.Dataset(X, label=y, weight=w)
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)

    ds2 = lgb.Dataset(path)
    assert ds2.num_data() == 1000
    assert ds2.num_feature() == 6
    np.testing.assert_allclose(ds2.get_label(), y)
    np.testing.assert_allclose(ds2.get_weight(), w)

    # training from the binary file must match training from raw data
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5}
    b1 = lgb.train(p, lgb.Dataset(X, label=y, weight=w), num_boost_round=5)
    b2 = lgb.train(p, ds2, num_boost_round=5)
    assert b1.model_to_string() == b2.model_to_string()


@pytest.mark.slow
def test_efb_bundling_with_nan_matches_unbundled():
    """Sparse mutually-exclusive features bundle under EFB; predictions must
    match the unbundled run, including NaN rows (VERDICT r1 weak #8)."""
    rs = np.random.RandomState(7)
    n = 3000
    dense = rs.randn(n, 2)
    # 6 mutually exclusive sparse features (one-hot-ish blocks)
    sparse = np.zeros((n, 6))
    which = rs.randint(0, 6, n)
    sparse[np.arange(n), which] = rs.rand(n) + 0.5
    X = np.column_stack([dense, sparse])
    X[rs.rand(n) < 0.05, 0] = np.nan
    y = (dense[:, 1] * 2 + (which == 2) * 1.5
         + np.nan_to_num(X[:, 0]) + 0.05 * rs.randn(n))

    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 5, "max_bin": 63}
    b_bundle = lgb.train(p, lgb.Dataset(
        X, label=y, params={"enable_bundle": True}), num_boost_round=8)
    b_plain = lgb.train(p, lgb.Dataset(
        X, label=y, params={"enable_bundle": False}), num_boost_round=8)
    # bundling must have occurred for the test to mean anything
    gb = b_bundle.engine.dd.bins.shape[1]
    gp = b_plain.engine.dd.bins.shape[1]
    assert gb < gp, f"expected bundling to reduce groups ({gb} vs {gp})"
    pr_b = b_bundle.predict(X)
    pr_p = b_plain.predict(X)
    # same information is available either way: models should agree closely
    mse_b = float(np.mean((pr_b - y) ** 2))
    mse_p = float(np.mean((pr_p - y) ** 2))
    assert mse_b < mse_p * 1.25 + 1e-3, (mse_b, mse_p)


def test_binary_valid_set_workflow(tmp_path):
    """save train.bin + valid.bin, reload BOTH, train with the reloaded
    valid set (reference LoadFromBinFile parity)."""
    rs = np.random.RandomState(9)
    X = rs.randn(1200, 5)
    y = X[:, 0] * 2 + 0.1 * rs.randn(1200)
    Xv = rs.randn(300, 5)
    yv = Xv[:, 0] * 2 + 0.1 * rs.randn(300)
    tr = lgb.Dataset(X, label=y)
    tr.save_binary(str(tmp_path / "train.bin"))
    lgb.Dataset(Xv, label=yv, reference=tr).save_binary(
        str(tmp_path / "valid.bin"))

    tr2 = lgb.Dataset(str(tmp_path / "train.bin"))
    va2 = lgb.Dataset(str(tmp_path / "valid.bin"))
    ev = {}
    lgb.train({"objective": "regression", "num_leaves": 15, "verbosity": -1,
               "min_data_in_leaf": 5}, tr2, num_boost_round=5,
              valid_sets=[va2], valid_names=["v"],
              callbacks=[lgb.record_evaluation(ev)])
    assert len(ev["v"]["l2"]) == 5
    assert ev["v"]["l2"][-1] < ev["v"]["l2"][0]


def test_chunk_list_of_1d_is_a_matrix():
    """A list of 1-D arrays is a plain (rows, cols) matrix, NOT row chunks."""
    X = [np.array([1.0, 2.0, 3.0]), np.array([4.0, 5.0, 6.0])]
    ds = lgb.Dataset(X, label=[0.0, 1.0])
    assert ds.num_data() == 2 and ds.num_feature() == 3


def test_binary_v1_pickle_rejected(tmp_path):
    """The deprecated pickle format must not be loadable (code execution)."""
    p = tmp_path / "old.bin"
    p.write_bytes(b"LGBTPU.BIN.v1\njunk")
    with pytest.raises(lgb.LightGBMError, match="v1 pickle"):
        lgb.Dataset(str(p)).construct()


def test_binary_file_is_not_a_pickle(tmp_path):
    """v2 files load with allow_pickle=False; no pickle opcodes involved."""
    X = np.random.RandomState(0).randn(80, 4)
    ds = lgb.Dataset(X, label=(X[:, 0] > 0).astype(float))
    p = tmp_path / "ds.bin"
    ds.save_binary(str(p))
    blob = p.read_bytes()
    assert blob.startswith(b"LGBTPU.BIN.v2\n")
    assert b"pickle" not in blob
