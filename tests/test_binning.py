"""Binning unit tests (model: reference bin-mapper semantics, bin.cpp)."""
import numpy as np
import pytest

from lightgbm_tpu.binning import (BIN_CATEGORICAL, MISSING_NAN, MISSING_NONE,
                                  BinMapper, construct_binned, find_bin_mappers,
                                  find_feature_groups)


def test_few_distinct_values_get_own_bins():
    # stock-verified: the reference CLI reports "Total Bins 4" for this
    # feature and tree threshold nextafter(2.5) — FindBinWithZeroAsOneBin
    # (bin.cpp:247) always reserves the [-kZeroThreshold, kZeroThreshold]
    # zero bin when positive values exist, so an all-positive feature gets
    # an empty bin 0 plus one bin per distinct value
    vals = np.array([1.0, 1.0, 2.0, 2.0, 3.0, 3.0, 3.0])
    m = BinMapper.find_numerical(vals, max_bin=255, min_data_in_bin=1,
                                 use_missing=True, zero_as_missing=False)
    assert m.num_bins == 4
    np.testing.assert_allclose(
        m.upper_bounds,
        [1e-35, np.nextafter(1.5, np.inf), np.nextafter(2.5, np.inf), np.inf])
    b = m.transform(np.array([1.0, 2.0, 3.0]))
    assert len(set(b.tolist())) == 3
    # ordering preserved; bin 0 (the zero bin) stays empty
    assert 0 < b[0] < b[1] < b[2]


def test_quantile_binning_many_values():
    rs = np.random.RandomState(0)
    vals = rs.randn(10000)
    m = BinMapper.find_numerical(vals, max_bin=64, min_data_in_bin=3,
                                 use_missing=True, zero_as_missing=False)
    assert 2 <= m.num_bins <= 64
    b = m.transform(vals)
    counts = np.bincount(b, minlength=m.num_bins)
    # roughly balanced bins: no bin with more than 15% of data
    assert counts.max() < 0.15 * len(vals)


def test_nan_gets_own_bin():
    vals = np.array([1.0, 2.0, np.nan, 3.0, np.nan])
    m = BinMapper.find_numerical(vals, max_bin=16, min_data_in_bin=1,
                                 use_missing=True, zero_as_missing=False)
    assert m.missing_type == MISSING_NAN
    b = m.transform(vals)
    assert b[2] == b[4] == m.num_bins - 1
    assert b[0] != b[2]


def test_monotone_transform():
    rs = np.random.RandomState(1)
    vals = rs.randn(1000)
    m = BinMapper.find_numerical(vals, max_bin=32, min_data_in_bin=3,
                                 use_missing=False, zero_as_missing=False)
    x = np.sort(rs.randn(100))
    b = m.transform(x)
    assert np.all(np.diff(b) >= 0), "binning must be monotone"


def test_categorical_binning():
    vals = np.array([3.0, 3.0, 3.0, 1.0, 1.0, 7.0], dtype=np.float64)
    m = BinMapper.find_categorical(vals, max_bin=16, min_data_in_bin=1,
                                   use_missing=True)
    assert m.bin_type == BIN_CATEGORICAL
    b = m.transform(np.array([3.0, 1.0, 7.0, 99.0]))
    assert b[0] == 0          # most frequent category = bin 0
    assert b[3] == 0          # unseen -> bin 0
    assert len({b[0], b[1], b[2]}) == 3


def test_efb_bundles_exclusive_features():
    n = 1000
    rs = np.random.RandomState(2)
    f0 = np.zeros(n); f0[:300] = rs.rand(300) + 1.0
    f1 = np.zeros(n); f1[500:700] = rs.rand(200) + 1.0
    f2 = rs.rand(n)  # dense — must not bundle
    data = np.column_stack([f0, f1, f2])
    mappers = find_bin_mappers(data, 255, 1, sample_cnt=1000)
    sample_bins = [mappers[f].transform(data[:, f]) for f in range(3)]
    groups = find_feature_groups(sample_bins, mappers, enable_bundle=True)
    grouped = [g for g in groups if len(g) > 1]
    assert any(set(g) == {0, 1} for g in grouped), f"expected bundle of 0,1: {groups}"


def test_construct_binned_layout():
    rs = np.random.RandomState(3)
    data = rs.randn(500, 4)
    mappers = find_bin_mappers(data, 16, 1)
    binned = construct_binned(data, mappers)
    assert binned.bins.shape == (500, 4)
    assert binned.num_total_bins == sum(m.num_bins for m in mappers)
    # round trip: bin values within range
    for f in range(4):
        assert binned.bins[:, f].max() < mappers[f].num_bins
