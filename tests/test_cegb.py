"""Cost-effective gradient boosting (reference:
src/treelearner/cost_effective_gradient_boosting.hpp:80 DeltaGain —
split-count and coupled feature-acquisition penalties)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow  # heavy multi-model tier (PERF.md test tiers)

BASE = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
        "min_data_in_leaf": 5}


def _data(seed=3, n=3000):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 10)
    # features 5-9 carry real signal so the unpenalized model uses them
    y = X[:, 0] * 2 + X[:, 1] + X[:, 5] + 0.5 * X[:, 6] + 0.1 * rs.randn(n)
    return X, y


def test_coupled_feature_penalty_suppresses_costly_features():
    X, y = _data()
    b0 = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=10)
    b1 = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                    "cegb_penalty_feature_coupled": [0.0] * 5 + [1e6] * 5},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    imp0 = b0.feature_importance()
    imp1 = b1.feature_importance()
    assert imp0[5:].sum() > 0, "baseline should use the signal features"
    assert imp1[5:].sum() < imp0[5:].sum()


def test_split_penalty_shrinks_trees():
    X, y = _data(seed=5)
    b0 = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=5)
    b1 = lgb.train({**BASE, "cegb_penalty_split": 2.0},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    l0 = sum(t.num_leaves for t in b0._all_trees())
    l1 = sum(t.num_leaves for t in b1._all_trees())
    assert l1 < l0


def test_lazy_penalty_suppresses_costly_features():
    """Lazy (per-row on-demand) acquisition costs: a feature's cost is the
    sum over the leaf's rows that have NOT paid it yet
    (CalculateOndemandCosts, cegb hpp:140); expensive features are avoided
    while cheap ones stay usable."""
    X, y = _data(seed=6)
    b0 = lgb.train(BASE, lgb.Dataset(X, label=y), num_boost_round=8)
    b1 = lgb.train({**BASE, "cegb_tradeoff": 1.0,
                    "cegb_penalty_feature_lazy": [0.0] * 5 + [1e5] * 5},
                   lgb.Dataset(X, label=y), num_boost_round=8)
    imp0 = b0.feature_importance()
    imp1 = b1.feature_importance()
    assert imp0[5:].sum() > 0, "baseline should use the signal features"
    assert imp1[5:].sum() < imp0[5:].sum()
    # cheap features keep working
    assert imp1[:5].sum() > 0


def test_lazy_penalty_charges_rows_once():
    """Once a leaf's rows have paid a feature, re-splitting THOSE rows on
    it is free — with a moderate per-row cost the model still trains (the
    first profitable acquisition amortizes; reference: the
    feature_used_in_data_ bitset persists across trees)."""
    X, y = _data(seed=7)
    pen = [0.05] * 10
    b = lgb.train({**BASE, "cegb_penalty_feature_lazy": pen},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    pred = np.asarray(b.predict(X))
    assert np.corrcoef(pred, y)[0, 1] > 0.8


def test_lazy_penalty_wrong_length_raises():
    X, y = _data(seed=6)
    with pytest.raises(lgb.LightGBMError):
        lgb.train({**BASE, "cegb_penalty_feature_lazy": [1.0]},
                  lgb.Dataset(X, label=y), num_boost_round=2)
