"""CLI application + data-file sidecars + position debias.

Reference: src/application/application.cpp:217 (task dispatch),
src/io/dataset_loader.cpp:211 (.query/.weight sidecars),
src/objective/rank_objective.hpp:303 (position debias)."""
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main as cli_main


def _write_train(tmp_path, n=600, seed=3, ranking=False):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, 5).round(4)
    if ranking:
        y = rs.randint(0, 4, n)
    else:
        y = (X[:, 0] + X[:, 1] > 0).astype(int)
    path = tmp_path / "train.csv"
    data = np.column_stack([y, X])
    np.savetxt(path, data, delimiter=",", fmt="%.5g")
    return path, X, y


@pytest.mark.slow
def test_cli_train_predict_roundtrip(tmp_path):
    train_csv, X, y = _write_train(tmp_path)
    model = tmp_path / "model.txt"
    conf = tmp_path / "train.conf"
    conf.write_text(
        f"task = train\ndata = {train_csv}\nobjective = binary\n"
        f"num_iterations = 5\nnum_leaves = 15\nmin_data_in_leaf = 5\n"
        f"output_model = {model}\nverbosity = -1\n")
    assert cli_main([f"config={conf}"]) == 0
    assert model.exists()

    out = tmp_path / "preds.txt"
    assert cli_main([f"task=predict", f"data={train_csv}",
                     f"input_model={model}", f"output_result={out}",
                     "verbosity=-1"]) == 0
    preds = np.loadtxt(out)
    assert preds.shape == (600,)
    assert ((preds > 0.5) == y).mean() > 0.85
    # CLI overrides config file values
    model2 = tmp_path / "model2.txt"
    assert cli_main([f"config={conf}", f"output_model={model2}",
                     "num_iterations=2"]) == 0
    b2 = lgb.Booster(model_file=str(model2))
    assert b2.num_trees() == 2


@pytest.mark.slow
def test_query_weight_sidecars(tmp_path):
    rs = np.random.RandomState(5)
    n = 400
    X = rs.randn(n, 4)
    y = rs.randint(0, 3, n)
    path = tmp_path / "rank.train"
    np.savetxt(path, np.column_stack([y, X]), delimiter=",", fmt="%.5g")
    groups = [40] * 10
    (tmp_path / "rank.train.query").write_text(
        "\n".join(str(g) for g in groups))
    weights = rs.rand(n) + 0.5
    (tmp_path / "rank.train.weight").write_text(
        "\n".join(f"{w:.4f}" for w in weights))

    ds = lgb.Dataset(str(path))
    ds.construct()
    assert ds.get_group() is not None
    np.testing.assert_array_equal(np.asarray(ds.get_group()), groups)
    np.testing.assert_allclose(ds.get_weight(), weights, rtol=1e-4)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 2},
                    ds, num_boost_round=3)
    assert bst.num_trees() == 3


@pytest.mark.slow
def test_position_debias_lambdarank(tmp_path):
    rs = np.random.RandomState(7)
    n = 400
    X = rs.randn(n, 4)
    y = rs.randint(0, 3, n)
    pos = np.tile(np.arange(40), 10)
    ds = lgb.Dataset(X, label=y.astype(float), group=[40] * 10,
                     position=pos)
    bst = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                     "verbosity": -1, "min_data_in_leaf": 2,
                     "lambdarank_position_bias_regularization": 0.1},
                    ds, num_boost_round=3)
    obj = bst.engine.objective
    assert obj._positions is not None
    # Newton updates must have moved the bias factors
    assert float(np.abs(np.asarray(obj.pos_biases)).sum()) > 0


def test_libsvm_qid_groups(tmp_path):
    path = tmp_path / "q.libsvm"
    lines = []
    rs = np.random.RandomState(1)
    for qid in range(5):
        for _ in range(8):
            feats = " ".join(f"{j}:{rs.rand():.3f}" for j in range(4))
            lines.append(f"{rs.randint(0, 3)} qid:{qid} {feats}")
    path.write_text("\n".join(lines))
    ds = lgb.Dataset(str(path))
    ds.construct()
    np.testing.assert_array_equal(np.asarray(ds.get_group()), [8] * 5)


@pytest.mark.slow
def test_cli_refit(tmp_path):
    """task=refit refits leaf values on new data (reference:
    application.cpp:236)."""
    train_csv, X, y = _write_train(tmp_path)
    model = str(tmp_path / "m.txt")
    cli_main([f"data={train_csv}", "objective=binary", "num_leaves=7",
              "num_iterations=5", f"output_model={model}", "verbosity=-1"])
    # new data: same shape, perturbed labels
    rs = np.random.RandomState(9)
    X2 = X + 0.05 * rs.randn(*X.shape)
    y2 = (X2[:, 0] + X2[:, 1] > 0).astype(int)
    refit_csv = tmp_path / "refit.csv"
    np.savetxt(refit_csv, np.column_stack([y2, X2]), delimiter=",",
               fmt="%.5g")
    out_model = str(tmp_path / "refit.txt")
    cli_main(["task=refit", f"data={refit_csv}", f"input_model={model}",
              f"output_model={out_model}", "verbosity=-1"])
    a = open(model).read()
    b = open(out_model).read()
    assert "tree" in b and a != b      # structure kept, leaf values moved
    # structure (splits) must be unchanged by refit
    for key in ("split_feature=", "threshold="):
        sa = [l for l in a.splitlines() if l.startswith(key)]
        sb = [l for l in b.splitlines() if l.startswith(key)]
        assert sa == sb


def test_cli_save_binary_then_train(tmp_path):
    """task=save_binary writes a binary dataset the train task can consume
    (reference: application.cpp:217, Dataset::SaveBinaryFile)."""
    train_csv, X, y = _write_train(tmp_path)
    binpath = str(tmp_path / "train.bin")
    cli_main(["task=save_binary", f"data={train_csv}",
              f"output_model={binpath}", "verbosity=-1"])
    assert open(binpath, "rb").read(14) == b"LGBTPU.BIN.v2\n"
    m1 = str(tmp_path / "m1.txt")
    m2 = str(tmp_path / "m2.txt")
    common = ["objective=binary", "num_leaves=7", "num_iterations=5",
              "verbosity=-1"]
    cli_main([f"data={train_csv}", f"output_model={m1}"] + common)
    cli_main([f"data={binpath}", f"output_model={m2}"] + common)
    t1 = open(m1).read().split("\nparameters:")[0]
    t2 = open(m2).read().split("\nparameters:")[0]
    assert t1 == t2


def test_cli_predict_writes_atomically(tmp_path):
    """task=predict goes through tmp + os.replace (the robustness
    checkpoint helper): a killed job never leaves a truncated result, and
    no tmp droppings survive a clean run."""
    train_csv, X, y = _write_train(tmp_path)
    model = str(tmp_path / "m.txt")
    cli_main([f"data={train_csv}", "objective=binary", "num_leaves=7",
              "num_iterations=3", f"output_model={model}", "verbosity=-1"])
    out = tmp_path / "preds" / "result.tsv"   # dir is created by the helper
    cli_main(["task=predict", f"data={train_csv}", f"input_model={model}",
              f"output_result={out}", "verbosity=-1"])
    got = np.loadtxt(out)
    want = lgb.Booster(model_file=model).predict(X)
    np.testing.assert_allclose(got, want, rtol=1e-15, atol=1e-18)
    leftovers = [p.name for p in out.parent.iterdir() if p.name != out.name]
    assert leftovers == []


def test_cli_convert_model(tmp_path):
    """task=convert_model dumps the model as JSON."""
    import json
    train_csv, X, y = _write_train(tmp_path)
    model = str(tmp_path / "m.txt")
    cli_main([f"data={train_csv}", "objective=binary", "num_leaves=7",
              "num_iterations=3", f"output_model={model}", "verbosity=-1"])
    out = str(tmp_path / "m.json")
    cli_main(["task=convert_model", f"input_model={model}",
              f"convert_model={out}", "verbosity=-1"])
    blob = json.loads(open(out).read())
    assert blob["num_tree_per_iteration"] == 1
    assert len(blob["tree_info"]) == 3
