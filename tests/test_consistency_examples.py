"""Real-data oracle tier: the reference's bundled example datasets.

Trains via OUR CLI on the reference's own example configs
(reference: examples/*/train.conf, the same data+confs its
tests/python_package_test/test_consistency.py and cpp_tests/testutils.cpp
consume) and asserts final validation metrics match stock LightGBM's
within tolerance.  The stock numbers are committed fixtures produced by
`LGBM_CLI=... python scripts/gen_example_fixtures.py` (a CLI built from
/root/reference; see the memory notes in that script).

Exact per-tree parity is impossible here by design — these confs use
feature_fraction/bagging, whose RNG differs between implementations —
so the gate is metric parity on real data, like the reference's own
consistency suite.
"""
import json
import shutil
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import lightgbm_tpu as lgb

EXAMPLES = Path("/root/reference/examples")
FIXTURES = json.loads(
    (Path(__file__).parent / "fixtures" / "examples_stock.json").read_text())

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not EXAMPLES.exists(),
                       reason="reference examples not mounted"),
]


def _run_cli(tmp_path, example, files, overrides=()):
    src = EXAMPLES / example
    for f in list(files) + ["train.conf"]:
        if (src / f).exists():
            shutil.copy(src / f, tmp_path / f)
    from lightgbm_tpu import cli
    import os
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rc = cli.main(["config=train.conf", "verbosity=-1", *overrides])
    finally:
        os.chdir(cwd)
    assert rc == 0
    return lgb.Booster(model_file=str(tmp_path / "LightGBM_model.txt"))


def _load_tsv(path):
    mat = np.loadtxt(path)
    return mat[:, 1:], mat[:, 0]


def _auc(y, p):
    order = np.argsort(p)
    r = np.empty(len(p))
    r[order] = np.arange(len(p))
    npos = (y > 0.5).sum()
    nneg = len(y) - npos
    return (r[y > 0.5].sum() - npos * (npos - 1) / 2) / (npos * nneg)


def test_binary_example(tmp_path):
    bst = _run_cli(tmp_path, "binary_classification",
                   ["binary.train", "binary.test", "binary.train.weight",
                    "binary.test.weight", "forced_splits.json"])
    X, y = _load_tsv(tmp_path / "binary.test")
    auc = _auc(y, bst.predict(X, raw_score=True))
    stock = FIXTURES["binary_classification"]["valid_1:auc"]
    assert abs(auc - stock) < 0.02, (auc, stock)


def test_regression_example(tmp_path):
    bst = _run_cli(tmp_path, "regression",
                   ["regression.train", "regression.test",
                    "regression.train.init", "regression.test.init"])
    X, y = _load_tsv(tmp_path / "regression.test")
    # stock evaluates l2 on the valid set INCLUDING its .init offsets
    init = np.loadtxt(tmp_path / "regression.test.init")
    l2 = float(np.mean((y - (bst.predict(X) + init)) ** 2))
    stock = FIXTURES["regression"]["valid_1:l2"]
    assert abs(l2 - stock) < 0.02, (l2, stock)


def test_lambdarank_example(tmp_path):
    bst = _run_cli(tmp_path, "lambdarank",
                   ["rank.train", "rank.test", "rank.train.query",
                    "rank.test.query"])
    from sklearn.datasets import load_svmlight_file
    X, y = load_svmlight_file(str(tmp_path / "rank.test"), zero_based=True)
    q = np.loadtxt(tmp_path / "rank.test.query").astype(int)
    score = bst.predict(X.toarray())
    # NDCG@5 with LightGBM's 2^label-1 gains and position discounts
    vals = []
    start = 0
    for s in q:
        lb, sc = y[start:start + s], score[start:start + s]
        start += s
        gains = 2.0 ** lb - 1
        if gains.max() <= 0:
            continue
        order = np.argsort(-sc)[:5]
        disc = 1.0 / np.log2(np.arange(2, 2 + len(order)))
        dcg = float(np.sum(gains[order] * disc))
        ideal = np.sort(gains)[::-1][:5]
        vals.append(dcg / float(np.sum(ideal * disc[:len(ideal)])))
    ndcg = float(np.mean(vals))
    stock = FIXTURES["lambdarank"]["valid_1:ndcg@5"]
    # stock's own ndcg@5 across seeds 1..4 on this conf spans
    # 0.6416..0.6851 (bagging_fraction=0.9 RNG) — tolerance covers that
    # seed variance, not implementation slack
    assert abs(ndcg - stock) < 0.05, (ndcg, stock)


def test_multiclass_example(tmp_path):
    bst = _run_cli(tmp_path, "multiclass_classification",
                   ["multiclass.train", "multiclass.test"])
    X, y = _load_tsv(tmp_path / "multiclass.test")
    p = bst.predict(X)
    eps = 1e-15
    logloss = float(np.mean(-np.log(
        np.clip(p[np.arange(len(y)), y.astype(int)], eps, 1.0))))
    stock = FIXTURES["multiclass_classification"]["valid_1:multi_logloss"]
    assert abs(logloss - stock) < 0.08, (logloss, stock)


def test_parallel_learning_example(tmp_path):
    """The parallel_learning example conf (tree_learner=feature) on the
    in-process device mesh; same binary data, same metric gate."""
    src = EXAMPLES / "parallel_learning"
    for f in ["binary.train", "binary.test", "train.conf"]:
        shutil.copy(src / f, tmp_path / f)
    bst = _run_cli(tmp_path, "parallel_learning",
                   ["binary.train", "binary.test"],
                   overrides=["num_machines=1"])
    X, y = _load_tsv(tmp_path / "binary.test")
    auc = _auc(y, bst.predict(X, raw_score=True))
    stock = FIXTURES["binary_classification"]["valid_1:auc"]
    assert abs(auc - stock) < 0.02, (auc, stock)
